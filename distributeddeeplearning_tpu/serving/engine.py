"""Slot-pool batched decode engine — the compiled heart of serving.

One pooled KV cache of ``[num_slots, max_len, heads, head_dim]`` rows
per attention layer, and exactly **bucket_count + 1 compiled programs**
for the engine's whole lifetime:

* one *decode step*: every occupied slot advances one token — per-slot
  positions (vector ``cache_index``/``pos_index``, see
  ``models/vit.Attention._decode_attention``), per-slot sampling config
  as data (``serving.sampling``), per-slot stop detection on device.
  Requests join and leave between steps; the program never changes.
* one *prefill* per prompt-length bucket: the prompt padded up the
  bucket ladder runs one full causal forward with a fresh zero cache
  and writes K/V straight into the assigned slot's pool rows
  (``dynamic_update_slice`` at the slot index — the padded tail beyond
  ``prompt_len`` lands in rows the decode mask can never attend before
  they are overwritten, so it needs no cleanup). The first token is
  sampled inside the program from the true last prompt position.

Static shapes everywhere; admission, eviction and any greedy/sampled
request mix are pure data. Both programs are AOT-compiled
(``.lower().compile()``, cache pool donated) at :meth:`SlotEngine.warmup`
— after it, the engine *cannot* recompile, which
``tests/test_serving.py`` pins with a backend-compile listener across
an admission/eviction churn.

Bitwise contract: each request's token stream equals sequential
``inference.generate`` (same prompt, config and rng) — the per-request
key ladder is precomputed on the host (``serving.keys``) and fed per
step, so co-scheduling cannot perturb any request's randomness.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.serving import keys as keylib
from distributeddeeplearning_tpu.serving.blocks import (
    BlockAllocator,
    BlockPoolExhausted,
)
from distributeddeeplearning_tpu.serving.sampling import (
    DEFAULT_TOP_K_CAP,
    sample_slot,
    sample_slots,
    spec_verify_slots,
)
from distributeddeeplearning_tpu.serving.spec import (
    NgramDrafter,
    propose_all,
    validate_spec_config,
)
from distributeddeeplearning_tpu.utils.logging import get_logger

_INDEX_NAMES = ("cache_index", "pos_index")
# Paged layout (kv_layout="paged"): the block pools are batch-independent
# shared tensors; the block table is per-row routing data fed each step
# exactly like the position vectors. The *_scale pools exist only under
# kv_dtype="int8" (f32 scales resident beside the int8 payload) and
# follow the same block addressing.
_PAGED_POOL_NAMES = ("paged_k", "paged_v", "paged_k_scale", "paged_v_scale")
_TABLE_NAME = "block_table"


@dataclasses.dataclass
class ProgramSpec:
    """One member of the engine's closed program set — everything needed
    to compile it (:meth:`SlotEngine.warmup`) or to lower it for
    inspection (the ddlint HLO audit, ``analysis/hlo_audit.py``). Both
    consumers iterate the SAME table (:meth:`SlotEngine.program_specs`),
    so what the lint audits is, by construction, what serves."""

    name: str
    fn: Callable
    donate_argnums: Tuple[int, ...]
    example_args: tuple
    span: Dict[str, Any]  # labels for the `compile` span
    _get: Callable[[], Any]  # read the installed executable slot
    _set: Callable[[Any], None]  # install a compiled executable

    @property
    def installed(self) -> bool:
        return self._get() is not None

    def install(self, compiled: Any) -> None:
        self._set(compiled)


def default_buckets(max_len: int, smallest: int = 16) -> Tuple[int, ...]:
    """Power-of-two prefill ladder up to ``max_len`` (always including
    ``max_len`` itself so any admissible prompt has a bucket)."""
    out: List[int] = []
    b = smallest
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(sorted(set(out)))


@dataclasses.dataclass
class ReqSpec:
    """One request's generation spec — mirrors ``inference.generate``'s
    keyword surface; ``rng`` is raw key data ([2] uint32), an int seed,
    or None (PRNGKey(0), like ``generate``)."""

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token: Optional[int] = None
    rng: Any = None

    def validate(self, max_len: int, max_bucket: int) -> None:
        t = int(np.asarray(self.prompt).shape[-1])
        if np.asarray(self.prompt).ndim != 1 or t < 1:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        if t > max_bucket:
            raise ValueError(
                f"prompt length {t} exceeds the largest prefill bucket "
                f"{max_bucket}"
            )
        if t + self.max_new_tokens > max_len:
            raise ValueError(
                f"prompt {t} + max_new_tokens {self.max_new_tokens} "
                f"exceeds the engine cache length {max_len}"
            )
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")

    def key_data(self) -> np.ndarray:
        if self.rng is None:
            return keylib.key_from_seed(0)
        if isinstance(self.rng, (int, np.integer)):
            return keylib.key_from_seed(int(self.rng))
        return np.asarray(self.rng, np.uint32).reshape(2)


class SlotEngine:
    """Continuous-batching decode over ``num_slots`` KV-cache slots.

    Low-level and mechanical by design: it owns the device cache pool,
    the compiled programs and per-slot decode bookkeeping. Queueing,
    deadlines and request lifecycles live in
    :class:`~distributeddeeplearning_tpu.serving.scheduler.Server`.
    """

    def __init__(
        self,
        model,
        params,
        *,
        num_slots: int = 8,
        max_len: Optional[int] = None,
        buckets: Optional[Tuple[int, ...]] = None,
        top_k_cap: int = DEFAULT_TOP_K_CAP,
        kv_layout: str = "dense",
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        prefix_cache: bool = True,
        kv_dtype: str = "bf16",
        weight_dtype: str = "bf16",
        decode_kernel: str = "xla",
        spec_k: int = 0,
        spec_draft: str = "int8",
        spec_ngram_n: int = 3,
        pool_role: str = "both",
    ) -> None:
        from distributeddeeplearning_tpu.ops import quant as quantlib

        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got {kv_layout!r}"
            )
        # Disaggregated serving (docs/SERVING.md): a pool-typed engine
        # compiles only its phase's programs — "prefill" skips the
        # decode step, "decode" skips the prefill ladder — so each pool
        # keeps a smaller closed program set. Pool typing requires the
        # paged layout (the block table is the handoff unit) and no
        # speculation (the draft pool's state does not travel).
        if pool_role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"pool_role must be one of ('both', 'prefill', 'decode'), "
                f"got {pool_role!r}"
            )
        if pool_role != "both":
            if kv_layout != "paged":
                raise ValueError(
                    f"pool_role={pool_role!r} requires kv_layout='paged' "
                    "(the block table is the handoff unit)"
                )
            if spec_k:
                raise ValueError(
                    f"pool_role={pool_role!r} is incompatible with "
                    f"spec_k={spec_k} (draft state does not travel)"
                )
        self.pool_role = pool_role
        # "bf16" means *native* (store the model's compute dtype — the
        # pre-quantization behaviour); "int8"/"fp8" engage ops/quant.py.
        # The supported tiers live in ONE registry (quant.KV_DTYPES /
        # quant.WEIGHT_DTYPES) so the enum, the env parsing (ServeConfig)
        # and this boundary reject unknown dtypes with the same list.
        quantlib.validate_store_dtype("kv_dtype", kv_dtype)
        quantlib.validate_store_dtype("weight_dtype", weight_dtype)
        # fp8 is platform-gated: where the compiled backend cannot
        # round-trip float8 we fall back to the int8 tier (same scale
        # layout, one extra bit of mantissa) rather than crash mid-build.
        if "fp8" in (kv_dtype, weight_dtype) and not quantlib.fp8_supported():
            get_logger().warning(
                "fp8 storage unsupported on backend %r; falling back to "
                "int8 (kv_dtype=%s weight_dtype=%s)",
                jax.default_backend(), kv_dtype, weight_dtype,
            )
            kv_dtype = "int8" if kv_dtype == "fp8" else kv_dtype
            weight_dtype = "int8" if weight_dtype == "fp8" else weight_dtype
        if decode_kernel not in ("xla", "fused"):
            raise ValueError(
                f"decode_kernel must be one of ('xla', 'fused'), got "
                f"{decode_kernel!r}"
            )
        validate_spec_config(spec_k, spec_draft, spec_ngram_n, weight_dtype)
        model_max = getattr(model, "max_seq_len", None)
        if max_len is None:
            if model_max is None:
                raise ValueError("max_len required for models without "
                                 "max_seq_len")
            max_len = int(model_max)
        if model_max is not None and max_len > model_max:
            raise ValueError(
                f"max_len {max_len} exceeds model.max_seq_len {model_max}"
            )
        from distributeddeeplearning_tpu.inference import decode_variant

        self.model = model
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.kv_layout = kv_layout
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        self.decode_kernel = decode_kernel
        self.allocator: Optional[BlockAllocator] = None
        self.prefix_cache = bool(prefix_cache) and kv_layout == "paged"
        quant_kw = dict(kv_dtype=kv_dtype) if kv_dtype != "bf16" else {}
        # The kernel knob changes the decode programs' LOWERING, not the
        # program set: decode_variant threads it into the model clone and
        # vit.Attention dispatches the vector-position decode paths to
        # the fused Pallas kernel (ops/pallas/paged_decode.py). The
        # draft model below stays XLA — its lookahead scratch decode is
        # not on the audited hot path.
        kernel_kw = (
            dict(decode_kernel=decode_kernel) if decode_kernel != "xla"
            else {}
        )
        if kv_layout == "paged":
            if block_size < 1:
                raise ValueError(f"block_size must be >= 1, got {block_size}")
            self.block_size = int(block_size)
            self.blocks_per_slot = -(-self.max_len // self.block_size)
            if num_blocks is None:
                # Dense-equivalent KV bytes by default (+ the trash
                # block): paging then wins by ADMITTING more, not by
                # shrinking the pool.
                num_blocks = self.num_slots * self.blocks_per_slot + 1
            self.num_blocks = int(num_blocks)
            self.allocator = BlockAllocator(self.num_blocks, self.block_size)
            self.decode_model = decode_variant(
                model, paged_blocks=self.num_blocks,
                paged_block_size=self.block_size, **quant_kw, **kernel_kw,
            )
        else:
            self.block_size = 0
            self.blocks_per_slot = 0
            self.num_blocks = 0
            self.decode_model = decode_variant(model, **quant_kw, **kernel_kw)
        # Speculative decode tier (docs/SERVING.md): spec_k draft
        # proposals per slot per tick, then ONE fixed-shape batched
        # verify runs the target over [num_slots, spec_k + 1] positions.
        # Draft sources: "int8" — greedy self-draft on the quantized
        # weights (own dense draft KV pool, quantized twin programs);
        # "ngram" — host-side prompt lookup (serving/spec.py), zero
        # device cost. Either way acceptance is data and the program
        # set stays closed (see programs_expected).
        self.spec_k = int(spec_k)
        self.spec_draft = spec_draft if self.spec_k else "off"
        self.spec_ngram_n = int(spec_ngram_n)
        # The draft decode model is ALWAYS the dense layout (its pool is
        # private lookahead scratch — block granularity buys nothing);
        # it follows the engine's kv_dtype so an int8 KV tier quantizes
        # the draft cache too.
        self._draft_model = (
            decode_variant(model, **quant_kw)
            if self.spec_draft == "int8" else None
        )
        bs = tuple(sorted(set(int(b) for b in (buckets or default_buckets(max_len)))))
        if not bs or bs[0] < 1:
            raise ValueError(f"invalid bucket ladder {bs}")
        if bs[-1] > max_len:
            raise ValueError(
                f"largest bucket {bs[-1]} exceeds max_len {max_len}"
            )
        self.buckets = bs
        if top_k_cap < 1:
            raise ValueError(f"top_k_cap must be >= 1, got {top_k_cap}")
        self.top_k_cap = int(top_k_cap)
        # Params live on device once; an already-placed (possibly
        # TP/FSDP-sharded) tree is kept as-is so GSPMD decodes in place.
        leaves = jax.tree.leaves(params)
        if leaves and all(isinstance(l, jax.Array) for l in leaves):
            self.params = params
        else:
            self.params = jax.device_put(params)
        # Inference weight quantization (SERVE_WEIGHT_DTYPE=int8|fp8): a
        # one-shot tree pass — matmul kernels + the tied embedding
        # become int8/fp8 + per-channel f32 scales; the decode programs
        # dequantize on use, so what each step STREAMS is the quantized
        # bytes (ops/quant.py).
        if weight_dtype != "bf16":
            self.params = jax.jit(
                lambda p: quantlib.quantize_params(p, dtype=weight_dtype)
            )(self.params)
        # Self-speculative draft weights: the PR-8 int8 tier of the SAME
        # model — one-shot quantized at build (any quantized
        # weight_dtype is rejected above for this source, so self.params
        # is the native tree). The draft programs dequantize on use
        # (_spec_draft_fn), so draft steps stream the int8 + scale bytes.
        self._draft_params = None
        if self.spec_draft == "int8":
            self._draft_params = jax.jit(quantlib.quantize_params)(
                self.params
            )

        # Cache pool template: shape-only trace of the decode model's
        # init at [num_slots, max_len] (no parameter initializers run).
        from distributeddeeplearning_tpu.inference import decode_cache_shapes

        tmpl = decode_cache_shapes(
            self.decode_model, self.num_slots, self.max_len
        )
        from flax import traverse_util
        from flax.core import unfreeze

        self._flatten = traverse_util.flatten_dict
        self._unflatten = traverse_util.unflatten_dict
        self._unfreeze = unfreeze
        self._template = self._flatten(unfreeze(tmpl))
        for path, leaf in self._template.items():
            if path[-1] not in _INDEX_NAMES and leaf.ndim < 2:
                raise ValueError(f"unexpected cache leaf {path}: {leaf}")
        # Draft cache template (int8 self-draft): a second dense pool at
        # the same [num_slots, max_len] geometry, written by the draft
        # programs only.
        self._draft_template = (
            self._flatten(unfreeze(decode_cache_shapes(
                self._draft_model, self.num_slots, self.max_len
            )))
            if self._draft_model is not None else None
        )

        # Host-side slot state (the scheduler-visible mirror of the
        # device pool; positions are re-fed every step, so the device
        # copies are never authoritative).
        s = self.num_slots
        self._active = np.zeros(s, bool)
        self._tokens = np.zeros(s, np.int32)
        self._positions = np.zeros(s, np.int32)
        self._temps = np.zeros(s, np.float32)
        self._top_ks = np.zeros(s, np.int32)
        self._top_ps = np.zeros(s, np.float32)
        self._eos = np.full(s, -1, np.int32)
        self._ladders: List[Optional[np.ndarray]] = [None] * s
        self._cursor = np.zeros(s, np.int64)
        # Speculative bookkeeping: the committed token BEFORE the next
        # input (the draft catch-up pair), the per-slot commit budget
        # (spec_step clamps multi-token commits to it), and — when a
        # drafter needs it — the slot's emitted history (prompt +
        # committed tokens).
        self._prev_tokens = np.zeros(s, np.int32)
        self._max_new = np.zeros(s, np.int32)
        self._history: List[Optional[List[int]]] = [None] * s
        self._drafter = (
            NgramDrafter(self.spec_ngram_n)
            if self.spec_draft == "ngram" else None
        )
        # Paged bookkeeping: per-slot block table (unused entries point
        # at the trash block 0) and the owned block-id lists.
        self._tables = (
            np.zeros((s, self.blocks_per_slot), np.int32)
            if kv_layout == "paged" else None
        )
        self._slot_blocks: List[List[int]] = [[] for _ in range(s)]
        # Introspection for the prefix-sharing oracle: what the most
        # recent prefill actually did (bucket, start, shared blocks).
        self.last_prefill: Optional[Dict[str, Any]] = None

        self._pool = None
        self._decode_exec = None
        self._prefill_exec: Dict[int, Any] = {}
        self._draft_pool = None
        self._spec_verify_exec = None
        self._spec_draft_exec = None
        self._spec_draft_prefill_exec: Dict[int, Any] = {}
        self.compile_count = 0
        self.compile_sec = 0.0
        self.decode_steps = 0
        # Prefill-program executions (the disagg bench's
        # prefill-once-per-fleet oracle: a directory adoption must add
        # exactly zero here across the whole fleet).
        self.prefill_execs = 0
        self._warmed = False
        # Brownout ladder hook (serving/scheduler.py): True routes
        # ticks through the plain decode program (already compiled —
        # the program set is unchanged); draft state keeps tracking the
        # committed stream so resuming speculation stays correct (the
        # int8 draft's KV falls behind and proposals degrade until the
        # slot turns over, but the verify commits target tokens either
        # way — a throughput knob, never a correctness one).
        self.spec_suspended = False
        # Running speculative tallies (serve_bench's accept-rate
        # percentiles; the serve.spec_* gauges/counters mirror them).
        self.spec_stats: Dict[str, Any] = {
            "verify_ticks": 0, "tokens_accepted": 0, "tokens_rejected": 0,
            "tokens_committed": 0, "draft_s": 0.0, "verify_s": 0.0,
            "accept_rates": [],
        }

    # -- cache plumbing ----------------------------------------------------

    def _zero_cache(self, batch: int, template=None):
        return self._unflatten({
            path: jnp.zeros(
                ((batch,) + leaf.shape[1:]) if leaf.ndim else (), leaf.dtype
            )
            for path, leaf in (template or self._template).items()
        })

    def _with_positions(self, cache, positions, tables=None):
        """Feed the per-step routing data: position vectors into every
        index leaf and (paged layout) the block table into every
        ``block_table`` leaf. The device copies of both are never
        authoritative — the host re-feeds them each call."""
        flat = self._flatten(self._unfreeze(cache))
        out = {}
        for path, leaf in flat.items():
            if path[-1] in _INDEX_NAMES:
                out[path] = positions
            elif tables is not None and path[-1] == _TABLE_NAME:
                out[path] = tables
            else:
                out[path] = leaf
        return self._unflatten(out)

    # -- traced programs ---------------------------------------------------

    def _live_params(self, params):
        """Dequant-on-use (``weight_dtype="int8"``/``"fp8"``): inside
        the traced program the quantized tree is the *streamed* operand;
        the f32 view XLA rebuilds here is a fused temporary, so per-step
        param traffic is the quantized + scale bytes."""
        if self.weight_dtype == "bf16":
            return params
        from distributeddeeplearning_tpu.ops import quant as quantlib

        return quantlib.dequantize_params(params)

    def _decode_fn(
        self, params, cache, tokens, positions, step_keys, temps, top_ks,
        top_ps, eos,
    ):
        params = self._live_params(params)
        cache = self._with_positions(cache, positions)
        logits, mutated = self.decode_model.apply(
            {"params": params, "cache": cache},
            tokens[:, None],
            train=False,
            mutable=["cache"],
        )
        nxt = sample_slots(
            logits[:, -1], step_keys, temps, top_ks, top_ps,
            top_k_cap=self.top_k_cap,
        )
        eos_hit = (nxt == eos) & (eos >= 0)
        return self._unfreeze(mutated["cache"]), nxt, eos_hit

    def _prefill_fn(
        self, params, pool, slot, tokens, prompt_len, key, temp, top_k,
        top_p, eos,
    ):
        params = self._live_params(params)
        # Fresh zero cache, scalar index 0: the prompt's forward IS the
        # lockstep decode path inference.generate runs — same K/V, same
        # logits at every prompt position.
        fresh = self._with_positions(
            self._zero_cache(1), jnp.zeros((), jnp.int32)
        )
        logits, mutated = self.decode_model.apply(
            {"params": params, "cache": fresh},
            tokens,
            train=False,
            mutable=["cache"],
        )
        last = lax.dynamic_index_in_dim(
            logits[0], prompt_len - 1, axis=0, keepdims=False
        )
        first = sample_slot(last, key, temp, top_k, top_p, self.top_k_cap)
        eos_hit = (first == eos) & (eos >= 0)
        mflat = self._flatten(self._unfreeze(mutated["cache"]))
        pflat = self._flatten(self._unfreeze(pool))
        out = {
            path: (
                lax.dynamic_update_slice(
                    leaf, mflat[path], (slot,) + (0,) * (leaf.ndim - 1)
                )
                if path[-1] not in _INDEX_NAMES
                else leaf
            )
            for path, leaf in pflat.items()
        }
        return self._unflatten(out), first, eos_hit

    def _decode_paged_fn(
        self, params, cache, tokens, positions, tables, step_keys, temps,
        top_ks, top_ps, eos,
    ):
        """Paged twin of :meth:`_decode_fn`: identical math per slot —
        only the KV residency differs (block pool + table routing)."""
        params = self._live_params(params)
        cache = self._with_positions(cache, positions, tables)
        logits, mutated = self.decode_model.apply(
            {"params": params, "cache": cache},
            tokens[:, None],
            train=False,
            mutable=["cache"],
        )
        nxt = sample_slots(
            logits[:, -1], step_keys, temps, top_ks, top_ps,
            top_k_cap=self.top_k_cap,
        )
        eos_hit = (nxt == eos) & (eos >= 0)
        return self._unfreeze(mutated["cache"]), nxt, eos_hit

    def _prefill_paged_fn(
        self, params, pool, table_row, start, tokens, last_idx, key, temp,
        top_k, top_p, eos,
    ):
        """Paged prefill: run the (suffix of the) prompt at absolute
        positions ``[start, start + bucket)`` THROUGH the pool — K/V
        writes scatter into the slot's table-mapped blocks, attention
        gathers any already-shared prefix blocks, and the first token is
        sampled at ``last_idx`` (the true last prompt position relative
        to ``start``). With ``start == 0`` this is a plain full-prompt
        prefill; with a prefix-cache hit it computes ONLY the divergent
        suffix — the shared blocks are never recomputed or rewritten
        (writes begin at the block-aligned ``start``). One program per
        bucket either way: start/table/last_idx are data, so the program
        set stays closed at ``len(buckets) + 1``."""
        params = self._live_params(params)
        cache = self._with_positions(pool, start, table_row)
        logits, mutated = self.decode_model.apply(
            {"params": params, "cache": cache},
            tokens,
            train=False,
            mutable=["cache"],
        )
        last = lax.dynamic_index_in_dim(
            logits[0], last_idx, axis=0, keepdims=False
        )
        first = sample_slot(last, key, temp, top_k, top_p, self.top_k_cap)
        eos_hit = (first == eos) & (eos >= 0)
        mflat = self._flatten(self._unfreeze(mutated["cache"]))
        pflat = self._flatten(self._unfreeze(pool))
        # Only the shared block pools were meaningfully mutated; the
        # [1]-batch table/index leaves are re-fed by the host anyway, so
        # the pool passes its own [num_slots]-shaped copies through.
        out = {
            path: (mflat[path] if path[-1] in _PAGED_POOL_NAMES else leaf)
            for path, leaf in pflat.items()
        }
        return self._unflatten(out), first, eos_hit

    # -- traced programs: speculative tier ---------------------------------

    def _spec_verify_core(self, params, cache, tokens, step_keys, temps,
                          top_ks, top_ps):
        """Shared tail of both verify layouts: one [S, K+1] forward of
        the target (multi-token decode view — per-row positions, writes
        land at [pos, pos+K], the position mask makes each query attend
        exactly its own prefix), then the rejection-sampling acceptance
        (serving/sampling.spec_verify_slots). Rejected-tail K/V writes
        land beyond the committed cursor and are overwritten by the next
        tick's writes before any query can attend them — the same
        trash-tail argument the bucketed prefill already relies on."""
        logits, mutated = self.decode_model.apply(
            {"params": params, "cache": cache},
            tokens,
            train=False,
            mutable=["cache"],
        )
        committed, accepted = spec_verify_slots(
            logits, tokens[:, 1:], step_keys, temps, top_ks, top_ps,
            top_k_cap=self.top_k_cap,
        )
        return self._unfreeze(mutated["cache"]), committed, accepted

    def _spec_verify_fn(self, params, pool, tokens, positions, step_keys,
                        temps, top_ks, top_ps):
        params = self._live_params(params)
        cache = self._with_positions(pool, positions)
        return self._spec_verify_core(
            params, cache, tokens, step_keys, temps, top_ks, top_ps
        )

    def _spec_verify_paged_fn(self, params, pool, tokens, positions,
                              tables, step_keys, temps, top_ks, top_ps):
        """Paged twin: identical math, K/V routed through the block
        tables (out-of-range lookahead writes land in the trash block;
        admission reserves ``spec_k`` extra positions so in-range ones
        stay inside the slot's own blocks — ``blocks_needed``)."""
        params = self._live_params(params)
        cache = self._with_positions(pool, positions, tables)
        return self._spec_verify_core(
            params, cache, tokens, step_keys, temps, top_ks, top_ps
        )

    def _spec_draft_fn(self, draft_params, dpool, catchup, positions):
        """The int8 self-draft phase as ONE program: a [S, 2] catch-up
        forward (re-feeds the previous committed token and the next
        input — after an all-accepted tick the draft cache is exactly
        one position behind, and the 2-wide window closes that gap;
        otherwise the first write is an idempotent re-write), whose last
        logits propose draft 1, then a lax.scan of K-1 greedy
        single-token steps. One dispatch per tick regardless of K. The
        dequantize runs ONCE per tick, hoisted outside the scan — K
        back-to-back draft forwards amortize one f32 materialization
        (decode_audit charges the draft steps at the dequantized bytes
        plus the resident int8 copy; re-dequantizing per scan step
        measured ~K× slower on the CPU tier for no byte win)."""
        from distributeddeeplearning_tpu.ops import quant as quantlib

        params = quantlib.dequantize_params(draft_params)
        cache = self._with_positions(dpool, positions)
        logits, mutated = self._draft_model.apply(
            {"params": params, "cache": cache},
            catchup,
            train=False,
            mutable=["cache"],
        )
        d1 = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        cache = self._unfreeze(mutated["cache"])
        if self.spec_k == 1:
            return cache, d1[:, None]

        def body(carry, _):
            cache, tok = carry
            # Position counters advance on-device inside the scan (the
            # cache's index leaves ride the carry); the host only feeds
            # the start positions. `params` is the hoisted once-per-tick
            # dequantized view from above.
            logits, mutated = self._draft_model.apply(
                {"params": params, "cache": cache},
                tok[:, None],
                train=False,
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (self._unfreeze(mutated["cache"]), nxt), nxt

        (cache, _), rest = lax.scan(
            body, (cache, d1), None, length=self.spec_k - 1
        )
        drafts = jnp.concatenate(
            [d1[:, None], jnp.moveaxis(rest, 0, 1)], axis=1
        )
        return cache, drafts

    def _spec_draft_prefill_fn(self, draft_params, dpool, slot, tokens):
        """Draft-pool prefill (int8 source): the full prompt through the
        quantized weights into the slot's draft rows — the draft's
        attention needs its OWN K/V of the prefix (int8-weight K/V
        differ from the target's). Always the full prompt, even when
        the target prefill rode a prefix-cache hit."""
        from distributeddeeplearning_tpu.ops import quant as quantlib

        params = quantlib.dequantize_params(draft_params)
        fresh = self._with_positions(
            self._zero_cache(1, self._draft_template),
            jnp.zeros((), jnp.int32),
        )
        _, mutated = self._draft_model.apply(
            {"params": params, "cache": fresh},
            tokens,
            train=False,
            mutable=["cache"],
        )
        mflat = self._flatten(self._unfreeze(mutated["cache"]))
        pflat = self._flatten(self._unfreeze(dpool))
        out = {
            path: (
                lax.dynamic_update_slice(
                    leaf, mflat[path], (slot,) + (0,) * (leaf.ndim - 1)
                )
                if path[-1] not in _INDEX_NAMES
                else leaf
            )
            for path, leaf in pflat.items()
        }
        return self._unflatten(out)

    # -- compilation -------------------------------------------------------

    @property
    def spec_enabled(self) -> bool:
        return self.spec_k > 0

    @property
    def programs_expected(self) -> int:
        """The closed program set's static size: decode + one prefill
        per bucket, plus — speculative tier — the batched verify and,
        for the int8 self-draft, the draft phase + one draft prefill
        per bucket. Enlarged but CLOSED: ``compile_count`` equals this
        for the engine's whole lifetime after :meth:`warmup`. A
        pool-typed engine (disaggregated serving) owns only its phase's
        programs: ``prefill`` → one per bucket, ``decode`` → one."""
        if self.pool_role == "prefill":
            return len(self.buckets)
        if self.pool_role == "decode":
            return 1
        n = len(self.buckets) + 1
        if self.spec_enabled:
            n += 1  # the [S, spec_k+1] verify
            if self.spec_draft == "int8":
                n += 1 + len(self.buckets)  # draft phase + draft prefills
        return n

    def _ensure_pools(self) -> None:
        """Build the KV pool(s) the program set closes over (idempotent).

        Canonical pool layout: index leaves are [num_slots] vectors (the
        decode step's per-slot positions) so every program — prefill
        passes them through, decode rewrites them — sees one stable
        signature; everything else keeps its template shape (dense K/V
        rows batched over slots; in the paged layout the block pools are
        batch-independent shared tensors and the block table is
        [num_slots, blocks_per_slot] routing data). Each leaf gets its
        OWN buffer: the pool is donated, and donating one aliased buffer
        through several leaves is an XLA error."""

        def zero_pool(template):
            return jax.device_put(self._unflatten({
                path: jnp.zeros(
                    (self.num_slots,) if path[-1] in _INDEX_NAMES
                    else leaf.shape,
                    jnp.int32 if path[-1] in _INDEX_NAMES else leaf.dtype,
                )
                for path, leaf in template.items()
            }))

        if self._pool is None:
            self._pool = zero_pool(self._template)
        if (
            self.spec_enabled
            and self.spec_draft == "int8"
            and self._draft_pool is None
        ):
            self._draft_pool = zero_pool(self._draft_template)

    def program_specs(self) -> List[ProgramSpec]:
        """The closed program set as data: one :class:`ProgramSpec` per
        member, each carrying the traced fn, donation, example args and
        the executable slot it installs into. :meth:`warmup` compiles
        exactly this list; the ddlint HLO audit lowers exactly this list
        — a program can't exist in one view and not the other."""
        self._ensure_pools()
        s, k = self.num_slots, self.spec_k
        paged = self.kv_layout == "paged"
        specs: List[ProgramSpec] = []

        def slot_attr(attr):
            return (
                lambda: getattr(self, attr),
                lambda ex: setattr(self, attr, ex),
            )

        def slot_dict(d, key):
            return (
                lambda: d.get(key),
                lambda ex: d.__setitem__(key, ex),
            )

        if paged:
            decode_args = (
                self.params, self._pool,
                np.zeros(s, np.int32), np.zeros(s, np.int32),
                np.zeros((s, self.blocks_per_slot), np.int32),
                np.zeros((s, 2), np.uint32),
                np.zeros(s, np.float32), np.zeros(s, np.int32),
                np.zeros(s, np.float32),
                np.full(s, -1, np.int32),
            )
        else:
            decode_args = (
                self.params, self._pool,
                np.zeros(s, np.int32), np.zeros(s, np.int32),
                np.zeros((s, 2), np.uint32),
                np.zeros(s, np.float32),
                np.zeros(s, np.int32), np.zeros(s, np.float32),
                np.full(s, -1, np.int32),
            )
        if self.pool_role != "prefill":
            specs.append(ProgramSpec(
                "decode",
                self._decode_paged_fn if paged else self._decode_fn,
                (1,), decode_args,
                {"what": "serve_decode", "slots": s},
                *slot_attr("_decode_exec"),
            ))
        if self.pool_role == "decode":
            return specs
        for bucket in self.buckets:
            if paged:
                prefill_args = (
                    self.params, self._pool,
                    np.zeros((1, self.blocks_per_slot), np.int32),
                    np.zeros(1, np.int32),
                    np.zeros((1, bucket), np.int32),
                    np.int32(0), np.zeros(2, np.uint32),
                    np.float32(0), np.int32(0), np.float32(0),
                    np.int32(-1),
                )
            else:
                prefill_args = (
                    self.params, self._pool,
                    np.int32(0), np.zeros((1, bucket), np.int32),
                    np.int32(1), np.zeros(2, np.uint32),
                    np.float32(0), np.int32(0), np.float32(0),
                    np.int32(-1),
                )
            specs.append(ProgramSpec(
                f"prefill_b{bucket}",
                self._prefill_paged_fn if paged else self._prefill_fn,
                (1,), prefill_args,
                {"what": f"serve_prefill_b{bucket}"},
                *slot_dict(self._prefill_exec, bucket),
            ))
        if self.spec_enabled:
            verify_args = [
                self.params, self._pool,
                np.zeros((s, k + 1), np.int32), np.zeros(s, np.int32),
            ]
            if paged:
                verify_args.append(
                    np.zeros((s, self.blocks_per_slot), np.int32)
                )
            verify_args += [
                np.zeros((s, k + 1, 2), np.uint32),
                np.zeros(s, np.float32), np.zeros(s, np.int32),
                np.zeros(s, np.float32),
            ]
            specs.append(ProgramSpec(
                "spec_verify",
                self._spec_verify_paged_fn if paged else self._spec_verify_fn,
                (1,), tuple(verify_args),
                {"what": "serve_spec_verify", "k": k},
                *slot_attr("_spec_verify_exec"),
            ))
            if self.spec_draft == "int8":
                specs.append(ProgramSpec(
                    "spec_draft",
                    self._spec_draft_fn,
                    (1,),
                    (
                        self._draft_params, self._draft_pool,
                        np.zeros((s, 2), np.int32), np.zeros(s, np.int32),
                    ),
                    {"what": "serve_spec_draft", "k": k},
                    *slot_attr("_spec_draft_exec"),
                ))
                for bucket in self.buckets:
                    specs.append(ProgramSpec(
                        f"spec_draft_prefill_b{bucket}",
                        self._spec_draft_prefill_fn,
                        (1,),
                        (
                            self._draft_params, self._draft_pool,
                            np.int32(0), np.zeros((1, bucket), np.int32),
                        ),
                        {"what": f"serve_spec_draft_prefill_b{bucket}"},
                        *slot_dict(self._spec_draft_prefill_exec, bucket),
                    ))
        return specs

    def warmup(self) -> Dict[str, float]:
        """AOT-compile the decode step and every bucket's prefill
        (idempotent) — plus, with speculation on, the verify and draft
        programs. After this the engine's program set is closed:
        ``compile_count == programs_expected`` for its whole lifetime."""
        log = get_logger()
        t_all = time.perf_counter()
        for ps in self.program_specs():
            if ps.installed:
                continue
            with obs.span("compile", **ps.span):
                t0 = time.perf_counter()
                ps.install(
                    jax.jit(ps.fn, donate_argnums=ps.donate_argnums)
                    .lower(*ps.example_args)
                    .compile()
                )
                self.compile_sec += time.perf_counter() - t0
            self.compile_count += 1
        self._warmed = True
        if self.kv_layout == "paged":
            self._emit_pool_gauges()
        acct = self.byte_accounting()
        obs.gauge(
            "serve.kv_bytes_per_token", float(acct["kv_bytes_per_token"])
        )
        obs.gauge("serve.param_bytes", float(acct["param_bytes"]))
        # Which decode lowering this engine compiled (0 = xla stitched,
        # 1 = fused Pallas kernel); the string rides as a label.
        obs.gauge(
            "serve.decode_kernel",
            1.0 if self.decode_kernel == "fused" else 0.0,
            kernel=self.decode_kernel,
        )
        info = {
            "compile_sec": self.compile_sec,
            "programs": float(self.compile_count),
        }
        log.info(
            "serve warmup: %d programs (decode + %d prefill buckets %s%s) "
            "in %.2fs, slots=%d cache_len=%d",
            self.compile_count, len(self.buckets), list(self.buckets),
            (f" + spec k={self.spec_k} draft={self.spec_draft}"
             if self.spec_enabled else ""),
            time.perf_counter() - t_all, self.num_slots, self.max_len,
        )
        obs.gauge("serve.programs", float(self.compile_count))
        return info

    # -- slot lifecycle ----------------------------------------------------

    def _emit_pool_gauges(self) -> None:
        a = self.allocator
        obs.gauge("serve.block_pool_total", float(a.capacity))
        obs.gauge("serve.block_pool_free", float(a.free_count))
        obs.gauge("serve.prefix_hits", float(a.stats["prefix_hit_blocks"]))

    def pool_stats(self) -> Optional[Dict[str, int]]:
        """Block-pool gauges (None on the dense layout)."""
        return None if self.allocator is None else self.allocator.snapshot()

    def byte_accounting(self) -> Dict[str, float]:
        """Dtype-aware byte ledger (the ``serve.kv_bytes_per_token`` /
        ``serve.param_bytes`` gauges, serve_bench's quant compare):
        KV-pool bytes per cached token position — int8 payload PLUS f32
        scales when ``kv_dtype="int8"``, never just the payload — and
        the resident param bytes a decode step streams (a quantized
        tree counts its int8 + scale leaves)."""
        kv = 0
        for path, leaf in self._template.items():
            if path[-1] in _INDEX_NAMES or path[-1] == _TABLE_NAME:
                continue
            kv += (
                int(np.prod(leaf.shape, dtype=np.int64))
                * np.dtype(leaf.dtype).itemsize
            )
        positions = (
            self.num_blocks * self.block_size if self.kv_layout == "paged"
            else self.num_slots * self.max_len
        )
        param_bytes = sum(
            leaf.size * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(self.params)
        )
        out = {
            "kv_pool_bytes": float(kv),
            "kv_bytes_per_token": kv / max(positions, 1),
            "param_bytes": float(param_bytes),
        }
        # Speculative tier (int8 self-draft): the draft's resident bytes
        # are itemized, never hidden — a second dense KV pool plus the
        # quantized weight tree (decode_audit --spec-k charges both).
        if self.spec_draft == "int8":
            dkv = sum(
                int(np.prod(leaf.shape, dtype=np.int64))
                * np.dtype(leaf.dtype).itemsize
                for path, leaf in self._draft_template.items()
                if path[-1] not in _INDEX_NAMES
            )
            out["draft_kv_pool_bytes"] = float(dkv)
            out["draft_param_bytes"] = float(sum(
                leaf.size * np.dtype(leaf.dtype).itemsize
                for leaf in jax.tree.leaves(self._draft_params)
            ))
        return out

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Physical blocks a request writes: positions 0 ..
        prompt_len + max_new_tokens - 2 (the final sampled token is
        never fed back, so its K/V is never written). The speculative
        tier reserves ``spec_k`` positions MORE: a verify writes K
        lookahead candidates past the committed cursor, and reserving
        them keeps those transient writes inside the slot's own blocks
        instead of thrashing the trash block."""
        return self.allocator.blocks_for_tokens(
            prompt_len + max_new_tokens - 1 + self.spec_k
        )

    def can_admit(self, spec: "ReqSpec") -> bool:
        """Admission gate beyond slot availability: on the paged layout
        a request needs its (prefix-discounted) block count free. The
        scheduler checks this before committing a queue pop — block
        exhaustion is backpressure, not an error."""
        if self.allocator is None:
            return True
        prompt = np.asarray(spec.prompt, np.int32).reshape(-1)
        t = prompt.shape[0]
        hit = (
            self.allocator.peek_prefix(prompt, t - 1)
            if self.prefix_cache else 0
        )
        hit = self._prefix_fit(t, hit)
        need = self.blocks_needed(t, spec.max_new_tokens) - hit
        return self.allocator.free_count >= max(need, 0)

    def _prefix_fit(self, t: int, n_blocks: int) -> int:
        """Largest usable cached-prefix block count for a ``t``-token
        prompt. A prefix hit shifts the suffix program's bucket window
        to ``[start, start + bucket)``; rows past ``max_len`` have no
        position embedding — the padded tail gathers NaN fill, the NaN
        K/V lands in the trash block, and the zero-masked-weight ×
        NaN value product poisons every slot's attention output.
        Recomputing a few cached positions is correct; a NaN is never
        recoverable."""
        start = n_blocks * self.block_size
        while n_blocks and start + self.bucket_for(t - start) > self.max_len:
            n_blocks -= 1
            start -= self.block_size
        return n_blocks

    @property
    def free_slots(self) -> List[int]:
        return [i for i in range(self.num_slots) if not self._active[i]]

    @property
    def active_slots(self) -> List[int]:
        return [i for i in range(self.num_slots) if self._active[i]]

    @property
    def occupancy(self) -> float:
        return float(self._active.sum()) / self.num_slots

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    def validate_spec(self, spec: ReqSpec) -> int:
        """Full admission validation (shape limits + the sort-free
        sampling cap) — called by ``Server.submit`` so a malformed
        request fails the *submitting* caller, never the serving loop.
        Returns the effective top_k (``top_k >= vocab`` maps to 0 =
        filter off, the reference's clamp — same draw)."""
        spec.validate(self.max_len, self.buckets[-1])
        if self.spec_enabled:
            t = int(np.asarray(spec.prompt).shape[-1])
            if t + spec.max_new_tokens + self.spec_k > self.max_len:
                # dynamic_update_slice clamps out-of-range starts, so a
                # verify window spilling past max_len would CORRUPT
                # earlier rows — the dense analogue of the paged
                # lookahead reservation.
                raise ValueError(
                    f"prompt {t} + max_new_tokens {spec.max_new_tokens} "
                    f"+ spec_k {self.spec_k} lookahead exceeds the "
                    f"engine cache length {self.max_len}; shorten the "
                    "request or build the engine with max_len + spec_k"
                )
        if self.allocator is not None:
            t = int(np.asarray(spec.prompt).shape[-1])
            worst = self.blocks_needed(t, spec.max_new_tokens)
            if worst > self.allocator.capacity:
                raise ValueError(
                    f"request needs {worst} KV blocks but the pool holds "
                    f"{self.allocator.capacity}; raise SERVE_NUM_BLOCKS / "
                    "SlotEngine(num_blocks=...)"
                )
        tk = int(spec.top_k or 0)
        vocab = getattr(self.model, "vocab_size", None)
        if tk and vocab is not None and tk >= int(vocab):
            tk = 0
        if tk > self.top_k_cap and spec.top_p is None:
            # Without nucleus sampling the request runs the sort-free
            # path, whose static lax.top_k window is the cap.
            raise ValueError(
                f"top_k {tk} exceeds the engine's sort-free cap "
                f"{self.top_k_cap}; raise SlotEngine(top_k_cap=...) / "
                "SERVE_TOP_K_CAP"
            )
        return tk

    def prefill(self, slot: int, spec: ReqSpec) -> Tuple[int, bool]:
        """Admit ``spec`` into ``slot``: run the bucketed prefill, seat
        the request's sampling state, and return (first token, eos hit).
        The slot is occupied afterwards even on an immediate eos — the
        caller decides to :meth:`release`."""
        if self._active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        if self.pool_role == "decode":
            raise RuntimeError(
                "a decode-pool engine has no prefill programs; requests "
                "reach it only through import_slot (handoff/migration)"
            )
        tk = self.validate_spec(spec)
        if not self._warmed:
            self.warmup()
        prompt = np.asarray(spec.prompt, np.int32).reshape(-1)
        t = prompt.shape[0]
        sampled = spec.temperature > 0.0
        # Speculative ticks consume one key per VERIFY POSITION (cursor
        # .. cursor+K), so the ladder carries spec_k lookahead rows past
        # max_new_tokens. The partitionable-threefry split is
        # prefix-stable in n (serving/keys.py), so rows 0..max_new-1
        # are unchanged — spec off/on cannot re-key the non-spec path.
        ladder = (
            keylib.request_key_ladder(
                spec.key_data(), spec.max_new_tokens + self.spec_k
            )
            if sampled
            else None
        )
        key0 = ladder[0] if sampled else np.zeros(2, np.uint32)
        temp = np.float32(spec.temperature if sampled else 0.0)
        top_k = np.int32(tk)
        top_p = np.float32(spec.top_p or 0.0)
        eos = np.int32(-1 if spec.eos_token is None else spec.eos_token)
        if self.allocator is not None:
            first, eos_hit = self._prefill_paged(
                slot, spec, prompt, key0, temp, top_k, top_p, eos
            )
        else:
            bucket = self.bucket_for(t)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :t] = prompt
            self._pool, first, eos_hit = self._prefill_exec[bucket](
                self.params, self._pool, np.int32(slot), padded,
                np.int32(t), np.asarray(key0, np.uint32), temp, top_k,
                top_p, eos,
            )
            self.prefill_execs += 1
            self.last_prefill = {
                "slot": slot, "bucket": bucket, "start": 0,
                "shared_blocks": 0,
            }
        self._active[slot] = True
        self._tokens[slot] = int(first)
        self._positions[slot] = t
        self._temps[slot] = temp
        self._top_ks[slot] = top_k
        self._top_ps[slot] = top_p
        self._eos[slot] = eos
        self._ladders[slot] = ladder
        self._cursor[slot] = 1
        if self.spec_enabled:
            self._max_new[slot] = spec.max_new_tokens
            self._prev_tokens[slot] = int(prompt[-1])
            self._history[slot] = [int(x) for x in prompt] + [int(first)]
            if self.spec_draft == "int8":
                bucket = self.bucket_for(t)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :t] = prompt
                self._draft_pool = self._spec_draft_prefill_exec[bucket](
                    self._draft_params, self._draft_pool, np.int32(slot),
                    padded,
                )
        return int(first), bool(eos_hit)

    def _prefill_paged(
        self, slot, spec, prompt, key0, temp, top_k, top_p, eos
    ) -> Tuple[Any, Any]:
        """Paged admission: match the prompt's block-aligned prefix
        against the prefix cache, allocate the remaining blocks
        (all-or-nothing; :class:`BlockPoolExhausted` propagates as
        backpressure), and prefill ONLY the divergent suffix through the
        slot's block table. The match is capped at ``prompt_len - 1``
        tokens so at least the last prompt position is always computed —
        the first token's logits come from this program."""
        a = self.allocator
        t = prompt.shape[0]
        shared: List[int] = (
            a.match_prefix(prompt, t - 1) if self.prefix_cache else []
        )
        keep = self._prefix_fit(t, len(shared))
        if keep < len(shared):
            a.release_match(shared[keep:])
            shared = shared[:keep]
        start = len(shared) * self.block_size
        suffix = prompt[start:]
        suffix_len = t - start
        bucket = self.bucket_for(suffix_len)
        need_new = self.blocks_needed(t, spec.max_new_tokens) - len(shared)
        try:
            fresh = a.alloc(max(need_new, 0))
        except BlockPoolExhausted:
            a.release_match(shared)
            raise
        blocks = shared + fresh
        table_row = np.zeros((1, self.blocks_per_slot), np.int32)
        table_row[0, :len(blocks)] = blocks
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :suffix_len] = suffix
        self._pool, first, eos_hit = self._prefill_exec[bucket](
            self.params, self._pool, table_row,
            np.asarray([start], np.int32), padded,
            np.int32(suffix_len - 1), np.asarray(key0, np.uint32), temp,
            top_k, top_p, eos,
        )
        self.prefill_execs += 1
        if self.prefix_cache:
            # The full prompt blocks this request owns are now written
            # and immutable (decode writes start at prompt_len) — make
            # them discoverable. Already-shared blocks are skipped.
            a.register_prefix(prompt, blocks)
        self._tables[slot] = table_row[0]
        self._slot_blocks[slot] = blocks
        self.last_prefill = {
            "slot": slot, "bucket": bucket, "start": start,
            "shared_blocks": len(shared), "blocks": list(blocks),
        }
        if len(shared):
            obs.counter("serve.prefix_hit_blocks", len(shared))
        self._emit_pool_gauges()
        return first, eos_hit

    def decode_step(self) -> List[Tuple[int, int, bool]]:
        """One batched decode tick: every occupied slot emits its next
        token. Returns ``[(slot, token, eos_hit), ...]`` for occupied
        slots (empty when the pool is idle)."""
        slots = self.active_slots
        if not slots:
            return []
        step_keys = np.zeros((self.num_slots, 2), np.uint32)
        for i in slots:
            ladder = self._ladders[i]
            if ladder is not None:
                step_keys[i] = ladder[min(self._cursor[i], len(ladder) - 1)]
        if self.allocator is not None:
            self._pool, nxt, eos_hit = self._decode_exec(
                self.params, self._pool, self._tokens, self._positions,
                self._tables, step_keys, self._temps, self._top_ks,
                self._top_ps, self._eos,
            )
        else:
            self._pool, nxt, eos_hit = self._decode_exec(
                self.params, self._pool, self._tokens, self._positions,
                step_keys, self._temps, self._top_ks, self._top_ps,
                self._eos,
            )
        nxt = np.array(nxt)
        eos_hit = np.array(eos_hit)
        self.decode_steps += 1
        out = []
        for i in slots:
            if self.spec_k:
                # A spec engine stepping plainly (brownout spec_off):
                # keep the drafter's view of the committed stream
                # current so resuming speculation proposes from real
                # history.
                self._prev_tokens[i] = int(self._tokens[i])
                if self._history[i] is not None:
                    self._history[i].append(int(nxt[i]))
            self._tokens[i] = nxt[i]
            self._positions[i] += 1
            self._cursor[i] += 1
            out.append((i, int(nxt[i]), bool(eos_hit[i])))
        return out

    def spec_step(self) -> List[Tuple[int, List[int], bool]]:
        """One speculative tick: draft ``spec_k`` proposals per slot,
        ONE batched verify of the target over ``[num_slots, spec_k+1]``
        positions, commit per-slot ``1 .. spec_k+1`` tokens. Returns
        ``[(slot, committed_tokens, eos_hit), ...]`` for occupied slots
        — each list already clamped to the request's remaining token
        budget and truncated at eos (the scheduler releases on either).
        """
        if not self.spec_enabled:
            raise RuntimeError("spec_step requires SlotEngine(spec_k > 0)")
        slots = self.active_slots
        if not slots:
            return []
        s, k = self.num_slots, self.spec_k
        tokens = np.zeros((s, k + 1), np.int32)
        tokens[:, 0] = self._tokens
        t0 = time.perf_counter()
        if self.spec_draft == "int8":
            catchup = np.stack(
                [self._prev_tokens, self._tokens], axis=1
            ).astype(np.int32)
            self._draft_pool, drafts = self._spec_draft_exec(
                self._draft_params, self._draft_pool, catchup,
                np.maximum(self._positions - 1, 0).astype(np.int32),
            )
            drafts = np.asarray(drafts)
        else:
            drafts = propose_all(self._drafter, self._history, slots, s, k)
        draft_s = time.perf_counter() - t0
        tokens[:, 1:] = drafts
        step_keys = np.zeros((s, k + 1, 2), np.uint32)
        for i in slots:
            ladder = self._ladders[i]
            if ladder is not None:
                c = int(self._cursor[i])
                step_keys[i] = ladder[c:c + k + 1]
        t1 = time.perf_counter()
        if self.allocator is not None:
            self._pool, committed, accepted = self._spec_verify_exec(
                self.params, self._pool, tokens, self._positions,
                self._tables, step_keys, self._temps, self._top_ks,
                self._top_ps,
            )
        else:
            self._pool, committed, accepted = self._spec_verify_exec(
                self.params, self._pool, tokens, self._positions,
                step_keys, self._temps, self._top_ks, self._top_ps,
            )
        committed = np.asarray(committed)
        accepted = np.asarray(accepted)
        verify_s = time.perf_counter() - t1
        self.decode_steps += 1
        out: List[Tuple[int, List[int], bool]] = []
        acc_total = rej_total = commit_total = 0
        for i in slots:
            a = int(accepted[i])
            acc_total += a
            rej_total += k - a
            remaining = int(self._max_new[i]) - int(self._cursor[i])
            n = min(a + 1, remaining)
            toks = [int(x) for x in committed[i, :n]]
            eos = int(self._eos[i])
            eos_hit = False
            if eos >= 0:
                for j, tok in enumerate(toks):
                    if tok == eos:
                        toks = toks[: j + 1]
                        eos_hit = True
                        break
            n = len(toks)
            commit_total += n
            self._prev_tokens[i] = (
                toks[-2] if n >= 2 else int(self._tokens[i])
            )
            self._tokens[i] = toks[-1]
            self._positions[i] += n
            self._cursor[i] += n
            if self._history[i] is not None:
                self._history[i].extend(toks)
            out.append((i, toks, eos_hit))
        st = self.spec_stats
        st["verify_ticks"] += 1
        st["tokens_accepted"] += acc_total
        st["tokens_rejected"] += rej_total
        st["tokens_committed"] += commit_total
        st["draft_s"] += draft_s
        st["verify_s"] += verify_s
        rate = acc_total / max(len(slots) * k, 1)
        if len(st["accept_rates"]) < 100_000:
            st["accept_rates"].append(rate)
        obs.gauge("serve.spec_accept_rate", rate)
        obs.gauge("serve.spec_draft_ms", draft_s * 1e3)
        obs.gauge("serve.spec_verify_ms", verify_s * 1e3)
        obs.counter("serve.spec_tokens_accepted", acc_total)
        obs.counter("serve.spec_tokens_rejected", rej_total)
        return out

    def force_token(self, slot: int, token: int) -> None:
        """Teacher-forcing hook for quality oracles (serve_bench's
        quantization compare, ``tests/test_serving_quant.py``): override
        the token the NEXT decode step feeds this slot. The step then
        answers "given this exact context, what would the engine emit?"
        — per-step agreement without free-running divergence cascades.
        Positions/keys/sampling state are untouched; never use while a
        request's own stream matters."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not occupied")
        self._tokens[slot] = np.int32(token)

    def release(self, slot: int) -> None:
        """Free a slot (eviction). Pure host bookkeeping — the stale
        cache rows are unreachable (per-slot position masks) and fully
        overwritten by the next prefill into this slot. On the paged
        layout the slot's blocks are dereferenced (prefix-cached blocks
        stay resident and evictable; private ones return to the free
        list) and its table row re-points at the trash block."""
        self._active[slot] = False
        self._ladders[slot] = None
        self._tokens[slot] = 0
        self._positions[slot] = 0
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 0.0
        self._eos[slot] = -1
        self._cursor[slot] = 0
        self._prev_tokens[slot] = 0
        self._max_new[slot] = 0
        self._history[slot] = None
        if self.allocator is not None:
            for bid in self._slot_blocks[slot]:
                self.allocator.decref(bid)
            self._slot_blocks[slot] = []
            self._tables[slot] = 0
            self._emit_pool_gauges()

    # -- slot state transfer (disaggregation / migration) ------------------

    def export_blocks(self, block_ids) -> Dict[Tuple[str, ...], np.ndarray]:
        """Host-stage the KV content of ``block_ids``: leaf path ->
        ``[len(block_ids), block_size, ...]`` numpy rows gathered from
        every paged pool leaf. Pure read — no program runs, the pool is
        untouched. The caller must hold the blocks resident (referenced
        or pinned) for the read to be meaningful."""
        if self.allocator is None:
            raise RuntimeError("export_blocks requires kv_layout='paged'")
        idx = np.asarray(list(block_ids), np.int64)
        flat = self._flatten(self._unfreeze(self._pool))
        out: Dict[Tuple[str, ...], np.ndarray] = {}
        for path, leaf in flat.items():
            if path[-1] in _PAGED_POOL_NAMES:
                out[path] = np.asarray(leaf)[idx].copy()
        return out

    def _import_block_payload(self, block_ids, payload) -> None:
        """Write host-staged block content into ``block_ids`` of the
        local pool. Host copy + ``jax.device_put`` — no program runs,
        nothing compiles, so the closed program set is untouched (the
        CPU tier's stand-in for a device-to-device block DMA)."""
        idx = np.asarray(list(block_ids), np.int64)
        flat = self._flatten(self._unfreeze(self._pool))
        out = {}
        for path, leaf in flat.items():
            if path[-1] in _PAGED_POOL_NAMES and path in payload:
                host = np.array(leaf)
                host[idx] = payload[path]
                out[path] = jax.device_put(host)
            else:
                out[path] = leaf
        self._pool = self._unflatten(out)

    def export_slot(self, slot: int) -> Dict[str, Any]:
        """Snapshot everything slot ``slot`` needs to continue decoding
        bitwise-identically on ANOTHER engine: the sampling state, the
        key-ladder cursor, and the host-staged content of every written
        KV block. The slot itself is untouched — the caller releases it
        (handoff) or keeps it (directory publish reads). The importing
        engine replays nothing: decode resumes at the exact cursor with
        the exact ladder row, so the continuation is the same stream the
        exporting engine would have produced."""
        if self.allocator is None:
            raise RuntimeError("export_slot requires kv_layout='paged'")
        if self.spec_enabled:
            raise RuntimeError(
                "export_slot is incompatible with spec_k > 0 (the draft "
                "pool's lookahead state does not travel)"
            )
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not occupied")
        written = int(self._positions[slot])
        blocks = list(self._slot_blocks[slot])
        nwritten = self.allocator.blocks_for_tokens(written)
        ladder = self._ladders[slot]
        return {
            "block_size": self.block_size,
            "n_blocks": len(blocks),
            "blocks": blocks,
            "written": written,
            "token": int(self._tokens[slot]),
            "temp": float(self._temps[slot]),
            "top_k": int(self._top_ks[slot]),
            "top_p": float(self._top_ps[slot]),
            "eos": int(self._eos[slot]),
            "ladder": None if ladder is None else np.array(ladder),
            "cursor": int(self._cursor[slot]),
            "payload": self.export_blocks(blocks[:nwritten]),
        }

    def can_import(self, state: Dict[str, Any]) -> bool:
        """Room for an imported slot right now? (a free slot AND the
        state's block count allocatable)."""
        if self.allocator is None:
            return False
        return (
            bool(self.free_slots)
            and self.allocator.free_count >= int(state["n_blocks"])
        )

    def import_slot(
        self, slot: int, state: Dict[str, Any],
        prompt: Optional[np.ndarray] = None,
    ) -> None:
        """Seat an exported slot state (:meth:`export_slot`, or a
        directory adoption's synthetic state): allocate fresh blocks,
        write the staged KV content, and restore the sampling state so
        the next :meth:`decode_step` continues the stream bitwise.
        ``prompt`` (when given, with the prefix cache on) registers the
        full prompt blocks locally so later requests prefix-hit here."""
        if self.allocator is None:
            raise RuntimeError("import_slot requires kv_layout='paged'")
        if self.spec_enabled:
            raise RuntimeError("import_slot is incompatible with spec_k > 0")
        if self._active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        if int(state["block_size"]) != self.block_size:
            raise ValueError(
                f"block_size mismatch: exported {state['block_size']}, "
                f"local {self.block_size}"
            )
        if not self._warmed:
            self.warmup()
        n = int(state["n_blocks"])
        blocks = self.allocator.alloc(n)  # BlockPoolExhausted -> caller
        nwritten = self.allocator.blocks_for_tokens(int(state["written"]))
        self._import_block_payload(blocks[:nwritten], state["payload"])
        self._tables[slot] = 0
        self._tables[slot, :n] = blocks
        self._slot_blocks[slot] = blocks
        self._active[slot] = True
        self._tokens[slot] = np.int32(state["token"])
        self._positions[slot] = np.int32(state["written"])
        self._temps[slot] = np.float32(state["temp"])
        self._top_ks[slot] = np.int32(state["top_k"])
        self._top_ps[slot] = np.float32(state["top_p"])
        self._eos[slot] = np.int32(state["eos"])
        ladder = state.get("ladder")
        self._ladders[slot] = None if ladder is None else np.array(ladder)
        self._cursor[slot] = int(state["cursor"])
        if prompt is not None and self.prefix_cache:
            self.allocator.register_prefix(
                np.asarray(prompt, np.int32).reshape(-1), blocks
            )
        self._emit_pool_gauges()

    def adopt_prefix_blocks(self, tokens, payload) -> int:
        """Seed the LOCAL prefix cache with directory-fetched full-block
        content (a chain prefetch): allocate, write, register, then
        decref into the evictable cache. The next prefill of a prompt
        starting with ``tokens``' leading blocks hits locally and
        computes only its suffix. Returns the number of blocks seeded
        (0 when already cached or no room — prefill then computes them,
        which is always correct, just not free)."""
        if self.allocator is None or not self.prefix_cache:
            return 0
        a = self.allocator
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n = min(
            (len(next(iter(payload.values()))) if payload else 0),
            len(toks) // self.block_size,
        )
        if n < 1:
            return 0
        if a.peek_prefix(toks, n * self.block_size) >= n:
            return 0
        if a.free_count < n:
            return 0
        blocks = a.alloc(n)
        self._import_block_payload(
            blocks, {p: arr[:n] for p, arr in payload.items()}
        )
        a.register_prefix(toks[: n * self.block_size], blocks)
        for bid in blocks:
            a.decref(bid)
        self._emit_pool_gauges()
        return n
