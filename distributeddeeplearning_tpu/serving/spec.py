"""Host-side speculative-decode helpers — draft proposals as pure data.

The speculative tier (``docs/SERVING.md``) keeps the engine's two
load-bearing invariants intact: static shapes and a closed program set.
Everything that *varies* per tick — which tokens are proposed, how many
get accepted — is data, and everything on the host side lives here:

* :class:`NgramDrafter` — the model-free **prompt-lookup** draft source
  (``SERVE_SPEC_DRAFT=ngram``): propose the ``k`` tokens that followed
  the most recent earlier occurrence of the slot's current suffix in
  its own emitted prefix (prompt + committed tokens). Zero device cost;
  useful on self-referential traffic (code, extraction, templated
  text). Proposals are **deterministic** — a point-mass draft
  distribution — which is what makes the engine's acceptance rule (the
  prompt-lookup special case of rejection sampling) exact; see
  ``serving.sampling.spec_verify_slots``.
* :func:`validate_spec_config` — one place for the SERVE_SPEC_* rules,
  shared by ``SlotEngine`` and ``ServeConfig`` error paths.

The int8 self-speculative draft source is device-side (quantized twin
programs in ``serving.engine``); it has no host component beyond the
catch-up token bookkeeping the engine already keeps.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

DRAFT_SOURCES = ("int8", "ngram")


def validate_spec_config(
    spec_k: int, spec_draft: str, spec_ngram_n: int, weight_dtype: str,
) -> None:
    """The SERVE_SPEC_* contract (docs/ORCHESTRATION.md). Raises
    ``ValueError`` with a pointer to the offending knob."""
    if spec_k < 0:
        raise ValueError(f"spec_k must be >= 0, got {spec_k}")
    if spec_k == 0:
        return  # speculation off: the other knobs are inert
    if spec_draft not in DRAFT_SOURCES:
        raise ValueError(
            f"spec_draft must be one of {DRAFT_SOURCES} when spec_k > 0, "
            f"got {spec_draft!r} (SERVE_SPEC_DRAFT)"
        )
    if spec_draft == "int8" and weight_dtype not in ("", "bf16"):
        # The self-speculative draft IS the int8 quantization of the
        # target; a quantized target (int8 OR fp8) leaves no cheaper
        # tier to draft from (and would double-quantize the
        # already-quantized tree).
        raise ValueError(
            "spec_draft='int8' requires the native (bf16) weight tier — "
            f"with weight_dtype={weight_dtype!r} the target already runs "
            "quantized weights; use spec_draft='ngram' or drop "
            "SERVE_WEIGHT_DTYPE"
        )
    if spec_draft == "ngram" and spec_ngram_n < 2:
        raise ValueError(
            f"spec_ngram_n must be >= 2 (match on >= 1 trailing token), "
            f"got {spec_ngram_n}"
        )


class NgramDrafter:
    """Prompt-lookup draft proposals from a slot's own token history.

    For match lengths ``n-1`` down to 1 (longest first), find the most
    recent earlier occurrence of the history's trailing ``m`` tokens and
    propose the ``k`` tokens that followed it. No match → propose token
    0 ``k`` times: a deliberately *rejectable* proposal — the verify
    step then degenerates to one committed token per tick, exactly the
    non-speculative rate (correctness never depends on draft quality).
    """

    def __init__(self, n: int = 3) -> None:
        if n < 2:
            raise ValueError(f"ngram n must be >= 2, got {n}")
        self.n = int(n)
        self.stats = {"proposals": 0, "lookups_hit": 0, "lookups_miss": 0}

    def propose(self, history: Sequence[int], k: int) -> np.ndarray:
        """``k`` draft tokens ([k] int32) continuing ``history``."""
        h = np.asarray(history, np.int64).reshape(-1)
        out = np.zeros(k, np.int32)
        self.stats["proposals"] += 1
        if h.shape[0] < 2:
            self.stats["lookups_miss"] += 1
            return out
        for m in range(min(self.n - 1, h.shape[0] - 1), 0, -1):
            suffix = h[-m:]
            # Most recent earlier occurrence: window ends strictly
            # before the final position so the match has a continuation.
            windows = np.lib.stride_tricks.sliding_window_view(h[:-1], m)
            hits = np.flatnonzero((windows == suffix).all(axis=1))
            if hits.size == 0:
                continue
            start = int(hits[-1]) + m  # first continuation token index
            cont = h[start:start + k]
            out[: cont.shape[0]] = cont.astype(np.int32)
            # Short continuations (match near the end) cycle the found
            # pattern rather than padding with zeros — still data, still
            # merely a proposal.
            if 0 < cont.shape[0] < k:
                reps = -(-k // cont.shape[0])
                out[:] = np.tile(cont, reps)[:k].astype(np.int32)
            self.stats["lookups_hit"] += 1
            return out
        self.stats["lookups_miss"] += 1
        return out


def propose_all(
    drafter: NgramDrafter,
    histories: List,
    slots: Sequence[int],
    num_slots: int,
    k: int,
) -> np.ndarray:
    """[num_slots, k] proposal matrix for one tick (inactive rows 0)."""
    out = np.zeros((num_slots, k), np.int32)
    for i in slots:
        if histories[i]:
            out[i] = drafter.propose(histories[i], k)
    return out
