"""Continuous-batching serving tier (request-level batching).

The training stack scales batches across chips; this package scales
*requests* across time on one set of chips. ``inference.generate`` is a
one-request-at-a-time sampler — a server built on it would idle the
accelerator between requests and recompile per prompt length. Here:

* :class:`~.engine.SlotEngine` — one fixed-shape compiled decode step
  over ``[num_slots]`` KV-cache slots; requests join/leave at step
  granularity (iteration-level scheduling, Orca OSDI'22), each slot a
  static row of the pooled cache (slot-granular KV management in the
  spirit of vLLM's PagedAttention, block size = one request). No
  recompile ever on admission/eviction.
* Bucketed prefill — prompt lengths padded up a small bucket ladder;
  one compiled prefill program per bucket, writing straight into the
  assigned slot's cache rows.
* :class:`~.scheduler.Server` — bounded admission queue with
  backpressure, FIFO + prefill/decode interleave, per-request
  deadline/cancel, graceful drain, instrumentation through the obs bus,
  and a pluggable :class:`~.scheduler.AdmissionPolicy`:
  :class:`~.scheduler.AdaptiveAdmissionPolicy` closes the telemetry
  loop — it reads the live plane's rollup snapshot and derates
  admission while a latency SLO burns (docs/SERVING.md).

* Speculative decode tier (``spec_k > 0``, docs/SERVING.md) — a draft
  source proposes K tokens per slot (int8 self-draft or host-side
  n-gram prompt lookup, :mod:`~.spec`), one fixed-shape batched verify
  runs the target over ``[num_slots, K+1]`` positions, and the
  rejection-sampling rule (:func:`~.sampling.spec_verify_slots`)
  commits 1..K+1 tokens per slot per tick. Greedy streams stay
  token-for-token identical to non-speculative decode; sampled streams
  keep the target's distribution exactly.

* Chaos plane + self-healing fleet (:mod:`~.chaos`,
  docs/ROBUSTNESS.md serving failure model) — seeded tick-indexed
  fleet fault verbs (``SERVE_CHAOS_PLAN``:
  crash/hang/slow/corrupt/flap) drive the router's monitor sweep:
  heartbeat hard-faults, straggler quarantine with splice-verified
  hedging, corrupt detect-and-heal, a crash-loop circuit breaker, and
  the :class:`~.scheduler.BrownoutLadder` degradation stages.

Per-request output is **bitwise-identical** to sequential
``inference.generate`` (greedy and seeded sampling) whatever the
co-scheduling — ``tests/test_serving.py`` is the oracle
(``tests/test_serving_spec.py`` for the speculative tier,
``tests/test_serving_chaos.py`` for the chaos plane).
"""

from distributeddeeplearning_tpu.serving.blocks import (  # noqa: F401
    BlockAllocator,
    BlockPoolExhausted,
    PrefixDirectory,
)
from distributeddeeplearning_tpu.serving.chaos import (  # noqa: F401
    ChaosCrash,
    ChaosInjector,
    FleetFault,
    SpliceMismatch,
    parse_chaos_plan,
    storm_plan,
)
from distributeddeeplearning_tpu.serving.engine import (  # noqa: F401
    ReqSpec,
    SlotEngine,
)
from distributeddeeplearning_tpu.serving.keys import (  # noqa: F401
    request_key_ladder,
    split_key,
)
from distributeddeeplearning_tpu.serving.sampling import (  # noqa: F401
    sample_slot,
    sample_slots,
    spec_verify_slots,
)
from distributeddeeplearning_tpu.serving.spec import (  # noqa: F401
    NgramDrafter,
)
from distributeddeeplearning_tpu.serving.scheduler import (  # noqa: F401
    AdaptiveAdmissionPolicy,
    AdmissionPolicy,
    BrownoutLadder,
    BrownoutStage,
    QueueFull,
    Request,
    RequestHandle,
    Server,
    ServeConfig,
    generate_with_engine,
    parse_brownout_stages,
)
from distributeddeeplearning_tpu.serving.fleet import (  # noqa: F401
    ControllerConfig,
    FleetConfig,
    FleetController,
    FleetHandle,
    Replica,
    Router,
    build_fleet,
)
