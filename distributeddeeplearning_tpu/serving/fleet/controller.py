"""Autoscaler: turn the router's pressure signal into replica count.

The :class:`~distributeddeeplearning_tpu.serving.fleet.router.Router`
publishes ``serve.fleet_pressure`` every tick (demanded capacity /
ready capacity, block saturation on paged fleets). This controller
consumes that signal *between* ticks and moves the fleet toward the
demand: sustained pressure above the high watermark adds a replica
(factory-built, warmed in its own thread — serving never pauses);
sustained pressure below the low watermark drains the least-loaded
replica (its queued requests re-route immediately, running streams
finish) and removes it once drained.

**Pools** (disaggregated fleets, docs/SERVING.md): a homogeneous fleet
scales as one pool against the fleet-wide pressure — the legacy path,
unchanged. ``ControllerConfig.pools`` lifts the same watermark
hysteresis to per-pool control: each named pool (``prefill`` /
``decode``) carries its own :class:`PoolWatermarks`, reads its own
``Router.pool_pressure`` signal, and counts its own hot/cold streaks,
so a prefill burst grows the prefill pool without touching decode.
The arbiter's lease accounting is pool-blind on purpose: every
scale-up still leases ``replica:<rid>`` and every completed drain
releases it — colocation sees devices, not pool labels.

Signal sources, in priority order:

* an injected ``reader`` callable (tests);
* the live plane's ``rollup.json`` (``snapshot_path`` — the gauge as
  every other consumer sees it, dashboard included);
* the router's own ``last_pressure`` (in-process default), or
  ``Router.pool_pressure`` when pools are configured.

Hysteresis is tick-counted, not wall-timed, so the controller is
deterministic under synthetic pressure traces (oracle-tested) and the
caller owns the cadence (``FleetController.tick`` from the serving
loop, a supervisor thread, or a cron).
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, List, Optional

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.serving.fleet.replica import Replica
from distributeddeeplearning_tpu.serving.fleet.router import Router


@dataclasses.dataclass
class PoolWatermarks:
    """One pool's scaling envelope: replica bounds + watermark
    hysteresis. The flat (single-pool) config is the degenerate case
    of one of these applied to the whole fleet."""

    min_replicas: int = 1
    max_replicas: int = 4
    high_pressure: float = 1.0   # demand >= ready capacity
    low_pressure: float = 0.35
    up_ticks: int = 3            # consecutive hot ticks before scale-up
    down_ticks: int = 8          # consecutive cold ticks before drain

    def validate(self, pool: str = "") -> None:
        tag = f" (pool {pool!r})" if pool else ""
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min {self.min_replicas} <= max "
                f"{self.max_replicas}{tag}"
            )
        if self.low_pressure >= self.high_pressure:
            raise ValueError(
                f"low watermark {self.low_pressure} must be below high "
                f"{self.high_pressure}{tag}"
            )
        if self.up_ticks < 1 or self.down_ticks < 1:
            raise ValueError(f"up_ticks and down_ticks must be >= 1{tag}")


@dataclasses.dataclass
class ControllerConfig:
    """Watermarks + hysteresis for the autoscaler. ``pools`` (e.g.
    ``{"prefill": PoolWatermarks(...), "decode": PoolWatermarks(...)}``)
    switches to per-pool control; None keeps the flat single-pool
    policy on the fleet-wide pressure signal."""

    min_replicas: int = 1
    max_replicas: int = 4
    high_pressure: float = 1.0   # demand >= ready capacity
    low_pressure: float = 0.35
    up_ticks: int = 3            # consecutive hot ticks before scale-up
    down_ticks: int = 8          # consecutive cold ticks before drain
    # Crash-loop respect (docs/ROBUSTNESS.md serving failure model): a
    # breaker opening means a replica crash-looped through its whole
    # restart budget — blindly adding capacity right after would feed
    # the same failure. Scale-up is held for this many router ticks
    # after the most recent breaker opening (0 = never hold).
    breaker_block_ticks: int = 10
    # Hardware is NOT infinite: when scale-up is denied (breaker
    # cooldown or an arbiter lease refusal) the controller backs off
    # for this many router ticks instead of re-asking every tick.
    denied_backoff_ticks: int = 10
    pools: Optional[Dict[str, PoolWatermarks]] = None

    def validate(self) -> None:
        self.flat_watermarks().validate()
        for pool, wm in (self.pools or {}).items():
            wm.validate(pool)

    def flat_watermarks(self) -> PoolWatermarks:
        """The single-pool envelope the flat fields describe."""
        return PoolWatermarks(
            min_replicas=self.min_replicas,
            max_replicas=self.max_replicas,
            high_pressure=self.high_pressure,
            low_pressure=self.low_pressure,
            up_ticks=self.up_ticks,
            down_ticks=self.down_ticks,
        )


# The flat policy runs as "one unnamed pool spanning the fleet": pool
# None selects every replica and the legacy fleet-wide pressure signal.
_FLAT = None


class FleetController:
    """Add/drain replicas from the ``serve.fleet_pressure`` signal.

    ``factory(rid)`` — or ``factory(rid, pool)`` under per-pool
    watermarks — builds a NEW (unstarted) :class:`Replica`; the
    controller starts it through ``Router.add_replica``. ``tick()``
    returns the action taken (``"scale_up"`` / ``"drain"`` /
    ``"remove"`` / None) so callers and tests can assert the policy.
    """

    def __init__(
        self,
        router: Router,
        factory: Callable[..., Replica],
        config: Optional[ControllerConfig] = None,
        *,
        reader: Optional[Callable[[], Optional[float]]] = None,
        snapshot_path: Optional[str] = None,
        threaded_replicas: bool = True,
        arbiter=None,
    ) -> None:
        self.router = router
        self.factory = factory
        self.config = config or ControllerConfig()
        self.config.validate()
        self._reader = reader
        self.snapshot_path = snapshot_path
        self.threaded_replicas = threaded_replicas
        # Colocation (serving/arbiter.py): when an arbiter owns the
        # pool, every scale-up must hold a lease on freed devices —
        # the controller asks, it does not assume free hardware.
        self.arbiter = arbiter
        # Hot/cold streaks per pool (the flat policy is pool None).
        self._hot: Dict[Optional[str], int] = {}
        self._cold: Dict[Optional[str], int] = {}
        self._denied_until: Optional[int] = None
        try:
            params = [
                p for p in
                inspect.signature(factory).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY,
                              p.POSITIONAL_OR_KEYWORD)
                or p.kind == p.VAR_POSITIONAL
            ]
            self._factory_takes_pool = (
                len(params) >= 2
                or any(p.kind == p.VAR_POSITIONAL for p in params)
            )
        except (TypeError, ValueError):
            self._factory_takes_pool = False
        self.actions: List[Dict[str, Any]] = []

    # -- signal ------------------------------------------------------------

    def read_pressure(self, pool: Optional[str] = _FLAT
                      ) -> Optional[float]:
        if self._reader is not None:
            try:
                return self._reader(pool) if pool is not None else (
                    self._reader()
                )
            except TypeError:
                return self._reader()
        if pool is not None:
            return float(self.router.pool_pressure(pool))
        if self.snapshot_path:
            from distributeddeeplearning_tpu.obs.rollup import read_snapshot

            snap = read_snapshot(self.snapshot_path)
            if snap:
                g = (snap.get("gauges") or {}).get("serve.fleet_pressure")
                if g and g.get("value") is not None:
                    return float(g["value"])
            return None
        return float(self.router.last_pressure)

    # -- policy ------------------------------------------------------------

    def _pool_replicas(self, pool: Optional[str]) -> List[Replica]:
        if pool is None:
            return list(self.router.replicas)
        return [r for r in self.router.replicas if r.pool == pool]

    def _ready_count(self, pool: Optional[str] = _FLAT) -> int:
        return sum(
            1 for r in self._pool_replicas(pool)
            if r.state in ("starting", "ready")
        )

    def tick(self) -> Optional[str]:
        """One control decision. Finalizes any replica that finished
        draining (remove), then applies the watermark hysteresis —
        flat, or once per configured pool (first action wins the
        tick)."""
        # Finalize drains the policy started earlier. A leased replica's
        # devices return to the arbiter only once the drain completed —
        # zero-drop: running streams finished, nothing was cut mid-air.
        for r in list(self.router.replicas):
            if r.state == "drained":
                self.router.remove_replica(r.rid)
                if self.arbiter is not None:
                    self.arbiter.release_lease(f"replica:{r.rid}")
                self._record("remove", r.rid)
                return "remove"
        # Training reclaim (priority order, docs/ROBUSTNESS.md): when
        # the arbiter wants its devices back, drain one leased replica
        # per tick regardless of the pressure hysteresis.
        if self.arbiter is not None and self.arbiter.reclaiming:
            for r in self.router.replicas:
                if r.state == "ready" and self.arbiter.has_lease(
                    f"replica:{r.rid}"
                ):
                    self.router.drain_replica(r.rid)
                    self._record("drain", r.rid, reason="reclaim")
                    obs.point(
                        "fleet.scale_down", replica=r.rid,
                        reason="reclaim",
                    )
                    return "drain"
        if self.config.pools:
            for pool, wm in sorted(self.config.pools.items()):
                action = self._pool_tick(pool, wm)
                if action is not None:
                    return action
            return None
        return self._pool_tick(_FLAT, self.config.flat_watermarks())

    def _pool_tick(self, pool: Optional[str], wm: PoolWatermarks
                   ) -> Optional[str]:
        """The watermark hysteresis for ONE pool (pool None = the whole
        fleet on the legacy fleet-wide signal)."""
        p = self.read_pressure(pool)
        if p is None:
            return None
        cfg = self.config
        if p >= wm.high_pressure:
            self._hot[pool] = self._hot.get(pool, 0) + 1
            self._cold[pool] = 0
        elif p <= wm.low_pressure:
            self._cold[pool] = self._cold.get(pool, 0) + 1
            self._hot[pool] = 0
        else:
            self._hot[pool] = self._cold[pool] = 0
        ready = self._ready_count(pool)
        if self._hot.get(pool, 0) >= wm.up_ticks and (
            ready < wm.max_replicas
        ):
            # Backing off after a denial: do not re-ask (and re-emit)
            # every tick — that is the spin this guard exists to stop.
            if (
                self._denied_until is not None
                and self.router._ticks < self._denied_until
            ):
                return None
            # Respect open breakers: right after a replica crash-looped
            # through its restart budget, hold scale-up for a cooldown
            # window instead of feeding the same failure more capacity.
            # (The Router's membership door separately refuses a
            # breaker-open rid forever.)
            last = self.router.last_breaker_tick
            if (
                cfg.breaker_block_ticks
                and last is not None
                and self.router._ticks - last < cfg.breaker_block_ticks
            ):
                self._deny("breaker", p, breaker_tick=last, pool=pool)
                return None
            rid = self.router.next_rid()
            # Colocated pool: the arbiter must lease the devices first
            # — hardware is whatever training has actually freed. The
            # lease key stays pool-blind: devices are devices.
            if self.arbiter is not None and not self.arbiter.request_lease(
                f"replica:{rid}"
            ):
                self._deny("lease", p, replica=rid, pool=pool)
                return None
            replica = (
                self.factory(rid, pool)
                if pool is not None and self._factory_takes_pool
                else self.factory(rid)
            )
            self.router.add_replica(
                replica, start=True, threaded=self.threaded_replicas,
            )
            self._hot[pool] = 0
            self._denied_until = None
            self._record("scale_up", rid, pressure=p, pool=pool)
            obs.point(
                "fleet.scale_up", replica=rid, pressure=round(p, 4),
                **({"pool": pool} if pool is not None else {}),
            )
            return "scale_up"
        if self._cold.get(pool, 0) >= wm.down_ticks and (
            ready > wm.min_replicas
        ):
            victim = self._pick_drain_victim(pool)
            if victim is not None:
                self.router.drain_replica(victim.rid)
                self._cold[pool] = 0
                self._record("drain", victim.rid, pressure=p, pool=pool)
                obs.point(
                    "fleet.scale_down", replica=victim.rid,
                    pressure=round(p, 4),
                    **({"pool": pool} if pool is not None else {}),
                )
                return "drain"
        return None

    def _pick_drain_victim(self, pool: Optional[str] = _FLAT
                           ) -> Optional[Replica]:
        """Least-loaded ready replica (fewest running + queued) of the
        pool: the cheapest drain — it finishes fastest and re-routes
        the least."""
        ready = [
            r for r in self._pool_replicas(pool) if r.state == "ready"
        ]
        if not ready:
            return None
        return min(
            ready,
            key=lambda r: (
                r.server.active_count + r.server.queued_count
                if r.server is not None else 0
            ),
        )

    def _deny(self, reason: str, pressure: float, *,
              pool: Optional[str] = _FLAT, **labels: Any) -> None:
        """Scale-up refused (breaker cooldown / arbiter lease): emit
        one ``fleet.scaleup_denied`` and enter a tick-counted backoff
        instead of re-asking every tick."""
        self._denied_until = (
            self.router._ticks + self.config.denied_backoff_ticks
        )
        if pool is not None:
            labels = {"pool": pool, **labels}
        self.actions.append({
            "action": "scaleup_denied", "reason": reason,
            "pressure": pressure, **labels,
        })
        obs.point(
            "fleet.scaleup_denied", reason=reason,
            pressure=round(pressure, 4), **labels,
        )

    def _record(self, action: str, rid: int, *,
                pool: Optional[str] = _FLAT, **extra: Any) -> None:
        if pool is not None:
            extra = {"pool": pool, **extra}
        self.actions.append({"action": action, "replica": rid, **extra})
