"""Autoscaler: turn the router's pressure signal into replica count.

The :class:`~distributeddeeplearning_tpu.serving.fleet.router.Router`
publishes ``serve.fleet_pressure`` every tick (demanded capacity /
ready capacity, block saturation on paged fleets). This controller
consumes that signal *between* ticks and moves the fleet toward the
demand: sustained pressure above the high watermark adds a replica
(factory-built, warmed in its own thread — serving never pauses);
sustained pressure below the low watermark drains the least-loaded
replica (its queued requests re-route immediately, running streams
finish) and removes it once drained.

Signal sources, in priority order:

* an injected ``reader`` callable (tests);
* the live plane's ``rollup.json`` (``snapshot_path`` — the gauge as
  every other consumer sees it, dashboard included);
* the router's own ``last_pressure`` (in-process default).

Hysteresis is tick-counted, not wall-timed, so the controller is
deterministic under synthetic pressure traces (oracle-tested) and the
caller owns the cadence (``FleetController.tick`` from the serving
loop, a supervisor thread, or a cron).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.serving.fleet.replica import Replica
from distributeddeeplearning_tpu.serving.fleet.router import Router


@dataclasses.dataclass
class ControllerConfig:
    """Watermarks + hysteresis for the autoscaler."""

    min_replicas: int = 1
    max_replicas: int = 4
    high_pressure: float = 1.0   # demand >= ready capacity
    low_pressure: float = 0.35
    up_ticks: int = 3            # consecutive hot ticks before scale-up
    down_ticks: int = 8          # consecutive cold ticks before drain
    # Crash-loop respect (docs/ROBUSTNESS.md serving failure model): a
    # breaker opening means a replica crash-looped through its whole
    # restart budget — blindly adding capacity right after would feed
    # the same failure. Scale-up is held for this many router ticks
    # after the most recent breaker opening (0 = never hold).
    breaker_block_ticks: int = 10
    # Hardware is NOT infinite: when scale-up is denied (breaker
    # cooldown or an arbiter lease refusal) the controller backs off
    # for this many router ticks instead of re-asking every tick.
    denied_backoff_ticks: int = 10

    def validate(self) -> None:
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min {self.min_replicas} <= max "
                f"{self.max_replicas}"
            )
        if self.low_pressure >= self.high_pressure:
            raise ValueError(
                f"low watermark {self.low_pressure} must be below high "
                f"{self.high_pressure}"
            )
        if self.up_ticks < 1 or self.down_ticks < 1:
            raise ValueError("up_ticks and down_ticks must be >= 1")


class FleetController:
    """Add/drain replicas from the ``serve.fleet_pressure`` signal.

    ``factory(rid)`` builds a NEW (unstarted) :class:`Replica`; the
    controller starts it through ``Router.add_replica``. ``tick()``
    returns the action taken (``"scale_up"`` / ``"drain"`` /
    ``"remove"`` / None) so callers and tests can assert the policy.
    """

    def __init__(
        self,
        router: Router,
        factory: Callable[[int], Replica],
        config: Optional[ControllerConfig] = None,
        *,
        reader: Optional[Callable[[], Optional[float]]] = None,
        snapshot_path: Optional[str] = None,
        threaded_replicas: bool = True,
        arbiter=None,
    ) -> None:
        self.router = router
        self.factory = factory
        self.config = config or ControllerConfig()
        self.config.validate()
        self._reader = reader
        self.snapshot_path = snapshot_path
        self.threaded_replicas = threaded_replicas
        # Colocation (serving/arbiter.py): when an arbiter owns the
        # pool, every scale-up must hold a lease on freed devices —
        # the controller asks, it does not assume free hardware.
        self.arbiter = arbiter
        self._hot = 0
        self._cold = 0
        self._denied_until: Optional[int] = None
        self.actions: List[Dict[str, Any]] = []

    # -- signal ------------------------------------------------------------

    def read_pressure(self) -> Optional[float]:
        if self._reader is not None:
            return self._reader()
        if self.snapshot_path:
            from distributeddeeplearning_tpu.obs.rollup import read_snapshot

            snap = read_snapshot(self.snapshot_path)
            if snap:
                g = (snap.get("gauges") or {}).get("serve.fleet_pressure")
                if g and g.get("value") is not None:
                    return float(g["value"])
            return None
        return float(self.router.last_pressure)

    # -- policy ------------------------------------------------------------

    def _ready_count(self) -> int:
        return sum(
            1 for r in self.router.replicas
            if r.state in ("starting", "ready")
        )

    def tick(self) -> Optional[str]:
        """One control decision. Finalizes any replica that finished
        draining (remove), then applies the watermark hysteresis."""
        # Finalize drains the policy started earlier. A leased replica's
        # devices return to the arbiter only once the drain completed —
        # zero-drop: running streams finished, nothing was cut mid-air.
        for r in list(self.router.replicas):
            if r.state == "drained":
                self.router.remove_replica(r.rid)
                if self.arbiter is not None:
                    self.arbiter.release_lease(f"replica:{r.rid}")
                self._record("remove", r.rid)
                return "remove"
        # Training reclaim (priority order, docs/ROBUSTNESS.md): when
        # the arbiter wants its devices back, drain one leased replica
        # per tick regardless of the pressure hysteresis.
        if self.arbiter is not None and self.arbiter.reclaiming:
            for r in self.router.replicas:
                if r.state == "ready" and self.arbiter.has_lease(
                    f"replica:{r.rid}"
                ):
                    self.router.drain_replica(r.rid)
                    self._record("drain", r.rid, reason="reclaim")
                    obs.point(
                        "fleet.scale_down", replica=r.rid,
                        reason="reclaim",
                    )
                    return "drain"
        p = self.read_pressure()
        if p is None:
            return None
        cfg = self.config
        if p >= cfg.high_pressure:
            self._hot += 1
            self._cold = 0
        elif p <= cfg.low_pressure:
            self._cold += 1
            self._hot = 0
        else:
            self._hot = self._cold = 0
        ready = self._ready_count()
        if self._hot >= cfg.up_ticks and ready < cfg.max_replicas:
            # Backing off after a denial: do not re-ask (and re-emit)
            # every tick — that is the spin this guard exists to stop.
            if (
                self._denied_until is not None
                and self.router._ticks < self._denied_until
            ):
                return None
            # Respect open breakers: right after a replica crash-looped
            # through its restart budget, hold scale-up for a cooldown
            # window instead of feeding the same failure more capacity.
            # (The Router's membership door separately refuses a
            # breaker-open rid forever.)
            last = self.router.last_breaker_tick
            if (
                cfg.breaker_block_ticks
                and last is not None
                and self.router._ticks - last < cfg.breaker_block_ticks
            ):
                self._deny("breaker", p, breaker_tick=last)
                return None
            rid = self.router.next_rid()
            # Colocated pool: the arbiter must lease the devices first
            # — hardware is whatever training has actually freed.
            if self.arbiter is not None and not self.arbiter.request_lease(
                f"replica:{rid}"
            ):
                self._deny("lease", p, replica=rid)
                return None
            self.router.add_replica(
                self.factory(rid), start=True,
                threaded=self.threaded_replicas,
            )
            self._hot = 0
            self._denied_until = None
            self._record("scale_up", rid, pressure=p)
            obs.point("fleet.scale_up", replica=rid, pressure=round(p, 4))
            return "scale_up"
        if self._cold >= cfg.down_ticks and ready > cfg.min_replicas:
            victim = self._pick_drain_victim()
            if victim is not None:
                self.router.drain_replica(victim.rid)
                self._cold = 0
                self._record("drain", victim.rid, pressure=p)
                obs.point(
                    "fleet.scale_down", replica=victim.rid,
                    pressure=round(p, 4),
                )
                return "drain"
        return None

    def _pick_drain_victim(self) -> Optional[Replica]:
        """Least-loaded ready replica (fewest running + queued): the
        cheapest drain — it finishes fastest and re-routes the least."""
        ready = [r for r in self.router.replicas if r.state == "ready"]
        if not ready:
            return None
        return min(
            ready,
            key=lambda r: (
                r.server.active_count + r.server.queued_count
                if r.server is not None else 0
            ),
        )

    def _deny(self, reason: str, pressure: float, **labels: Any) -> None:
        """Scale-up refused (breaker cooldown / arbiter lease): emit
        one ``fleet.scaleup_denied`` and enter a tick-counted backoff
        instead of re-asking every tick."""
        self._denied_until = (
            self.router._ticks + self.config.denied_backoff_ticks
        )
        self.actions.append({
            "action": "scaleup_denied", "reason": reason,
            "pressure": pressure, **labels,
        })
        obs.point(
            "fleet.scaleup_denied", reason=reason,
            pressure=round(pressure, 4), **labels,
        )

    def _record(self, action: str, rid: int, **extra: Any) -> None:
        self.actions.append({"action": action, "replica": rid, **extra})
