"""Multi-replica serving fleet: router, fairness, streaming, autoscale.

PRs 5–9 built a complete single-engine serving tier (SlotEngine
continuous batching, paged KV + prefix cache, int8 quantization,
speculative decode, adaptive admission) — one engine, one device. This
package is the next layer up (ROADMAP item 1): N warmed engines behind
one front door.

* :class:`~.replica.Replica` — one SlotEngine + Server on its own pump
  thread and its own event stream (``events-p0-s<k>.jsonl``), with a
  drain/fault lifecycle classified by the faults exit taxonomy.
* :class:`~.router.Router` — per-tenant deficit-weighted fair queueing,
  prefix-affinity/least-loaded placement, zero-drop drain and fault
  re-routing (splicing restarts on the per-request determinism
  contract), incremental token streaming, and the
  ``serve.fleet_pressure`` autoscale gauge.
* :class:`~.controller.FleetController` — consumes the pressure signal
  between ticks to add or drain replicas.

Certified by ``scripts/fleet_bench.py`` (``make fleet-bench``) and
``tests/test_serving_fleet.py``; architecture in docs/SERVING.md.
"""

from distributeddeeplearning_tpu.serving.fleet.controller import (  # noqa: F401
    ControllerConfig,
    FleetController,
    PoolWatermarks,
)
from distributeddeeplearning_tpu.serving.fleet.replica import (  # noqa: F401
    Replica,
)
from distributeddeeplearning_tpu.serving.fleet.router import (  # noqa: F401
    DEFAULT_TENANT,
    FleetConfig,
    FleetHandle,
    Router,
    build_fleet,
    parse_tenant_weights,
)
