"""One serving replica: a warmed SlotEngine + Server on its own pump.

A :class:`Replica` is the fleet's unit of capacity — one compiled slot
pool with its own scheduler, pumped by its own worker thread (or, in
tests and deterministic benches, pumped inline by the router). Two
properties make N of them composable inside one process:

* **Private event stream** — each replica binds its own
  :class:`~distributeddeeplearning_tpu.obs.bus.EventBus` (proc
  ``p<k>-s<rid>`` → ``events-p0-s0.jsonl``, ``events-p0-s1.jsonl``, …)
  around everything its pump runs, via the thread-local binding in
  ``obs/bus.py``. Every existing instrumentation site — scheduler tick
  spans, engine warmup compiles, pool gauges — lands in the replica's
  file untouched, and the tailer / rollup / report machinery renders
  per-replica views for free (``scripts/obs_watch.py``). With no
  ``obs_dir`` the replica stays on the process-global bus.
* **Lifecycle with an exit taxonomy** — ``new → starting → ready →
  draining → drained`` plus ``faulted``/``removed``. A pump that dies
  maps its exception onto the fault exit codes
  (:mod:`distributeddeeplearning_tpu.faults`): a
  ``NonFiniteLossError``-style deterministic failure is non-retryable
  (121 — rejoining would replay it), anything else classifies as a
  retryable crash (125), and :meth:`Replica.retryable` is exactly
  ``classify_exit(rc).retryable`` — the same table the restart
  supervisor uses. The router re-routes a faulted replica's work; a
  retryable replica may :meth:`rejoin` (rebuilding its engine — a
  faulted pool's device state is not trusted).

The health plane (docs/ROBUSTNESS.md serving failure model) rides the
pump: every iteration stamps ``heartbeat_t`` (a hung pump goes stale
and the router hard-faults it), every busy tick feeds the latency EWMA
the straggler detector compares against the fleet median, and the
chaos injector (``SERVE_CHAOS_PLAN``) is consulted at the top of every
tick so fault drills are tick-deterministic. ``stop()`` detaches an
unjoinable thread instead of leaking it silently
(``fleet.thread_leaked``), and a pump *generation* counter guarantees
a detached zombie that later wakes can never pump or drain a rebuilt
server.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.faults import (
    EXIT_HUNG,
    EXIT_NONFINITE,
    classify_exit,
)
from distributeddeeplearning_tpu.serving.engine import ReqSpec, SlotEngine
from distributeddeeplearning_tpu.serving.scheduler import (
    Request,
    RequestHandle,
    ServeConfig,
    Server,
)
from distributeddeeplearning_tpu.utils.logging import get_logger

#: Lifecycle states (docs/SERVING.md fleet section).
STATES = (
    "new", "starting", "ready", "draining", "drained", "faulted", "removed",
)


def _proc_tag(rid: int) -> str:
    """The replica's event-stream identity: the process's own proc tag
    (``DDL_PROCESS_ID`` + any supervisor ``OBS_PROC_SUFFIX`` restart
    suffix) with ``-s<rid>`` appended — ``events-p0-s1.jsonl``. The
    tailer treats it as just another part file; the rollup's per-proc
    view keys on it."""
    base = f"p{int(os.environ.get('DDL_PROCESS_ID', '0'))}"
    base += os.environ.get("OBS_PROC_SUFFIX", "")
    return f"{base}-s{rid}"


class Replica:
    """One SlotEngine + Server behind a private pump and event stream.

    ``model``/``params`` are shared host-side across replicas (the
    engine device-puts or reuses committed arrays); every replica
    compiles its own closed program set at :meth:`start` and keeps the
    zero-recompile invariant independently (``engine.compile_count ==
    engine.programs_expected`` for its lifetime).
    """

    def __init__(
        self,
        rid: int,
        model,
        params,
        config: Optional[ServeConfig] = None,
        *,
        max_len: Optional[int] = None,
        obs_dir: Optional[str] = None,
        run_id: Optional[str] = None,
        idle_sleep_s: float = 0.001,
        pool: str = "mixed",
    ) -> None:
        if pool not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"pool must be one of ('mixed', 'prefill', 'decode'), "
                f"got {pool!r}"
            )
        self.rid = int(rid)
        self.model = model
        self.params = params
        self.config = config or ServeConfig()
        # Disaggregated serving (docs/SERVING.md): a pool-typed replica
        # serves one phase. "prefill" runs prefills then exports each
        # slot (Server handoff mode); "decode" never takes submissions
        # — work arrives only through import_running. "mixed" is the
        # colocated default (every existing fleet unchanged).
        self.pool = pool
        self.max_len = max_len
        self.obs_dir = obs_dir
        self.run_id = run_id
        self.idle_sleep_s = float(idle_sleep_s)
        self.state = "new"
        self.threaded = True
        self.engine: Optional[SlotEngine] = None
        self.server: Optional[Server] = None
        self.bus: Optional[obs.EventBus] = None
        self.fault: Optional[BaseException] = None
        self.exit_code: Optional[int] = None
        self.dispatched = 0  # requests this replica was handed
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Set by Router.fail_replica: the pump must NOT gracefully
        # drain on stop — the router is taking the work elsewhere.
        self._abandon = threading.Event()
        # Health plane (Router._monitor_sweep, docs/ROBUSTNESS.md
        # serving failure model): the pump stamps heartbeat_t every
        # iteration it is alive (a hung pump goes stale), and every
        # busy scheduler tick feeds the latency EWMA the straggler
        # detector compares against the fleet median.
        self.heartbeat_t: Optional[float] = None
        self.tick_ewma: float = 0.0
        self.tick_samples: int = 0
        self.straggle_ticks = 0      # consecutive over-factor sightings
        self.quarantined = False     # drained of placements, on probation
        self.quarantine_until = 0    # router tick the probation ends at
        self.leaked_threads = 0      # unjoinable pumps detached by stop()
        # Chaos plane (serving/chaos.py): the router hands every
        # replica its injector; the pump consults it per tick.
        self.chaos = None
        # Quarantine hedge: the router pauses the pump at a tick
        # boundary before evicting running work (take_running is only
        # safe with the pump parked), then resumes it.
        self._pause = threading.Event()
        self._pause_ack = threading.Event()
        self._hang_until = 0.0  # inline pumps' silent-skip window
        # Pump generation: bumped by every start(). A detached zombie
        # thread (stop() join timeout) that later wakes compares its
        # captured generation and exits — it can never pump or drain a
        # rebuilt server, even after rejoin cleared _stop/_abandon.
        self._gen = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self, threaded: bool = True) -> "Replica":
        """Build + warm the engine and begin pumping. ``threaded=False``
        builds inline and leaves pumping to :meth:`pump_once` (the
        router's deterministic single-thread mode for tests)."""
        if self.state not in ("new", "drained", "faulted", "removed"):
            raise RuntimeError(f"replica {self.rid} is {self.state}")
        self.threaded = threaded
        self.state = "starting"
        self._gen += 1
        self._stop.clear()
        if self.bus is None and self.obs_dir:
            self.bus = obs.EventBus(
                directory=self.obs_dir,
                run_id=self.run_id or obs.get_bus().run_id,
                proc=_proc_tag(self.rid),
            )
        if threaded:
            self._thread = threading.Thread(
                target=self._worker, args=(self._gen,),
                name=f"replica-{self.rid}", daemon=True,
            )
            self._thread.start()
        else:
            with obs.bound_bus(self.bus):
                self._build()
            self.state = "ready"
        return self

    def _build(self) -> None:
        if self.engine is not None:
            return
        kw = dict(self.config.engine_kwargs())
        if self.max_len is not None:
            kw.setdefault("max_len", self.max_len)
        if self.pool != "mixed":
            kw["pool_role"] = self.pool
        engine = SlotEngine(self.model, self.params, **kw)
        engine.warmup()
        self.engine = engine
        self.server = Server(
            engine,
            queue_depth=self.config.queue_depth,
            prefills_per_step=self.config.prefills_per_step,
            default_deadline_ms=self.config.deadline_ms,
            admission_policy=self.config.build_admission_policy(),
            handoff=(self.pool == "prefill"),
        )
        obs.point("fleet.replica_ready", replica=self.rid, pool=self.pool)

    def _chaos_gate(self) -> bool:
        """Consult the chaos injector before a pump tick. Returns False
        when this tick must be skipped (hang: silent-but-alive, the
        heartbeat deliberately NOT stamped); raises :class:`ChaosCrash`
        for crash/flap; sleeps the slow verb's stall inline."""
        if self.chaos is None:
            return True
        action = self.chaos.pump_action(self.rid, time.monotonic())
        if action is None:
            return True
        if action["kind"] == "crash":
            from distributeddeeplearning_tpu.serving.chaos import ChaosCrash

            raise ChaosCrash(f"chaos crash (replica {self.rid})")
        if action["kind"] == "hang":
            if self.threaded:
                # A genuine wedge: the thread sleeps unjoinably — the
                # router's heartbeat monitor hard-faults it and stop()
                # detaches the leaked thread.
                time.sleep(action["secs"])
            else:
                # Inline pumps cannot block the router; silent skip —
                # heartbeat still goes stale, same detection path.
                self._hang_until = time.monotonic() + action["secs"]
            return False
        if action["kind"] == "slow":
            time.sleep(action["stall_s"])
        return True

    def record_tick(self, dur_s: float) -> None:
        """Feed one busy scheduler-tick latency into the straggler
        EWMA (alpha 0.3 — reacts within a few ticks, forgets within a
        probation window)."""
        self.tick_ewma = (
            dur_s if self.tick_samples == 0
            else 0.7 * self.tick_ewma + 0.3 * dur_s
        )
        self.tick_samples += 1

    def reset_latency(self) -> None:
        """Clear the EWMA (leaving quarantine / rejoining): the replica
        must re-offend with fresh samples to be quarantined again."""
        self.tick_ewma = 0.0
        self.tick_samples = 0
        self.straggle_ticks = 0

    def _worker(self, gen: int) -> None:
        obs.bind_bus(self.bus)
        try:
            self._build()
            if self.state == "starting":  # a drain may already be asked
                self.state = "ready"
            while not self._stop.is_set() and gen == self._gen:
                self.heartbeat_t = time.monotonic()
                if self._pause.is_set():
                    # Parked at a tick boundary for a quarantine hedge:
                    # alive (heartbeat flows) but not stepping.
                    self._pause_ack.set()
                    time.sleep(0.0005)
                    continue
                t0 = time.monotonic()
                if not self._chaos_gate():
                    continue
                busy = self.server.step()
                if busy:
                    # The tick latency includes any injected stall —
                    # the straggler detector sees what a client would.
                    self.record_tick(time.monotonic() - t0)
                if not busy:
                    if self.state == "draining":
                        break  # empty while draining: done
                    time.sleep(self.idle_sleep_s)
            # stop requested with work possibly remaining: finish it —
            # a stopping replica never drops admitted work (the router
            # reclaims *queued* requests before stopping a pump) —
            # unless the router declared this replica failed and is
            # re-routing everything it holds (_abandon), or this is a
            # detached zombie whose replica already restarted (gen).
            if not self._abandon.is_set() and gen == self._gen:
                self.server.drain()
                if self.state in ("draining", "ready", "starting"):
                    self.state = "drained"
                    obs.point("fleet.replica_drained", replica=self.rid)
        except BaseException as e:  # the pump is a thread main: classify
            if gen != self._gen:
                return  # detached zombie: the replica already restarted
            self.fault = e
            code = e.code if isinstance(e, SystemExit) and isinstance(
                getattr(e, "code", None), int
            ) else EXIT_HUNG  # generic crash: retryable class
            if type(e).__name__ == "NonFiniteLossError":
                code = EXIT_NONFINITE
            self.exit_code = int(code)
            self.state = "faulted"
            get_logger().error(
                "replica %d faulted (%s): %r", self.rid,
                classify_exit(self.exit_code).reason, e,
            )
            obs.point(
                "fleet.replica_fault", replica=self.rid, error=repr(e),
                exit_code=self.exit_code,
                retryable=classify_exit(self.exit_code).retryable,
            )
        finally:
            if self.bus is not None:
                self.bus.flush()
            obs.bind_bus(None)

    def pump_once(self) -> bool:
        """Inline pump (unthreaded replicas): one scheduler tick on the
        caller's thread, with this replica's event stream bound. A
        pump-side exception faults the replica exactly like the worker
        path (the router then re-routes its work)."""
        if self.server is None or self.state not in ("ready", "draining"):
            return False
        now = time.monotonic()
        if now < self._hang_until:
            return False  # chaos hang: silent-but-alive, heartbeat stale
        try:
            with obs.bound_bus(self.bus):
                t0 = time.monotonic()
                if not self._chaos_gate():
                    return False
                self.heartbeat_t = time.monotonic()
                busy = self.server.step()
                if busy:
                    # Tick latency includes any injected stall — the
                    # straggler detector sees what a client would.
                    self.record_tick(time.monotonic() - t0)
        except BaseException as e:
            self.fault = e
            self.exit_code = EXIT_HUNG
            self.state = "faulted"
            obs.point(
                "fleet.replica_fault", replica=self.rid, error=repr(e),
                exit_code=self.exit_code, retryable=True,
            )
            return False
        if not busy and self.state == "draining":
            self.state = "drained"
            obs.point("fleet.replica_drained", replica=self.rid)
        return busy

    def begin_drain(self) -> None:
        """Stop taking placements; finish what is running. The router
        reclaims this replica's *queued* requests — see
        ``Router.drain_replica`` — so only in-flight slots remain, and
        the pump parks the state at ``drained`` once they finish."""
        if self.state in ("ready", "starting"):
            self.state = "draining"
            obs.point("fleet.replica_drain", replica=self.rid)

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop the pump thread (drains admitted work first). A pump
        that does not join within ``timeout`` — a hung thread blocked
        inside a wedged step or a chaos ``hang`` — is **detached**, not
        leaked silently: the thread object is dropped (``_abandon`` is
        already set on the fault path, so if it ever wakes it exits
        without draining, and a faulted rejoin rebuilds engine+server
        so the zombie can only touch the abandoned objects), and a
        ``fleet.thread_leaked`` point records it for drills to assert
        on."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                self.leaked_threads += 1
                self._abandon.set()  # a waking zombie must not drain
                obs.point(
                    "fleet.thread_leaked", replica=self.rid,
                    state=self.state,
                )
            self._thread = None

    def pause(self, timeout: float = 2.0) -> bool:
        """Park the pump at a tick boundary (quarantine hedge: the
        router must not evict running slots while a step is in flight).
        Returns False when the pump never acknowledged — it is hung,
        and the caller escalates to a hard fault. Inline replicas are
        trivially paused (the router thread IS the pump)."""
        if not self.threaded or self._thread is None:
            return True
        self._pause_ack.clear()
        self._pause.set()
        return self._pause_ack.wait(timeout)

    def resume(self) -> None:
        self._pause.clear()
        self._pause_ack.clear()

    @property
    def retryable(self) -> bool:
        """May this replica rejoin after a fault? — the supervisor's
        exit-code table (``faults.classify_exit``). A non-faulted
        replica is always rejoinable."""
        if self.exit_code is None:
            return True
        return classify_exit(self.exit_code).retryable

    def rejoin(self, threaded: Optional[bool] = None) -> "Replica":
        """Bring a drained/faulted replica back into service. A faulted
        replica's engine is rebuilt from scratch (its device pool and
        host mirrors are not trusted after an arbitrary pump death); a
        cleanly drained one reuses its warmed programs. Non-retryable
        faults (deterministic failures) refuse — restarting would
        replay them."""
        if self.state not in ("drained", "faulted", "removed"):
            raise RuntimeError(f"replica {self.rid} is {self.state}")
        if not self.retryable:
            raise RuntimeError(
                f"replica {self.rid} fault is non-retryable "
                f"(exit {self.exit_code}: "
                f"{classify_exit(self.exit_code).reason})"
            )
        if self.state == "faulted":
            self.engine = None
            self.server = None
        self.fault = None
        self.exit_code = None
        self._abandon.clear()
        self._pause.clear()
        self._pause_ack.clear()
        self._hang_until = 0.0
        self.quarantined = False
        self.reset_latency()
        self.heartbeat_t = None
        obs.point("fleet.replica_rejoin", replica=self.rid)
        return self.start(
            threaded=self.threaded if threaded is None else threaded
        )

    # -- placement inputs --------------------------------------------------

    @property
    def placeable(self) -> bool:
        return (
            self.state == "ready" and self.server is not None
            and not self.quarantined
        )

    def free_slot_count(self) -> int:
        if self.engine is None:
            return 0
        # Slots not occupied AND not already promised to queued requests
        # the pump will admit on its next ticks — keeps replica queues
        # shallow so a drain has almost nothing to re-route.
        free = self.engine.num_slots - self.server.active_count
        return max(free - self.server.queued_count, 0)

    def load(self) -> Dict[str, float]:
        """Placement score inputs: free-slot and free-block fractions."""
        if self.engine is None:
            return {"free_slots": 0.0, "free_blocks": 1.0}
        free_slots = self.free_slot_count() / max(self.engine.num_slots, 1)
        free_blocks = 1.0
        if self.engine.allocator is not None:
            a = self.engine.allocator
            free_blocks = a.free_count / max(a.capacity, 1)
        return {"free_slots": free_slots, "free_blocks": free_blocks}

    def prefix_hit_blocks(self, prompt: np.ndarray) -> int:
        """How many leading KV blocks of ``prompt`` this replica's
        allocator already holds (0 on dense / prefix-cache-off) — the
        affinity tier's routing signal."""
        if (
            self.engine is None
            or self.engine.allocator is None
            or not self.engine.prefix_cache
        ):
            return 0
        p = np.asarray(prompt, np.int32).reshape(-1)
        return self.engine.allocator.peek_prefix(p, p.shape[0] - 1)

    def can_take(self, spec: ReqSpec) -> bool:
        return (
            self.placeable
            and self.free_slot_count() > 0
            and self.engine.can_admit(spec)
        )

    def submit(self, request: Request) -> RequestHandle:
        """Submit into this replica's server, on its event stream."""
        with obs.bound_bus(self.bus):
            handle = self.server.submit(request)
        self.dispatched += 1
        return handle

    def reclaim_queued(self) -> List[RequestHandle]:
        with obs.bound_bus(self.bus):
            return self.server.reclaim_queued() if self.server else []

    def inject_prefix(self, tokens: np.ndarray, payload) -> int:
        """Directory chain prefetch: seed this replica's local prefix
        cache with full-block KV content fetched from the fleet
        directory, so the NEXT prefill of a prompt sharing those blocks
        computes only its suffix. The pump is paused around the pool
        write (allocator + pool mutation must not race a stepping
        pump); inline replicas need no pause — the caller's thread IS
        the pump. Returns blocks seeded (0 = skipped, always safe)."""
        if self.engine is None or self.state not in ("ready", "draining"):
            return 0
        if self.threaded and not self.pause():
            return 0  # pump never parked: skip, prefill computes it
        try:
            with obs.bound_bus(self.bus):
                return self.engine.adopt_prefix_blocks(tokens, payload)
        finally:
            if self.threaded:
                self.resume()

    def snapshot(self) -> Dict[str, Any]:
        """One row of the router's fleet view."""
        out: Dict[str, Any] = {"replica": self.rid, "state": self.state}
        if self.pool != "mixed":
            out["pool"] = self.pool
        if self.server is not None:
            out.update(
                active=self.server.active_count,
                queued=self.server.queued_count,
                dispatched=self.dispatched,
                completed=self.server.stats["completed"],
                tokens=self.server.stats["tokens"],
            )
        if self.engine is not None:
            out.update(
                slots=self.engine.num_slots,
                occupancy=self.engine.occupancy,
                programs=self.engine.compile_count,
                programs_expected=self.engine.programs_expected,
            )
            if self.engine.allocator is not None:
                out["free_blocks"] = self.engine.allocator.free_count
        if self.quarantined:
            out["quarantined"] = True
        if self.tick_samples:
            out["tick_ewma_ms"] = round(self.tick_ewma * 1e3, 3)
        if self.leaked_threads:
            out["leaked_threads"] = self.leaked_threads
        if self.exit_code is not None:
            out["exit_code"] = self.exit_code
            out["retryable"] = self.retryable
        return out
