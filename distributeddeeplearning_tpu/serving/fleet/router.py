"""Fleet router: per-tenant fair admission over N serving replicas.

The front door of the multi-replica serving tier (docs/SERVING.md).
Clients submit tenant-tagged requests; the router owns everything
between submission and a replica's slot pool:

* **Deficit-weighted fair queueing** — one bounded queue per tenant,
  served by token-cost deficit round robin: each dispatch round every
  backlogged tenant banks ``quantum × weight`` deficit and may dispatch
  requests while its deficit covers their ``max_new_tokens`` cost. A
  hot tenant flooding the fleet cannot starve a weight-1 neighbour:
  the neighbour banks deficit every round and dispatches as soon as one
  request's cost is covered, and completed-token shares track weight
  shares under contention (the fleet bench's fairness gate). An idle
  tenant banks nothing (classic DRR — no credit hoarding).
* **Placement** — among ``ready`` replicas that can admit the request
  (free slot, free KV blocks): a **prefix-affinity tier** first
  (``SERVE_PLACEMENT=affinity``, default): requests whose prompt shares
  a block-aligned cached prefix route to the replica whose
  BlockAllocator already holds those blocks (prefill then computes only
  the divergent suffix); ties and affinity-less requests fall to
  **least-loaded** (free-slot + free-block fraction); ``load`` skips
  the affinity tier, ``rr`` round-robins (the A/B control).
* **Health / drain / rejoin** — :meth:`drain_replica` stops placement
  and reclaims the replica's queued requests back into the tenant
  queues (front, original submit order); running streams finish on the
  replica. A **faulted** replica's queued *and* running requests
  re-route: per-request determinism (the serving tier's bitwise-parity
  contract) means a from-scratch restart on another replica replays the
  identical stream, so the fleet handle splices at the exact token
  where delivery stopped — zero drops, zero duplicates, oracle-tested.
  Rejoin eligibility follows the faults exit taxonomy
  (``faults.classify_exit`` — deterministic failures don't rejoin).
* **Streaming** — tokens flow to :class:`FleetHandle` the moment a
  replica commits them (``Request.on_token`` push), so ``stream()`` /
  client callbacks see a true incremental stream and TTFT is a real
  first-token measurement end to end, queueing and routing included.
* **Autoscale signal** — every router tick publishes
  ``serve.fleet_pressure`` (demanded slots / ready slots, and KV-block
  saturation on paged fleets) plus ``serve.fleet_replicas`` /
  ``serve.fleet_queued`` / ``serve.fleet_active`` gauges; a
  :class:`~distributeddeeplearning_tpu.serving.fleet.controller.FleetController`
  consumes the signal between ticks to add or drain replicas.

Env contract (:meth:`FleetConfig.from_env`, docs/ORCHESTRATION.md):
``SERVE_REPLICAS``, ``SERVE_TENANT_WEIGHTS`` (``name:weight,…``),
``SERVE_PLACEMENT`` (``affinity`` | ``load`` | ``rr``),
``SERVE_FLEET_QUEUE_DEPTH``, ``SERVE_FLEET_QUANTUM``.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.serving.fleet.replica import Replica
from distributeddeeplearning_tpu.serving.scheduler import (
    QueueFull,
    Request,
    RequestHandle,
    ServeConfig,
)

DEFAULT_TENANT = "default"


@dataclasses.dataclass
class FleetConfig:
    """Fleet-level knobs, env-overridable (SERVE_* — docs/SERVING.md).
    Per-replica engine/scheduler knobs stay on :class:`ServeConfig`."""

    replicas: int = 2
    tenant_weights: Optional[Dict[str, float]] = None
    placement: str = "affinity"
    queue_depth: int = 1024
    # DRR quantum: deficit banked per weight unit per fresh cursor
    # visit, in token-cost units (a request costs its max_new_tokens).
    # Smaller = finer-grained interleave (smoother fairness at the cost
    # of more cursor cycles); a weight-1 tenant still always progresses
    # — it banks every visit and dispatches once its deficit covers one
    # request.
    quantum: int = 16

    @classmethod
    def from_env(cls, env=None) -> "FleetConfig":
        e = os.environ if env is None else env
        weights = None
        if e.get("SERVE_TENANT_WEIGHTS"):
            weights = parse_tenant_weights(e["SERVE_TENANT_WEIGHTS"])
        return cls(
            replicas=int(e.get("SERVE_REPLICAS", cls.replicas)),
            tenant_weights=weights,
            placement=str(e.get("SERVE_PLACEMENT", cls.placement)),
            queue_depth=int(
                e.get("SERVE_FLEET_QUEUE_DEPTH", cls.queue_depth)
            ),
            quantum=int(e.get("SERVE_FLEET_QUANTUM", cls.quantum)),
        )

    def validate(self) -> None:
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.placement not in ("affinity", "load", "rr"):
            raise ValueError(
                f"SERVE_PLACEMENT must be affinity|load|rr, got "
                f"{self.placement!r}"
            )
        if self.queue_depth < 1 or self.quantum < 1:
            raise ValueError("queue_depth and quantum must be >= 1")
        for t, w in (self.tenant_weights or {}).items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight must be > 0, got {w}")


def parse_tenant_weights(text: str) -> Dict[str, float]:
    """``"a:3,b:1.5,c:1"`` → ``{"a": 3.0, "b": 1.5, "c": 1.0}`` (bare
    ``"a"`` means weight 1)."""
    out: Dict[str, float] = {}
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        out[name.strip()] = float(w) if w.strip() else 1.0
    return out


class _Tenant:
    """One tenant's DRR lane: weight, FIFO backlog, banked deficit."""

    __slots__ = ("name", "weight", "queue", "deficit", "tokens_done",
                 "completed")

    def __init__(self, name: str, weight: float) -> None:
        self.name = name
        self.weight = float(weight)
        self.queue: Deque["FleetHandle"] = collections.deque()
        self.deficit = 0.0
        self.tokens_done = 0
        self.completed = 0


class FleetHandle:
    """Client-side view of one fleet request — survives re-routing.

    The underlying per-replica :class:`RequestHandle` is an *attempt*;
    this handle splices attempts into one exact stream: tokens already
    delivered are never re-emitted, and a restarted attempt's replay
    (identical by the per-request determinism contract) is verified
    token-for-token against the delivered prefix
    (``restart_consistent``). API mirrors :class:`RequestHandle`:
    ``tokens`` / ``result()`` / ``stream()`` / ``cancel()``.
    """

    def __init__(self, request: Request, tenant: str, fid: int,
                 now: float) -> None:
        self.request = request
        self.tenant = tenant
        self.id = fid
        self.status = "queued"
        self.finish_reason: Optional[str] = None
        self.new_tokens: List[int] = []
        self.submitted_t = now
        self.ttft_s: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.done = threading.Event()
        self.replica_id: Optional[int] = None
        self.attempts = 0
        self.restart_consistent = True
        self._cond = threading.Condition()
        self._cancel = False
        self._client_cb = request.on_token
        self._sub: Optional[RequestHandle] = None
        self._sub_seen = 0  # tokens ingested from the CURRENT attempt
        self._deadline_t = (
            now + request.deadline_ms / 1e3
            if request.deadline_ms is not None else None
        )

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([
            np.asarray(self.request.prompt, np.int32).reshape(-1),
            np.asarray(self.new_tokens, np.int32),
        ])

    def cancel(self) -> None:
        self._cancel = True
        sub = self._sub
        if sub is not None:
            sub.cancel()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} still {self.status}")
        return self.tokens

    def stream(self, timeout: Optional[float] = None):
        """Incremental token iterator across attempts — yields each
        token exactly once, in order, whatever re-routing happened
        underneath (``RequestHandle.stream`` semantics otherwise)."""
        i = 0
        while True:
            with self._cond:
                while i >= len(self.new_tokens) and not self.done.is_set():
                    if not self._cond.wait(timeout):
                        raise TimeoutError(
                            f"request {self.id}: no token within {timeout}s"
                        )
                fresh = self.new_tokens[i:]
            for tok in fresh:
                yield int(tok)
            i += len(fresh)
            if self.done.is_set() and i >= len(self.new_tokens):
                return

    def expired(self, now: float) -> bool:
        return self._deadline_t is not None and now > self._deadline_t

    # -- router side -------------------------------------------------------

    def _attach(self, sub: RequestHandle, replica_id: int) -> None:
        self._sub = sub
        self._sub_seen = 0
        self.replica_id = replica_id
        self.attempts += 1
        self.status = "running"

    def _detach(self) -> None:
        self._sub = None
        self._sub_seen = 0
        self.replica_id = None
        self.status = "queued"

    def _ingest(self, toks: List[int]) -> None:
        """Splice one attempt's delivery into the fleet stream. Called
        from the replica's serving thread (via ``Request.on_token``)."""
        fresh: List[int] = []
        with self._cond:
            start = self._sub_seen
            self._sub_seen += len(toks)
            for j, tok in enumerate(toks):
                gi = start + j
                if gi < len(self.new_tokens):
                    # Replay of an already-delivered prefix (post-fault
                    # restart): determinism says it must match.
                    if self.new_tokens[gi] != int(tok):
                        self.restart_consistent = False
                else:
                    self.new_tokens.append(int(tok))
                    fresh.append(int(tok))
            if fresh and self.ttft_s is None:
                self.ttft_s = time.monotonic() - self.submitted_t
            if fresh:
                self._cond.notify_all()
        if not self.restart_consistent:
            obs.point("fleet.restart_divergence", req=self.id)
        if fresh and self._client_cb is not None:
            try:
                self._client_cb(self, fresh)
            except Exception as e:
                obs.point(
                    "serve.stream_callback_error", req=self.id, error=repr(e)
                )

    def _finish(self, reason: str) -> None:
        self.status = "done" if reason in ("eos", "length") else reason
        self.finish_reason = reason
        self.finished_t = time.monotonic()
        with self._cond:
            self.done.set()
            self._cond.notify_all()


class Router:
    """The fleet front end: tenant queues → placement → replicas.

    Single-pumper model like :class:`Server`: one thread drives
    :meth:`step` / :meth:`drain` / :meth:`serve_forever`; ``submit`` /
    ``cancel`` are safe from any thread. Replica pumps are their own
    threads (``Replica.start(threaded=True)``) or are pumped inline by
    :meth:`step` (deterministic tests).
    """

    def __init__(
        self,
        replicas: Optional[List[Replica]] = None,
        *,
        config: Optional[FleetConfig] = None,
    ) -> None:
        self.config = config or FleetConfig()
        self.config.validate()
        self.replicas: List[Replica] = []
        self._tenants: Dict[str, _Tenant] = {}
        for name, w in (self.config.tenant_weights or {}).items():
            self._tenants[name] = _Tenant(name, w)
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._inflight: List[FleetHandle] = []
        self._rr_cursor = 0
        self._drr_cursor = 0
        self._drr_fresh = True
        self._closed = False
        self.last_pressure = 0.0
        self.stats: Dict[str, Any] = {
            "submitted": 0, "dispatched": 0, "requeued": 0, "completed": 0,
            "rejected": 0, "cancelled": 0, "deadline": 0,
        }
        for r in replicas or []:
            self.add_replica(r, start=False)

    # -- fleet membership --------------------------------------------------

    def add_replica(self, replica: Replica, *, start: bool = True,
                    threaded: bool = True) -> Replica:
        """Register (and by default start) one replica."""
        self.replicas.append(replica)
        obs.point("fleet.replica_add", replica=replica.rid)
        if start and replica.state == "new":
            replica.start(threaded=threaded)
        return replica

    def _replica(self, rid: int) -> Replica:
        for r in self.replicas:
            if r.rid == rid:
                return r
        raise KeyError(f"no replica {rid}")

    def next_rid(self) -> int:
        """A fresh replica id (controller scale-up)."""
        return max((r.rid for r in self.replicas), default=-1) + 1

    def drain_replica(self, rid: int) -> int:
        """Graceful drain: stop placing onto ``rid``, pull its queued
        requests back into the tenant queues (front — they keep their
        place), let running streams finish there. Returns the number of
        requests re-routed."""
        replica = self._replica(rid)
        replica.begin_drain()
        return self._requeue_from(replica, running_too=False)

    def fail_replica(self, rid: int, error: Optional[BaseException] = None
                     ) -> int:
        """Treat ``rid`` as faulted NOW (health probe / operator):
        stop its pump and re-route queued AND running requests."""
        replica = self._replica(rid)
        replica._abandon.set()  # do not drain: we re-route instead
        replica.stop(timeout=5.0)
        if replica.state != "faulted":
            replica.state = "faulted"
            replica.fault = error
            from distributeddeeplearning_tpu.faults import EXIT_HUNG

            replica.exit_code = EXIT_HUNG
            obs.point(
                "fleet.replica_fault", replica=rid,
                error=repr(error) if error else "declared_failed",
                exit_code=replica.exit_code, retryable=True,
            )
        return self._requeue_from(replica, running_too=True)

    def remove_replica(self, rid: int) -> Replica:
        """Take a drained/faulted replica out of the fleet (its queued
        and — when faulted — running work must already be re-routed;
        this asserts that, it does not silently drop)."""
        replica = self._replica(rid)
        if replica.state not in ("drained", "faulted", "removed"):
            raise RuntimeError(
                f"replica {rid} is {replica.state}; drain or fail it first"
            )
        if replica.server is not None and (
            replica.server.queued_count
            or (replica.state == "faulted" and replica.server.active_count)
        ):
            raise RuntimeError(
                f"replica {rid} still holds un-rerouted requests"
            )
        replica.stop(timeout=5.0)
        replica.state = "removed"
        self.replicas = [r for r in self.replicas if r.rid != rid]
        obs.point("fleet.replica_remove", replica=rid)
        return replica

    def rejoin_replica(self, replica_or_rid, *, threaded: Optional[bool]
                       = None) -> Replica:
        """Bring a drained/faulted/removed replica back into rotation
        (``Replica.rejoin`` rules: non-retryable faults refuse)."""
        replica = (
            replica_or_rid if isinstance(replica_or_rid, Replica)
            else self._replica(replica_or_rid)
        )
        replica.rejoin(threaded=threaded)
        if replica not in self.replicas:
            self.replicas.append(replica)
        return replica

    def _requeue_from(self, replica: Replica, *, running_too: bool) -> int:
        """Reclaim a replica's requests and put them back at the front
        of their tenant queues, preserving relative submit order."""
        subs = replica.reclaim_queued()
        if running_too and replica.server is not None:
            subs += replica.server.take_running()
        moved = 0
        with self._lock:
            sub_ids = {id(s) for s in subs}
            victims = [
                fh for fh in self._inflight
                if fh._sub is not None and id(fh._sub) in sub_ids
            ]
            # oldest first so appendleft() restores submit order
            for fh in sorted(victims, key=lambda f: f.id, reverse=True):
                self._inflight.remove(fh)
                fh._detach()
                self._tenant(fh.tenant).queue.appendleft(fh)
                moved += 1
                self.stats["requeued"] += 1
        if moved:
            obs.counter("fleet.requeued", moved, replica=replica.rid)
        return moved

    # -- client side -------------------------------------------------------

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = self._tenants[name] = _Tenant(name, 1.0)
        return t

    def set_tenant_weight(self, name: str, weight: float) -> None:
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        self._tenant(name).weight = float(weight)

    def submit(self, request: Request, tenant: str = DEFAULT_TENANT
               ) -> FleetHandle:
        """Enqueue one tenant-tagged request. Backpressure
        (:class:`QueueFull`) when the fleet-wide backlog is at
        capacity. Validation is eager against any ready replica so a
        malformed request fails the caller, not the dispatch loop."""
        if self._closed:
            raise RuntimeError("router is closed")
        for r in self.replicas:
            if r.placeable:
                r.engine.validate_spec(request.spec())
                break
        now = time.monotonic()
        with self._lock:
            backlog = sum(len(t.queue) for t in self._tenants.values())
            if backlog >= self.config.queue_depth:
                self.stats["rejected"] += 1
                obs.counter("serve.rejected", tenant=tenant)
                raise QueueFull(
                    f"fleet queue at capacity ({self.config.queue_depth})"
                )
            fh = FleetHandle(request, tenant, next(self._ids), now)
            self._tenant(tenant).queue.append(fh)
            self.stats["submitted"] += 1
        obs.counter("fleet.submitted", tenant=tenant)
        return fh

    # -- pump --------------------------------------------------------------

    def step(self, now: Optional[float] = None) -> bool:
        """One router tick: health sweep → finish sweep → DRR dispatch
        → inline replica pumps → fleet gauges. Returns True while work
        remains anywhere in the fleet."""
        now = time.monotonic() if now is None else now
        self._health_sweep()
        self._finish_sweep()
        self._dispatch(now)
        busy = False
        for r in self.replicas:
            if not r.threaded:
                busy = r.pump_once() or busy
        self._finish_sweep()
        with self._lock:
            backlog = sum(len(t.queue) for t in self._tenants.values())
            inflight = len(self._inflight)
        self._emit_gauges(backlog, inflight)
        return bool(backlog or inflight or busy)

    def _health_sweep(self) -> None:
        for r in list(self.replicas):
            if r.state == "faulted" and (
                r.server is not None
                and (r.server.queued_count or r.server.active_count)
            ):
                # the pump is dead: reclaim everything it held
                self._requeue_from(r, running_too=True)

    def _finish_sweep(self) -> None:
        with self._lock:
            inflight = list(self._inflight)
        for fh in inflight:
            sub = fh._sub
            if sub is None:
                continue
            if sub.status == "requeued":
                # reclaim raced us (drain path) — the requeue already
                # moved fh back to its tenant queue; nothing to do here.
                continue
            if not sub.done.is_set():
                continue
            reason = sub.finish_reason or "done"
            with self._lock:
                if fh in self._inflight:
                    self._inflight.remove(fh)
            t = self._tenant(fh.tenant)
            if reason in ("eos", "length"):
                t.completed += 1
                t.tokens_done += len(fh.new_tokens)
                self.stats["completed"] += 1
                obs.counter("fleet.completed", tenant=fh.tenant)
                obs.counter(
                    "fleet.tenant_tokens", len(fh.new_tokens),
                    tenant=fh.tenant,
                )
            else:
                key = "cancelled" if reason == "cancelled" else "deadline"
                self.stats[key] += 1
            fh._finish(reason)

    def _reap_queued(self, t: _Tenant, now: float) -> None:
        finished: List = []
        with self._lock:  # submit() appends under the same lock
            keep: Deque[FleetHandle] = collections.deque()
            for fh in t.queue:
                if fh._cancel:
                    finished.append((fh, "cancelled"))
                elif fh.expired(now):
                    finished.append((fh, "deadline"))
                else:
                    keep.append(fh)
            t.queue = keep
        for fh, reason in finished:
            key = "cancelled" if reason == "cancelled" else "deadline"
            self.stats[key] += 1
            obs.counter(
                "serve.cancelled" if reason == "cancelled"
                else "serve.evicted_deadline",
                tenant=t.name,
            )
            fh._finish(reason)

    def _dispatch(self, now: float) -> None:
        """Deficit round robin with a cursor that persists across ticks.

        Classic DRR semantics (the properties the fairness oracle
        pins): the cursor banks ``quantum × weight`` exactly once per
        *fresh visit* to a backlogged tenant, serves that tenant until
        its deficit no longer covers the head request's token cost (or
        its queue empties), then advances. Crucially, when fleet
        capacity runs out **mid-service**, the cursor stays put and
        resumes the same tenant — without banking again — on the next
        tick; otherwise a fleet whose slots free up one at a time would
        hand every trickle slot to whichever tenant the scan happened
        to start at, and weights would stop meaning anything. A tenant
        that empties its queue forfeits its deficit (no credit
        hoarding while idle)."""
        with self._lock:
            tenants = sorted(self._tenants.values(), key=lambda t: t.name)
        for t in tenants:
            self._reap_queued(t, now)
        if not any(t.queue for t in tenants):
            for t in tenants:
                t.deficit = 0.0
            return
        capacity = sum(
            r.free_slot_count() for r in self.replicas if r.placeable
        )
        idle_visits = 0
        while capacity > 0 and idle_visits <= len(tenants):
            t = tenants[self._drr_cursor % len(tenants)]
            if not t.queue:
                t.deficit = 0.0
                self._drr_cursor += 1
                self._drr_fresh = True
                idle_visits += 1
                continue
            if self._drr_fresh:
                t.deficit += self.config.quantum * t.weight
                self._drr_fresh = False
            served = 0
            blocked = False
            while t.queue and capacity > 0:
                fh = t.queue[0]
                cost = float(fh.request.max_new_tokens)
                if t.deficit < cost:
                    break
                replica = self._place(fh)
                if replica is None:
                    blocked = True  # no replica can admit this request
                    break
                with self._lock:
                    t.queue.popleft()
                t.deficit -= cost
                self._dispatch_to(replica, fh)
                capacity -= 1
                served += 1
            if capacity <= 0 and t.queue and not blocked:
                return  # resume THIS tenant next tick (cursor stays)
            # service ended on its own terms: move on
            if not t.queue:
                t.deficit = 0.0
            self._drr_cursor += 1
            self._drr_fresh = True
            idle_visits = 0 if served else idle_visits + 1

    def _place(self, fh: FleetHandle) -> Optional[Replica]:
        spec = fh.request.spec()
        candidates = [
            r for r in self.replicas if r.placeable and r.can_take(spec)
        ]
        if not candidates:
            return None
        mode = self.config.placement
        if mode == "rr":
            self._rr_cursor += 1
            return candidates[self._rr_cursor % len(candidates)]
        if mode == "affinity":
            hits = [
                (r.prefix_hit_blocks(fh.request.prompt), r)
                for r in candidates
            ]
            best = max(h for h, _ in hits)
            if best > 0:
                candidates = [r for h, r in hits if h == best]
                if len(candidates) == 1:
                    return candidates[0]
        # least-loaded: most free capacity wins (slot + block fractions)
        def score(r: Replica) -> float:
            ld = r.load()
            return ld["free_slots"] + ld["free_blocks"]

        return max(candidates, key=score)

    def _dispatch_to(self, replica: Replica, fh: FleetHandle) -> None:
        req = dataclasses.replace(
            fh.request,
            on_token=lambda _h, toks, fh=fh: fh._ingest(toks),
            # fleet-level deadline already tracked on the FleetHandle;
            # the remaining budget rides to the replica so running
            # streams still get evicted there.
            deadline_ms=(
                None if fh._deadline_t is None
                else max((fh._deadline_t - time.monotonic()) * 1e3, 1.0)
            ),
        )
        sub = replica.submit(req)
        fh._attach(sub, replica.rid)
        with self._lock:
            self._inflight.append(fh)
        self.stats["dispatched"] += 1
        obs.counter("fleet.dispatched", tenant=fh.tenant,
                    replica=replica.rid)

    # -- autoscale signal --------------------------------------------------

    def pressure(self) -> float:
        """The autoscaling signal: demanded capacity over ready
        capacity. 1.0 = the fleet's slots exactly cover current demand
        (router backlog + replica queues + running streams); above it,
        work is waiting; paged fleets also saturate on KV blocks
        (whichever is scarcer). Derived from the same quantities the
        ``serve.slot_occupancy`` / queue / block-pool rollups carry —
        this is their fleet-level composition."""
        ready = [r for r in self.replicas if r.placeable]
        total_slots = sum(r.engine.num_slots for r in ready)
        with self._lock:
            backlog = sum(len(t.queue) for t in self._tenants.values())
        demand = backlog + sum(
            r.server.active_count + r.server.queued_count for r in ready
        )
        slot_pressure = demand / max(total_slots, 1)
        block_pressure = 0.0
        for r in ready:
            if r.engine.allocator is not None:
                a = r.engine.allocator
                used = 1.0 - a.free_count / max(a.capacity, 1)
                block_pressure = max(block_pressure, used)
        return max(slot_pressure, block_pressure)

    def _emit_gauges(self, backlog: int, inflight: int) -> None:
        p = self.pressure()
        self.last_pressure = p
        obs.gauge("serve.fleet_pressure", round(p, 4))
        obs.gauge(
            "serve.fleet_replicas",
            float(sum(1 for r in self.replicas if r.placeable)),
        )
        obs.gauge("serve.fleet_queued", float(backlog))
        obs.gauge("serve.fleet_active", float(inflight))

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> None:
        """Pump until every submitted request has finished."""
        t0 = time.monotonic()
        while self.step():
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError("fleet drain timed out")
            time.sleep(0.0005)

    def serve_forever(self, stop: threading.Event,
                      idle_sleep_s: float = 0.001) -> None:
        while not stop.is_set():
            if not self.step():
                time.sleep(idle_sleep_s)
        self.drain()

    def close(self) -> None:
        """Stop accepting, drain everything, stop every replica pump."""
        self._closed = True
        self.drain()
        for r in self.replicas:
            r.stop()

    def tenant_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant accounting (the fairness gate's numerator)."""
        with self._lock:
            return {
                t.name: {
                    "weight": t.weight,
                    "queued": len(t.queue),
                    "completed": t.completed,
                    "tokens_done": t.tokens_done,
                }
                for t in self._tenants.values()
            }

    def fleet_snapshot(self) -> List[Dict[str, Any]]:
        return [r.snapshot() for r in self.replicas]


def build_fleet(
    model,
    params,
    *,
    fleet_config: Optional[FleetConfig] = None,
    serve_config: Optional[ServeConfig] = None,
    max_len: Optional[int] = None,
    obs_dir: Optional[str] = None,
    threaded: bool = True,
    start: bool = True,
) -> Router:
    """Router + N replicas from the env-driven configs (the fleet twin
    of ``Server.build``). ``obs_dir`` defaults to ``$OBS_DIR`` so each
    replica lands its own ``events-p0-s<k>.jsonl`` stream whenever the
    process is capturing events."""
    fcfg = fleet_config or FleetConfig.from_env()
    scfg = serve_config or ServeConfig.from_env()
    if obs_dir is None:
        obs_dir = os.environ.get("OBS_DIR") or None
    router = Router(config=fcfg)
    for k in range(fcfg.replicas):
        router.add_replica(
            Replica(
                k, model, params, scfg, max_len=max_len, obs_dir=obs_dir
            ),
            start=start, threaded=threaded,
        )
    return router
