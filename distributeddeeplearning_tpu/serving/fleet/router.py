"""Fleet router: per-tenant fair admission over N serving replicas.

The front door of the multi-replica serving tier (docs/SERVING.md).
Clients submit tenant-tagged requests; the router owns everything
between submission and a replica's slot pool:

* **Deficit-weighted fair queueing** — one bounded queue per tenant,
  served by token-cost deficit round robin: each dispatch round every
  backlogged tenant banks ``quantum × weight`` deficit and may dispatch
  requests while its deficit covers their ``max_new_tokens`` cost. A
  hot tenant flooding the fleet cannot starve a weight-1 neighbour:
  the neighbour banks deficit every round and dispatches as soon as one
  request's cost is covered, and completed-token shares track weight
  shares under contention (the fleet bench's fairness gate). An idle
  tenant banks nothing (classic DRR — no credit hoarding).
* **Placement** — among ``ready`` replicas that can admit the request
  (free slot, free KV blocks): a **prefix-affinity tier** first
  (``SERVE_PLACEMENT=affinity``, default): requests whose prompt shares
  a block-aligned cached prefix route to the replica whose
  BlockAllocator already holds those blocks (prefill then computes only
  the divergent suffix); ties and affinity-less requests fall to
  **least-loaded** (free-slot + free-block fraction); ``load`` skips
  the affinity tier, ``rr`` round-robins (the A/B control).
* **Health / drain / rejoin** — :meth:`drain_replica` stops placement
  and reclaims the replica's queued requests back into the tenant
  queues (front, original submit order); running streams finish on the
  replica. A **faulted** replica's queued *and* running requests
  re-route: per-request determinism (the serving tier's bitwise-parity
  contract) means a from-scratch restart on another replica replays the
  identical stream, so the fleet handle splices at the exact token
  where delivery stopped — zero drops, zero duplicates, oracle-tested.
  Rejoin eligibility follows the faults exit taxonomy
  (``faults.classify_exit`` — deterministic failures don't rejoin).
* **Self-healing monitor** (docs/ROBUSTNESS.md serving failure model)
  — every tick: stale pump heartbeats hard-fault hung replicas (the
  unjoinable thread is detached, ``fleet.thread_leaked``); a
  straggler (busy-tick EWMA > ``SERVE_STRAGGLER_FACTOR`` x the fleet
  median, sustained) is **quarantined** and its running work hedge
  re-routed through the splice path; a replay diverging from the
  delivered prefix (``fleet.splice_mismatch``) hard-faults the
  divergent replica and heals from the deterministic prefix; faulted
  replicas auto-rejoin behind a per-replica restart budget with
  exponential backoff, and budget exhaustion opens a **circuit
  breaker** (``fleet.breaker_open``) that removes the rid for good.
  A :class:`~distributeddeeplearning_tpu.serving.scheduler.BrownoutLadder`
  (``SERVE_BROWNOUT_STAGES``) degrades under sustained SLO burn and
  walks back on recovery; a seeded
  :class:`~distributeddeeplearning_tpu.serving.chaos.ChaosInjector`
  (``SERVE_CHAOS_PLAN``) makes every one of these paths a
  deterministic drill (``scripts/chaos_bench.py``).
* **Streaming** — tokens flow to :class:`FleetHandle` the moment a
  replica commits them (``Request.on_token`` push), so ``stream()`` /
  client callbacks see a true incremental stream and TTFT is a real
  first-token measurement end to end, queueing and routing included.
* **Autoscale signal** — every router tick publishes
  ``serve.fleet_pressure`` (demanded slots / ready slots, and KV-block
  saturation on paged fleets) plus ``serve.fleet_replicas`` /
  ``serve.fleet_queued`` / ``serve.fleet_active`` gauges; a
  :class:`~distributeddeeplearning_tpu.serving.fleet.controller.FleetController`
  consumes the signal between ticks to add or drain replicas.

Env contract (:meth:`FleetConfig.from_env`, docs/ORCHESTRATION.md):
``SERVE_REPLICAS``, ``SERVE_TENANT_WEIGHTS`` (``name:weight,…``),
``SERVE_PLACEMENT`` (``affinity`` | ``load`` | ``rr``),
``SERVE_FLEET_QUEUE_DEPTH``, ``SERVE_FLEET_QUANTUM``; health/chaos:
``SERVE_STRAGGLER_FACTOR``, ``SERVE_STRAGGLER_TICKS``,
``SERVE_QUARANTINE_TICKS``, ``SERVE_PUMP_HEARTBEAT_S``,
``SERVE_REPLICA_MAX_RESTARTS``, ``SERVE_REPLICA_RESTART_BACKOFF``,
``SERVE_FAULT_JOIN_S``, ``SERVE_BROWNOUT_STAGES``,
``SERVE_CHAOS_PLAN``, ``SERVE_CHAOS_SEED``; disaggregation:
``SERVE_DISAGG``, ``SERVE_POOL_PREFILL``, ``SERVE_POOL_DECODE``,
``SERVE_DISAGG_DIRECTORY``, ``SERVE_DISAGG_PREFETCH``.

**Disaggregated serving** (``SERVE_DISAGG=1``, docs/SERVING.md): the
fleet splits into a *prefill pool* and a *decode pool*. A prefill
replica admits, runs the bucketed prefill, delivers the first token,
then exports the slot's state + KV block content and frees the slot —
the router's handoff sweep seats the export on a decode replica as a
RUNNING stream (no replay; the block table is the handoff unit), so a
bursty long prompt never sits in anyone's decode tick. Greedy exports
also publish into the fleet-wide :class:`PrefixDirectory`: a second
consumer of an identical prompt **adopts** the entry (decode state
transplanted straight from the directory — zero prefill programs run),
and a prompt sharing only a full-block prefix **chain-prefetches**
those blocks into its target replica's local cache. The same
export/import machinery backs :meth:`Router.migrate` — scheduled live
KV-block migration of a running stream between replicas, bitwise
spliced, zero drops.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.serving.blocks import (
    BlockPoolExhausted,
    PrefixDirectory,
)
from distributeddeeplearning_tpu.serving.chaos import SpliceMismatch
from distributeddeeplearning_tpu.serving.fleet.replica import Replica
from distributeddeeplearning_tpu.serving.scheduler import (
    QueueFull,
    Request,
    RequestHandle,
    ServeConfig,
)

DEFAULT_TENANT = "default"


@dataclasses.dataclass
class FleetConfig:
    """Fleet-level knobs, env-overridable (SERVE_* — docs/SERVING.md).
    Per-replica engine/scheduler knobs stay on :class:`ServeConfig`."""

    replicas: int = 2
    tenant_weights: Optional[Dict[str, float]] = None
    placement: str = "affinity"
    queue_depth: int = 1024
    # DRR quantum: deficit banked per weight unit per fresh cursor
    # visit, in token-cost units (a request costs its max_new_tokens).
    # Smaller = finer-grained interleave (smoother fairness at the cost
    # of more cursor cycles); a weight-1 tenant still always progresses
    # — it banks every visit and dispatches once its deficit covers one
    # request.
    quantum: int = 16
    # Health plane (docs/ROBUSTNESS.md serving failure model): a
    # replica whose busy-tick latency EWMA exceeds straggler_factor x
    # the fleet median for straggler_ticks consecutive monitor sweeps
    # is quarantined (drained of placements, running work hedge
    # re-routed through the splice path) for quarantine_ticks router
    # ticks; a threaded pump whose heartbeat goes stale past
    # heartbeat_timeout_s while it holds work is hard-faulted.
    straggler_factor: float = 4.0
    straggler_ticks: int = 5
    quarantine_ticks: int = 50
    heartbeat_timeout_s: float = 5.0
    # Crash-loop circuit breaker (launch_supervised semantics): a
    # faulted retryable replica auto-rejoins after restart_backoff_s x
    # 2^attempt; after max_restarts rejoins the breaker opens
    # (fleet.breaker_open) and the replica is removed. fault_join_s
    # bounds how long fail/remove wait for a pump before detaching it.
    max_restarts: int = 3
    restart_backoff_s: float = 1.0
    fault_join_s: float = 5.0
    # Brownout degradation ladder (SERVE_BROWNOUT_STAGES, e.g.
    # "spec_off,max_new:8,shed:1") and the chaos plane's drill plan.
    brownout_stages: str = ""
    chaos_plan: str = ""
    chaos_seed: int = 0
    # Disaggregated prefill/decode pools (docs/SERVING.md): pool sizes
    # of 0 auto-split (prefill gets floor(replicas/2), min 1); setting
    # exactly one fixes that pool and the other takes the remainder.
    # ``directory`` enables the fleet-wide prefix directory (adoption +
    # chain prefetch); ``prefetch`` gates just the chain-prefetch leg.
    disagg: bool = False
    prefill_pool: int = 0
    decode_pool: int = 0
    directory: bool = True
    prefetch: bool = True

    @classmethod
    def from_env(cls, env=None) -> "FleetConfig":
        e = os.environ if env is None else env
        weights = None
        if e.get("SERVE_TENANT_WEIGHTS"):
            weights = parse_tenant_weights(e["SERVE_TENANT_WEIGHTS"])
        return cls(
            replicas=int(e.get("SERVE_REPLICAS", cls.replicas)),
            tenant_weights=weights,
            placement=str(e.get("SERVE_PLACEMENT", cls.placement)),
            queue_depth=int(
                e.get("SERVE_FLEET_QUEUE_DEPTH", cls.queue_depth)
            ),
            quantum=int(e.get("SERVE_FLEET_QUANTUM", cls.quantum)),
            straggler_factor=float(
                e.get("SERVE_STRAGGLER_FACTOR", cls.straggler_factor)
            ),
            straggler_ticks=int(
                e.get("SERVE_STRAGGLER_TICKS", cls.straggler_ticks)
            ),
            quarantine_ticks=int(
                e.get("SERVE_QUARANTINE_TICKS", cls.quarantine_ticks)
            ),
            heartbeat_timeout_s=float(
                e.get("SERVE_PUMP_HEARTBEAT_S", cls.heartbeat_timeout_s)
            ),
            max_restarts=int(
                e.get("SERVE_REPLICA_MAX_RESTARTS", cls.max_restarts)
            ),
            restart_backoff_s=float(
                e.get("SERVE_REPLICA_RESTART_BACKOFF", cls.restart_backoff_s)
            ),
            fault_join_s=float(e.get("SERVE_FAULT_JOIN_S", cls.fault_join_s)),
            brownout_stages=str(e.get("SERVE_BROWNOUT_STAGES", "")),
            chaos_plan=str(e.get("SERVE_CHAOS_PLAN", "")),
            chaos_seed=int(e.get("SERVE_CHAOS_SEED", "0")),
            disagg=_env_flag(e.get("SERVE_DISAGG"), cls.disagg),
            prefill_pool=int(e.get("SERVE_POOL_PREFILL", cls.prefill_pool)),
            decode_pool=int(e.get("SERVE_POOL_DECODE", cls.decode_pool)),
            directory=_env_flag(
                e.get("SERVE_DISAGG_DIRECTORY"), cls.directory
            ),
            prefetch=_env_flag(e.get("SERVE_DISAGG_PREFETCH"), cls.prefetch),
        )

    def validate(self) -> None:
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.placement not in ("affinity", "load", "rr"):
            raise ValueError(
                f"SERVE_PLACEMENT must be affinity|load|rr, got "
                f"{self.placement!r}"
            )
        if self.queue_depth < 1 or self.quantum < 1:
            raise ValueError("queue_depth and quantum must be >= 1")
        for t, w in (self.tenant_weights or {}).items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight must be > 0, got {w}")
        if self.straggler_factor <= 1.0:
            raise ValueError(
                f"SERVE_STRAGGLER_FACTOR must be > 1, got "
                f"{self.straggler_factor}"
            )
        if self.straggler_ticks < 1 or self.quarantine_ticks < 1:
            raise ValueError(
                "straggler_ticks and quarantine_ticks must be >= 1"
            )
        if self.max_restarts < 0 or self.restart_backoff_s < 0:
            raise ValueError(
                "max_restarts and restart_backoff_s must be >= 0"
            )
        if self.brownout_stages:
            from distributeddeeplearning_tpu.serving.scheduler import (
                parse_brownout_stages,
            )

            parse_brownout_stages(self.brownout_stages)
        if self.chaos_plan:
            from distributeddeeplearning_tpu.serving.chaos import (
                parse_chaos_plan,
            )

            parse_chaos_plan(self.chaos_plan)
        if self.prefill_pool < 0 or self.decode_pool < 0:
            raise ValueError(
                "SERVE_POOL_PREFILL and SERVE_POOL_DECODE must be >= 0"
            )
        if self.disagg:
            if self.replicas < 2:
                raise ValueError(
                    f"SERVE_DISAGG needs >= 2 replicas (one per pool), "
                    f"got {self.replicas}"
                )
            pre, dec = self.pool_split()
            if pre < 1 or dec < 1:
                raise ValueError(
                    f"pool split {pre}+{dec} must leave at least one "
                    f"replica in each pool (SERVE_REPLICAS="
                    f"{self.replicas}, SERVE_POOL_PREFILL="
                    f"{self.prefill_pool}, SERVE_POOL_DECODE="
                    f"{self.decode_pool})"
                )
            if pre + dec != self.replicas:
                raise ValueError(
                    f"SERVE_POOL_PREFILL + SERVE_POOL_DECODE = "
                    f"{pre + dec} != SERVE_REPLICAS {self.replicas}"
                )

    def pool_split(self) -> "tuple[int, int]":
        """``(prefill, decode)`` replica counts under ``disagg``
        (``(0, 0)`` otherwise). Unset pools auto-split."""
        if not self.disagg:
            return (0, 0)
        n = self.replicas
        if self.prefill_pool and self.decode_pool:
            return (self.prefill_pool, self.decode_pool)
        if self.prefill_pool:
            return (self.prefill_pool, n - self.prefill_pool)
        if self.decode_pool:
            return (n - self.decode_pool, self.decode_pool)
        pre = max(n // 2, 1)
        return (pre, n - pre)


def _env_flag(raw: Optional[str], default: bool) -> bool:
    """``"1"/"true"/"yes"/"on"`` → True, ``"0"/"false"/"no"/"off"`` →
    False, unset/empty → ``default``."""
    if raw is None or str(raw).strip() == "":
        return bool(default)
    return str(raw).strip().lower() not in ("0", "false", "no", "off")


def parse_tenant_weights(text: str) -> Dict[str, float]:
    """``"a:3,b:1.5,c:1"`` → ``{"a": 3.0, "b": 1.5, "c": 1.0}`` (bare
    ``"a"`` means weight 1)."""
    out: Dict[str, float] = {}
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        out[name.strip()] = float(w) if w.strip() else 1.0
    return out


class _Tenant:
    """One tenant's DRR lane: weight, FIFO backlog, banked deficit."""

    __slots__ = ("name", "weight", "queue", "deficit", "tokens_done",
                 "completed")

    def __init__(self, name: str, weight: float) -> None:
        self.name = name
        self.weight = float(weight)
        self.queue: Deque["FleetHandle"] = collections.deque()
        self.deficit = 0.0
        self.tokens_done = 0
        self.completed = 0


class FleetHandle:
    """Client-side view of one fleet request — survives re-routing.

    The underlying per-replica :class:`RequestHandle` is an *attempt*;
    this handle splices attempts into one exact stream: tokens already
    delivered are never re-emitted, and a restarted attempt's replay
    (identical by the per-request determinism contract) is verified
    token-for-token against the delivered prefix
    (``restart_consistent``). API mirrors :class:`RequestHandle`:
    ``tokens`` / ``result()`` / ``stream()`` / ``cancel()``.
    """

    def __init__(self, request: Request, tenant: str, fid: int,
                 now: float) -> None:
        self.request = request
        self.tenant = tenant
        self.id = fid
        self.status = "queued"
        self.finish_reason: Optional[str] = None
        self.new_tokens: List[int] = []
        self.submitted_t = now
        self.ttft_s: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.done = threading.Event()
        self.replica_id: Optional[int] = None
        self.attempts = 0
        self.restart_consistent = True
        # Trace plane (docs/OBSERVABILITY.md): the fleet mints the
        # request's causal identity at submission; every attempt's
        # Request carries it to the replica (Request.trace), and a
        # re-route stamps its cause (hedge|splice|brownout|migration)
        # on the child span the next dispatch emits.
        self.trace = request.trace or obs.new_trace_id()
        self._reroute_cause: Optional[str] = None
        self._requeued_t: Optional[float] = None
        self._reroute_from: Optional[int] = None
        # Splice-integrity ledger (docs/ROBUSTNESS.md serving failure
        # model): every replay mismatch ever seen (the corrupt
        # detector's count — survives healing), the live divergence
        # flag the router's monitor sweep heals, and the per-attempt
        # taint that stops a divergent attempt's tokens from ever
        # reaching the client.
        self.splice_mismatches = 0
        self._divergent = False
        self._sub_tainted = False
        self._chaos = None  # set by the router when a drill is armed
        self._cond = threading.Condition()
        self._cancel = False
        self._client_cb = request.on_token
        self._sub: Optional[RequestHandle] = None
        self._sub_seen = 0  # tokens ingested from the CURRENT attempt
        self._deadline_t = (
            now + request.deadline_ms / 1e3
            if request.deadline_ms is not None else None
        )

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([
            np.asarray(self.request.prompt, np.int32).reshape(-1),
            np.asarray(self.new_tokens, np.int32),
        ])

    def cancel(self) -> None:
        self._cancel = True
        sub = self._sub
        if sub is not None:
            sub.cancel()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} still {self.status}")
        return self.tokens

    def stream(self, timeout: Optional[float] = None):
        """Incremental token iterator across attempts — yields each
        token exactly once, in order, whatever re-routing happened
        underneath (``RequestHandle.stream`` semantics otherwise).

        **Timeout contract:** ``timeout`` bounds the wait for EACH next
        token. On expiry the stream **cancels the request and raises
        TimeoutError** — the handle detaches from its replica attempt
        and the fleet reaps it as ``cancelled``, so an abandoned stream
        never leaves a zombie request decoding (chaos drills submit
        thousands of bounded streams; without cancel-on-timeout every
        straggler-stalled stream would leak its slot)."""
        i = 0
        while True:
            with self._cond:
                while i >= len(self.new_tokens) and not self.done.is_set():
                    if not self._cond.wait(timeout):
                        self.cancel()
                        raise TimeoutError(
                            f"request {self.id}: no token within "
                            f"{timeout}s (request cancelled)"
                        )
                fresh = self.new_tokens[i:]
            for tok in fresh:
                yield int(tok)
            i += len(fresh)
            if self.done.is_set() and i >= len(self.new_tokens):
                return

    def expired(self, now: float) -> bool:
        return self._deadline_t is not None and now > self._deadline_t

    # -- router side -------------------------------------------------------

    def _attach(self, sub: RequestHandle, replica_id: int,
                seen: int = 0) -> None:
        """Bind one replica attempt. ``seen`` is how many of this
        handle's delivered tokens the attempt ALREADY accounts for: a
        from-scratch dispatch replays from token 0 (``seen=0``, every
        replayed token verified against the delivered prefix), while a
        handoff/migration continuation was seeded with the delivered
        prefix (``import_running(prior_tokens=...)``) and emits only
        fresh tokens — ``seen=len(new_tokens)`` keeps the splice
        cursor exact so the continuation neither re-verifies nor
        mis-indexes."""
        self._sub = sub
        self._sub_seen = seen
        self._sub_tainted = False
        self.replica_id = replica_id
        self.attempts += 1
        self.status = "running"

    def _detach(self) -> None:
        self._sub = None
        self._sub_seen = 0
        self._sub_tainted = False
        self.replica_id = None
        self.status = "queued"

    def _ingest(self, toks: List[int]) -> None:
        """Splice one attempt's delivery into the fleet stream. Called
        from the replica's serving thread (via ``Request.on_token``).

        Replayed tokens (an attempt re-covering the already-delivered
        prefix after a re-route) are verified token-for-token against
        the delivered stream and never re-emitted. A mismatch —
        determinism says a healthy replica cannot produce one, so the
        attempt is emitting corrupt data — **taints the whole attempt**:
        nothing further from it reaches the client, and the router's
        monitor sweep hard-faults the replica and replays the stream
        from the deterministic prefix elsewhere (the corrupt verb's
        detect-and-heal path, docs/ROBUSTNESS.md)."""
        mismatch = False
        fresh: List[int] = []
        with self._cond:
            if self._sub_tainted:
                return
            start = self._sub_seen
            self._sub_seen += len(toks)
            for j, tok in enumerate(toks):
                gi = start + j
                if gi < len(self.new_tokens):
                    t_in = int(tok)
                    if self._chaos is not None:
                        t_in = self._chaos.maybe_corrupt(self.id, t_in)
                    if self.new_tokens[gi] != t_in:
                        self.restart_consistent = False
                        self.splice_mismatches += 1
                        self._divergent = True
                        self._sub_tainted = True
                        mismatch = True
                        break  # drop the attempt's remaining tokens
                else:
                    self.new_tokens.append(int(tok))
                    fresh.append(int(tok))
            if fresh and self.ttft_s is None:
                self.ttft_s = time.monotonic() - self.submitted_t
            if fresh:
                self._cond.notify_all()
        if mismatch:
            obs.point("fleet.restart_divergence", req=self.id)
        if fresh and self._client_cb is not None:
            try:
                self._client_cb(self, fresh)
            except Exception as e:
                obs.point(
                    "serve.stream_callback_error", req=self.id, error=repr(e)
                )

    def _finish(self, reason: str) -> None:
        self.status = "done" if reason in ("eos", "length") else reason
        self.finish_reason = reason
        self.finished_t = time.monotonic()
        with self._cond:
            self.done.set()
            self._cond.notify_all()


class Router:
    """The fleet front end: tenant queues → placement → replicas.

    Single-pumper model like :class:`Server`: one thread drives
    :meth:`step` / :meth:`drain` / :meth:`serve_forever`; ``submit`` /
    ``cancel`` are safe from any thread. Replica pumps are their own
    threads (``Replica.start(threaded=True)``) or are pumped inline by
    :meth:`step` (deterministic tests).
    """

    def __init__(
        self,
        replicas: Optional[List[Replica]] = None,
        *,
        config: Optional[FleetConfig] = None,
        chaos=None,
        brownout=None,
    ) -> None:
        self.config = config or FleetConfig()
        self.config.validate()
        self.replicas: List[Replica] = []
        self._tenants: Dict[str, _Tenant] = {}
        for name, w in (self.config.tenant_weights or {}).items():
            self._tenants[name] = _Tenant(name, w)
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._inflight: List[FleetHandle] = []
        self._rr_cursor = 0
        self._drr_cursor = 0
        self._drr_fresh = True
        self._closed = False
        self.last_pressure = 0.0
        # Chaos plane + brownout ladder (env-wired by default; tests
        # and benches inject their own).
        if chaos is None and self.config.chaos_plan:
            from distributeddeeplearning_tpu.serving.chaos import (
                ChaosInjector,
                parse_chaos_plan,
            )

            chaos = ChaosInjector(
                parse_chaos_plan(self.config.chaos_plan),
                seed=self.config.chaos_seed,
            )
        self.chaos = chaos
        if brownout is None and self.config.brownout_stages:
            from distributeddeeplearning_tpu.serving.scheduler import (
                BrownoutLadder,
                parse_brownout_stages,
            )

            brownout = BrownoutLadder(
                parse_brownout_stages(self.config.brownout_stages)
            )
        self.brownout = brownout
        self._ticks = 0  # completed router ticks (the chaos clock)
        # Crash-loop breaker ledger: rid -> {attempts, next_t, pending,
        # open}. Survives a replica's removal so a breaker-open rid can
        # never slip back into rotation.
        self._breakers: Dict[int, Dict[str, Any]] = {}
        self.last_breaker_tick: Optional[int] = None
        # Brownout state applied by apply_brownout_stage.
        self._shed_tenants: set = set()
        self._shed_by_stage: Dict[int, set] = {}
        self._brownout_max_new: Optional[int] = None
        # Disaggregation plane (docs/SERVING.md): the fleet-wide prefix
        # directory (greedy prefill exports publish; adoptions and
        # chain prefetches consume) and the prefill→decode handoff
        # queue — exports waiting for a decode replica with room.
        # Entries retry every tick until seated: backpressure, never a
        # drop.
        self.directory: Optional[PrefixDirectory] = (
            PrefixDirectory()
            if self.config.disagg and self.config.directory else None
        )
        self._pending_handoffs: Deque[Any] = collections.deque()
        self.stats: Dict[str, Any] = {
            "submitted": 0, "dispatched": 0, "requeued": 0, "completed": 0,
            "rejected": 0, "cancelled": 0, "deadline": 0,
            "quarantined": 0, "unquarantined": 0, "splice_mismatch": 0,
            "breaker_open": 0, "rejoins": 0, "brownout": 0,
            "handoffs": 0, "migrations": 0, "directory_hits": 0,
        }
        for r in replicas or []:
            self.add_replica(r, start=False)

    # -- fleet membership --------------------------------------------------

    def add_replica(self, replica: Replica, *, start: bool = True,
                    threaded: bool = True) -> Replica:
        """Register (and by default start) one replica. A rid whose
        circuit breaker is open is refused — a crash-looping replica
        does not slip back in through the membership door."""
        b = self._breakers.get(replica.rid)
        if b is not None and b.get("open"):
            raise RuntimeError(
                f"replica {replica.rid} breaker is open "
                f"(restart budget exhausted)"
            )
        replica.chaos = self.chaos
        self.replicas.append(replica)
        obs.point("fleet.replica_add", replica=replica.rid)
        if start and replica.state == "new":
            replica.start(threaded=threaded)
        return replica

    def _replica(self, rid: int) -> Replica:
        for r in self.replicas:
            if r.rid == rid:
                return r
        raise KeyError(f"no replica {rid}")

    def next_rid(self) -> int:
        """A fresh replica id (controller scale-up). The breaker
        ledger counts as used — a breaker-open rid is never re-minted
        for a new replica."""
        used = [r.rid for r in self.replicas] + list(self._breakers)
        return max(used, default=-1) + 1

    def drain_replica(self, rid: int) -> int:
        """Graceful drain: stop placing onto ``rid``, pull its queued
        requests back into the tenant queues (front — they keep their
        place), let running streams finish there. Returns the number of
        requests re-routed."""
        replica = self._replica(rid)
        replica.begin_drain()
        return self._requeue_from(replica, running_too=False,
                                  cause="migration")

    def fail_replica(self, rid: int, error: Optional[BaseException] = None
                     ) -> int:
        """Treat ``rid`` as faulted NOW (health probe / operator /
        heartbeat monitor): stop its pump and re-route queued AND
        running requests. Double-fault-safe: a second call on an
        already-faulted replica only re-sweeps leftover work (it never
        re-stops, re-classifies, or double-requeues). A pump that will
        not join (hung thread) is *detached* by ``Replica.stop`` — a
        ``fleet.thread_leaked`` point, never a silent zombie still
        mutating the server (the rejoin path rebuilds engine+server, so
        a waking zombie can only touch the abandoned objects)."""
        replica = self._replica(rid)
        already = replica.state == "faulted" and replica._abandon.is_set()
        replica._abandon.set()  # do not drain: we re-route instead
        if not already:
            replica.stop(timeout=self.config.fault_join_s)
        if replica.state != "faulted":
            replica.state = "faulted"
            replica.fault = error
            from distributeddeeplearning_tpu.faults import EXIT_HUNG

            replica.exit_code = EXIT_HUNG
            obs.point(
                "fleet.replica_fault", replica=rid,
                error=repr(error) if error else "declared_failed",
                exit_code=replica.exit_code, retryable=True,
            )
        if self.directory is not None:
            # The dead replica's blocks are gone with its engine:
            # re-home each entry to a surviving holder or drop it.
            # Payload-backed adoption keeps working either way.
            self.directory.drop_replica(rid)
        return self._requeue_from(replica, running_too=True, cause="splice")

    def quarantine_replica(self, rid: int, **labels: Any) -> int:
        """Straggler quarantine: stop placing onto ``rid`` and hedge
        re-route its queued AND running requests through the splice
        path — the replica stays alive (still pumping, on probation for
        ``quarantine_ticks`` router ticks) so a transient stall heals
        without a rebuild. The pump is paused at a tick boundary before
        running slots are evicted (``take_running`` is only safe with
        the pump parked); a pump that never acknowledges the pause is
        hung, and the monitor escalates to :meth:`fail_replica`."""
        replica = self._replica(rid)
        if replica.quarantined:
            return 0
        if not replica.pause(timeout=self.config.fault_join_s):
            return self.fail_replica(
                rid, TimeoutError("pump unresponsive to quarantine pause")
            )
        replica.quarantined = True
        replica.quarantine_until = self._ticks + self.config.quarantine_ticks
        replica.straggle_ticks = 0
        self.stats["quarantined"] += 1
        obs.point("fleet.quarantine", replica=rid, **labels)
        moved = self._requeue_from(replica, running_too=True, cause="hedge")
        replica.resume()
        return moved

    def remove_replica(self, rid: int) -> Replica:
        """Take a drained/faulted replica out of the fleet (its queued
        and — when faulted — running work must already be re-routed;
        this asserts that, it does not silently drop)."""
        replica = self._replica(rid)
        if replica.state not in ("drained", "faulted", "removed"):
            raise RuntimeError(
                f"replica {rid} is {replica.state}; drain or fail it first"
            )
        if replica.server is not None and (
            replica.server.queued_count
            or (replica.state == "faulted" and replica.server.active_count)
        ):
            raise RuntimeError(
                f"replica {rid} still holds un-rerouted requests"
            )
        replica.stop(timeout=self.config.fault_join_s)
        replica.state = "removed"
        self.replicas = [r for r in self.replicas if r.rid != rid]
        if self.directory is not None:
            self.directory.drop_replica(rid)
        obs.point("fleet.replica_remove", replica=rid)
        return replica

    def rejoin_replica(self, replica_or_rid, *, threaded: Optional[bool]
                       = None) -> Replica:
        """Bring a drained/faulted/removed replica back into rotation
        (``Replica.rejoin`` rules: non-retryable faults refuse).

        Every post-fault rejoin burns the replica's restart budget
        (``SERVE_REPLICA_MAX_RESTARTS``, launch_supervised semantics):
        budget exhausted or breaker already open → refused. An
        already-ready replica (the monitor's auto-heal beat a manual
        call) is returned unchanged."""
        replica = (
            replica_or_rid if isinstance(replica_or_rid, Replica)
            else self._replica(replica_or_rid)
        )
        b = self._breakers.get(replica.rid)
        if b is not None and b["open"]:
            raise RuntimeError(
                f"replica {replica.rid} breaker is open "
                f"(restart budget exhausted)"
            )
        if replica.state in ("ready", "starting", "draining"):
            return replica  # auto-heal already brought it back
        if replica.state == "faulted":
            b = self._breaker(replica.rid)
            if b["attempts"] >= self.config.max_restarts:
                self._open_breaker(replica, b)
                raise RuntimeError(
                    f"replica {replica.rid} restart budget exhausted "
                    f"({b['attempts']}/{self.config.max_restarts}); "
                    f"breaker opened"
                )
            b["attempts"] += 1
            b["pending"] = False
            self.stats["rejoins"] += 1
        replica.rejoin(threaded=threaded)
        if replica not in self.replicas:
            self.replicas.append(replica)
        return replica

    def _breaker(self, rid: int) -> Dict[str, Any]:
        return self._breakers.setdefault(
            rid, {"attempts": 0, "next_t": 0.0, "pending": False,
                  "open": False},
        )

    def _open_breaker(self, replica: Replica, b: Dict[str, Any]) -> None:
        """Budget exhausted (or non-retryable fault): open the circuit,
        re-route whatever the replica still holds, take it out of the
        fleet. The breaker ledger outlives the removal, so the rid can
        never slip back in (``add_replica``/``rejoin_replica`` refuse)."""
        b["open"] = True
        self.last_breaker_tick = self._ticks
        self.stats["breaker_open"] += 1
        obs.point(
            "fleet.breaker_open", replica=replica.rid,
            attempts=b["attempts"], retryable=replica.retryable,
            exit_code=replica.exit_code,
        )
        self._requeue_from(replica, running_too=True, cause="splice")
        if any(r.rid == replica.rid for r in self.replicas):
            self.remove_replica(replica.rid)

    def _requeue_from(self, replica: Replica, *, running_too: bool,
                      cause: str = "migration") -> int:
        """Reclaim a replica's requests and put them back at the front
        of their tenant queues, preserving relative submit order.
        ``cause`` (hedge|splice|migration) rides each handle to the
        next dispatch, which emits the re-route child span under the
        request's trace."""
        subs = replica.reclaim_queued()
        if replica.server is not None and replica.server.handoff:
            # Pending prefill exports are pure host data: they outlive
            # this replica (fault or drain alike), so hand them to the
            # handoff queue instead of replaying the prefill — the
            # lossless half of "a prefill replica dying mid-handoff".
            alive = replica.state not in ("faulted", "removed")
            for sub, state in replica.server.take_handoffs():
                fh = self._fh_for_sub(sub)
                if fh is not None:
                    self._publish_handoff(
                        replica.rid, fh, state, resident=alive
                    )
                    self._pending_handoffs.append(
                        (fh, state, replica.rid, "handoff")
                    )
        if running_too and replica.server is not None:
            # The replica's private event stream must see the
            # trace_close for the running work being taken from it.
            with obs.bound_bus(replica.bus):
                subs += replica.server.take_running()
        moved = 0
        now = time.monotonic()
        with self._lock:
            sub_ids = {id(s) for s in subs}
            victims = [
                fh for fh in self._inflight
                if fh._sub is not None and id(fh._sub) in sub_ids
            ]
            # oldest first so appendleft() restores submit order
            for fh in sorted(victims, key=lambda f: f.id, reverse=True):
                self._inflight.remove(fh)
                fh._detach()
                fh._reroute_cause = cause
                fh._requeued_t = now
                fh._reroute_from = replica.rid
                self._tenant(fh.tenant).queue.appendleft(fh)
                moved += 1
                self.stats["requeued"] += 1
        if moved:
            obs.counter("fleet.requeued", moved, replica=replica.rid)
        return moved

    # -- client side -------------------------------------------------------

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = self._tenants[name] = _Tenant(name, 1.0)
        return t

    def set_tenant_weight(self, name: str, weight: float) -> None:
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        self._tenant(name).weight = float(weight)

    def submit(self, request: Request, tenant: str = DEFAULT_TENANT
               ) -> FleetHandle:
        """Enqueue one tenant-tagged request. Backpressure
        (:class:`QueueFull`) when the fleet-wide backlog is at
        capacity. Validation is eager against any ready replica so a
        malformed request fails the caller, not the dispatch loop."""
        if self._closed:
            raise RuntimeError("router is closed")
        now = time.monotonic()
        if tenant in self._shed_tenants:
            # Brownout shed: a distinct, client-visible outcome — the
            # handle finishes as "brownout" immediately, never a silent
            # drop and never a generic QueueFull masquerade. The shed
            # counter is the trace's terminal marker (cause=brownout).
            fh = FleetHandle(request, tenant, next(self._ids), now)
            self.stats["brownout"] += 1
            with obs.trace_ctx(fh.trace, cause="brownout"):
                obs.counter("serve.brownout_shed", tenant=tenant)
            fh._finish("brownout")
            return fh
        for r in self.replicas:
            if r.placeable:
                r.engine.validate_spec(request.spec())
                break
        with self._lock:
            backlog = sum(len(t.queue) for t in self._tenants.values())
            if backlog >= self.config.queue_depth:
                self.stats["rejected"] += 1
                obs.counter("serve.rejected", tenant=tenant)
                raise QueueFull(
                    f"fleet queue at capacity ({self.config.queue_depth})"
                )
            fh = FleetHandle(request, tenant, next(self._ids), now)
            self._tenant(tenant).queue.append(fh)
            self.stats["submitted"] += 1
        # The trace's fleet-level admission point (req labels let the
        # trace reconstructor name the fleet request id).
        with obs.trace_ctx(fh.trace):
            obs.counter("fleet.submitted", tenant=tenant, req=fh.id)
        return fh

    # -- pump --------------------------------------------------------------

    def step(self, now: Optional[float] = None) -> bool:
        """One router tick: chaos clock → monitor sweep (heartbeat,
        stragglers, splice integrity, breaker auto-heal) → health sweep
        → brownout ladder → finish sweep → DRR dispatch → inline
        replica pumps → fleet gauges. Returns True while work remains
        anywhere in the fleet."""
        now = time.monotonic() if now is None else now
        self._ticks += 1
        if self.chaos is not None:
            self._chaos_tick(now)
        self._monitor_sweep(now)
        self._health_sweep()
        if self.brownout is not None:
            self.brownout.tick(self, now)
        self._finish_sweep()
        self._dispatch(now)
        busy = False
        for r in self.replicas:
            if not r.threaded:
                busy = r.pump_once() or busy
        self._handoff_sweep(time.monotonic())
        self._finish_sweep()
        with self._lock:
            backlog = sum(len(t.queue) for t in self._tenants.values())
            inflight = len(self._inflight)
        self._emit_gauges(backlog, inflight)
        return bool(
            backlog or inflight or busy or self._pending_handoffs
        )

    def _chaos_tick(self, now: float) -> None:
        """Activate the drill directives due at this tick: pump verbs
        arm on their replica; ``corrupt`` picks its victim (the
        lowest-id running handle with a delivered prefix — the replay
        window the flip must land in), arms the one-shot flip, and
        hedge re-routes the victim's replica so the replay happens."""
        for f in self.chaos.due(self._ticks):
            if f.kind == "corrupt":
                with self._lock:
                    running = sorted(
                        (
                            fh for fh in self._inflight
                            if fh.new_tokens and fh.replica_id is not None
                        ),
                        key=lambda fh: (fh.replica_id != f.replica, fh.id),
                    )
                if not running:
                    # nothing replayable yet: re-queue the directive for
                    # the next tick rather than dropping the drill verb
                    # (victims on the named replica are preferred; any
                    # running handle with a delivered prefix will do —
                    # the flip rides the handle, not the replica).
                    self.chaos.defer(f)
                    continue
                fh = running[0]
                fh._chaos = self.chaos
                self.chaos.arm_corrupt(f, fh.id)
                self.quarantine_replica(
                    fh.replica_id, reason="chaos_corrupt_hedge"
                )
            else:
                self.chaos.arm_pump(f, now)

    def _monitor_sweep(self, now: float) -> None:
        """The health monitor (docs/ROBUSTNESS.md serving failure
        model): four checks, all tick-deterministic.

        1. **Heartbeat** — a threaded pump whose heartbeat is stale past
           ``heartbeat_timeout_s`` while the replica holds work is hung
           (alive-but-silent): hard-fault, re-route, detach the thread.
        2. **Stragglers** — a replica whose busy-tick EWMA exceeds
           ``straggler_factor`` x the fleet median for
           ``straggler_ticks`` consecutive sweeps is quarantined; the
           probation expires after ``quarantine_ticks`` router ticks.
        3. **Splice integrity** — a handle whose replay diverged from
           its delivered prefix hard-faults the divergent replica and
           replays from the deterministic prefix (the corrupt
           detect-and-heal path).
        4. **Breaker auto-heal** — faulted retryable replicas rejoin
           after ``restart_backoff_s x 2^attempt``; budget exhausted or
           non-retryable → breaker opens, replica removed.
        """
        cfg = self.config
        for r in list(self.replicas):
            if (
                r.threaded and r.state in ("ready", "draining")
                and r.server is not None and r.heartbeat_t is not None
                and now - r.heartbeat_t > cfg.heartbeat_timeout_s
                and (r.server.active_count or r.server.queued_count)
            ):
                self.fail_replica(
                    r.rid,
                    TimeoutError(
                        f"pump heartbeat stale "
                        f"{now - r.heartbeat_t:.2f}s"
                    ),
                )
        sampled = [
            r for r in self.replicas
            if r.state == "ready" and r.tick_samples >= 3
        ]
        if len(sampled) >= 2:
            ewmas = sorted(r.tick_ewma for r in sampled)
            median = ewmas[(len(ewmas) - 1) // 2]
            for r in sampled:
                if r.quarantined:
                    continue
                if median > 0 and r.tick_ewma > cfg.straggler_factor * median:
                    r.straggle_ticks += 1
                    if r.straggle_ticks >= cfg.straggler_ticks:
                        self.quarantine_replica(
                            r.rid,
                            ewma_ms=round(r.tick_ewma * 1e3, 3),
                            median_ms=round(median * 1e3, 3),
                        )
                else:
                    r.straggle_ticks = 0
        for r in self.replicas:
            if r.quarantined and self._ticks >= r.quarantine_until:
                r.quarantined = False
                r.reset_latency()
                self.stats["unquarantined"] += 1
                obs.point("fleet.unquarantine", replica=r.rid)
        with self._lock:
            divergent = [fh for fh in self._inflight if fh._divergent]
        for fh in divergent:
            rid = fh.replica_id
            self.stats["splice_mismatch"] += 1
            with obs.trace_ctx(fh.trace, cause="splice"):
                obs.point("fleet.splice_mismatch", req=fh.id, replica=rid)
            # The delivered prefix is immutable (already streamed); the
            # divergent attempt is the corrupt one. Heal: hard-fault
            # the replica producing it and replay from the prefix.
            fh._divergent = False
            fh.restart_consistent = True
            if rid is not None and any(r.rid == rid for r in self.replicas):
                self.fail_replica(
                    rid, SpliceMismatch(f"request {fh.id} replay diverged")
                )
            elif fh._sub is not None:
                # replica already gone: just re-queue the handle itself
                with self._lock:
                    if fh in self._inflight:
                        self._inflight.remove(fh)
                        fh._reroute_from = fh.replica_id
                        fh._detach()
                        fh._reroute_cause = "splice"
                        fh._requeued_t = time.monotonic()
                        self._tenant(fh.tenant).queue.appendleft(fh)
                        self.stats["requeued"] += 1
        for r in list(self.replicas):
            if r.state != "faulted":
                continue
            b = self._breaker(r.rid)
            if b["open"]:
                continue
            if not r.retryable or b["attempts"] >= cfg.max_restarts:
                self._open_breaker(r, b)
                continue
            if not b["pending"]:
                b["pending"] = True
                b["next_t"] = now + cfg.restart_backoff_s * (
                    2 ** b["attempts"]
                )
                obs.point(
                    "fleet.rejoin_scheduled", replica=r.rid,
                    attempt=b["attempts"] + 1,
                    backoff_s=round(b["next_t"] - now, 3),
                )
            elif now >= b["next_t"]:
                try:
                    self.rejoin_replica(r.rid)
                except RuntimeError:
                    pass  # breaker opened (budget raced) — ledger has it

    def _health_sweep(self) -> None:
        for r in list(self.replicas):
            if r.state == "faulted" and (
                r.server is not None
                and (r.server.queued_count or r.server.active_count)
            ):
                # the pump is dead: reclaim everything it held
                self._requeue_from(r, running_too=True, cause="splice")

    def _finish_sweep(self) -> None:
        with self._lock:
            inflight = list(self._inflight)
        for fh in inflight:
            sub = fh._sub
            if sub is None:
                continue
            if fh._divergent:
                # splice mismatch pending: the monitor sweep re-routes
                # this handle — finishing it now would deliver a stream
                # cut at the divergence point.
                continue
            if sub.status == "requeued":
                # reclaim raced us (drain path) — the requeue already
                # moved fh back to its tenant queue; nothing to do here.
                continue
            if not sub.done.is_set():
                continue
            reason = sub.finish_reason or "done"
            with self._lock:
                if fh in self._inflight:
                    self._inflight.remove(fh)
            t = self._tenant(fh.tenant)
            if reason in ("eos", "length"):
                t.completed += 1
                t.tokens_done += len(fh.new_tokens)
                self.stats["completed"] += 1
                with obs.trace_ctx(fh.trace):
                    obs.counter("fleet.completed", tenant=fh.tenant)
                    obs.counter(
                        "fleet.tenant_tokens", len(fh.new_tokens),
                        tenant=fh.tenant,
                    )
            else:
                key = "cancelled" if reason == "cancelled" else "deadline"
                self.stats[key] += 1
            fh._finish(reason)

    def _reap_queued(self, t: _Tenant, now: float) -> None:
        finished: List = []
        with self._lock:  # submit() appends under the same lock
            keep: Deque[FleetHandle] = collections.deque()
            for fh in t.queue:
                if fh._cancel:
                    finished.append((fh, "cancelled"))
                elif fh.expired(now):
                    finished.append((fh, "deadline"))
                else:
                    keep.append(fh)
            t.queue = keep
        for fh, reason in finished:
            key = "cancelled" if reason == "cancelled" else "deadline"
            self.stats[key] += 1
            # Trace-stamped: the router-side terminal marker for a
            # request reaped before (or between) replica attempts.
            with obs.trace_ctx(fh.trace):
                obs.counter(
                    "serve.cancelled" if reason == "cancelled"
                    else "serve.evicted_deadline",
                    tenant=t.name,
                )
            fh._finish(reason)

    def _dispatch(self, now: float) -> None:
        """Deficit round robin with a cursor that persists across ticks.

        Classic DRR semantics (the properties the fairness oracle
        pins): the cursor banks ``quantum × weight`` exactly once per
        *fresh visit* to a backlogged tenant, serves that tenant until
        its deficit no longer covers the head request's token cost (or
        its queue empties), then advances. Crucially, when fleet
        capacity runs out **mid-service**, the cursor stays put and
        resumes the same tenant — without banking again — on the next
        tick; otherwise a fleet whose slots free up one at a time would
        hand every trickle slot to whichever tenant the scan happened
        to start at, and weights would stop meaning anything. A tenant
        that empties its queue forfeits its deficit (no credit
        hoarding while idle)."""
        with self._lock:
            tenants = sorted(self._tenants.values(), key=lambda t: t.name)
        for t in tenants:
            self._reap_queued(t, now)
        if not any(t.queue for t in tenants):
            for t in tenants:
                t.deficit = 0.0
            return
        # Admission capacity = slots that can PREFILL. Decode-pool
        # replicas never take submissions (their work arrives through
        # the handoff sweep), so they are invisible here; adoptions
        # bypass this budget entirely (no prefill slot is consumed).
        capacity = sum(
            r.free_slot_count() for r in self.replicas
            if r.placeable and r.pool != "decode"
        )
        idle_visits = 0
        while capacity > 0 and idle_visits <= len(tenants):
            t = tenants[self._drr_cursor % len(tenants)]
            if not t.queue:
                t.deficit = 0.0
                self._drr_cursor += 1
                self._drr_fresh = True
                idle_visits += 1
                continue
            if self._drr_fresh:
                t.deficit += self.config.quantum * t.weight
                self._drr_fresh = False
            served = 0
            blocked = False
            while t.queue and capacity > 0:
                fh = t.queue[0]
                cost = float(fh.request.max_new_tokens)
                if t.deficit < cost:
                    break
                if self._try_adopt(fh, now):
                    # Directory hit: the stream was seated straight on
                    # a decode replica (or finished outright) — no
                    # prefill slot consumed, so `capacity` is untouched.
                    with self._lock:
                        t.queue.popleft()
                    t.deficit -= cost
                    served += 1
                    continue
                replica = self._place(fh)
                if replica is None:
                    blocked = True  # no replica can admit this request
                    break
                with self._lock:
                    t.queue.popleft()
                t.deficit -= cost
                self._dispatch_to(replica, fh)
                capacity -= 1
                served += 1
            if capacity <= 0 and t.queue and not blocked:
                return  # resume THIS tenant next tick (cursor stays)
            # service ended on its own terms: move on
            if not t.queue:
                t.deficit = 0.0
            self._drr_cursor += 1
            self._drr_fresh = True
            idle_visits = 0 if served else idle_visits + 1

    def _place(self, fh: FleetHandle) -> Optional[Replica]:
        spec = fh.request.spec()
        candidates = [
            r for r in self.replicas
            if r.pool != "decode" and r.placeable and r.can_take(spec)
        ]
        if not candidates:
            return None
        mode = self.config.placement
        if mode == "rr":
            self._rr_cursor += 1
            return candidates[self._rr_cursor % len(candidates)]
        if mode == "affinity":
            hits = [
                (r.prefix_hit_blocks(fh.request.prompt), r)
                for r in candidates
            ]
            best = max(h for h, _ in hits)
            if best > 0:
                candidates = [r for h, r in hits if h == best]
                if len(candidates) == 1:
                    return candidates[0]
        # least-loaded: most free capacity wins (slot + block fractions)
        def score(r: Replica) -> float:
            ld = r.load()
            return ld["free_slots"] + ld["free_blocks"]

        return max(candidates, key=score)

    def _dispatch_to(self, replica: Replica, fh: FleetHandle) -> None:
        if self.directory is not None and replica.pool == "prefill":
            if (
                replica.server is not None and replica.engine is not None
                and replica.engine.prefix_cache
            ):
                # Arm pin-at-export before any request reaches the
                # server: the pump pins a greedy export's full prefix
                # blocks on its own thread, so every block the
                # directory maps stays resident (never a router-thread
                # allocator mutation racing an eviction).
                replica.server.handoff_pin = True
            if self.config.prefetch:
                self._chain_prefetch(replica, fh)
        max_new = fh.request.max_new_tokens
        if self._brownout_max_new is not None:
            # Brownout cap applies at dispatch (new placements only —
            # running streams keep their budget). Replays of a capped
            # request use the same cap via the unchanged fh.request, so
            # the splice contract is unaffected.
            max_new = min(max_new, self._brownout_max_new)
        req = dataclasses.replace(
            fh.request,
            max_new_tokens=max_new,
            on_token=lambda _h, toks, fh=fh: fh._ingest(toks),
            # The trace rides the Request across the router→replica
            # thread boundary (thread-locals do not), so every attempt
            # keeps the original request's causal identity.
            trace=fh.trace,
            # fleet-level deadline already tracked on the FleetHandle;
            # the remaining budget rides to the replica so running
            # streams still get evicted there.
            deadline_ms=(
                None if fh._deadline_t is None
                else max((fh._deadline_t - time.monotonic()) * 1e3, 1.0)
            ),
        )
        sub = replica.submit(req)
        fh._attach(sub, replica.rid)
        with self._lock:
            self._inflight.append(fh)
        self.stats["dispatched"] += 1
        cause = fh._reroute_cause
        with obs.trace_ctx(fh.trace, cause=cause):
            if cause is not None:
                # The re-route child span, linked to the parent trace:
                # covers the requeue→re-dispatch window so the wall a
                # chaos-plane intervention cost the request is an
                # attributed phase, not an unexplained gap.
                t_rq = fh._requeued_t
                dur = 0.0 if t_rq is None else max(
                    time.monotonic() - t_rq, 0.0
                )
                obs.span_event(
                    "fleet.reroute", dur, t=t_rq, req=fh.id,
                    replica=replica.rid, src=fh._reroute_from,
                    attempt=fh.attempts,
                )
            obs.counter("fleet.dispatched", tenant=fh.tenant,
                        replica=replica.rid)
        fh._reroute_cause = None
        fh._requeued_t = None
        fh._reroute_from = None

    # -- disaggregation: handoff, directory, migration ---------------------

    def _fh_for_sub(self, sub: RequestHandle) -> Optional[FleetHandle]:
        with self._lock:
            for fh in self._inflight:
                if fh._sub is sub:
                    return fh
        return None

    def _publish_handoff(self, rid: int, fh: FleetHandle,
                         state: Dict[str, Any], *,
                         resident: bool = True) -> None:
        """Publish a greedy prefill export into the fleet directory.
        ``resident=False`` (the exporter is faulted) publishes payload
        only — the directory must never map blocks on a dead engine."""
        if self.directory is None or float(state["temp"]) != 0.0:
            return
        bids = state.get("pinned", []) if resident else []
        self.directory.publish(
            rid, fh.request.prompt, bids, state["payload"],
            first_token=int(state["token"]),
            block_size=int(state["block_size"]),
        )

    def _chain_prefetch(self, replica: Replica, fh: FleetHandle) -> None:
        """Directory chain prefetch: when the fleet holds more leading
        full blocks of this prompt than ``replica`` does locally, seed
        them into its prefix cache before the submit — the prefill then
        computes only the divergent suffix (prefill-once-per-fleet for
        shared prefixes, not just identical prompts)."""
        eng = replica.engine
        if eng is None or eng.allocator is None or not eng.prefix_cache:
            return
        n, ent, payload = self.directory.lookup_chain(
            fh.request.prompt, eng.block_size
        )
        if ent is None or n < 1:
            return
        if replica.prefix_hit_blocks(fh.request.prompt) >= n:
            return
        prompt = np.asarray(fh.request.prompt, np.int32).reshape(-1)
        seeded = replica.inject_prefix(
            prompt[: n * eng.block_size], payload
        )
        if seeded:
            self.stats["directory_hits"] += 1
            with obs.trace_ctx(fh.trace):
                obs.counter(
                    "serve.directory_hits", req=fh.id, kind="prefetch",
                    blocks=seeded,
                )

    def _try_adopt(self, fh: FleetHandle, now: float) -> bool:
        """Fleet-wide prefix directory fast path: an identical greedy
        prompt already prefilled somewhere in the fleet is ADOPTED —
        decode state transplanted straight from the directory entry
        onto a decode replica, zero prefill programs run. Returns True
        when the handle was seated (or finished outright); False falls
        through to normal placement."""
        if self.directory is None or fh.new_tokens or fh._cancel:
            return False
        req = fh.request
        if float(req.temperature) != 0.0:
            return False
        ent = self.directory.lookup(req.prompt)
        if ent is None:
            return False
        first = int(ent["first_token"])
        eos = -1 if req.eos_token is None else int(req.eos_token)
        if first == eos or req.max_new_tokens <= 1:
            # The adopted stream is already complete: deliver the
            # deterministic first token and finish locally.
            self.directory.adopt(req.prompt)
            self.stats["directory_hits"] += 1
            with obs.trace_ctx(fh.trace):
                obs.counter(
                    "serve.directory_hits", req=fh.id, kind="adopt"
                )
            fh._ingest([first])
            self._complete_local(fh, "eos" if first == eos else "length")
            return True
        bs = int(ent["block_size"])
        t = int(np.asarray(req.prompt).reshape(-1).shape[0])
        # Same budget prefill would have allocated (decode-pool engines
        # run spec_k=0): positions 0 .. t + max_new - 2.
        need = -(-(t + int(req.max_new_tokens) - 1) // bs)
        state = {
            "block_size": bs,
            "n_blocks": need,
            "blocks": [],
            "written": t,
            "token": first,
            "temp": 0.0,
            "top_k": 0,
            "top_p": 0.0,
            "eos": eos,
            "ladder": None,
            "cursor": 1,
            "payload": ent["payload"],
            "handoff_t": now,
        }
        dst = self._decode_target(state)
        if dst is None:
            return False  # no decode room: the prefill path keeps liveness
        self.directory.adopt(req.prompt)
        self.stats["directory_hits"] += 1
        with obs.trace_ctx(fh.trace):
            obs.counter("serve.directory_hits", req=fh.id, kind="adopt")
        fh._ingest([first])
        if not self._import_to(
            dst, fh, state, cause="handoff", src=int(ent["owner"]), now=now
        ):
            # Lost the room mid-import. The delivered first token is
            # safe: a from-scratch dispatch re-verifies it (splice).
            return False
        return True

    def _complete_local(self, fh: FleetHandle, reason: str) -> None:
        """Finish a handle the router itself completed (adoption edge
        cases) with exactly the accounting ``_finish_sweep`` does."""
        with self._lock:
            if fh in self._inflight:
                self._inflight.remove(fh)
        t = self._tenant(fh.tenant)
        t.completed += 1
        t.tokens_done += len(fh.new_tokens)
        self.stats["completed"] += 1
        with obs.trace_ctx(fh.trace):
            obs.counter("fleet.completed", tenant=fh.tenant)
            obs.counter(
                "fleet.tenant_tokens", len(fh.new_tokens), tenant=fh.tenant
            )
        fh._finish(reason)

    def _handoff_sweep(self, now: float) -> None:
        """Collect prefill exports, publish greedy ones to the
        directory, and seat every pending export on a decode replica.
        An export with no room retries next tick — backpressure, never
        a drop; ``_requeue_from`` feeds this same queue when a prefill
        replica faults mid-handoff (the export is host data and
        outlives its producer)."""
        if not self.config.disagg:
            return
        for r in self.replicas:
            if r.pool != "prefill" or r.server is None:
                continue
            for sub, state in r.server.take_handoffs():
                fh = self._fh_for_sub(sub)
                if fh is None:
                    continue  # handle already finished: drop the export
                self._publish_handoff(r.rid, fh, state)
                self._pending_handoffs.append(
                    (fh, state, r.rid, "handoff")
                )
                self.stats["handoffs"] += 1
        retry: Deque[Any] = collections.deque()
        while self._pending_handoffs:
            fh, state, src, cause = self._pending_handoffs.popleft()
            if fh.done.is_set():
                continue
            if fh._cancel:
                self._drop_handoff(fh)
                continue
            if fh.expired(now):
                # No replica owns a parked export, so the router is
                # the one enforcing its deadline.
                self._drop_handoff(fh, reason="deadline")
                continue
            dst = self._decode_target(state)
            if dst is None or not self._import_to(
                dst, fh, state, cause=cause, src=src, now=now
            ):
                retry.append((fh, state, src, cause))
        self._pending_handoffs = retry

    def _drop_handoff(self, fh: FleetHandle,
                      reason: str = "cancelled") -> None:
        """Cancel/deadline-mid-handoff: the exported blocks were
        already released at export and the payload is host data, so
        dropping the state leaks nothing — only the handle needs its
        terminal accounting."""
        with self._lock:
            if fh in self._inflight:
                self._inflight.remove(fh)
        if not fh.done.is_set():
            key = "cancelled" if reason == "cancelled" else "deadline"
            self.stats[key] += 1
            with obs.trace_ctx(fh.trace):
                obs.counter(
                    "serve.cancelled" if reason == "cancelled"
                    else "serve.evicted_deadline",
                    tenant=fh.tenant,
                )
            fh._finish(reason)

    def _decode_target(self, state: Dict[str, Any],
                       exclude: Optional[int] = None) -> Optional[Replica]:
        """Best decode-capable replica that can seat ``state`` right
        now (free slot + allocatable blocks), least-loaded first."""
        cands = [
            r for r in self.replicas
            if r.pool in ("decode", "mixed") and r.placeable
            and r.rid != exclude and r.engine is not None
            and r.engine.can_import(state)
        ]
        if not cands:
            return None

        def score(r: Replica) -> float:
            ld = r.load()
            return ld["free_slots"] + ld["free_blocks"]

        return max(cands, key=score)

    def _import_to(self, replica: Replica, fh: FleetHandle,
                   state: Dict[str, Any], *, cause: str,
                   src: Optional[int], now: float) -> bool:
        """Seat an exported slot state on ``replica`` as a RUNNING
        stream and splice the fleet handle onto it. The pump is parked
        around the import (slot + pool mutation must not race a
        stepping pump); the new attempt is seeded with the delivered
        prefix and attached at ``seen=len(prefix)`` so it emits only
        fresh tokens. Returns False when the import lost its room —
        the caller retries elsewhere or later."""
        if replica.threaded and not replica.pause(
            timeout=self.config.fault_join_s
        ):
            return False
        try:
            prior = list(fh.new_tokens)
            req = dataclasses.replace(
                fh.request,
                on_token=lambda _h, toks, fh=fh: fh._ingest(toks),
                trace=fh.trace,
                deadline_ms=(
                    None if fh._deadline_t is None
                    else max(
                        (fh._deadline_t - time.monotonic()) * 1e3, 1.0
                    )
                ),
            )
            try:
                with obs.bound_bus(replica.bus):
                    sub = replica.server.import_running(
                        req, state, prior_tokens=prior
                    )
            except (RuntimeError, BlockPoolExhausted):
                return False
        finally:
            if replica.threaded:
                replica.resume()
        fh._attach(sub, replica.rid, seen=len(prior))
        with self._lock:
            if fh not in self._inflight:
                self._inflight.append(fh)
        dur = max(now - float(state.get("handoff_t", now)), 0.0)
        span = (
            "fleet.migration" if cause == "migration" else "fleet.handoff"
        )
        with obs.trace_ctx(fh.trace, cause=cause):
            obs.span_event(
                span, dur, req=fh.id, replica=replica.rid, src=src,
                attempt=fh.attempts,
            )
            if cause == "migration":
                obs.counter("serve.migrations")
            else:
                obs.gauge("serve.handoff_ms", round(dur * 1e3, 3))
        return True

    def migrate(self, src_rid: int, dst_rid: Optional[int] = None,
                *, max_streams: int = 1) -> int:
        """Scheduled live KV-block migration (docs/SERVING.md): move up
        to ``max_streams`` running streams off replica ``src_rid`` as
        state transplants — export under a parked pump, import on
        ``dst_rid`` (or the best-fit decode-capable replica), splice
        bitwise at the exact delivered token, zero drops. The splice
        machinery that heals faults, now a first-class operation:
        defragment a pool, empty a replica before drain, rebalance.
        A stream that finds no import room falls back to the
        requeue-replay path (still lossless — the splice verifies the
        replayed prefix). Returns the number of streams moved by
        transplant. Paged, non-speculative engines only
        (``export_slot`` contract)."""
        if dst_rid is not None and dst_rid == src_rid:
            raise ValueError("migrate needs distinct src and dst replicas")
        src = self._replica(src_rid)
        if src.server is None:
            return 0
        if src.threaded and not src.pause(
            timeout=self.config.fault_join_s
        ):
            raise TimeoutError(
                f"replica {src_rid} pump unresponsive to migrate pause"
            )
        moved = 0
        now = time.monotonic()
        try:
            with self._lock:
                live = [
                    fh for fh in self._inflight
                    if fh.replica_id == src_rid and fh._sub is not None
                    and not fh.done.is_set()
                ]
            for fh in live[:max_streams]:
                with obs.bound_bus(src.bus):
                    state = src.server.export_running(fh._sub)
                if state is None:
                    continue  # not running here (handoff-parked, raced)
                dst = (
                    self._replica(dst_rid) if dst_rid is not None
                    else self._decode_target(state, exclude=src_rid)
                )
                if dst is not None and self._import_to(
                    dst, fh, state, cause="migration", src=src_rid,
                    now=now,
                ):
                    moved += 1
                    self.stats["migrations"] += 1
                    continue
                # No destination (or it lost its room): requeue-replay.
                with self._lock:
                    if fh in self._inflight:
                        self._inflight.remove(fh)
                fh._detach()
                fh._reroute_cause = "migration"
                fh._requeued_t = now
                fh._reroute_from = src_rid
                with self._lock:
                    self._tenant(fh.tenant).queue.appendleft(fh)
                    self.stats["requeued"] += 1
        finally:
            if src.threaded:
                src.resume()
        return moved

    def pool_pressure(self, pool: str) -> float:
        """Per-pool autoscale signal (the FleetController's per-pool
        watermarks): prefill pressure is the admission backlog over
        prefill slots, decode pressure is seated streams plus pending
        handoffs over decode slots; both saturate on KV blocks —
        :meth:`pressure` semantics, restricted to one pool."""
        ready = [
            r for r in self.replicas if r.placeable and r.pool == pool
        ]
        slots = sum(r.engine.num_slots for r in ready)
        if pool == "prefill":
            with self._lock:
                demand = sum(
                    len(t.queue) for t in self._tenants.values()
                )
            demand += sum(
                r.server.active_count + r.server.queued_count
                for r in ready
            )
        else:
            demand = len(self._pending_handoffs) + sum(
                r.server.active_count + r.server.queued_count
                for r in ready
            )
        p = demand / max(slots, 1)
        for r in ready:
            a = r.engine.allocator
            if a is not None:
                p = max(p, 1.0 - a.free_count / max(a.capacity, 1))
        return p

    # -- brownout ladder actions (scheduler.BrownoutLadder drives) ---------

    def apply_brownout_stage(self, stage, on: bool, key: int = 0) -> None:
        """Apply (``on=True``) or revert one declared degradation stage
        (docs/ROBUSTNESS.md degradation ladder):

        * ``spec_off`` — suspend speculative decode on every replica
          engine (the plain decode program is already in the closed
          set, so this compiles nothing);
        * ``max_new:N`` — cap ``max_new_tokens`` for newly dispatched
          requests at N;
        * ``shed:K`` — shed the K lowest-weight tenant lanes: queued
          and arriving requests finish with the distinct ``brownout``
          outcome, never silently dropped.

        ``key`` identifies the stage instance so revert releases
        exactly what this stage shed."""
        if stage.kind == "spec_off":
            for r in self.replicas:
                if r.engine is not None:
                    r.engine.spec_suspended = on
        elif stage.kind == "max_new":
            self._brownout_max_new = int(stage.value) if on else None
        elif stage.kind == "shed":
            if on:
                with self._lock:
                    ranked = sorted(
                        (
                            t for t in self._tenants.values()
                            if t.name not in self._shed_tenants
                        ),
                        key=lambda t: (t.weight, t.name),
                    )
                shed = {t.name for t in ranked[: int(stage.value)]}
                self._shed_by_stage[key] = shed
                self._shed_tenants |= shed
                for name in shed:
                    self._flush_shed_lane(name)
            else:
                self._shed_tenants -= self._shed_by_stage.pop(key, set())
        else:
            raise ValueError(f"unknown brownout stage {stage.kind!r}")

    def _flush_shed_lane(self, tenant: str) -> None:
        """Finish every queued request of a newly shed lane with the
        ``brownout`` outcome (running streams are never interrupted —
        shedding relieves *future* load)."""
        with self._lock:
            t = self._tenants.get(tenant)
            victims = list(t.queue) if t is not None else []
            if t is not None:
                t.queue.clear()
                t.deficit = 0.0
        for fh in victims:
            self.stats["brownout"] += 1
            with obs.trace_ctx(fh.trace, cause="brownout"):
                obs.counter("serve.brownout_shed", tenant=tenant)
            fh._finish("brownout")

    # -- autoscale signal --------------------------------------------------

    def pressure(self) -> float:
        """The autoscaling signal: demanded capacity over ready
        capacity. 1.0 = the fleet's slots exactly cover current demand
        (router backlog + replica queues + running streams); above it,
        work is waiting; paged fleets also saturate on KV blocks
        (whichever is scarcer). Derived from the same quantities the
        ``serve.slot_occupancy`` / queue / block-pool rollups carry —
        this is their fleet-level composition."""
        ready = [r for r in self.replicas if r.placeable]
        total_slots = sum(r.engine.num_slots for r in ready)
        with self._lock:
            backlog = sum(len(t.queue) for t in self._tenants.values())
        demand = backlog + sum(
            r.server.active_count + r.server.queued_count for r in ready
        )
        slot_pressure = demand / max(total_slots, 1)
        block_pressure = 0.0
        for r in ready:
            if r.engine.allocator is not None:
                a = r.engine.allocator
                used = 1.0 - a.free_count / max(a.capacity, 1)
                block_pressure = max(block_pressure, used)
        return max(slot_pressure, block_pressure)

    def _emit_gauges(self, backlog: int, inflight: int) -> None:
        p = self.pressure()
        self.last_pressure = p
        obs.gauge("serve.fleet_pressure", round(p, 4))
        obs.gauge(
            "serve.fleet_replicas",
            float(sum(1 for r in self.replicas if r.placeable)),
        )
        obs.gauge("serve.fleet_queued", float(backlog))
        obs.gauge("serve.fleet_active", float(inflight))
        # Health-plane gauges (docs/OBSERVABILITY.md; obs_watch renders
        # them as the fleet-health row).
        obs.gauge(
            "fleet.quarantined",
            float(sum(1 for r in self.replicas if r.quarantined)),
        )
        obs.gauge(
            "fleet.breaker_open",
            float(sum(1 for b in self._breakers.values() if b["open"])),
        )
        obs.gauge(
            "fleet.brownout_stage",
            float(self.brownout.level) if self.brownout is not None else 0.0,
        )
        if self.config.disagg:
            obs.gauge(
                "fleet.prefill_replicas",
                float(sum(
                    1 for r in self.replicas
                    if r.pool == "prefill" and r.placeable
                )),
            )
            obs.gauge(
                "fleet.decode_replicas",
                float(sum(
                    1 for r in self.replicas
                    if r.pool == "decode" and r.placeable
                )),
            )

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> None:
        """Pump until every submitted request has finished."""
        t0 = time.monotonic()
        while self.step():
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError("fleet drain timed out")
            time.sleep(0.0005)

    def serve_forever(self, stop: threading.Event,
                      idle_sleep_s: float = 0.001) -> None:
        while not stop.is_set():
            if not self.step():
                time.sleep(idle_sleep_s)
        self.drain()

    def close(self) -> None:
        """Stop accepting, drain everything, stop every replica pump."""
        self._closed = True
        self.drain()
        for r in self.replicas:
            r.stop()

    def tenant_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant accounting (the fairness gate's numerator)."""
        with self._lock:
            return {
                t.name: {
                    "weight": t.weight,
                    "queued": len(t.queue),
                    "completed": t.completed,
                    "tokens_done": t.tokens_done,
                }
                for t in self._tenants.values()
            }

    def fleet_snapshot(self) -> List[Dict[str, Any]]:
        return [r.snapshot() for r in self.replicas]


def build_fleet(
    model,
    params,
    *,
    fleet_config: Optional[FleetConfig] = None,
    serve_config: Optional[ServeConfig] = None,
    max_len: Optional[int] = None,
    obs_dir: Optional[str] = None,
    threaded: bool = True,
    start: bool = True,
) -> Router:
    """Router + N replicas from the env-driven configs (the fleet twin
    of ``Server.build``). ``obs_dir`` defaults to ``$OBS_DIR`` so each
    replica lands its own ``events-p0-s<k>.jsonl`` stream whenever the
    process is capturing events."""
    fcfg = fleet_config or FleetConfig.from_env()
    scfg = serve_config or ServeConfig.from_env()
    if obs_dir is None:
        obs_dir = os.environ.get("OBS_DIR") or None
    router = Router(config=fcfg)
    npre, _ = fcfg.pool_split()
    for k in range(fcfg.replicas):
        pool = "mixed"
        if fcfg.disagg:
            pool = "prefill" if k < npre else "decode"
        router.add_replica(
            Replica(
                k, model, params, scfg, max_len=max_len, obs_dir=obs_dir,
                pool=pool,
            ),
            start=start, threaded=threaded,
        )
    return router
