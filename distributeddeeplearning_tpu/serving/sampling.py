"""Per-slot, data-driven token sampling for the batched decode step.

``inference._sample`` specialises the compiled program on the sampling
config (temperature / top-k / top-p are Python values). A slot engine
cannot: every admission would recompile the decode step. Here the knobs
are **per-slot data** — ``[num_slots]`` vectors fed each step — so one
compiled program serves any mix of greedy and sampled requests, and the
disabled sentinels (``temperature <= 0`` = greedy, ``top_k == 0`` /
``top_p == 0`` = filter off) are resolved with ``where`` selects, not
Python branches.

Performance shape: a full-vocab **sort is only paid when some slot
actually runs nucleus sampling** — a batch-level ``lax.cond`` (legal
on data: both branches are traced into the one program, one executes)
routes greedy/top-k traffic through ``lax.top_k`` at a static
``top_k_cap`` instead (decode at 32k vocab is otherwise dominated by
8× per-slot sorts, not the model). This mirrors the reference's own
top-k fast path.

Bitwise contract: for any one slot, the emitted token equals what
``inference._sample`` produces for the same ``[1, vocab]`` logits row,
key and config (``tests/test_serving.py`` sweeps the config matrix).
That holds because every numeric step mirrors the reference — same f32
upcast and temperature divide, the k-th threshold *by value* (the k-th
largest is the same number whether ``lax.top_k`` or a sort finds it),
the nucleus keep-rule computed on the *unfiltered* sorted distribution,
filters composed in the same order, and the categorical draw made with
the same ``[1, vocab]`` operand shape so the per-lane threefry bits are
identical under ``vmap``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Largest per-request top_k the sort-free path serves; requests above it
# (and below vocab) are rejected at admission with a pointer to this
# knob (SlotEngine(top_k_cap=...) / SERVE_TOP_K_CAP). top_k >= vocab
# keeps every token — the reference clamps it, so admission maps it to
# "filter off" and parity is preserved.
DEFAULT_TOP_K_CAP = 128


def _scale(logits, temperature):
    return logits.astype(jnp.float32) / jnp.where(
        temperature > 0, temperature, 1.0
    )


def _draw(out, key, temperature, greedy):
    sampled = jax.random.categorical(key, out[None, :], axis=-1)[0].astype(
        jnp.int32
    )
    return jnp.where(temperature > 0, sampled, greedy)


def _row_topk(logits, key, temperature, top_k, top_k_cap):
    """Sort-free row sampler (greedy / top-k): threshold from
    ``lax.top_k`` at the static cap — same k-th *value* as a sort."""
    neg_inf = jnp.finfo(jnp.float32).min
    greedy = jnp.argmax(logits).astype(jnp.int32)
    scaled = _scale(logits, temperature)
    cap = min(top_k_cap, scaled.shape[-1])
    top_vals = lax.top_k(scaled, cap)[0]
    kth = top_vals[jnp.clip(top_k, 1, cap) - 1]
    out = jnp.where(top_k > 0, jnp.where(scaled < kth, neg_inf, scaled),
                    scaled)
    return _draw(out, key, temperature, greedy)


def _row_full(logits, key, temperature, top_k, top_p):
    """Full-sort row sampler (any config, needed once nucleus filtering
    is in play): one descending sort serves both filters."""
    neg_inf = jnp.finfo(jnp.float32).min
    greedy = jnp.argmax(logits).astype(jnp.int32)
    scaled = _scale(logits, temperature)
    vocab = scaled.shape[-1]
    sorted_desc = jnp.sort(scaled)[::-1]
    kth = sorted_desc[jnp.clip(top_k, 1, vocab) - 1]
    out = jnp.where(top_k > 0, jnp.where(scaled < kth, neg_inf, scaled),
                    scaled)
    # Nucleus rule on the UNFILTERED sorted distribution (reference
    # behaviour): keep tokens while the mass before them is < p.
    probs = jax.nn.softmax(sorted_desc)
    cum = jnp.cumsum(probs)
    keep_sorted = (cum - probs) < top_p
    threshold = jnp.min(jnp.where(keep_sorted, sorted_desc, jnp.inf))
    filtered_p = jnp.where(out < threshold, neg_inf, out)
    out = jnp.where(top_p > 0, filtered_p, out)
    return _draw(out, key, temperature, greedy)


def sample_slot(logits, key, temperature, top_k, top_p,
                top_k_cap: int = DEFAULT_TOP_K_CAP):
    """One slot's next token from ``[vocab]`` logits.

    ``temperature <= 0`` → greedy argmax (key unused). ``top_k == 0`` /
    ``top_p == 0`` disable the respective filter; active values follow
    ``inference._sample`` semantics (filters compose, intersection).
    All three are traced scalars — no recompilation across requests.
    """
    return lax.cond(
        top_p > 0,
        lambda: _row_full(logits, key, temperature, top_k, top_p),
        lambda: _row_topk(logits, key, temperature, top_k, top_k_cap),
    )


def sample_slots(logits, keys, temperatures, top_ks, top_ps,
                 top_k_cap: int = DEFAULT_TOP_K_CAP):
    """Vectorised sampler over the slot axis: ``[S, vocab]`` logits +
    per-slot ``[S]`` configs → ``[S]`` tokens. The batch-level cond
    keeps the sort out of the program's hot path whenever no occupied
    slot runs nucleus sampling."""
    return lax.cond(
        jnp.any(top_ps > 0),
        lambda: jax.vmap(_row_full)(logits, keys, temperatures, top_ks,
                                    top_ps),
        lambda: jax.vmap(
            lambda l, k, t, tk: _row_topk(l, k, t, tk, top_k_cap)
        )(logits, keys, temperatures, top_ks),
    )
