"""Per-slot, data-driven token sampling for the batched decode step.

``inference._sample`` specialises the compiled program on the sampling
config (temperature / top-k / top-p are Python values). A slot engine
cannot: every admission would recompile the decode step. Here the knobs
are **per-slot data** — ``[num_slots]`` vectors fed each step — so one
compiled program serves any mix of greedy and sampled requests, and the
disabled sentinels (``temperature <= 0`` = greedy, ``top_k == 0`` /
``top_p == 0`` = filter off) are resolved with ``where`` selects, not
Python branches.

Performance shape: a full-vocab **sort is only paid when some slot
actually runs nucleus sampling** — a batch-level ``lax.cond`` (legal
on data: both branches are traced into the one program, one executes)
routes greedy/top-k traffic through ``lax.top_k`` at a static
``top_k_cap`` instead (decode at 32k vocab is otherwise dominated by
8× per-slot sorts, not the model). This mirrors the reference's own
top-k fast path.

Bitwise contract: for any one slot, the emitted token equals what
``inference._sample`` produces for the same ``[1, vocab]`` logits row,
key and config (``tests/test_serving.py`` sweeps the config matrix).
That holds because every numeric step mirrors the reference — same f32
upcast and temperature divide, the k-th threshold *by value* (the k-th
largest is the same number whether ``lax.top_k`` or a sort finds it),
the nucleus keep-rule computed on the *unfiltered* sorted distribution,
filters composed in the same order, and the categorical draw made with
the same ``[1, vocab]`` operand shape so the per-lane threefry bits are
identical under ``vmap``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Largest per-request top_k the sort-free path serves; requests above it
# (and below vocab) are rejected at admission with a pointer to this
# knob (SlotEngine(top_k_cap=...) / SERVE_TOP_K_CAP). top_k >= vocab
# keeps every token — the reference clamps it, so admission maps it to
# "filter off" and parity is preserved.
DEFAULT_TOP_K_CAP = 128


def _scale(logits, temperature):
    return logits.astype(jnp.float32) / jnp.where(
        temperature > 0, temperature, 1.0
    )


def _draw(out, key, temperature, greedy):
    sampled = jax.random.categorical(key, out[None, :], axis=-1)[0].astype(
        jnp.int32
    )
    return jnp.where(temperature > 0, sampled, greedy)


def _filter_topk(scaled, top_k, top_k_cap):
    """Sort-free filter (greedy / top-k): threshold from ``lax.top_k``
    at the static cap — same k-th *value* as a sort."""
    neg_inf = jnp.finfo(jnp.float32).min
    cap = min(top_k_cap, scaled.shape[-1])
    top_vals = lax.top_k(scaled, cap)[0]
    kth = top_vals[jnp.clip(top_k, 1, cap) - 1]
    return jnp.where(top_k > 0, jnp.where(scaled < kth, neg_inf, scaled),
                     scaled)


def _filter_full(scaled, top_k, top_p):
    """Full-sort filter (any config, needed once nucleus filtering is
    in play): one descending sort serves both filters."""
    neg_inf = jnp.finfo(jnp.float32).min
    vocab = scaled.shape[-1]
    sorted_desc = jnp.sort(scaled)[::-1]
    kth = sorted_desc[jnp.clip(top_k, 1, vocab) - 1]
    out = jnp.where(top_k > 0, jnp.where(scaled < kth, neg_inf, scaled),
                    scaled)
    # Nucleus rule on the UNFILTERED sorted distribution (reference
    # behaviour): keep tokens while the mass before them is < p.
    probs = jax.nn.softmax(sorted_desc)
    cum = jnp.cumsum(probs)
    keep_sorted = (cum - probs) < top_p
    threshold = jnp.min(jnp.where(keep_sorted, sorted_desc, jnp.inf))
    filtered_p = jnp.where(out < threshold, neg_inf, out)
    return jnp.where(top_p > 0, filtered_p, out)


def _row_topk(logits, key, temperature, top_k, top_k_cap):
    """Sort-free row sampler (greedy / top-k)."""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    out = _filter_topk(_scale(logits, temperature), top_k, top_k_cap)
    return _draw(out, key, temperature, greedy)


def _row_full(logits, key, temperature, top_k, top_p):
    """Full-sort row sampler (any config)."""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    out = _filter_full(_scale(logits, temperature), top_k, top_p)
    return _draw(out, key, temperature, greedy)


def sample_slot(logits, key, temperature, top_k, top_p,
                top_k_cap: int = DEFAULT_TOP_K_CAP):
    """One slot's next token from ``[vocab]`` logits.

    ``temperature <= 0`` → greedy argmax (key unused). ``top_k == 0`` /
    ``top_p == 0`` disable the respective filter; active values follow
    ``inference._sample`` semantics (filters compose, intersection).
    All three are traced scalars — no recompilation across requests.
    """
    return lax.cond(
        top_p > 0,
        lambda: _row_full(logits, key, temperature, top_k, top_p),
        lambda: _row_topk(logits, key, temperature, top_k, top_k_cap),
    )


def sample_slots(logits, keys, temperatures, top_ks, top_ps,
                 top_k_cap: int = DEFAULT_TOP_K_CAP):
    """Vectorised sampler over the slot axis: ``[S, vocab]`` logits +
    per-slot ``[S]`` configs → ``[S]`` tokens. The batch-level cond
    keeps the sort out of the program's hot path whenever no occupied
    slot runs nucleus sampling."""
    return lax.cond(
        jnp.any(top_ps > 0),
        lambda: jax.vmap(_row_full)(logits, keys, temperatures, top_ks,
                                    top_ps),
        lambda: jax.vmap(
            lambda l, k, t, tk: _row_topk(l, k, t, tk, top_k_cap)
        )(logits, keys, temperatures, top_ks),
    )


# ---------------------------------------------------------------------------
# Speculative verify (docs/SERVING.md): rejection-sampling acceptance
# ---------------------------------------------------------------------------
#
# Both draft sources (int8 greedy self-draft, n-gram prompt lookup) are
# DETERMINISTIC proposers — the draft distribution q is a point mass at
# the proposed token. The standard speculative-sampling rule (Leviathan
# et al.; Chen et al.) then specialises to the prompt-lookup form:
#
#     accept d with probability min(1, p(d)/q(d)) = p(d);
#     on rejection, sample from norm(max(0, p - q)) = p with d masked
#     out (renormalised); if every draft is accepted, draw one bonus
#     token from the last position's p.
#
# Marginally P(x) = [x==d]·p(d) + (1-p(d))·p(x)(1-[x==d])/(1-p(d)) =
# p(x): the output distribution is EXACTLY the target's, whatever the
# proposals (tests/test_serving_spec.py pins it with a chi-squared
# bound against inference._sample). For greedy slots (temperature <= 0)
# the rule degenerates to argmax equality, so the committed stream is
# the target's greedy chain token for token.


def _spec_row(logits_row, drafts_row, keys_row, temperature, top_k,
              top_p, top_k_cap):
    """One slot's verify: ``[K+1, vocab]`` target logits (position j
    conditioned on the committed context + drafts ``< j``), ``[K]``
    proposed tokens, ``[K+1, 2]`` per-position keys. Returns
    ``(committed [K+1], accepted_drafts scalar)`` — entries past
    ``accepted + 1`` are padding the caller never reads.

    Each position's key splits into two independent sub-draws
    (``fold_in`` 0/1): the acceptance uniform and the residual/bonus
    categorical — a rejected position's unused draws may share a key
    with a later tick's fresh draws at the same output index, which is
    statistically inert because no committed token ever depended on
    them."""
    k = drafts_row.shape[0]
    neg_inf = jnp.finfo(jnp.float32).min
    greedy = jnp.argmax(logits_row, axis=-1).astype(jnp.int32)  # [K+1]
    filt = jax.vmap(
        lambda l: lax.cond(
            top_p > 0,
            lambda: _filter_full(_scale(l, temperature), top_k, top_p),
            lambda: _filter_topk(_scale(l, temperature), top_k, top_k_cap),
        )
    )(logits_row)  # [K+1, vocab] f32, -inf where filtered
    probs = jax.nn.softmax(filt, axis=-1)
    p_draft = jnp.take_along_axis(
        probs[:k], drafts_row[:, None], axis=-1
    )[:, 0]  # [K] target prob of each proposal
    u = jax.vmap(
        lambda kk: jax.random.uniform(jax.random.fold_in(kk, 0))
    )(keys_row[:k])
    accept = jnp.where(temperature > 0, u < p_draft, drafts_row == greedy[:k])
    a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32))).astype(jnp.int32)
    # Residual (positions < K: this proposal masked out, implicit
    # renormalisation in the categorical) / bonus (position K, unmasked)
    # draws at EVERY position; index a selects the one that commits.
    # The [1, vocab] operand shape mirrors _draw's per-lane bits.
    vocab_ids = jnp.arange(filt.shape[-1])[None, :]
    mask_tok = jnp.concatenate(
        [drafts_row, jnp.full((1,), -1, jnp.int32)]
    )  # -1 never matches a vocab id: the bonus row stays unmasked
    res = jnp.where(vocab_ids == mask_tok[:, None], neg_inf, filt)
    draws = jax.vmap(
        lambda kk, l: jax.random.categorical(
            jax.random.fold_in(kk, 1), l[None, :], axis=-1
        )[0].astype(jnp.int32)
    )(keys_row, res)
    final = jnp.where(temperature > 0, draws, greedy)  # [K+1]
    idx = jnp.arange(k + 1)
    pad_drafts = jnp.concatenate([drafts_row, jnp.zeros((1,), jnp.int32)])
    committed = jnp.where(
        idx < a, pad_drafts, jnp.where(idx == a, final, 0)
    )
    return committed, a


def spec_verify_slots(logits, drafts, keys, temperatures, top_ks, top_ps,
                      top_k_cap: int = DEFAULT_TOP_K_CAP):
    """Vectorised speculative verify over the slot axis.

    ``logits`` ``[S, K+1, vocab]`` (the batched verify forward over
    ``[committed_next, d_1 .. d_K]``), ``drafts`` ``[S, K]``, ``keys``
    ``[S, K+1, 2]``, per-slot configs ``[S]``. Returns
    ``(committed [S, K+1] int32, accepted [S] int32)`` — slot ``i``
    commits ``accepted[i] + 1`` tokens this tick (1 when every draft is
    rejected, K+1 when all are accepted plus the bonus token).

    The batch-level cond keeps ALL sampling machinery (softmax over
    K+1 positions, acceptance uniforms, residual categoricals) out of
    the program whenever every occupied slot is greedy — the serve
    bench's regime, where the verify reduces to one argmax + compare.
    """
    k = drafts.shape[1]

    def greedy_all():
        choice = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, K+1]
        acc = (drafts == choice[:, :k]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(acc, axis=1), axis=1).astype(jnp.int32)
        idx = jnp.arange(k + 1)[None, :]
        pad = jnp.pad(drafts, ((0, 0), (0, 1)))
        committed = jnp.where(
            idx < a[:, None], pad, jnp.where(idx == a[:, None], choice, 0)
        )
        return committed, a

    def mixed():
        return jax.vmap(
            lambda l, d, kk, t, tk, tp: _spec_row(l, d, kk, t, tk, tp,
                                                  top_k_cap)
        )(logits, drafts, keys, temperatures, top_ks, top_ps)

    return lax.cond(jnp.any(temperatures > 0), mixed, greedy_all)
