"""Block-granular KV-cache accounting for the paged serving engine.

vLLM's PagedAttention observation, TPU-adapted: a dense slot pool wastes
most of its HBM on long-tail traffic because every slot owns a full
``max_len`` row. Here the physical KV store is a fixed
``[num_blocks, block_size, heads, head_dim]`` tensor per layer and each
request maps *logical* blocks (position // block_size) to *physical*
blocks through a per-slot int32 table. This module is the host-side
brain of that mapping — pure Python/numpy, no jax:

* **allocation** — a free list of physical block ids; ``alloc`` raises
  :class:`BlockPoolExhausted` when the pool (free + evictable) cannot
  cover a request, which the scheduler turns into admission
  backpressure (queued requests wait; a full queue raises ``QueueFull``
  at ``submit``, same as slot exhaustion).
* **refcounting + prefix cache** — full prompt blocks are content-hashed
  (a position-dependent chain, so block k's hash commits to every token
  before it) and registered; a later request whose prompt starts with
  the same block-aligned prefix maps its leading table entries to the
  *same physical blocks* (refcount++) and prefills only its suffix.
  RadixAttention's reuse, restricted to block granularity.
* **LRU retention** — blocks whose refcount drops to zero but that are
  registered in the prefix cache stay resident (evictable, LRU) so a
  follow-up request can still hit them; ``alloc`` evicts from that LRU
  only when the free list is empty.
* **copy-on-write** — ``ensure_private`` hands a writer its own block.
  Because sharing is restricted to *full* prompt blocks and writes
  start at the block-aligned shared length, the serving engine never
  writes a shared block mid-content — so "copy" never needs a device
  copy: a shared block is swapped for a fresh one (the caller fully
  rewrites it), and a privately-held but registered block is simply
  unregistered.

Physical block **0 is the trash sink**: never allocated, every unused
table entry points at it, so a compiled program's padded-tail writes
land harmlessly in rows no request ever attends (position masks keep
them unread). The pool therefore serves ``num_blocks - 1`` real blocks.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

TRASH_BLOCK = 0


class BlockPoolExhausted(RuntimeError):
    """Not enough free (or evictable) physical blocks for a request."""


def hash_prefix_chain(tokens: np.ndarray, block_size: int) -> List[bytes]:
    """Position-dependent content hashes for every FULL block of
    ``tokens``: ``h_k = H(h_{k-1} || tokens[k*bs:(k+1)*bs])``. Chaining
    makes block k's hash commit to the whole prefix before it, so two
    prompts share block k only when they agree on every earlier token."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    out: List[bytes] = []
    prev = b""
    for k in range(len(toks) // block_size):
        h = hashlib.sha1(
            prev + toks[k * block_size:(k + 1) * block_size].tobytes()
        ).digest()
        out.append(h)
        prev = h
    return out


class BlockAllocator:
    """Host-side ledger of the physical block pool.

    Invariants (pinned by ``tests/test_serving_paged.py``):

    * block 0 (trash) is never handed out;
    * every id is in exactly one of {free list, LRU cache, referenced};
    * a registered hash always maps to a resident block (referenced or
      cached), and eviction removes the mapping with the block.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the trash sink), "
                f"got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: collections.deque = collections.deque(
            range(1, num_blocks)
        )
        self._ref: Dict[int, int] = {}
        self._hash_of: Dict[int, bytes] = {}
        self._by_hash: Dict[bytes, int] = {}
        # zero-ref blocks still registered in the prefix cache, oldest
        # first — the eviction order when the free list runs dry.
        self._lru: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )
        # Blocks the fleet-wide prefix directory maps on this replica:
        # never evicted, never recycled to the free list while pinned
        # (docs/SERVING.md disaggregation section).
        self._pinned: set = set()
        self.stats = {
            "allocated": 0, "freed": 0, "evicted": 0, "cow": 0,
            "prefix_hit_blocks": 0, "prefix_hit_requests": 0,
            "registered": 0, "peak_live": 0,
        }

    # -- capacity ----------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the trash sink excluded)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        """Blocks an ``alloc`` could hand out right now (free +
        evictable cached; pinned cache entries are not evictable)."""
        pinned_cached = sum(1 for b in self._lru if b in self._pinned)
        return len(self._free) + len(self._lru) - pinned_cached

    @property
    def live_count(self) -> int:
        """Blocks currently referenced by at least one request."""
        return len(self._ref)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Physical blocks needed to hold ``n_tokens`` written positions."""
        if n_tokens <= 0:
            return 0
        return -(-int(n_tokens) // self.block_size)

    # -- alloc / free ------------------------------------------------------

    def _evict_one(self) -> int:
        bid = next(
            (b for b in self._lru if b not in self._pinned), None
        )
        if bid is None:  # alloc's free_count guard makes this unreachable
            raise BlockPoolExhausted("every cached block is pinned")
        del self._lru[bid]
        h = self._hash_of.pop(bid, None)
        if h is not None:
            self._by_hash.pop(h, None)
        self.stats["evicted"] += 1
        return bid

    def alloc(self, n: int) -> List[int]:
        """``n`` fresh private blocks (refcount 1 each), evicting
        zero-ref cached blocks LRU-first when the free list is empty.
        All-or-nothing: raises :class:`BlockPoolExhausted` without
        side effects when the pool cannot cover the request."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        if n > self.free_count:
            raise BlockPoolExhausted(
                f"need {n} blocks, {self.free_count} available "
                f"({len(self._free)} free, {len(self._lru)} evictable) "
                f"of {self.capacity}"
            )
        out: List[int] = []
        for _ in range(n):
            bid = self._free.popleft() if self._free else self._evict_one()
            self._ref[bid] = 1
            out.append(bid)
        self.stats["allocated"] += n
        self.stats["peak_live"] = max(self.stats["peak_live"], len(self._ref))
        return out

    def incref(self, bid: int) -> None:
        self._ref[bid] += 1

    def decref(self, bid: int) -> None:
        """Drop one reference. At zero the block either stays resident
        as an evictable prefix-cache entry (when registered) or returns
        to the free list."""
        left = self._ref[bid] - 1
        if left > 0:
            self._ref[bid] = left
            return
        del self._ref[bid]
        if bid in self._hash_of or bid in self._pinned:
            # Registered content stays discoverable; a pinned partial
            # block (directory tail payload source) stays resident even
            # though it has no chain hash — both sit in the LRU, and
            # eviction skips pinned entries.
            self._lru[bid] = None
            self._lru.move_to_end(bid)
        else:
            self._free.append(bid)
        self.stats["freed"] += 1

    def refcount(self, bid: int) -> int:
        return self._ref.get(bid, 0)

    # -- directory pins ----------------------------------------------------

    def pin(self, bid: int) -> None:
        """Exempt ``bid`` from eviction and free-list recycling: the
        fleet-wide prefix directory maps this block, possibly from
        another replica. Pin while the block is resident (referenced or
        cached); the pin survives the refcount reaching zero."""
        if bid == TRASH_BLOCK:
            raise ValueError("cannot pin the trash sink")
        if bid not in self._ref and bid not in self._lru:
            raise KeyError(f"block {bid} is not resident")
        self._pinned.add(bid)

    def unpin(self, bid: int) -> None:
        """Release a directory pin. An unpinned zero-ref block becomes
        evictable again (registered) or returns to the free list
        (unregistered partial block)."""
        self._pinned.discard(bid)
        if bid in self._lru and bid not in self._hash_of:
            del self._lru[bid]
            self._free.append(bid)

    def pinned(self, bid: int) -> bool:
        return bid in self._pinned

    def ensure_private(self, bid: int) -> int:
        """Copy-on-write entry point: return a block id the caller may
        freely overwrite. A block referenced only by the caller and not
        registered is returned as-is; a registered-but-exclusive block
        is unregistered (its cached content is about to change); a
        *shared* block is released (refcount--) and replaced by a fresh
        block — the caller is about to rewrite the content wholesale,
        so no device copy is needed."""
        if self._ref.get(bid, 0) <= 1:
            h = self._hash_of.pop(bid, None)
            if h is not None:
                self._by_hash.pop(h, None)
            return bid
        self.decref(bid)
        new = self.alloc(1)[0]
        self.stats["cow"] += 1
        return new

    # -- prefix cache ------------------------------------------------------

    def peek_prefix(self, tokens: np.ndarray, max_tokens: int) -> int:
        """How many leading FULL blocks of ``tokens`` (covering at most
        ``max_tokens`` tokens) the cache currently holds — no refcount
        side effects; admission gating uses this to size the true need."""
        n = 0
        for h in hash_prefix_chain(tokens, self.block_size):
            if (n + 1) * self.block_size > max_tokens:
                break
            if h not in self._by_hash:
                break
            n += 1
        return n

    def match_prefix(self, tokens: np.ndarray, max_tokens: int) -> List[int]:
        """Longest cached chain of leading full blocks (covering at most
        ``max_tokens`` tokens). Matched blocks are referenced (revived
        out of the LRU when needed) and returned in logical order."""
        matched: List[int] = []
        for h in hash_prefix_chain(tokens, self.block_size):
            if (len(matched) + 1) * self.block_size > max_tokens:
                break
            bid = self._by_hash.get(h)
            if bid is None:
                break
            if bid in self._ref:
                self.incref(bid)
            else:  # revive from the evictable cache
                self._lru.pop(bid, None)
                self._ref[bid] = 1
            matched.append(bid)
        if matched:
            self.stats["prefix_hit_blocks"] += len(matched)
            self.stats["prefix_hit_requests"] += 1
            self.stats["peak_live"] = max(
                self.stats["peak_live"], len(self._ref)
            )
        return matched

    def release_match(self, block_ids: Sequence[int]) -> None:
        """Undo a ``match_prefix`` (admission failed after matching)."""
        for bid in block_ids:
            self.decref(bid)

    def register_prefix(
        self, tokens: np.ndarray, block_ids: Sequence[int]
    ) -> int:
        """Make the full prompt blocks of ``tokens`` (physically
        ``block_ids[k]`` for logical block k) discoverable by later
        requests. First writer wins: a hash already mapped keeps its
        existing block. Returns how many new registrations were made."""
        new = 0
        for k, h in enumerate(hash_prefix_chain(tokens, self.block_size)):
            if k >= len(block_ids):
                break
            bid = int(block_ids[k])
            if h in self._by_hash or bid in self._hash_of:
                continue
            self._by_hash[h] = bid
            self._hash_of[bid] = h
            new += 1
        self.stats["registered"] += new
        return new

    def snapshot(self) -> Dict[str, int]:
        """Pool gauges for the obs bus / bench records."""
        return {
            "capacity": self.capacity,
            "free": self.free_count,
            "live": self.live_count,
            "cached": len(self._lru),
            "pinned": len(self._pinned),
            **self.stats,
        }


def prompt_key(tokens: np.ndarray) -> bytes:
    """Directory key for a *whole* prompt (full and partial blocks):
    one hash over every token, position-dependent by construction."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    return hashlib.sha1(toks.tobytes()).digest()


class PrefixDirectory:
    """Fleet-wide prefix directory: which replica holds which prefilled
    KV blocks, keyed by the same position-dependent content-hash chains
    the per-replica prefix cache uses (:func:`hash_prefix_chain`).

    Pure host-side metadata plus host-staged block payloads — the
    directory never touches a device or an allocator. The Router is the
    only writer: it publishes after a prefill replica exports a slot
    (the exporter pinned the blocks first, so every ``(rid, bid)`` the
    directory maps stays resident on that replica), serves **adoptions**
    (a second consumer of an identical greedy prompt seats decode state
    straight from the entry — zero prefill-program executions), serves
    **chain prefetches** (a different prompt sharing a full-block prefix
    imports just those blocks into its target replica's local cache),
    and re-homes or drops entries when a holder replica dies.

    Entries are published only for greedy (``temperature == 0.0``)
    requests: the entry carries the deterministic first token, which is
    what makes adoption a pure state transplant. Payloads are staged on
    host at export time (CPU tier; a device-to-device block DMA is the
    TPU path) so no cross-thread device read ever races a replica's
    pump donating its pool.

    Refcount surface (``tests/test_serving_disagg.py`` ledger oracle):
    ``holders`` maps ``rid -> [bid, ...]`` per entry — every mapped
    block is pinned on that replica; ``drop_replica`` re-homes the
    owner to a surviving holder or drops the entry, and ``clear``
    returns every pin so allocator ledgers balance at teardown.
    """

    def __init__(self) -> None:
        # prompt_key -> entry dict (see publish()).
        self._entries: Dict[bytes, Dict] = {}
        # chain hash -> (prompt_key, block index) for full-block
        # prefix lookups across entries.
        self._chains: Dict[bytes, Tuple[bytes, int]] = {}
        self.stats = {
            "publishes": 0, "lookups": 0, "hits": 0,
            "chain_hits": 0, "rehomed": 0, "dropped": 0,
        }

    def __len__(self) -> int:
        return len(self._entries)

    # -- publish -----------------------------------------------------------

    def publish(
        self,
        rid: int,
        prompt: np.ndarray,
        block_ids: Sequence[int],
        payload: Dict,
        *,
        first_token: int,
        block_size: int,
    ) -> bool:
        """Record that replica ``rid`` holds the prefilled blocks of
        ``prompt`` (``block_ids`` in logical order, covering every
        written position — the tail entry may be a partial block).
        ``payload`` is the host-staged block content (leaf-path ->
        ``[len(block_ids), block_size, ...]`` numpy). First writer
        wins; a later publish of the same prompt adds ``rid`` as
        another holder. Returns True when ``rid`` became a holder
        (caller keeps its pins), False when the publish was a no-op
        (caller should unpin)."""
        toks = np.ascontiguousarray(np.asarray(prompt, np.int32))
        key = prompt_key(toks)
        ent = self._entries.get(key)
        if ent is not None:
            if rid in ent["holders"]:
                return False
            ent["holders"][rid] = [int(b) for b in block_ids]
            self.stats["publishes"] += 1
            return True
        ent = {
            "prompt": toks.copy(),
            "owner": int(rid),
            "holders": {int(rid): [int(b) for b in block_ids]},
            "payload": payload,
            "first_token": int(first_token),
            "block_size": int(block_size),
            "adoptions": 0,
        }
        self._entries[key] = ent
        for k, h in enumerate(hash_prefix_chain(toks, block_size)):
            if k >= len(block_ids):
                break
            self._chains.setdefault(h, (key, k))
        self.stats["publishes"] += 1
        return True

    # -- lookup ------------------------------------------------------------

    def lookup(self, prompt: np.ndarray) -> Optional[Dict]:
        """Exact whole-prompt entry (adoption candidate) or None."""
        self.stats["lookups"] += 1
        ent = self._entries.get(prompt_key(prompt))
        if ent is not None:
            self.stats["hits"] += 1
        return ent

    def adopt(self, prompt: np.ndarray) -> Optional[Dict]:
        """:meth:`lookup` that also counts an adoption on the entry."""
        ent = self.lookup(prompt)
        if ent is not None:
            ent["adoptions"] += 1
        return ent

    def lookup_chain(
        self, prompt: np.ndarray, block_size: int
    ) -> Tuple[int, Optional[Dict], Dict]:
        """Longest directory-held chain of leading FULL blocks of
        ``prompt``. Returns ``(n_blocks, entry, payload_slice)`` where
        ``payload_slice`` maps leaf path -> the first ``n_blocks`` rows
        of the holding entry's payload (host numpy). ``(0, None, {})``
        on a miss or block-size mismatch."""
        chain = hash_prefix_chain(prompt, block_size)
        n = 0
        ref: Optional[Tuple[bytes, int]] = None
        for k, h in enumerate(chain):
            hit = self._chains.get(h)
            if hit is None:
                break
            ref = hit
            n += 1
        if n == 0 or ref is None:
            return 0, None, {}
        ent = self._entries.get(ref[0])
        if ent is None or ent["block_size"] != block_size:
            return 0, None, {}
        self.stats["chain_hits"] += 1
        sliced = {p: a[:n] for p, a in ent["payload"].items()}
        return n, ent, sliced

    # -- membership --------------------------------------------------------

    def drop_replica(self, rid: int) -> List[Tuple[int, List[int]]]:
        """Forget every block ``rid`` held (replica failed/removed).
        Entries re-home to a surviving holder; an entry with no holder
        left is dropped (its chain hashes too). Returns the
        ``(rid, block_ids)`` pairs that were unmapped so a caller with
        a live replica (drain path) can unpin them."""
        unmapped: List[Tuple[int, List[int]]] = []
        dead: List[bytes] = []
        for key, ent in self._entries.items():
            bids = ent["holders"].pop(rid, None)
            if bids is None:
                continue
            unmapped.append((rid, bids))
            if not ent["holders"]:
                dead.append(key)
            elif ent["owner"] == rid:
                ent["owner"] = next(iter(ent["holders"]))
                self.stats["rehomed"] += 1
        for key in dead:
            ent = self._entries.pop(key)
            self._chains = {
                h: ref for h, ref in self._chains.items() if ref[0] != key
            }
            self.stats["dropped"] += 1
        return unmapped

    def mapped_blocks(self, rid: int) -> List[int]:
        """Every block id the directory maps on ``rid`` (test oracle:
        each must be pinned + resident there)."""
        out: List[int] = []
        for ent in self._entries.values():
            out.extend(ent["holders"].get(rid, []))
        return out

    def clear(self) -> List[Tuple[int, List[int]]]:
        """Drop every entry, returning all ``(rid, block_ids)``
        mappings so the caller can unpin them (teardown ledger
        balance)."""
        out: List[Tuple[int, List[int]]] = []
        for ent in self._entries.values():
            for rid, bids in ent["holders"].items():
                out.append((int(rid), list(bids)))
        self._entries.clear()
        self._chains.clear()
        return out

    def snapshot(self) -> Dict[str, int]:
        return {
            "entries": len(self._entries),
            "chains": len(self._chains),
            **self.stats,
        }
