"""Block-granular KV-cache accounting for the paged serving engine.

vLLM's PagedAttention observation, TPU-adapted: a dense slot pool wastes
most of its HBM on long-tail traffic because every slot owns a full
``max_len`` row. Here the physical KV store is a fixed
``[num_blocks, block_size, heads, head_dim]`` tensor per layer and each
request maps *logical* blocks (position // block_size) to *physical*
blocks through a per-slot int32 table. This module is the host-side
brain of that mapping — pure Python/numpy, no jax:

* **allocation** — a free list of physical block ids; ``alloc`` raises
  :class:`BlockPoolExhausted` when the pool (free + evictable) cannot
  cover a request, which the scheduler turns into admission
  backpressure (queued requests wait; a full queue raises ``QueueFull``
  at ``submit``, same as slot exhaustion).
* **refcounting + prefix cache** — full prompt blocks are content-hashed
  (a position-dependent chain, so block k's hash commits to every token
  before it) and registered; a later request whose prompt starts with
  the same block-aligned prefix maps its leading table entries to the
  *same physical blocks* (refcount++) and prefills only its suffix.
  RadixAttention's reuse, restricted to block granularity.
* **LRU retention** — blocks whose refcount drops to zero but that are
  registered in the prefix cache stay resident (evictable, LRU) so a
  follow-up request can still hit them; ``alloc`` evicts from that LRU
  only when the free list is empty.
* **copy-on-write** — ``ensure_private`` hands a writer its own block.
  Because sharing is restricted to *full* prompt blocks and writes
  start at the block-aligned shared length, the serving engine never
  writes a shared block mid-content — so "copy" never needs a device
  copy: a shared block is swapped for a fresh one (the caller fully
  rewrites it), and a privately-held but registered block is simply
  unregistered.

Physical block **0 is the trash sink**: never allocated, every unused
table entry points at it, so a compiled program's padded-tail writes
land harmlessly in rows no request ever attends (position masks keep
them unread). The pool therefore serves ``num_blocks - 1`` real blocks.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

TRASH_BLOCK = 0


class BlockPoolExhausted(RuntimeError):
    """Not enough free (or evictable) physical blocks for a request."""


def hash_prefix_chain(tokens: np.ndarray, block_size: int) -> List[bytes]:
    """Position-dependent content hashes for every FULL block of
    ``tokens``: ``h_k = H(h_{k-1} || tokens[k*bs:(k+1)*bs])``. Chaining
    makes block k's hash commit to the whole prefix before it, so two
    prompts share block k only when they agree on every earlier token."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    out: List[bytes] = []
    prev = b""
    for k in range(len(toks) // block_size):
        h = hashlib.sha1(
            prev + toks[k * block_size:(k + 1) * block_size].tobytes()
        ).digest()
        out.append(h)
        prev = h
    return out


class BlockAllocator:
    """Host-side ledger of the physical block pool.

    Invariants (pinned by ``tests/test_serving_paged.py``):

    * block 0 (trash) is never handed out;
    * every id is in exactly one of {free list, LRU cache, referenced};
    * a registered hash always maps to a resident block (referenced or
      cached), and eviction removes the mapping with the block.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the trash sink), "
                f"got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: collections.deque = collections.deque(
            range(1, num_blocks)
        )
        self._ref: Dict[int, int] = {}
        self._hash_of: Dict[int, bytes] = {}
        self._by_hash: Dict[bytes, int] = {}
        # zero-ref blocks still registered in the prefix cache, oldest
        # first — the eviction order when the free list runs dry.
        self._lru: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )
        self.stats = {
            "allocated": 0, "freed": 0, "evicted": 0, "cow": 0,
            "prefix_hit_blocks": 0, "prefix_hit_requests": 0,
            "registered": 0, "peak_live": 0,
        }

    # -- capacity ----------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the trash sink excluded)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        """Blocks an ``alloc`` could hand out right now (free +
        evictable cached)."""
        return len(self._free) + len(self._lru)

    @property
    def live_count(self) -> int:
        """Blocks currently referenced by at least one request."""
        return len(self._ref)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Physical blocks needed to hold ``n_tokens`` written positions."""
        if n_tokens <= 0:
            return 0
        return -(-int(n_tokens) // self.block_size)

    # -- alloc / free ------------------------------------------------------

    def _evict_one(self) -> int:
        bid, _ = self._lru.popitem(last=False)
        h = self._hash_of.pop(bid, None)
        if h is not None:
            self._by_hash.pop(h, None)
        self.stats["evicted"] += 1
        return bid

    def alloc(self, n: int) -> List[int]:
        """``n`` fresh private blocks (refcount 1 each), evicting
        zero-ref cached blocks LRU-first when the free list is empty.
        All-or-nothing: raises :class:`BlockPoolExhausted` without
        side effects when the pool cannot cover the request."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        if n > self.free_count:
            raise BlockPoolExhausted(
                f"need {n} blocks, {self.free_count} available "
                f"({len(self._free)} free, {len(self._lru)} evictable) "
                f"of {self.capacity}"
            )
        out: List[int] = []
        for _ in range(n):
            bid = self._free.popleft() if self._free else self._evict_one()
            self._ref[bid] = 1
            out.append(bid)
        self.stats["allocated"] += n
        self.stats["peak_live"] = max(self.stats["peak_live"], len(self._ref))
        return out

    def incref(self, bid: int) -> None:
        self._ref[bid] += 1

    def decref(self, bid: int) -> None:
        """Drop one reference. At zero the block either stays resident
        as an evictable prefix-cache entry (when registered) or returns
        to the free list."""
        left = self._ref[bid] - 1
        if left > 0:
            self._ref[bid] = left
            return
        del self._ref[bid]
        if bid in self._hash_of:
            self._lru[bid] = None
            self._lru.move_to_end(bid)
        else:
            self._free.append(bid)
        self.stats["freed"] += 1

    def refcount(self, bid: int) -> int:
        return self._ref.get(bid, 0)

    def ensure_private(self, bid: int) -> int:
        """Copy-on-write entry point: return a block id the caller may
        freely overwrite. A block referenced only by the caller and not
        registered is returned as-is; a registered-but-exclusive block
        is unregistered (its cached content is about to change); a
        *shared* block is released (refcount--) and replaced by a fresh
        block — the caller is about to rewrite the content wholesale,
        so no device copy is needed."""
        if self._ref.get(bid, 0) <= 1:
            h = self._hash_of.pop(bid, None)
            if h is not None:
                self._by_hash.pop(h, None)
            return bid
        self.decref(bid)
        new = self.alloc(1)[0]
        self.stats["cow"] += 1
        return new

    # -- prefix cache ------------------------------------------------------

    def peek_prefix(self, tokens: np.ndarray, max_tokens: int) -> int:
        """How many leading FULL blocks of ``tokens`` (covering at most
        ``max_tokens`` tokens) the cache currently holds — no refcount
        side effects; admission gating uses this to size the true need."""
        n = 0
        for h in hash_prefix_chain(tokens, self.block_size):
            if (n + 1) * self.block_size > max_tokens:
                break
            if h not in self._by_hash:
                break
            n += 1
        return n

    def match_prefix(self, tokens: np.ndarray, max_tokens: int) -> List[int]:
        """Longest cached chain of leading full blocks (covering at most
        ``max_tokens`` tokens). Matched blocks are referenced (revived
        out of the LRU when needed) and returned in logical order."""
        matched: List[int] = []
        for h in hash_prefix_chain(tokens, self.block_size):
            if (len(matched) + 1) * self.block_size > max_tokens:
                break
            bid = self._by_hash.get(h)
            if bid is None:
                break
            if bid in self._ref:
                self.incref(bid)
            else:  # revive from the evictable cache
                self._lru.pop(bid, None)
                self._ref[bid] = 1
            matched.append(bid)
        if matched:
            self.stats["prefix_hit_blocks"] += len(matched)
            self.stats["prefix_hit_requests"] += 1
            self.stats["peak_live"] = max(
                self.stats["peak_live"], len(self._ref)
            )
        return matched

    def release_match(self, block_ids: Sequence[int]) -> None:
        """Undo a ``match_prefix`` (admission failed after matching)."""
        for bid in block_ids:
            self.decref(bid)

    def register_prefix(
        self, tokens: np.ndarray, block_ids: Sequence[int]
    ) -> int:
        """Make the full prompt blocks of ``tokens`` (physically
        ``block_ids[k]`` for logical block k) discoverable by later
        requests. First writer wins: a hash already mapped keeps its
        existing block. Returns how many new registrations were made."""
        new = 0
        for k, h in enumerate(hash_prefix_chain(tokens, self.block_size)):
            if k >= len(block_ids):
                break
            bid = int(block_ids[k])
            if h in self._by_hash or bid in self._hash_of:
                continue
            self._by_hash[h] = bid
            self._hash_of[bid] = h
            new += 1
        self.stats["registered"] += new
        return new

    def snapshot(self) -> Dict[str, int]:
        """Pool gauges for the obs bus / bench records."""
        return {
            "capacity": self.capacity,
            "free": self.free_count,
            "live": self.live_count,
            "cached": len(self._lru),
            **self.stats,
        }
