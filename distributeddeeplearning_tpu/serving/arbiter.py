"""Train/serve colocation arbiter — one elastic device pool.

Elastic training worlds (launch.py, docs/ROBUSTNESS.md) and the
self-healing serving fleet (serving/fleet/) each manage their own
hardware; production TPU pods run both on the *same* devices. The
:class:`PoolArbiter` owns one pool and arbitrates under a declared
priority order:

* **Training holds the mesh by default.** The pool starts fully owned
  by the supervised training world (``pool_devices`` processes).
* **Serving escalates, never grabs.** Only when the fleet pressure
  gauge (``serve.fleet_pressure``) and the SLO burn-rate engine
  (obs/slo.py) sustain a breach *past* the brownout ladder — every
  declared degradation stage applied and the burn still standing
  (``BrownoutLadder.exhausted``) — does the arbiter shrink training:
  it writes a reduced capacity through the existing capacity-file
  protocol (``faults.write_capacity``, ``owner="arbiter"``), the
  supervisor's grow/shrink poller sees it and restarts the world at
  the largest fitting divisor (``EXIT_RESIZE``, budget-free, with the
  BATCHSIZE/ACCUM_STEPS rescale), and the freed devices become
  leasable.
* **Serving *requests* capacity.** ``FleetController`` scale-up asks
  for a lease (:meth:`request_lease`) instead of assuming free
  hardware; a denial is ``fleet.scaleup_denied`` + backoff, not a
  spin.
* **Training reclaims.** When pressure drops (``grow_ticks`` calm
  observations) or a training epoch boundary arrives
  (:meth:`epoch_boundary`), the arbiter stops granting leases, the
  controller drains leased replicas (zero-drop: running streams
  finish), and once the last lease is released the arbiter restores
  full capacity — training grows back.

The escalation ladder is therefore: admission derate → brownout
stages (shed) → shrink training. Every decision is telemetry:
``arbiter.shrink`` / ``arbiter.grow`` / ``arbiter.reclaim`` /
``arbiter.lease_grant`` / ``arbiter.lease_deny`` /
``arbiter.lease_release`` / ``arbiter.lease_expired`` points plus the
``pool.train_world`` / ``pool.serve_replicas`` ownership gauges
(docs/OBSERVABILITY.md).

Signal sources mirror the other control loops: an injected ``reader``
(tests, drills), else the live plane's ``rollup.json``. Deliberately
jax-free — the arbiter runs in the supervisor/controller process.

Env contract (``ArbiterConfig.from_env``; docs/ORCHESTRATION.md):
``ARBITER_POOL_DEVICES``, ``ARBITER_MIN_TRAIN_WORLD``,
``ARBITER_DEVICES_PER_REPLICA``, ``ARBITER_SHRINK_TICKS``,
``ARBITER_GROW_TICKS``, ``ARBITER_HIGH_PRESSURE``,
``ARBITER_LOW_PRESSURE``, ``ARBITER_LEASE_TTL_S``,
``ARBITER_WATCH_PREFIX``.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional

from distributeddeeplearning_tpu import faults, obs
from distributeddeeplearning_tpu.serving.scheduler import (
    burning_latency_objectives,
)


def _shrink_target(pool: int, current: int, floor: int) -> Optional[int]:
    """The largest divisor of ``pool`` strictly below ``current`` and no
    smaller than ``floor`` — the next training world size down the
    elastic ladder (mirrors launch.py ``_elastic_world``)."""
    for d in range(current - 1, max(floor, 1) - 1, -1):
        if pool % d == 0:
            return d
    return None


@dataclasses.dataclass
class ArbiterConfig:
    """Pool shape + escalation/hysteresis knobs, env-overridable
    (ARBITER_*)."""

    pool_devices: int               # total devices = full training world
    min_train_world: int = 1        # training never shrinks below this
    devices_per_replica: int = 1    # lease quantum for one replica
    shrink_ticks: int = 3           # exhausted+burning obs before shrink
    grow_ticks: int = 6             # calm obs before training reclaims
    high_pressure: float = 1.0      # fleet pressure >= this is "hot"
    low_pressure: float = 0.35      # fleet pressure <= this is "calm"
    lease_ttl_s: float = 600.0      # dead-holder safety net (0 = no TTL)
    watch_prefix: Optional[str] = None  # SLO metric filter (serve.*)

    def validate(self) -> None:
        if self.pool_devices < 1:
            raise ValueError(f"pool_devices {self.pool_devices} must be >= 1")
        if not 1 <= self.min_train_world <= self.pool_devices:
            raise ValueError(
                f"need 1 <= min_train_world {self.min_train_world} <= "
                f"pool {self.pool_devices}"
            )
        if self.devices_per_replica < 1:
            raise ValueError("devices_per_replica must be >= 1")
        if self.shrink_ticks < 1 or self.grow_ticks < 1:
            raise ValueError("shrink_ticks and grow_ticks must be >= 1")
        if self.low_pressure >= self.high_pressure:
            raise ValueError(
                f"low watermark {self.low_pressure} must be below high "
                f"{self.high_pressure}"
            )

    @classmethod
    def from_env(cls, env=None, **overrides: Any) -> "ArbiterConfig":
        e = os.environ if env is None else env
        kw: Dict[str, Any] = dict(
            pool_devices=int(e.get("ARBITER_POOL_DEVICES", "1")),
            min_train_world=int(e.get("ARBITER_MIN_TRAIN_WORLD", "1")),
            devices_per_replica=int(
                e.get("ARBITER_DEVICES_PER_REPLICA", "1")
            ),
            shrink_ticks=int(e.get("ARBITER_SHRINK_TICKS", "3")),
            grow_ticks=int(e.get("ARBITER_GROW_TICKS", "6")),
            high_pressure=float(e.get("ARBITER_HIGH_PRESSURE", "1.0")),
            low_pressure=float(e.get("ARBITER_LOW_PRESSURE", "0.35")),
            lease_ttl_s=float(e.get("ARBITER_LEASE_TTL_S", "600")),
            watch_prefix=e.get("ARBITER_WATCH_PREFIX") or None,
        )
        kw.update(overrides)
        cfg = cls(**kw)
        cfg.validate()
        return cfg


@dataclasses.dataclass
class Lease:
    """One serving claim on freed pool devices."""

    owner: str
    devices: int
    granted_at: float
    expires_at: Optional[float]  # lease TTL (dead-holder safety net)


class PoolArbiter:
    """Arbitrate one device pool between training and serving.

    ``tick()`` is the decision loop (call it at the controller cadence);
    ``request_lease`` / ``release_lease`` are the controller-facing
    capacity API; ``epoch_boundary`` is the training-side reclaim hook.
    ``decisions`` records every transition for tests and reports.
    """

    def __init__(
        self,
        config: ArbiterConfig,
        capacity_file: Optional[str] = None,
        *,
        reader: Optional[Callable[[], Optional[dict]]] = None,
        snapshot_path: Optional[str] = None,
        ladder=None,
    ) -> None:
        config.validate()
        self.config = config
        if capacity_file is None:
            capacity_file = os.environ.get(
                faults.CAPACITY_FILE_ENV
            ) or os.path.join(os.environ.get("OBS_DIR", "."), "capacity.json")
        self.capacity_file = capacity_file
        self._reader = reader
        if snapshot_path is None:
            snapshot_path = os.path.join(
                os.environ.get("OBS_DIR", "."), "rollup.json"
            )
        self.snapshot_path = snapshot_path
        self.ladder = ladder
        self.train_world = config.pool_devices  # training holds by default
        self.leases: Dict[str, Lease] = {}
        self.reclaiming = False
        self._hot = 0
        self._cool = 0
        self.decisions: List[Dict[str, Any]] = []
        self._gauges()

    # -- pool accounting ---------------------------------------------------

    @property
    def leased_devices(self) -> int:
        return sum(l.devices for l in self.leases.values())

    @property
    def free_devices(self) -> int:
        """Devices freed by shrinking training and not yet leased out."""
        return max(
            self.config.pool_devices - self.train_world
            - self.leased_devices, 0,
        )

    def has_lease(self, owner: str) -> bool:
        return owner in self.leases

    def _gauges(self) -> None:
        obs.gauge("pool.train_world", float(self.train_world))
        obs.gauge("pool.serve_replicas", float(len(self.leases)))

    def _decide(self, action: str, **labels: Any) -> None:
        self.decisions.append({"action": action, **labels})
        obs.point(f"arbiter.{action}", **labels)
        self._gauges()

    # -- signal ------------------------------------------------------------

    def _read(self) -> Optional[dict]:
        if self._reader is not None:
            return self._reader()
        from distributeddeeplearning_tpu.obs.rollup import read_snapshot

        return read_snapshot(self.snapshot_path)

    @staticmethod
    def _pressure(snap: dict) -> Optional[float]:
        g = (snap.get("gauges") or {}).get("serve.fleet_pressure")
        if g and g.get("value") is not None:
            return float(g["value"])
        return None

    # -- decision loop -----------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One arbitration decision. Returns ``"shrink"`` (training
        world reduced, capacity file written), ``"grow"`` (full
        capacity restored), ``"reclaim"`` (training wants its devices
        back; waiting on lease drains), or None."""
        if now is None:
            now = time.time()
        self._expire(now)
        snap = self._read()
        if snap is None:
            return None  # no plane publishing: hold current ownership
        pressure = self._pressure(snap)
        burning = burning_latency_objectives(snap, self.config.watch_prefix)
        # The ladder must be exhausted before training pays: brownout →
        # shed → shrink. With no ladder wired there is nothing left to
        # shed, so burn alone escalates.
        exhausted = self.ladder.exhausted if self.ladder is not None else True
        cfg = self.config
        hot = bool(burning) and exhausted and (
            pressure is not None and pressure >= cfg.high_pressure
        )
        calm = not burning and (
            pressure is None or pressure <= cfg.low_pressure
        )
        if hot:
            self._hot += 1
            self._cool = 0
        elif calm:
            self._cool += 1
            self._hot = 0
        else:
            self._hot = self._cool = 0
        if self._hot >= cfg.shrink_ticks and not self.reclaiming:
            target = _shrink_target(
                cfg.pool_devices, self.train_world, cfg.min_train_world
            )
            if (
                target is not None
                and self.train_world - target >= cfg.devices_per_replica
            ):
                return self._shrink(target, now, pressure, burning)
        if self._cool >= cfg.grow_ticks and (
            self.train_world < cfg.pool_devices
        ):
            return self._reclaim_or_grow(now, trigger="pressure_drop")
        return None

    def epoch_boundary(self, now: Optional[float] = None) -> Optional[str]:
        """Training-side reclaim hook: an epoch boundary is a natural
        grow-back point regardless of the pressure hysteresis (the
        declared priority order — training holds the mesh)."""
        if now is None:
            now = time.time()
        if self.train_world >= self.config.pool_devices:
            return None
        return self._reclaim_or_grow(now, trigger="epoch_boundary")

    # -- transitions -------------------------------------------------------

    def _shrink(
        self, target: int, now: float, pressure, burning
    ) -> str:
        cfg = self.config
        restore_at = now + cfg.lease_ttl_s if cfg.lease_ttl_s > 0 else None
        faults.write_capacity(
            self.capacity_file, target, restore_at=restore_at,
            owner="arbiter",
        )
        from_world, self.train_world = self.train_world, target
        self._hot = 0
        self._decide(
            "shrink", from_world=from_world, to_world=target,
            pressure=pressure,
            objectives=";".join(burning) if burning else "",
        )
        return "shrink"

    def _reclaim_or_grow(self, now: float, *, trigger: str) -> str:
        if self.leases:
            if not self.reclaiming:
                self.reclaiming = True
                self._decide(
                    "reclaim", trigger=trigger,
                    leases=len(self.leases),
                )
            return "reclaim"
        return self._grow(trigger=trigger)

    def _grow(self, *, trigger: str) -> str:
        faults.write_capacity(
            self.capacity_file, self.config.pool_devices, owner="arbiter"
        )
        from_world, self.train_world = (
            self.train_world, self.config.pool_devices
        )
        self.reclaiming = False
        self._cool = 0
        self._decide(
            "grow", from_world=from_world,
            to_world=self.train_world, trigger=trigger,
        )
        return "grow"

    # -- lease API (FleetController scale-up) ------------------------------

    def request_lease(
        self,
        owner: str,
        devices: Optional[int] = None,
        now: Optional[float] = None,
    ) -> bool:
        """Grant ``devices`` freed-pool devices to ``owner`` (one
        replica's claim). Denied while training is reclaiming (priority
        order) or when the freed share is exhausted."""
        if now is None:
            now = time.time()
        if devices is None:
            devices = self.config.devices_per_replica
        if owner in self.leases:
            return True  # idempotent: the claim is already held
        if self.reclaiming:
            self._decide(
                "lease_deny", owner=owner, devices=devices,
                reason="reclaiming",
            )
            return False
        if devices > self.free_devices:
            self._decide(
                "lease_deny", owner=owner, devices=devices,
                reason="exhausted", free=self.free_devices,
            )
            return False
        ttl = self.config.lease_ttl_s
        self.leases[owner] = Lease(
            owner=owner, devices=devices, granted_at=now,
            expires_at=now + ttl if ttl > 0 else None,
        )
        self._decide(
            "lease_grant", owner=owner, devices=devices,
            free=self.free_devices,
        )
        return True

    def release_lease(self, owner: str) -> bool:
        """Return ``owner``'s devices to the pool (the controller calls
        this when a leased replica finishes draining — zero-drop). If
        training was reclaiming and this was the last lease, capacity
        restores immediately."""
        lease = self.leases.pop(owner, None)
        if lease is None:
            return False
        self._decide(
            "lease_release", owner=owner, devices=lease.devices,
            free=self.free_devices,
        )
        if self.reclaiming and not self.leases:
            self._grow(trigger="last_lease_released")
        return True

    def _expire(self, now: float) -> None:
        """Reap leases past their TTL — a dead holder must not pin
        freed devices forever."""
        for owner in [
            o for o, l in self.leases.items()
            if l.expires_at is not None and now >= l.expires_at
        ]:
            lease = self.leases.pop(owner)
            self._decide(
                "lease_expired", owner=owner, devices=lease.devices,
            )
        if self.reclaiming and not self.leases:
            self._grow(trigger="last_lease_expired")
