"""Seeded serving load generation + warmup helpers (bench substrate).

``scripts/serve_bench.py`` grew three compare protocols (paged, quant,
spec) that each rebuilt the same seeded Poisson request stream and the
same per-shape ``inference.generate`` warmup loop; the fleet bench
(``scripts/fleet_bench.py``) needs both again, plus a multi-tenant
variant. This module is the one copy:

* :data:`PROFILES` / :data:`MIXED_PROMPT_LENS` — the request-shape
  mixes (``SERVE_PROFILE``): ``mixed`` cycles a handful of prompt
  lengths at one ``max_new``; ``longtail`` is the production-shaped
  distribution (mostly short prompts, a thin tail of long ones) the
  paged pool exists for; ``disagg`` is the bimodal
  long-prefill/long-decode storm the disaggregated fleet splits.
* :func:`build_requests` — seeded request set + Poisson arrival
  offsets over a shape mix. Deterministic in ``seed``: every protocol
  comparing two configurations replays the *same* load.
* :func:`build_tenant_requests` — the same stream with a tenant
  identity cycled over it (round-robin, so every tenant offers the
  same work mix and a fairness bound on *completed share vs weight
  share* is meaningful — scripts/fleet_bench.py).
* :func:`warm_shapes` — compile/warm every distinct
  ``(prompt_len, max_new)`` shape through ``inference.generate`` so a
  sequential baseline measures steady-state throughput, not compiles.
* :func:`percentile` — the nearest-rank percentile every serving bench
  reports TTFT/queue-wait with.

Pure host + numpy until :func:`warm_shapes` (the only jax touchpoint),
so load construction stays importable from jax-free tooling.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

# Request-shape mixes: (prompt_len, max_new) pairs cycled over the
# request stream. "longtail" is kept to few distinct shapes so a
# sequential baseline's per-shape warmup stays bounded.
PROFILES: Dict[str, Optional[List[Tuple[int, int]]]] = {
    "mixed": None,  # legacy: MIXED_PROMPT_LENS cycle, SERVE_MAX_NEW everywhere
    "longtail": (
        [(3, 8)] * 8 + [(4, 8)] * 6 + [(6, 8)] * 5 + [(8, 8)] * 4
        + [(12, 16)] * 3 + [(16, 16)] * 2
        + [(24, 16), (48, 24), (96, 32)]
    ),
    # Bimodal disaggregation storm: long-prefill/short-decode requests
    # (prefill-bound) interleaved with short-prefill/long-decode ones
    # (decode-bound). Under a colocated fleet the long decodes hold
    # slots and queue the long prefills behind them; a split fleet
    # serves each mode from its own pool. Few distinct shapes keeps the
    # sequential baseline's warmup (and the closed program set) small.
    "disagg": (
        [(96, 12)] * 4 + [(64, 12)] * 3
        + [(6, 48)] * 4 + [(4, 32)] * 3 + [(8, 48)] * 2
    ),
}
MIXED_PROMPT_LENS: Tuple[int, ...] = (4, 7, 12, 5, 16, 3, 9, 14)


def profile_shapes(
    profile: str, max_new: int
) -> List[Tuple[int, int]]:
    """The (prompt_len, max_new) mix for one ``SERVE_PROFILE`` value."""
    if profile not in PROFILES:
        raise ValueError(
            f"unknown SERVE_PROFILE {profile!r} (have: {sorted(PROFILES)})"
        )
    shapes = PROFILES[profile]
    if shapes is None:
        return [(tp, max_new) for tp in MIXED_PROMPT_LENS]
    return list(shapes)


def percentile(vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (the serving benches' TTFT/queue-wait
    convention; 0 on an empty sample)."""
    vals = sorted(vals)
    if not vals:
        return 0.0
    idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
    return vals[idx]


def hot_prompt(vocab: int, length: int, seed: int = 0):
    """The deterministic "hot system prompt": every caller with the
    same (vocab, length, seed) gets the bitwise-identical token run, so
    a shared prefix built from it hashes to the same directory chain on
    every replica (scripts/disagg_bench.py)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    return rng.randint(0, vocab, size=(length,)).astype(np.int32)


def build_requests(
    n: int, rate_rps: float, seed: int, vocab: int,
    shapes: Sequence[Tuple[int, int]],
    shared_prefix=None,
) -> List[Dict[str, Any]]:
    """Seeded request set + Poisson arrival offsets (seconds) over the
    (prompt_len, max_new) shape mix — mixed lengths, per-request
    sampling seeds: the adversarial mix the parity oracles certify, at
    load. ``rate_rps == 0`` is the closed-backlog special case (all
    arrivals at t=0). ``shared_prefix`` (a token array, e.g.
    :func:`hot_prompt`) is prepended to every prompt — the "hot system
    prompt" shape the fleet prefix directory amortises; per-request
    tails stay distinct so only the prefix blocks are shareable."""
    import numpy as np

    pre = None
    if shared_prefix is not None:
        pre = np.asarray(shared_prefix).reshape(-1).astype(np.int32)
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(shapes))
    reqs = []
    t = 0.0
    for i in range(n):
        if rate_rps > 0:
            t += float(rng.exponential(1.0 / rate_rps))
        tp, max_new = shapes[order[i % len(shapes)]]
        prompt = rng.randint(0, vocab, size=(tp,)).astype(np.int32)
        if pre is not None:
            prompt = np.concatenate([pre, prompt])
        reqs.append({
            "arrival_s": t,
            "prompt": prompt,
            "max_new": int(max_new),
            "seed": int(rng.randint(0, 2**31 - 1)),
        })
    return reqs


def build_tenant_requests(
    tenant_ids: Sequence[str], n: int, rate_rps: float, seed: int,
    vocab: int, shapes: Sequence[Tuple[int, int]],
    shared_prefix=None,
) -> List[Dict[str, Any]]:
    """:func:`build_requests` with a ``tenant`` identity cycled over the
    stream. Round-robin assignment means every tenant offers the same
    shape mix and (to within one request) the same total token work —
    under contention, each tenant's *completed* share is then pinned by
    the router's weights alone, which is exactly what the fairness gate
    measures (scripts/fleet_bench.py, docs/SERVING.md)."""
    reqs = build_requests(
        n, rate_rps, seed, vocab, shapes, shared_prefix=shared_prefix
    )
    for i, r in enumerate(reqs):
        r["tenant"] = str(tenant_ids[i % len(tenant_ids)])
    return reqs


def warm_shapes(
    model, params, reqs: Sequence[Dict[str, Any]],
    temperature: float, top_k,
) -> int:
    """Compile/warm every distinct (prompt_len, max_new) shape through
    ``inference.generate`` (the sequential baseline's program set) so a
    timed run measures steady-state throughput. Returns the number of
    distinct shapes warmed."""
    import jax
    import numpy as np

    from distributeddeeplearning_tpu.inference import generate

    shapes = sorted({(len(r["prompt"]), r["max_new"]) for r in reqs})
    for tp, n_new in shapes:
        generate(
            model, params, np.zeros((1, tp), np.int32),
            max_new_tokens=n_new, temperature=temperature, top_k=top_k,
            rng=jax.random.PRNGKey(0),
        )
    return len(shapes)
