"""AST lint for implicit host syncs and tracer leaks in hot paths.

The sync-free loop's dynamic oracle (``tests/test_sync_free_loop.py``)
counts materialisations at runtime — it only sees the code paths the
test happens to drive. This pass reads the *source* of every
compiled-step code path and flags, at any config:

* ``host-sync`` — materialising a value produced by jnp/jax/lax in the
  same scope (``float()/int()/bool()``, ``np.asarray``/``np.array``,
  ``.item()``), raw ``jax.device_get``, and ``.block_until_ready()``.
  Every repo-internal materialisation must route through
  ``utils/hostsync.device_get`` (the accountant books it and the run
  report shows the call site) — that call is the ONE allowlist.
* ``tracer-bool`` — truthiness tests on traced values (``if x:``,
  ``while x:``, ``assert x``, ``not x``, ``x and y``): under jit these
  either raise a ConcretizationTypeError at trace time or, in host-side
  glue, silently force a device sync per step.

Taint model (deliberately simple, per function scope with lexical
nesting): a name is *traced* when assigned from a call rooted at
``jnp``/``jax``/``lax`` (or from arithmetic/comparison/indexing on a
traced value); ``.shape``/``.ndim``/``.dtype``/``.size``/``len()``
launder the taint (host metadata); ``hostsync.device_get(x)`` is the
accounted materialisation and both consumes and clears taint. Values
returned by compiled executables (``self._decode_exec(...)``) are NOT
tainted — the serving tick's deliberate token materialisation is the
engine's contract, and the dynamic accountant still covers it.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from distributeddeeplearning_tpu.analysis import (
    Finding,
    PACKAGE_ROOT,
    register,
    repo_rel,
)

# The compiled-step code paths (ISSUE 14): every file whose functions
# are traced into an XLA program, or sit on the per-step/per-tick hot
# path around one. Keep sorted; adding a file here is how a new hot
# path opts into the lint.
HOT_PATHS = (
    "models/transformer_lm.py",
    "models/vit.py",
    "ops/attention.py",
    "ops/pallas/paged_decode.py",
    "serving/engine.py",
    "serving/sampling.py",
    "training/accum.py",
    "training/pjit_step.py",
    "training/pp_step.py",
    "training/sp_step.py",
    "training/train_step.py",
)

_TRACED_ROOTS = {"jnp", "lax"}
# jax.* calls that return host-side (or host-safe) values — not taint
# sources. jax.device_get is handled as an explicit sink instead.
_JAX_HOST_ATTRS = {
    "device_count", "process_count", "process_index", "local_device_count",
    "devices", "local_devices", "default_backend", "tree_structure",
    # jax.tree / tree_util container ops return host lists/structures
    # (of possibly-traced leaves — the list itself is host data, and its
    # truthiness/len is legitimate host logic).
    "leaves", "tree_leaves", "flatten", "tree_flatten", "structure",
    "unflatten", "tree_unflatten", "keystr", "leaves_with_path",
    "tree_leaves_with_path", "tree_flatten_with_path",
}
# jnp.* functions returning host metadata, not arrays.
_JNP_HOST_FUNCS = {
    "ndim", "shape", "size", "result_type", "issubdtype", "isdtype",
    "dtype", "iinfo", "finfo",
}
_DETAINT_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
_CAST_SINKS = {"float", "int", "bool", "complex"}
_NP_SINKS = {"asarray", "array", "float32", "float64", "int32", "int64"}


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` → "a.b.c" for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Scope:
    def __init__(self, parent: Optional["_Scope"] = None) -> None:
        self.parent = parent
        self.tainted: Set[str] = set()

    def is_tainted(self, name: str) -> bool:
        s: Optional[_Scope] = self
        while s is not None:
            if name in s.tainted:
                return True
            s = s.parent
        return False


class _SyncLinter(ast.NodeVisitor):
    def __init__(self, path: str) -> None:
        self.path = path
        self.findings: List[Finding] = []
        self.scope = _Scope()

    # -- taint -------------------------------------------------------------

    def _tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return self.scope.is_tainted(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in _DETAINT_ATTRS:
                return False
            return self._tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value)
        if isinstance(node, ast.Call):
            return self._call_taints(node)
        if isinstance(node, ast.BinOp):
            return self._tainted(node.left) or self._tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self._tainted(node.left) or any(
                self._tainted(c) for c in node.comparators
            )
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self._tainted(node.body) or self._tainted(node.orelse)
        return False

    def _call_taints(self, node: ast.Call) -> bool:
        """Does this call produce a traced value?"""
        name = _dotted(node.func)
        if name is None:
            return False
        root = name.split(".", 1)[0]
        if root in _TRACED_ROOTS:
            return name.split(".")[-1] not in _JNP_HOST_FUNCS
        if root == "jax":
            attr = name.split(".")[-1]
            if name == "jax.device_get" or attr in _JAX_HOST_ATTRS:
                return False
            return True
        # hostsync.device_get returns a host value.
        if name.endswith("device_get"):
            return False
        # Method calls on traced receivers stay traced (.astype, .sum,
        # .reshape ... — .item() is a sink, caught before we get here).
        if isinstance(node.func, ast.Attribute):
            return self._tainted(node.func.value)
        return False

    # -- sinks -------------------------------------------------------------

    def _flag(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(
            Finding(rule, self.path, getattr(node, "lineno", 0), message)
        )

    def _check_truthiness(self, test: ast.AST, context: str) -> None:
        if isinstance(test, ast.BoolOp):
            for v in test.values:
                self._check_truthiness(v, context)
            return
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            self._check_truthiness(test.operand, context)
            return
        # A Compare on traced values yields a traced bool array — its
        # truthiness is the leak; plain tainted names likewise.
        if self._tainted(test):
            self._flag(
                test, "tracer-bool",
                f"truthiness test on a traced value in {context} — under "
                f"jit this is a ConcretizationTypeError (or a silent host "
                f"sync per step); reduce on device (jnp.any/jnp.all) and "
                f"materialise once via utils/hostsync.device_get",
            )

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        # Raw jax.device_get: the unaccounted materialisation — the one
        # allowlisted spelling is utils/hostsync.device_get.
        if name == "jax.device_get":
            self._flag(
                node, "host-sync",
                "raw jax.device_get — route through utils/hostsync."
                "device_get so the materialisation is booked with the "
                "sync accountant (the ≤1-sync/epoch ledger)",
            )
        elif isinstance(node.func, ast.Attribute) and node.func.attr == (
            "block_until_ready"
        ):
            self._flag(
                node, "host-sync",
                ".block_until_ready() stalls the dispatch queue — hot "
                "paths must stay async (time at the epoch boundary "
                "instead)",
            )
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            if self._tainted(node.func.value):
                self._flag(
                    node, "host-sync",
                    ".item() on a traced value — a device→host sync; "
                    "use utils/hostsync.device_get at the boundary",
                )
        elif name in _CAST_SINKS and node.args:
            if self._tainted(node.args[0]):
                self._flag(
                    node, "host-sync",
                    f"{name}() on a traced value materialises it — keep "
                    f"the math on device, or hostsync.device_get at the "
                    f"epoch/tick boundary",
                )
        elif (
            name is not None
            and name.split(".", 1)[0] in ("np", "numpy")
            and name.split(".")[-1] in _NP_SINKS
            and node.args
            and self._tainted(node.args[0])
        ):
            self._flag(
                node, "host-sync",
                f"{name}() on a traced value is an implicit device_get — "
                f"route through utils/hostsync.device_get",
            )
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._check_truthiness(node.test, "an if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_truthiness(node.test, "a while")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_truthiness(node.test, "an assert")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_truthiness(node.test, "a conditional expression")
        self.generic_visit(node)

    # -- assignment taint propagation -------------------------------------

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.scope.tainted.add(target.id)
            else:
                self.scope.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        # Attribute/Subscript targets: no name-level taint to track.

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        tainted = self._tainted(node.value)
        for t in node.targets:
            self._bind(t, tainted)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if self._tainted(node.value):
            self._bind(node.target, True)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None:
            self._bind(node.target, self._tainted(node.value))

    def _visit_function(self, node) -> None:
        self.scope = _Scope(self.scope)
        for stmt in node.body:
            self.visit(stmt)
        self.scope = self.scope.parent

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.scope = _Scope(self.scope)
        self.visit(node.body)
        self.scope = self.scope.parent


def lint_source(source: str, path: str) -> List[Finding]:
    """Run the sync/tracer lint over one file's source text."""
    tree = ast.parse(source, filename=path)
    linter = _SyncLinter(path)
    linter.visit(tree)
    return linter.findings


def _run(rule: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in HOT_PATHS:
        path = os.path.join(PACKAGE_ROOT, rel)
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(
            f for f in lint_source(src, repo_rel(path)) if f.rule == rule
        )
    return findings


@register(
    "host-sync", "ast",
    "implicit device→host materialisations in compiled-step code paths "
    "(float/int/bool/.item/np.asarray on traced values, raw "
    "jax.device_get, block_until_ready)",
)
def run_host_sync() -> List[Finding]:
    return _run("host-sync")


@register(
    "tracer-bool", "ast",
    "truthiness tests on traced values in compiled-step code paths",
)
def run_tracer_bool() -> List[Finding]:
    return _run("tracer-bool")
