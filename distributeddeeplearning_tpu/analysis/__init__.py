"""ddlint — repo-invariant static analysis (docs/ANALYSIS.md).

The codebase's hardest-won properties — ≤1 host sync per epoch, closed
program sets, donated state buffers, the ORCHESTRATION.md env contract,
the OBSERVABILITY.md event registry, recertify's ``_PROTOCOL_VARS``
scrub list — were all enforced *dynamically*: an oracle has to re-run
(and has to happen to build the right config) before a regression is
even visible. This package is the static tier: three analyzer families
that check the whole class at lint time, on every config at once.

* :mod:`.ast_sync` — AST pass over the compiled-step code paths
  flagging implicit host syncs and tracer leaks (``float()/int()/
  bool()/.item()/np.asarray`` or truthiness on values traced from
  jnp/jax/lax), with the one allowlist anchored on
  ``utils/hostsync.device_get`` call sites.
* :mod:`.hlo_audit` — lowers each engine's step plus the SlotEngine
  program set on a CPU mesh and walks the compiled module: donation
  actually aliased, collectives where the design says they are (none
  inside the ACCUM_STEPS scan body), byte-identical HLO across two
  lowers of the same config (cache-key stability).
* :mod:`.contracts` — cross-checkers diffing every ``os.environ`` read
  against the docs' env tables, every ``obs``/``bus`` emit name
  against the OBSERVABILITY.md registry, and every SERVE_*/STREAM_*/
  BENCH_*/DATA_* config knob against recertify's ``_PROTOCOL_VARS``.

Suppression grammar (counted, never silent) — the marker names a rule
(or ``*``) and must carry a reason::

    tokens = np.asarray(out)  # ddlint: ok(host-sync): tick boundary

A reasonless or unparseable marker is itself a finding (rule
``bad-suppression``).

Entry point: ``scripts/ddlint.py`` / ``make lint`` (gated by
``make check`` via ``heavy_refresh.py --check``).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
PACKAGE_ROOT = os.path.join(REPO_ROOT, "distributeddeeplearning_tpu")


@dataclasses.dataclass
class Finding:
    """One lint finding, anchored to a file:line."""

    rule: str
    path: str  # repo-relative
    line: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None  # the suppression's reason, when suppressed

    def format(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


# ``# ddlint: ok(rule): reason`` — rule may be ``*`` (any rule on that
# line); the reason is mandatory (an unexplained suppression rots).
_SUPPRESS_RE = re.compile(
    r"#\s*ddlint:\s*ok\(\s*(?P<rule>[\w*\-]+)\s*\)\s*(?::\s*(?P<reason>.*\S))?"
)


def parse_suppressions(
    source: str,
) -> Tuple[Dict[int, List[Tuple[str, str]]], List[Tuple[int, str]]]:
    """Scan source for suppression markers.

    Returns ``(by_line, malformed)``: ``by_line[lineno]`` is the list of
    ``(rule, reason)`` markers on that line; ``malformed`` lists
    ``(lineno, problem)`` for reasonless markers (these become
    ``bad-suppression`` findings — a suppression must say why).
    """
    by_line: Dict[int, List[Tuple[str, str]]] = {}
    malformed: List[Tuple[int, str]] = []
    for i, text in enumerate(source.splitlines(), start=1):
        if "ddlint" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        if m is None:
            if re.search(r"#\s*ddlint", text):
                malformed.append(
                    (i, "unparseable ddlint marker — write it as "
                        "'ddlint: ok(<rule>): <reason>' in a comment")
                )
            continue
        reason = m.group("reason")
        if not reason:
            malformed.append(
                (i, f"suppression of {m.group('rule')!r} carries no reason")
            )
            continue
        by_line.setdefault(i, []).append((m.group("rule"), reason))
    return by_line, malformed


def apply_suppressions(
    findings: List[Finding], sources: Dict[str, str]
) -> List[Finding]:
    """Mark findings whose line carries a matching ``ok(...)`` marker as
    suppressed, and append ``bad-suppression`` findings for reasonless
    markers. ``sources`` maps repo-relative path → file text."""
    out: List[Finding] = []
    parsed = {
        path: parse_suppressions(src) for path, src in sources.items()
    }
    for f in findings:
        by_line, _ = parsed.get(f.path, ({}, []))
        # A marker binds to its own line, or up to two lines above it —
        # the tail of a wrapped statement (the finding anchors at the
        # statement's first line; the comment fits on its last).
        markers = [
            m for off in (0, 1, 2) for m in by_line.get(f.line + off, [])
        ]
        for rule, reason in markers:
            if rule in ("*", f.rule):
                f.suppressed = True
                f.reason = reason
                break
        out.append(f)
    for path, (_, malformed) in parsed.items():
        for lineno, problem in malformed:
            out.append(
                Finding("bad-suppression", path, lineno, problem)
            )
    return out


def repo_rel(path: str) -> str:
    return os.path.relpath(os.path.abspath(path), REPO_ROOT)


def package_sources(
    roots: Optional[List[str]] = None,
) -> Dict[str, str]:
    """Repo-relative path → source text for every ``.py`` under the
    given roots (default: the package + scripts + bench.py)."""
    if roots is None:
        roots = [
            PACKAGE_ROOT,
            os.path.join(REPO_ROOT, "scripts"),
            os.path.join(REPO_ROOT, "bench.py"),
        ]
    out: Dict[str, str] = {}
    for root in roots:
        if os.path.isfile(root):
            paths = [root]
        else:
            paths = [
                os.path.join(dirpath, name)
                for dirpath, dirnames, names in os.walk(root)
                for name in names
                if name.endswith(".py") and "__pycache__" not in dirpath
            ]
        for p in sorted(paths):
            with open(p, encoding="utf-8") as fh:
                out[repo_rel(p)] = fh.read()
    return out


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

# name -> (family, description, runner). Runners take no arguments and
# return raw (unsuppressed) findings against the repo at HEAD; the CLI
# applies suppressions over the scanned sources afterwards.
RuleRunner = Callable[[], List[Finding]]
_REGISTRY: Dict[str, Tuple[str, str, RuleRunner]] = {}


def register(name: str, family: str, description: str):
    def deco(fn: RuleRunner) -> RuleRunner:
        _REGISTRY[name] = (family, description, fn)
        return fn

    return deco


def rules(family: Optional[str] = None) -> Dict[str, Tuple[str, str, RuleRunner]]:
    """The registered rules (import side effect: loads all families).

    The HLO family imports jax lazily inside its runners, so listing
    rules stays instant."""
    from distributeddeeplearning_tpu.analysis import (  # noqa: F401
        ast_sync,
        contracts,
        hlo_audit,
    )

    if family is None:
        return dict(_REGISTRY)
    return {
        n: meta for n, meta in _REGISTRY.items() if meta[0] == family
    }


FAMILIES = ("ast", "hlo", "contract")
