"""Contract cross-checkers: env docs, obs registry, protocol scrub list.

Three contracts live in prose/data and rot silently when code moves:

* ``env-docs`` — the ORCHESTRATION.md / OBSERVABILITY.md env tables are
  the operator's API. Every ``os.environ`` read in the package (and the
  ``e = os.environ if env is None else env`` from_env idiom) must name a
  var those docs carry — an undocumented knob is a contract the operator
  can't see.
* ``obs-registry`` — docs/OBSERVABILITY.md's "What is instrumented"
  section is the event-name registry every report/rollup/SLO consumer
  keys on. Every literal ``obs.counter/gauge/point/span`` name emitted
  anywhere in the package must appear there; an unregistered name is
  telemetry nothing will ever render.
* ``protocol-vars`` — recertify scrubs ``_PROTOCOL_VARS`` from the
  environment before each row so an ambient export can't leak into rows
  that leave it unset. Two ways that list rots: a protocol row defines a
  var the scrub list misses, and a new SERVE_*/STREAM_*/BENCH_* knob is
  parsed by a config surface without joining the list. Both checked
  here, against recertify's own AST (no import side effects).

All three fail with the exact missing/stale names.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from distributeddeeplearning_tpu.analysis import (
    Finding,
    PACKAGE_ROOT,
    REPO_ROOT,
    package_sources,
    register,
)

DOCS = ("docs", "README.md")
_ENV_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")

# Vars owned by the platform/runtime, not this repo's contract: they are
# read here but documented (and set) elsewhere. Keep minimal — a var of
# OURS belongs in the docs, not in this set.
EXTERNAL_ENV = {
    "TPU_WORKER_HOSTNAMES",  # TPU-VM metadata (jax.distributed autodetect)
    "JAX_PLATFORMS", "XLA_FLAGS",  # jax/XLA runtime selection
    "PATH", "HOME", "PWD", "USER",
}


# ---------------------------------------------------------------------------
# Shared extraction: env reads, doc tokens
# ---------------------------------------------------------------------------

def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _EnvReads(ast.NodeVisitor):
    """Collect ``(var, line)`` for every env read, including the
    ``e = os.environ if env is None else env`` / ``e = _env(env)``
    from_env idiom (names bound to an environ-or-override mapping)."""

    def __init__(self) -> None:
        self.reads: List[Tuple[str, int]] = []
        self._env_aliases: Set[str] = set()

    def _is_environ(self, node: ast.AST) -> bool:
        name = _dotted(node)
        if name in ("os.environ", "environ"):
            return True
        return isinstance(node, ast.Name) and node.id in self._env_aliases

    def visit_Assign(self, node: ast.Assign) -> None:
        v = node.value
        aliasing = False
        if isinstance(v, ast.IfExp) and (
            self._is_environ(v.body) or self._is_environ(v.orelse)
        ):
            aliasing = True
        if isinstance(v, ast.Call) and _dotted(v.func) in ("_env",):
            aliasing = True
        if self._is_environ(v):
            aliasing = True
        if aliasing:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._env_aliases.add(t.id)
        self.generic_visit(node)

    def _note(self, var: Optional[str], line: int) -> None:
        if var is not None and _ENV_NAME_RE.match(var):
            self.reads.append((var, line))

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        if name == "os.getenv" and node.args:
            self._note(_str_const(node.args[0]), node.lineno)
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop", "setdefault")
            and self._is_environ(node.func.value)
            and node.args
        ):
            self._note(_str_const(node.args[0]), node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self._is_environ(node.value):
            self._note(_str_const(node.slice), node.lineno)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # "X" in e / "X" in os.environ
        if (
            len(node.ops) == 1
            and isinstance(node.ops[0], (ast.In, ast.NotIn))
            and self._is_environ(node.comparators[0])
        ):
            self._note(_str_const(node.left), node.lineno)
        self.generic_visit(node)


def env_reads(source: str) -> List[Tuple[str, int]]:
    v = _EnvReads()
    v.visit(ast.parse(source))
    return v.reads


_INLINE_CODE_RE = re.compile(r"`([^`\n]+)`")
_FENCE_RE = re.compile(r"^```.*?$(.*?)^```", re.M | re.S)
_UPPER_TOKEN_RE = re.compile(r"\b([A-Z][A-Z0-9_]{2,})\b")


def doc_texts() -> Dict[str, str]:
    out: Dict[str, str] = {}
    for entry in DOCS:
        path = os.path.join(REPO_ROOT, entry)
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if name.endswith(".md"):
                    with open(os.path.join(path, name), encoding="utf-8") as f:
                        out[f"{entry}/{name}"] = f.read()
        elif os.path.isfile(path):
            with open(path, encoding="utf-8") as f:
                out[entry] = f.read()
    return out


def documented_env_vars() -> Set[str]:
    """Every UPPER_CASE token that appears as code in the docs (inline
    backticks or fenced blocks) — the documented env surface."""
    vars_: Set[str] = set()
    for text in doc_texts().values():
        for m in _INLINE_CODE_RE.finditer(text):
            vars_.update(_UPPER_TOKEN_RE.findall(m.group(1)))
        for m in _FENCE_RE.finditer(text):
            vars_.update(_UPPER_TOKEN_RE.findall(m.group(1)))
    return vars_


@register(
    "env-docs", "contract",
    "every os.environ read in the package names a var documented in the "
    "docs' env tables (ORCHESTRATION.md / OBSERVABILITY.md / ...)",
)
def run_env_docs() -> List[Finding]:
    documented = documented_env_vars() | EXTERNAL_ENV
    findings: List[Finding] = []
    sources = package_sources([PACKAGE_ROOT])
    for path, src in sorted(sources.items()):
        for var, line in env_reads(src):
            if var not in documented:
                findings.append(Finding(
                    "env-docs", path, line,
                    f"env var {var!r} is read here but documented nowhere "
                    f"in docs/*.md or README.md — add it to the relevant "
                    f"env table (the operator contract)",
                ))
    return findings


# ---------------------------------------------------------------------------
# obs-registry
# ---------------------------------------------------------------------------

_EMIT_METHODS = {"counter", "gauge", "point", "span", "span_event"}
_BUS_RECEIVERS = {"obs", "bus", "_bus"}
_BUS_CALLS = {"get_bus", "current_bus"}


class _ObsEmits(ast.NodeVisitor):
    """Collect ``(name_or_prefix, is_prefix, kind, line)`` for every
    literal event emission (f-string names contribute their literal
    prefix, matched as a prefix against the registry)."""

    def __init__(self) -> None:
        self.emits: List[Tuple[str, bool, str, int]] = []

    def _is_bus(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in _BUS_RECEIVERS
        if isinstance(node, ast.Attribute):
            return node.attr in ("bus", "_bus") or (
                _dotted(node) or ""
            ).endswith(".obs")
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            return name is not None and name.split(".")[-1] in _BUS_CALLS
        return False

    def visit_Call(self, node: ast.Call) -> None:
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _EMIT_METHODS
            and self._is_bus(node.func.value)
            and node.args
        ):
            arg = node.args[0]
            name = _str_const(arg)
            if name is not None:
                self.emits.append((name, False, node.func.attr, node.lineno))
            elif isinstance(arg, ast.JoinedStr):
                prefix = ""
                for part in arg.values:
                    s = _str_const(part)
                    if s is None:
                        break
                    prefix += s
                if prefix:
                    self.emits.append(
                        (prefix, True, node.func.attr, node.lineno)
                    )
        self.generic_visit(node)


def obs_emits(source: str) -> List[Tuple[str, bool, str, int]]:
    v = _ObsEmits()
    v.visit(ast.parse(source))
    return v.emits


_EVENT_TOKEN_RE = re.compile(r"^[a-z][\w.*-]*$")


def registered_event_names() -> Set[str]:
    """The OBSERVABILITY.md registry: every inline-code token that looks
    like an event name (lowercase dotted identifier; ``*`` wildcards
    allowed, e.g. ``epoch.*``)."""
    path = os.path.join(REPO_ROOT, "docs", "OBSERVABILITY.md")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    names: Set[str] = set()
    for m in _INLINE_CODE_RE.finditer(text):
        token = m.group(1).strip()
        if _EVENT_TOKEN_RE.match(token):
            names.add(token)
    return names


def _name_registered(
    name: str, is_prefix: bool, registry: Set[str]
) -> bool:
    if name in registry:
        return True
    for r in registry:
        if r.endswith("*") and name.startswith(r[:-1].rstrip(".")):
            return True
        # f-string emissions (`f"epoch.{k}"`): the literal prefix must
        # prefix at least one registered name.
        if is_prefix and r.startswith(name):
            return True
    return False


@register(
    "obs-registry", "contract",
    "every obs/bus emit name in the package appears in the "
    "docs/OBSERVABILITY.md event registry",
)
def run_obs_registry() -> List[Finding]:
    registry = registered_event_names()
    findings: List[Finding] = []
    sources = package_sources([PACKAGE_ROOT])
    for path, src in sorted(sources.items()):
        for name, is_prefix, kind, line in obs_emits(src):
            if not _name_registered(name, is_prefix, registry):
                what = f"{name}*" if is_prefix else name
                findings.append(Finding(
                    "obs-registry", path, line,
                    f"{kind} {what!r} is emitted here but absent from the "
                    f"docs/OBSERVABILITY.md registry — register it (the "
                    f"report/rollup/SLO consumers key on that list)",
                ))
    return findings


# ---------------------------------------------------------------------------
# obs-trace-ctx
# ---------------------------------------------------------------------------

#: The serving hot paths where every per-request emit must carry its
#: request's trace id (docs/OBSERVABILITY.md trace plane).
TRACE_HOT_PATHS = (
    "distributeddeeplearning_tpu/serving/scheduler.py",
    "distributeddeeplearning_tpu/serving/fleet/router.py",
)

#: Event-name families whose emit sites must execute under a bound
#: trace context. Prefix-matched: ``serve.request`` also covers
#: ``serve.request_done``; ``serve.decode`` covers ``serve.decode_step``
#: (the shared tick, bound to the server's own tick trace) and
#: ``serve.decode_share`` (the per-slot attribution span).
TRACED_FAMILIES = (
    "serve.request", "serve.prefill", "serve.decode",
    "serve.queue_wait", "serve.ttft", "serve.delivery",
)


def _binds_trace_ctx(node: ast.With) -> bool:
    for item in node.items:
        ce = item.context_expr
        if isinstance(ce, ast.Call):
            name = _dotted(ce.func)
            if name is not None and name.split(".")[-1] == "trace_ctx":
                return True
    return False


class _NakedTracedEmits(ast.NodeVisitor):
    """Find traced-family emits with no lexically enclosing
    ``with ...trace_ctx(...)``. Function boundaries are barriers: a
    nested ``def``'s body runs later, possibly outside the ``with``, so
    an outer binding does not cover it."""

    def __init__(self) -> None:
        self.naked: List[Tuple[str, str, int]] = []
        self._stack: List[str] = []  # "trace" | "with" | "barrier"
        self._is_bus = _ObsEmits()._is_bus

    def _covered(self) -> bool:
        for frame in reversed(self._stack):
            if frame == "trace":
                return True
            if frame == "barrier":
                return False
        return False

    def visit_With(self, node: ast.With) -> None:
        self._stack.append(
            "trace" if _binds_trace_ctx(node) else "with"
        )
        self.generic_visit(node)
        self._stack.pop()

    def _visit_barrier(self, node: ast.AST) -> None:
        self._stack.append("barrier")
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_barrier
    visit_AsyncFunctionDef = _visit_barrier
    visit_Lambda = _visit_barrier

    def visit_Call(self, node: ast.Call) -> None:
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _EMIT_METHODS
            and self._is_bus(node.func.value)
            and node.args
        ):
            name = _str_const(node.args[0])
            if (
                name is not None
                and name.startswith(TRACED_FAMILIES)
                and not self._covered()
            ):
                self.naked.append((name, node.func.attr, node.lineno))
        self.generic_visit(node)


@register(
    "obs-trace-ctx", "contract",
    "every serve.request/serve.prefill/serve.decode-family emit in the "
    "serving hot paths executes under a lexically bound obs.trace_ctx, "
    "so the record carries its request's trace id",
)
def run_obs_trace_ctx() -> List[Finding]:
    findings: List[Finding] = []
    for rel in TRACE_HOT_PATHS:
        path = os.path.join(REPO_ROOT, rel)
        with open(path, encoding="utf-8") as f:
            src = f.read()
        v = _NakedTracedEmits()
        v.visit(ast.parse(src))
        for name, kind, line in v.naked:
            findings.append(Finding(
                "obs-trace-ctx", rel, line,
                f"{kind} {name!r} is emitted outside any bound trace "
                f"context — wrap it in `with obs.trace_ctx(...)` so the "
                f"record carries its request's trace id (the critical-"
                f"path reconstructor in obs/traces.py keys on it)",
            ))
    return findings


# ---------------------------------------------------------------------------
# protocol-vars
# ---------------------------------------------------------------------------

_PROTOCOL_PREFIXES = ("SERVE_", "STREAM_", "BENCH_", "ARBITER_", "COLOC_")


def _recertify_tables() -> Tuple[Set[str], Dict[str, Set[str]], str]:
    """(``_PROTOCOL_VARS``, protocol → row env keys, path) parsed from
    recertify's AST — no import, no side effects."""
    path = os.path.join(REPO_ROOT, "scripts", "recertify.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    scrub: Set[str] = set()
    rows: Dict[str, Set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id == "_PROTOCOL_VARS" and isinstance(
            node.value, (ast.Tuple, ast.List)
        ):
            for elt in node.value.elts:
                s = _str_const(elt)
                if s:
                    scrub.add(s)
        if target.id == "PROTOCOLS" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                proto = _str_const(k)
                if proto is None or not isinstance(v, ast.Dict):
                    continue
                keys = {
                    s for s in (_str_const(kk) for kk in v.keys)
                    if s and not s.startswith("_")
                }
                rows[proto] = keys
    return scrub, rows, os.path.relpath(path, REPO_ROOT)


@register(
    "protocol-vars", "contract",
    "every env knob a recertify row defines, and every SERVE_*/STREAM_*/"
    "BENCH_* knob parsed by a config surface, is in recertify's "
    "_PROTOCOL_VARS scrub list",
)
def run_protocol_vars() -> List[Finding]:
    scrub, rows, rec_path = _recertify_tables()
    findings: List[Finding] = []
    if not scrub or not rows:
        findings.append(Finding(
            "protocol-vars", rec_path, 1,
            "could not parse _PROTOCOL_VARS / PROTOCOLS from recertify — "
            "the checker needs both as module-level literals",
        ))
        return findings
    for proto, keys in sorted(rows.items()):
        missing = sorted(keys - scrub)
        if missing:
            findings.append(Finding(
                "protocol-vars", rec_path, 1,
                f"protocol row {proto!r} defines {missing} but "
                f"_PROTOCOL_VARS does not scrub them — an ambient export "
                f"of these can leak into every other row",
            ))
    # Config-surface knobs: any SERVE_*/STREAM_*/BENCH_* var read by the
    # package or the bench/serve scripts joins the scrub list the moment
    # it exists (recertify itself is exempt — it IS the scrubber).
    for path, src in sorted(package_sources().items()):
        if path.endswith("scripts/recertify.py"):
            continue
        for var, line in env_reads(src):
            if var.startswith(_PROTOCOL_PREFIXES) and var not in scrub:
                findings.append(Finding(
                    "protocol-vars", path, line,
                    f"env knob {var!r} is parsed here but missing from "
                    f"recertify's _PROTOCOL_VARS — an ambient export "
                    f"would leak into protocol rows that leave it unset",
                ))
    return findings
