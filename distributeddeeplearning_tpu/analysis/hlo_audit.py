"""HLO-level audit: donation, collective placement, cache-key stability.

The dynamic oracles sample these properties on whatever configs a test
happens to build; this family lowers the real programs — each training
engine's compiled step (dp / pjit / sp / pp at tiny-LM scale) plus the
SlotEngine's closed program set (via :meth:`SlotEngine.program_specs`,
the same table warmup compiles) — on the forced-8-CPU-device mesh and
walks the compiled modules:

* ``hlo-donation`` — every donated input leaf (the state under
  ``donate_argnums=(0,)``, the KV pool under ``(1,)``) must actually be
  reclaimed by a call: the compiled program runs once and each donated
  device buffer ≥ 4 KiB must come back ``is_deleted()``. A donation
  that silently fails doubles the state's HBM footprint; XLA only
  warns.
* ``hlo-collectives`` — the dp step carries its gradient all-reduce;
  the ACCUM_STEPS variant carries NO collective inside the scan body
  (``while``-loop computations, transitively) and exactly as many
  all-reduces as the plain step — collectives run once per dispatch on
  the accumulated means, never once per microbatch.
* ``hlo-cache-key`` — building + lowering the same config twice must
  produce byte-identical HLO text. Nondeterministic lowering (an
  unordered dict in a closure, a fresh uncached constant) silently
  defeats the persistent compilation cache that cheap restarts and the
  recertify battery depend on.

Everything here needs jax ≥ 8 CPU devices; the runners force
``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count=8``
when jax is not yet initialised (``scripts/ddlint.py`` sets both before
any import, tests inherit the conftest's).
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence, Set, Tuple

from distributeddeeplearning_tpu.analysis import Finding, register

# ---------------------------------------------------------------------------
# HLO text walking (pure string work — testable without jax)
# ---------------------------------------------------------------------------

_COMP_HEAD_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^{]*)?\{")
_WHILE_BODY_RE = re.compile(r"\bwhile\([^\n]*?body=%?([\w.\-]+)")
_CALLED_RE = re.compile(
    r"(?:to_apply|body|condition|branch_computations)=\{?%?([\w.\-{}, %]+)"
)
_ALLREDUCE_RE = re.compile(
    r"=\s*\S+\s+(all-reduce|all-reduce-start)\b"
)


def hlo_computations(text: str) -> Dict[str, List[str]]:
    """Computation name → its instruction lines (HLO text blocks start
    at column 0 with ``%name (...) {`` or ``ENTRY ...``)."""
    comps: Dict[str, List[str]] = {}
    current: str = ""
    for line in text.splitlines():
        if line and not line[0].isspace():
            m = _COMP_HEAD_RE.match(line.strip())
            if m:
                current = m.group(1)
                comps[current] = []
                continue
        if current:
            comps[current].append(line)
    return comps


def while_body_closure(text: str) -> Set[str]:
    """Every computation reachable from a ``while`` loop's body —
    "inside the scan", transitively through to_apply/call edges."""
    comps = hlo_computations(text)
    roots: Set[str] = set(_WHILE_BODY_RE.findall(text))
    seen: Set[str] = set()
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        if name in seen or name not in comps:
            continue
        seen.add(name)
        for line in comps[name]:
            for m in _CALLED_RE.finditer(line):
                for ref in re.findall(r"%?([\w.\-]+)", m.group(1)):
                    if ref in comps and ref not in seen:
                        frontier.append(ref)
    return seen


def allreduce_sites(text: str) -> List[Tuple[str, str]]:
    """``(computation, instruction line)`` for every all-reduce."""
    out: List[Tuple[str, str]] = []
    for comp, lines in hlo_computations(text).items():
        for line in lines:
            if _ALLREDUCE_RE.search(line):
                out.append((comp, line.strip()))
    return out


# XLA declines to alias tiny buffers (index vectors, scalar counters)
# whose liveness doesn't pay for aliasing — verified at runtime: the
# SlotEngine's s32[num_slots] position vectors stay undeleted after a
# donated call while every KV tensor is reclaimed. Donation exists to
# keep the BIG buffers single-resident, so leaves under a page are out
# of scope for the rule.
DONATION_BYTE_FLOOR = 4096


def check_donation(
    compiled,
    args: Sequence,
    donate_argnums: Sequence[int],
    program: str,
    path: str,
) -> List[Finding]:
    """Execute ``compiled`` once and verify every donated device leaf at
    or above :data:`DONATION_BYTE_FLOOR` was reclaimed (``is_deleted``).

    Runtime deletion is donation's actual semantics — the compiled
    module's ``input_output_alias`` text reorders parameters, but a
    donated-and-aliased input buffer is *deleted* by the call, and one
    XLA declined to alias is not. The donated args must be
    device-resident jax arrays (the real states/pools are)."""
    import jax

    donated = [
        (f"arg{ai}{jax.tree_util.keystr(p)}", leaf)
        for ai in donate_argnums
        for p, leaf in jax.tree_util.tree_leaves_with_path(args[ai])
        if isinstance(leaf, jax.Array)
        and leaf.nbytes >= DONATION_BYTE_FLOOR
    ]
    if not donated:
        return [Finding(
            "hlo-donation", path, 1,
            f"{program}: no device-resident donated leaves >= "
            f"{DONATION_BYTE_FLOOR}B to audit — the donation check "
            f"needs placed example args",
        )]
    compiled(*args)
    missing = [p for p, leaf in donated if not leaf.is_deleted()]
    if not missing:
        return []
    head = missing[:6]
    more = f" (+{len(missing) - 6} more)" if len(missing) > 6 else ""
    return [Finding(
        "hlo-donation", path, 1,
        f"{program}: donation not delivered for {len(missing)} donated "
        f"leaves — {head}{more}; an unaliased donated buffer is "
        f"double-resident in HBM (XLA only warns)",
    )]


def check_scan_collectives(
    accum_text: str, plain_text: str, program: str, path: str
) -> List[Finding]:
    """No all-reduce inside the accum scan body; same all-reduce count
    as the plain step (once per dispatch, not per microbatch)."""
    findings: List[Finding] = []
    inside = while_body_closure(accum_text)
    if not inside:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: no while-loop computation in the compiled "
            f"module — the ACCUM_STEPS scan is gone (unrolled or "
            f"dropped), so collective placement cannot be audited",
        ))
    in_scan = [
        (comp, line) for comp, line in allreduce_sites(accum_text)
        if comp in inside
    ]
    if in_scan:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: {len(in_scan)} all-reduce(s) INSIDE the "
            f"ACCUM_STEPS scan body (e.g. in computation "
            f"{in_scan[0][0]!r}) — gradients must accumulate locally "
            f"and reduce once per dispatch",
        ))
    n_plain = len(allreduce_sites(plain_text))
    n_accum = len(allreduce_sites(accum_text))
    if n_plain == 0:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: plain step compiled with ZERO all-reduces — "
            f"the gradient reduction is missing (or the mesh collapsed "
            f"to one device)",
        ))
    elif n_accum != n_plain:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: accum step has {n_accum} all-reduces vs the "
            f"plain step's {n_plain} — collectives must run once per "
            f"dispatch on the accumulated means",
        ))
    return findings


def check_cache_key(
    text_a: str, text_b: str, program: str, path: str
) -> List[Finding]:
    if text_a == text_b:
        return []
    # Name the first differing line — the usual culprits are unordered
    # closures and fresh constants, both visible right at the diff.
    for la, lb in zip(text_a.splitlines(), text_b.splitlines()):
        if la != lb:
            diff = f"first diff: {la.strip()[:80]!r} vs {lb.strip()[:80]!r}"
            break
    else:
        diff = "texts differ in length"
    return [Finding(
        "hlo-cache-key", path, 1,
        f"{program}: two lowers of the same config are not "
        f"byte-identical ({diff}) — nondeterministic lowering defeats "
        f"the persistent compilation cache",
    )]


# ---------------------------------------------------------------------------
# Program construction (tiny-LM scale, forced CPU mesh)
# ---------------------------------------------------------------------------

VOCAB, T = 32, 8


def _require_devices() -> None:
    import jax

    n = jax.device_count()
    if n < 8:
        raise RuntimeError(
            f"the HLO audit needs the forced 8-CPU-device mesh, got "
            f"{n} — run via scripts/ddlint.py (it exports JAX_PLATFORMS="
            f"cpu and --xla_force_host_platform_device_count=8 before "
            f"importing jax) or under tests/conftest.py"
        )


def _cfg(**kw):
    from distributeddeeplearning_tpu.config import TrainConfig

    base = dict(
        num_classes=VOCAB, batch_size_per_device=2, weight_decay=0.0,
        compute_dtype="float32",
    )
    base.update(kw)
    return TrainConfig(**base)


def _lm(**kw):
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models.transformer_lm import (
        TransformerLM,
    )

    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T,
        dtype=jnp.float32, **kw,
    )


def _tx():
    import optax

    return optax.sgd(0.1, momentum=0.9)


def _token_batch(rows: int):
    import numpy as np

    rng = np.random.RandomState(0)
    data = rng.randint(0, VOCAB, size=(rows, T + 1)).astype(np.int32)
    return data[:, :-1], data[:, 1:]


def _train_step_bundles() -> List[dict]:
    """(program, lowered_a, lowered_b, args, donate) for each engine's
    donated train step — the builder runs TWICE per engine so the
    cache-key rule sees two independent closures."""
    import jax

    from distributeddeeplearning_tpu.parallel.mesh import create_mesh

    _require_devices()
    bundles: List[dict] = []

    def lower_twice(build):
        """build() -> (step_callable_with_lower, args). Runs build twice:
        lowered module A and B must match byte-for-byte."""
        step_a, args = build()
        step_b, _ = build()
        return step_a.lower(*args), step_b.lower(*args), args

    # dp (plain + accum twin for the collective-placement rule)
    def build_dp(accum: int):
        def build():
            from distributeddeeplearning_tpu.training.train_step import (
                create_train_state,
                make_train_step,
                replicate_state,
            )

            mesh = create_mesh(axes=("data",), shape=(8,))
            cfg = _cfg(accum_steps=accum)
            model = _lm()
            tx = _tx()
            state = replicate_state(
                create_train_state(
                    model, cfg, tx, input_shape=(1, T),
                    input_dtype=jax.numpy.int32,
                ),
                mesh,
            )
            step = make_train_step(model, tx, mesh, cfg, donate_state=True)
            return step, (state, _token_batch(16))

        return build

    low_a, low_b, args = lower_twice(build_dp(1))
    dp_plain = dict(
        program="dp train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    )
    bundles.append(dp_plain)
    low_a, low_b, args = lower_twice(build_dp(2))
    bundles.append(dict(
        program="dp train step (ACCUM_STEPS=2)", lowered=low_a,
        lowered_b=low_b, args=args, donate=(0,), accum_twin_of=dp_plain,
    ))

    # pjit (GSPMD tensor parallel over data×model)
    def build_pjit():
        from distributeddeeplearning_tpu.training.pjit_step import (
            build_pjit_state,
            make_pjit_train_step,
        )

        mesh = create_mesh(axes=("data", "model"), shape=(4, 2))
        cfg = _cfg(engine="pjit")
        model = _lm()
        tx = _tx()
        state = build_pjit_state(
            model, cfg, tx, mesh, input_shape=(1, T),
            input_dtype=jax.numpy.int32,
        )
        step = make_pjit_train_step(model, tx, mesh, cfg)
        return step, (state, _token_batch(16))

    low_a, low_b, args = lower_twice(build_pjit)
    bundles.append(dict(
        program="pjit train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    ))

    # sp (ring attention over data×seq)
    def build_sp():
        from distributeddeeplearning_tpu.training.sp_step import (
            make_sp_train_step,
        )
        from distributeddeeplearning_tpu.training.train_step import (
            create_train_state,
            replicate_state,
        )

        mesh = create_mesh(axes=("data", "seq"), shape=(2, 4))
        cfg = _cfg()
        model = _lm(attn_impl="ring", seq_axis="seq")
        tx = _tx()
        state = replicate_state(
            create_train_state(
                model, cfg, tx, input_shape=(1, T),
                input_dtype=jax.numpy.int32,
            ),
            mesh,
        )
        step = make_sp_train_step(model, tx, mesh, cfg)
        return step, (state, _token_batch(4))

    low_a, low_b, args = lower_twice(build_sp)
    bundles.append(dict(
        program="sp train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    ))

    # pp (GPipe over data×pipe)
    def build_pp():
        from distributeddeeplearning_tpu.models.pipeline_lm import PipelineLM
        from distributeddeeplearning_tpu.training.pp_step import (
            create_pp_state,
            make_pp_train_step,
        )

        mesh = create_mesh(axes=("data", "pipe"), shape=(2, 4))
        cfg = _cfg(engine="pp", batch_size_per_device=2)
        model = PipelineLM(
            variant="tiny", vocab_size=VOCAB, max_seq_len=T,
            num_stages=4, n_layers=4, dtype=jax.numpy.float32,
        )
        tx = _tx()
        state = create_pp_state(model, cfg, tx, mesh, T)
        step = make_pp_train_step(
            model, tx, mesh, cfg, num_microbatches=2
        )
        return step, (state, _token_batch(4))

    low_a, low_b, args = lower_twice(build_pp)
    bundles.append(dict(
        program="pp train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    ))
    return bundles


def _audit_slot_engine(findings: Dict[str, List[Finding]]) -> None:
    """Audit the SlotEngine's dense program set — the exact table
    :meth:`SlotEngine.warmup` compiles (``program_specs``). The donation
    check *executes* each program, consuming the donated pool, so the
    pool is rebuilt between programs."""
    import jax

    import flax.linen as nn

    _require_devices()
    from distributeddeeplearning_tpu.serving.engine import SlotEngine

    model = _lm()
    variables = model.init(
        jax.random.PRNGKey(0),
        jax.numpy.zeros((2, T), jax.numpy.int32),
        train=False,
    )
    params = nn.unbox(variables["params"])
    eng = SlotEngine(
        model, params, num_slots=2, max_len=T, buckets=(4, T)
    )
    n_programs = len(eng.program_specs())
    for i in range(n_programs):
        # Fresh pool per program: the previous donation check deleted it.
        eng._pool = None
        eng._draft_pool = None
        spec = eng.program_specs()[i]
        program = f"SlotEngine {spec.name}"
        jitted = jax.jit(spec.fn, donate_argnums=spec.donate_argnums)
        low_a = jitted.lower(*spec.example_args)
        low_b = jitted.lower(*spec.example_args)
        findings["hlo-cache-key"].extend(check_cache_key(
            low_a.as_text(), low_b.as_text(), program, _ANALYSIS_PATH,
        ))
        # example_args[1] is the engine's device-resident pool (what a
        # real tick donates), so the execution check sees true deletion.
        findings["hlo-donation"].extend(check_donation(
            low_a.compile(), spec.example_args, spec.donate_argnums,
            program, _ANALYSIS_PATH,
        ))


_CACHE: Dict[str, List[Finding]] = {}
_ANALYSIS_PATH = "distributeddeeplearning_tpu/analysis/hlo_audit.py"


def _run_all() -> Dict[str, List[Finding]]:
    """Build + lower + compile everything once; route findings by rule.

    One pass feeds all three rules (compiles dominate the runtime; the
    walks are string work), memoised per process."""
    if _CACHE:
        return _CACHE
    findings: Dict[str, List[Finding]] = {
        "hlo-donation": [], "hlo-collectives": [], "hlo-cache-key": [],
    }
    texts: Dict[str, str] = {}
    for b in _train_step_bundles():
        program = b["program"]
        findings["hlo-cache-key"].extend(check_cache_key(
            b["lowered"].as_text(), b["lowered_b"].as_text(),
            program, _ANALYSIS_PATH,
        ))
        compiled = b["lowered"].compile()
        texts[program] = compiled.as_text()
        findings["hlo-donation"].extend(check_donation(
            compiled, b["args"], b["donate"], program, _ANALYSIS_PATH,
        ))
        twin = b.get("accum_twin_of")
        if twin is not None:
            findings["hlo-collectives"].extend(check_scan_collectives(
                texts[program], texts[twin["program"]], program,
                _ANALYSIS_PATH,
            ))
    _audit_slot_engine(findings)
    _CACHE.update(findings)
    return _CACHE


@register(
    "hlo-donation", "hlo",
    "donated buffers (train-step state, SlotEngine KV pool) are actually "
    "aliased in the compiled modules",
)
def run_hlo_donation() -> List[Finding]:
    return list(_run_all()["hlo-donation"])


@register(
    "hlo-collectives", "hlo",
    "the dp step carries its gradient all-reduce; the ACCUM_STEPS scan "
    "body carries none (collectives once per dispatch)",
)
def run_hlo_collectives() -> List[Finding]:
    return list(_run_all()["hlo-collectives"])


@register(
    "hlo-cache-key", "hlo",
    "the same config lowers to byte-identical HLO twice (persistent "
    "compilation cache stability)",
)
def run_hlo_cache_key() -> List[Finding]:
    return list(_run_all()["hlo-cache-key"])
