"""HLO-level audit: donation, collective placement, cache-key stability.

The dynamic oracles sample these properties on whatever configs a test
happens to build; this family lowers the real programs — each training
engine's compiled step (dp / pjit / sp / pp at tiny-LM scale) plus the
SlotEngine's closed program set (via :meth:`SlotEngine.program_specs`,
the same table warmup compiles) — on the forced-8-CPU-device mesh and
walks the compiled modules:

* ``hlo-donation`` — every donated input leaf (the state under
  ``donate_argnums=(0,)``, the KV pool under ``(1,)``) must actually be
  reclaimed by a call: the compiled program runs once and each donated
  device buffer ≥ 4 KiB must come back ``is_deleted()``. A donation
  that silently fails doubles the state's HBM footprint; XLA only
  warns.
* ``hlo-collectives`` — the dp step carries its gradient all-reduce;
  the ACCUM_STEPS variant carries NO collective inside the scan body
  (``while``-loop computations, transitively) and exactly as many
  all-reduces as the plain step — collectives run once per dispatch on
  the accumulated means, never once per microbatch.
* ``hlo-cache-key`` — building + lowering the same config twice must
  produce byte-identical HLO text. Nondeterministic lowering (an
  unordered dict in a closure, a fresh uncached constant) silently
  defeats the persistent compilation cache that cheap restarts and the
  recertify battery depend on.
* ``hlo-fused-decode`` — the SERVE_DECODE_KERNEL=fused decode program
  carries the fused-kernel evidence (the Pallas custom-call on TPU; the
  ``paged_decode_fused`` scope marker under CPU interpret mode) and
  contains NO full-sequence-length dequantized K/V buffer — the
  gather→dequant→HBM round-trip the kernel exists to eliminate. The
  detector self-calibrates: the stitched XLA twin of the same config
  MUST trip it, so a silently-broken detector is itself a finding.
  Fused programs also go through the cache-key rule.
* ``hlo-async-collective`` — the pjit/sp gradient all-reduces carry the
  ``training/overlap.py`` scope tag in their HLO metadata (provable on
  any backend, including this CPU CI), and wherever the backend DOES
  split them (``all-reduce-start``, TPU async flags), every start has a
  matching ``-done`` with real compute scheduled between — latency
  actually hidden, not just requested.

Everything here needs jax ≥ 8 CPU devices; the runners force
``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count=8``
when jax is not yet initialised (``scripts/ddlint.py`` sets both before
any import, tests inherit the conftest's).
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence, Set, Tuple

from distributeddeeplearning_tpu.analysis import Finding, register

# ---------------------------------------------------------------------------
# HLO text walking (pure string work — testable without jax)
# ---------------------------------------------------------------------------

_COMP_HEAD_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^{]*)?\{")
_WHILE_BODY_RE = re.compile(r"\bwhile\([^\n]*?body=%?([\w.\-]+)")
_CALLED_RE = re.compile(
    r"(?:to_apply|body|condition|branch_computations)=\{?%?([\w.\-{}, %]+)"
)
_ALLREDUCE_RE = re.compile(
    r"=\s*\S+\s+(all-reduce|all-reduce-start)\b"
)


def hlo_computations(text: str) -> Dict[str, List[str]]:
    """Computation name → its instruction lines (HLO text blocks start
    at column 0 with ``%name (...) {`` or ``ENTRY ...``)."""
    comps: Dict[str, List[str]] = {}
    current: str = ""
    for line in text.splitlines():
        if line and not line[0].isspace():
            m = _COMP_HEAD_RE.match(line.strip())
            if m:
                current = m.group(1)
                comps[current] = []
                continue
        if current:
            comps[current].append(line)
    return comps


def while_body_closure(text: str) -> Set[str]:
    """Every computation reachable from a ``while`` loop's body —
    "inside the scan", transitively through to_apply/call edges."""
    comps = hlo_computations(text)
    roots: Set[str] = set(_WHILE_BODY_RE.findall(text))
    seen: Set[str] = set()
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        if name in seen or name not in comps:
            continue
        seen.add(name)
        for line in comps[name]:
            for m in _CALLED_RE.finditer(line):
                for ref in re.findall(r"%?([\w.\-]+)", m.group(1)):
                    if ref in comps and ref not in seen:
                        frontier.append(ref)
    return seen


def allreduce_sites(text: str) -> List[Tuple[str, str]]:
    """``(computation, instruction line)`` for every all-reduce."""
    out: List[Tuple[str, str]] = []
    for comp, lines in hlo_computations(text).items():
        for line in lines:
            if _ALLREDUCE_RE.search(line):
                out.append((comp, line.strip()))
    return out


# XLA declines to alias tiny buffers (index vectors, scalar counters)
# whose liveness doesn't pay for aliasing — verified at runtime: the
# SlotEngine's s32[num_slots] position vectors stay undeleted after a
# donated call while every KV tensor is reclaimed. Donation exists to
# keep the BIG buffers single-resident, so leaves under a page are out
# of scope for the rule.
DONATION_BYTE_FLOOR = 4096


def check_donation(
    compiled,
    args: Sequence,
    donate_argnums: Sequence[int],
    program: str,
    path: str,
) -> List[Finding]:
    """Execute ``compiled`` once and verify every donated device leaf at
    or above :data:`DONATION_BYTE_FLOOR` was reclaimed (``is_deleted``).

    Runtime deletion is donation's actual semantics — the compiled
    module's ``input_output_alias`` text reorders parameters, but a
    donated-and-aliased input buffer is *deleted* by the call, and one
    XLA declined to alias is not. The donated args must be
    device-resident jax arrays (the real states/pools are)."""
    import jax

    donated = [
        (f"arg{ai}{jax.tree_util.keystr(p)}", leaf)
        for ai in donate_argnums
        for p, leaf in jax.tree_util.tree_leaves_with_path(args[ai])
        if isinstance(leaf, jax.Array)
        and leaf.nbytes >= DONATION_BYTE_FLOOR
    ]
    if not donated:
        return [Finding(
            "hlo-donation", path, 1,
            f"{program}: no device-resident donated leaves >= "
            f"{DONATION_BYTE_FLOOR}B to audit — the donation check "
            f"needs placed example args",
        )]
    compiled(*args)
    missing = [p for p, leaf in donated if not leaf.is_deleted()]
    if not missing:
        return []
    head = missing[:6]
    more = f" (+{len(missing) - 6} more)" if len(missing) > 6 else ""
    return [Finding(
        "hlo-donation", path, 1,
        f"{program}: donation not delivered for {len(missing)} donated "
        f"leaves — {head}{more}; an unaliased donated buffer is "
        f"double-resident in HBM (XLA only warns)",
    )]


def check_scan_collectives(
    accum_text: str, plain_text: str, program: str, path: str
) -> List[Finding]:
    """No all-reduce inside the accum scan body; same all-reduce count
    as the plain step (once per dispatch, not per microbatch)."""
    findings: List[Finding] = []
    inside = while_body_closure(accum_text)
    if not inside:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: no while-loop computation in the compiled "
            f"module — the ACCUM_STEPS scan is gone (unrolled or "
            f"dropped), so collective placement cannot be audited",
        ))
    in_scan = [
        (comp, line) for comp, line in allreduce_sites(accum_text)
        if comp in inside
    ]
    if in_scan:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: {len(in_scan)} all-reduce(s) INSIDE the "
            f"ACCUM_STEPS scan body (e.g. in computation "
            f"{in_scan[0][0]!r}) — gradients must accumulate locally "
            f"and reduce once per dispatch",
        ))
    n_plain = len(allreduce_sites(plain_text))
    n_accum = len(allreduce_sites(accum_text))
    if n_plain == 0:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: plain step compiled with ZERO all-reduces — "
            f"the gradient reduction is missing (or the mesh collapsed "
            f"to one device)",
        ))
    elif n_accum != n_plain:
        findings.append(Finding(
            "hlo-collectives", path, 1,
            f"{program}: accum step has {n_accum} all-reduces vs the "
            f"plain step's {n_plain} — collectives must run once per "
            f"dispatch on the accumulated means",
        ))
    return findings


def check_cache_key(
    text_a: str, text_b: str, program: str, path: str
) -> List[Finding]:
    if text_a == text_b:
        return []
    # Name the first differing line — the usual culprits are unordered
    # closures and fresh constants, both visible right at the diff.
    for la, lb in zip(text_a.splitlines(), text_b.splitlines()):
        if la != lb:
            diff = f"first diff: {la.strip()[:80]!r} vs {lb.strip()[:80]!r}"
            break
    else:
        diff = "texts differ in length"
    return [Finding(
        "hlo-cache-key", path, 1,
        f"{program}: two lowers of the same config are not "
        f"byte-identical ({diff}) — nondeterministic lowering defeats "
        f"the persistent compilation cache",
    )]


# Dequant detector: an f32 `multiply` whose output is a >= 4-dim
# tensor ([B, L, H, Dh] dense rows, [B, mb, bs, H, Dh] gathered blocks)
# holding at least a full KV pool's worth of elements is the stitched
# path's dequantize-into-HBM buffer. Attention/MLP activations at
# decode are [B, 1, ...] 3-dim tensors, and everything the fused
# kernel multiplies in f32 is block-sized or lane scratch — neither
# matches both conditions.
_F32_MUL_RE = re.compile(r"=\s*f32\[([\d,]*)\][^=]*\bmultiply\(")


def _full_kv_multiplies(text: str, min_elems: int) -> List[str]:
    """Instruction lines whose f32 multiply output is >= 4-dim and
    spans >= min_elems elements (the full-sequence dequantized K/V
    signature)."""
    out = []
    for line in text.splitlines():
        m = _F32_MUL_RE.search(line)
        if not m:
            continue
        dims = [int(d) for d in m.group(1).split(",") if d]
        if len(dims) < 4:
            continue
        n = 1
        for d in dims:
            n *= d
        if n >= min_elems:
            out.append(line.strip())
    return out


def check_fused_decode(
    fused_text: str, xla_text: str, min_elems: int, program: str,
    path: str,
) -> List[Finding]:
    """The fused decode program's two invariants + detector calibration
    against its stitched XLA twin (see module docstring)."""
    from distributeddeeplearning_tpu.ops.pallas.paged_decode import (
        FUSED_SCOPE,
    )

    findings: List[Finding] = []
    # Kernel evidence: the TPU lowering is a custom-call; the CPU
    # interpret lowering inlines the grid but keeps the named scope in
    # instruction metadata. Either form proves dispatch reached the
    # kernel.
    if "custom-call" not in fused_text and FUSED_SCOPE not in fused_text:
        findings.append(Finding(
            "hlo-fused-decode", path, 1,
            f"{program}: neither a Pallas custom-call nor the "
            f"{FUSED_SCOPE!r} scope marker appears in the lowered decode "
            f"program — SERVE_DECODE_KERNEL=fused never reached the "
            f"kernel (ops/pallas/paged_decode.py dispatch lost)",
        ))
    hits = _full_kv_multiplies(fused_text, min_elems)
    if hits:
        findings.append(Finding(
            "hlo-fused-decode", path, 1,
            f"{program}: fused decode still materialises a "
            f"full-sequence dequantized K/V buffer "
            f"({hits[0][:80]!r}) — the gather→dequant chain the kernel "
            f"exists to eliminate is back",
        ))
    if not _full_kv_multiplies(xla_text, min_elems):
        findings.append(Finding(
            "hlo-fused-decode", path, 1,
            f"{program}: the stitched XLA twin shows NO full-sequence "
            f"dequantized K/V multiply — the detector lost its signal "
            f"(threshold {min_elems} elems); fix _full_kv_multiplies "
            f"before trusting the fused assertion",
        ))
    return findings


_COMPUTE_OP_RE = re.compile(
    r"=\s*\S+\s+(fusion|dot|convolution|multiply|add|subtract|divide|"
    r"exponential|custom-call)\b"
)


def check_async_collectives(
    text: str, program: str, path: str,
) -> List[Finding]:
    """The overlap contract on one compiled train step: (a) >= 1
    all-reduce carries the ``training/overlap.py`` tag; (b) every
    ``all-reduce-start`` pairs with a ``-done`` and has compute
    scheduled between them (vacuously true where the backend never
    splits — the CPU CI proves (a), a TPU build proves both)."""
    from distributeddeeplearning_tpu.training.overlap import OVERLAP_SCOPE

    findings: List[Finding] = []
    sites = allreduce_sites(text)
    if not any(OVERLAP_SCOPE in line for _, line in sites):
        findings.append(Finding(
            "hlo-async-collective", path, 1,
            f"{program}: none of the {len(sites)} all-reduce sites "
            f"carries the {OVERLAP_SCOPE!r} tag — the step builder lost "
            f"the overlap scope (training/overlap.py; "
            f"TrainConfig.async_collectives)",
        ))
    for comp, lines in hlo_computations(text).items():
        starts: Dict[str, int] = {}
        for i, line in enumerate(lines):
            m = re.match(r"\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=.*"
                         r"\ball-reduce-start\b", line)
            if m:
                starts[m.group(1)] = i
        for name, i in starts.items():
            done = next(
                (j for j, line in enumerate(lines)
                 if "all-reduce-done" in line and name in line), None,
            )
            if done is None:
                findings.append(Finding(
                    "hlo-async-collective", path, 1,
                    f"{program}: all-reduce-start %{name} in {comp} has "
                    f"no matching all-reduce-done — unfinished async "
                    f"collective",
                ))
                continue
            between = [
                line for line in lines[i + 1:done]
                if _COMPUTE_OP_RE.search(line)
                and "all-reduce" not in line
            ]
            if not between:
                findings.append(Finding(
                    "hlo-async-collective", path, 1,
                    f"{program}: all-reduce-start %{name} in {comp} "
                    f"completes with no compute scheduled between start "
                    f"and done — the async pair hides nothing",
                ))
    return findings


# ---------------------------------------------------------------------------
# Program construction (tiny-LM scale, forced CPU mesh)
# ---------------------------------------------------------------------------

VOCAB, T = 32, 8


def _require_devices() -> None:
    import jax

    n = jax.device_count()
    if n < 8:
        raise RuntimeError(
            f"the HLO audit needs the forced 8-CPU-device mesh, got "
            f"{n} — run via scripts/ddlint.py (it exports JAX_PLATFORMS="
            f"cpu and --xla_force_host_platform_device_count=8 before "
            f"importing jax) or under tests/conftest.py"
        )


def _cfg(**kw):
    from distributeddeeplearning_tpu.config import TrainConfig

    base = dict(
        num_classes=VOCAB, batch_size_per_device=2, weight_decay=0.0,
        compute_dtype="float32",
    )
    base.update(kw)
    return TrainConfig(**base)


def _lm(**kw):
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models.transformer_lm import (
        TransformerLM,
    )

    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T,
        dtype=jnp.float32, **kw,
    )


def _tx():
    import optax

    return optax.sgd(0.1, momentum=0.9)


def _token_batch(rows: int):
    import numpy as np

    rng = np.random.RandomState(0)
    data = rng.randint(0, VOCAB, size=(rows, T + 1)).astype(np.int32)
    return data[:, :-1], data[:, 1:]


def _train_step_bundles() -> List[dict]:
    """(program, lowered_a, lowered_b, args, donate) for each engine's
    donated train step — the builder runs TWICE per engine so the
    cache-key rule sees two independent closures."""
    import jax

    from distributeddeeplearning_tpu.parallel.mesh import create_mesh

    _require_devices()
    bundles: List[dict] = []

    def lower_twice(build):
        """build() -> (step_callable_with_lower, args). Runs build twice:
        lowered module A and B must match byte-for-byte."""
        step_a, args = build()
        step_b, _ = build()
        return step_a.lower(*args), step_b.lower(*args), args

    # dp (plain + accum twin for the collective-placement rule)
    def build_dp(accum: int):
        def build():
            from distributeddeeplearning_tpu.training.train_step import (
                create_train_state,
                make_train_step,
                replicate_state,
            )

            mesh = create_mesh(axes=("data",), shape=(8,))
            cfg = _cfg(accum_steps=accum)
            model = _lm()
            tx = _tx()
            state = replicate_state(
                create_train_state(
                    model, cfg, tx, input_shape=(1, T),
                    input_dtype=jax.numpy.int32,
                ),
                mesh,
            )
            step = make_train_step(model, tx, mesh, cfg, donate_state=True)
            return step, (state, _token_batch(16))

        return build

    low_a, low_b, args = lower_twice(build_dp(1))
    dp_plain = dict(
        program="dp train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    )
    bundles.append(dp_plain)
    low_a, low_b, args = lower_twice(build_dp(2))
    bundles.append(dict(
        program="dp train step (ACCUM_STEPS=2)", lowered=low_a,
        lowered_b=low_b, args=args, donate=(0,), accum_twin_of=dp_plain,
    ))

    # pjit (GSPMD tensor parallel over data×model)
    def build_pjit():
        from distributeddeeplearning_tpu.training.pjit_step import (
            build_pjit_state,
            make_pjit_train_step,
        )

        mesh = create_mesh(axes=("data", "model"), shape=(4, 2))
        cfg = _cfg(engine="pjit")
        model = _lm()
        tx = _tx()
        state = build_pjit_state(
            model, cfg, tx, mesh, input_shape=(1, T),
            input_dtype=jax.numpy.int32,
        )
        step = make_pjit_train_step(model, tx, mesh, cfg)
        return step, (state, _token_batch(16))

    low_a, low_b, args = lower_twice(build_pjit)
    bundles.append(dict(
        program="pjit train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    ))

    # sp (ring attention over data×seq)
    def build_sp():
        from distributeddeeplearning_tpu.training.sp_step import (
            make_sp_train_step,
        )
        from distributeddeeplearning_tpu.training.train_step import (
            create_train_state,
            replicate_state,
        )

        mesh = create_mesh(axes=("data", "seq"), shape=(2, 4))
        cfg = _cfg()
        model = _lm(attn_impl="ring", seq_axis="seq")
        tx = _tx()
        state = replicate_state(
            create_train_state(
                model, cfg, tx, input_shape=(1, T),
                input_dtype=jax.numpy.int32,
            ),
            mesh,
        )
        step = make_sp_train_step(model, tx, mesh, cfg)
        return step, (state, _token_batch(4))

    low_a, low_b, args = lower_twice(build_sp)
    bundles.append(dict(
        program="sp train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    ))

    # pp (GPipe over data×pipe)
    def build_pp():
        from distributeddeeplearning_tpu.models.pipeline_lm import PipelineLM
        from distributeddeeplearning_tpu.training.pp_step import (
            create_pp_state,
            make_pp_train_step,
        )

        mesh = create_mesh(axes=("data", "pipe"), shape=(2, 4))
        cfg = _cfg(engine="pp", batch_size_per_device=2)
        model = PipelineLM(
            variant="tiny", vocab_size=VOCAB, max_seq_len=T,
            num_stages=4, n_layers=4, dtype=jax.numpy.float32,
        )
        tx = _tx()
        state = create_pp_state(model, cfg, tx, mesh, T)
        step = make_pp_train_step(
            model, tx, mesh, cfg, num_microbatches=2
        )
        return step, (state, _token_batch(4))

    low_a, low_b, args = lower_twice(build_pp)
    bundles.append(dict(
        program="pp train step", lowered=low_a, lowered_b=low_b,
        args=args, donate=(0,),
    ))
    return bundles


def _audit_slot_engine(findings: Dict[str, List[Finding]]) -> None:
    """Audit the SlotEngine's dense program set — the exact table
    :meth:`SlotEngine.warmup` compiles (``program_specs``). The donation
    check *executes* each program, consuming the donated pool, so the
    pool is rebuilt between programs."""
    import jax

    import flax.linen as nn

    _require_devices()
    from distributeddeeplearning_tpu.serving.engine import SlotEngine

    model = _lm()
    variables = model.init(
        jax.random.PRNGKey(0),
        jax.numpy.zeros((2, T), jax.numpy.int32),
        train=False,
    )
    params = nn.unbox(variables["params"])
    eng = SlotEngine(
        model, params, num_slots=2, max_len=T, buckets=(4, T)
    )
    n_programs = len(eng.program_specs())
    for i in range(n_programs):
        # Fresh pool per program: the previous donation check deleted it.
        eng._pool = None
        eng._draft_pool = None
        spec = eng.program_specs()[i]
        program = f"SlotEngine {spec.name}"
        jitted = jax.jit(spec.fn, donate_argnums=spec.donate_argnums)
        low_a = jitted.lower(*spec.example_args)
        low_b = jitted.lower(*spec.example_args)
        findings["hlo-cache-key"].extend(check_cache_key(
            low_a.as_text(), low_b.as_text(), program, _ANALYSIS_PATH,
        ))
        # example_args[1] is the engine's device-resident pool (what a
        # real tick donates), so the execution check sees true deletion.
        findings["hlo-donation"].extend(check_donation(
            low_a.compile(), spec.example_args, spec.donate_argnums,
            program, _ANALYSIS_PATH,
        ))


def _audit_fused_decode(findings: Dict[str, List[Finding]]) -> None:
    """Lower the fused decode program next to its stitched XLA twin
    (paged + int8 — the config whose dequant buffer is detectable) and
    run the fused invariants + cache-key stability on it."""
    import jax

    import flax.linen as nn

    _require_devices()
    from distributeddeeplearning_tpu.serving.engine import SlotEngine

    model = _lm()
    variables = model.init(
        jax.random.PRNGKey(0),
        jax.numpy.zeros((2, T), jax.numpy.int32),
        train=False,
    )
    params = nn.unbox(variables["params"])
    texts: Dict[str, str] = {}
    for kern in ("fused", "xla"):
        eng = SlotEngine(
            model, params, num_slots=2, max_len=T, buckets=(4, T),
            kv_layout="paged", block_size=4, kv_dtype="int8",
            decode_kernel=kern,
        )
        spec = next(s for s in eng.program_specs() if s.name == "decode")
        jitted = jax.jit(spec.fn, donate_argnums=spec.donate_argnums)
        low_a = jitted.lower(*spec.example_args)
        if kern == "fused":
            low_b = jitted.lower(*spec.example_args)
            findings["hlo-cache-key"].extend(check_cache_key(
                low_a.as_text(), low_b.as_text(),
                "SlotEngine decode (fused)", _ANALYSIS_PATH,
            ))
        texts[kern] = low_a.compile().as_text()
    # Full pool worth of elements: num_slots * max_len * hidden
    # (H * Dh = hidden; tiny variant hidden = 128).
    min_elems = 2 * T * 128
    findings["hlo-fused-decode"].extend(check_fused_decode(
        texts["fused"], texts["xla"], min_elems,
        "SlotEngine decode (paged int8)", _ANALYSIS_PATH,
    ))


_CACHE: Dict[str, List[Finding]] = {}
_ANALYSIS_PATH = "distributeddeeplearning_tpu/analysis/hlo_audit.py"


def _run_all() -> Dict[str, List[Finding]]:
    """Build + lower + compile everything once; route findings by rule.

    One pass feeds all three rules (compiles dominate the runtime; the
    walks are string work), memoised per process."""
    if _CACHE:
        return _CACHE
    findings: Dict[str, List[Finding]] = {
        "hlo-donation": [], "hlo-collectives": [], "hlo-cache-key": [],
        "hlo-fused-decode": [], "hlo-async-collective": [],
    }
    texts: Dict[str, str] = {}
    for b in _train_step_bundles():
        program = b["program"]
        findings["hlo-cache-key"].extend(check_cache_key(
            b["lowered"].as_text(), b["lowered_b"].as_text(),
            program, _ANALYSIS_PATH,
        ))
        compiled = b["lowered"].compile()
        texts[program] = compiled.as_text()
        findings["hlo-donation"].extend(check_donation(
            compiled, b["args"], b["donate"], program, _ANALYSIS_PATH,
        ))
        twin = b.get("accum_twin_of")
        if twin is not None:
            findings["hlo-collectives"].extend(check_scan_collectives(
                texts[program], texts[twin["program"]], program,
                _ANALYSIS_PATH,
            ))
    # The overlap tag is a step-builder invariant of the sharded
    # engines whose gradient reduction the builders own (pjit GSPMD +
    # sp shard_map; dp's reduction lives in train_step/accum, pp's in
    # its pipeline loop — out of the ASYNC_COLLECTIVES contract).
    for program in ("pjit train step", "sp train step"):
        findings["hlo-async-collective"].extend(check_async_collectives(
            texts[program], program, _ANALYSIS_PATH,
        ))
    _audit_slot_engine(findings)
    _audit_fused_decode(findings)
    _CACHE.update(findings)
    return _CACHE


@register(
    "hlo-donation", "hlo",
    "donated buffers (train-step state, SlotEngine KV pool) are actually "
    "aliased in the compiled modules",
)
def run_hlo_donation() -> List[Finding]:
    return list(_run_all()["hlo-donation"])


@register(
    "hlo-collectives", "hlo",
    "the dp step carries its gradient all-reduce; the ACCUM_STEPS scan "
    "body carries none (collectives once per dispatch)",
)
def run_hlo_collectives() -> List[Finding]:
    return list(_run_all()["hlo-collectives"])


@register(
    "hlo-cache-key", "hlo",
    "the same config lowers to byte-identical HLO twice (persistent "
    "compilation cache stability)",
)
def run_hlo_cache_key() -> List[Finding]:
    return list(_run_all()["hlo-cache-key"])


@register(
    "hlo-fused-decode", "hlo",
    "the SERVE_DECODE_KERNEL=fused decode program reaches the Pallas "
    "kernel and materialises no full-sequence dequantized K/V buffer "
    "(detector calibrated against the stitched XLA twin)",
)
def run_hlo_fused_decode() -> List[Finding]:
    return list(_run_all()["hlo-fused-decode"])


@register(
    "hlo-async-collective", "hlo",
    "pjit/sp gradient all-reduces carry the overlap tag; any "
    "all-reduce-start pairs with a -done with compute between "
    "(training/overlap.py, ASYNC_COLLECTIVES)",
)
def run_hlo_async_collective() -> List[Finding]:
    return list(_run_all()["hlo-async-collective"])
