#!/usr/bin/env python3
"""Repo-root launcher shim — ``python launch.py …``.

See :mod:`distributeddeeplearning_tpu.launch` (the mpirun / Batch-AI job
submission equivalent; reference ``Horovod*/00_CreateImageAndTest.ipynb``
cells 6-7 and ``01_Train*.ipynb`` cells 15-26).
"""

from distributeddeeplearning_tpu.launch import main

if __name__ == "__main__":
    raise SystemExit(main())
