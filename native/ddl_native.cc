// Native IO tier: TFRecord codec + threaded deterministic fill.
//
// The reference's native tier is vendored comms/kernels (Horovod/NCCL/MPI,
// SURVEY.md §2a); on TPU the collectives belong to XLA, so the native
// layer that actually earns its keep is the HOST side of the data path —
// the part that must outrun the accelerator (SURVEY.md §7 hard part (a)):
//
//   * crc32c (Castagnoli, slicing-by-8) + the TFRecord masking rule
//   * TFRecord framing: batched record append, and a full-file
//     index/verify scan (offset+length per payload) that lets a reader
//     mmap/seek instead of streaming through a framework graph — also
//     gives an O(file) record *count* with no protobuf parsing
//     (imagenet.py's length counting otherwise iterates tf.data)
//   * ddl_fill_uniform_f32: splitmix64 counter-mode fill — each element
//     is hash(seed + index), so the result is bit-identical for any
//     thread count, and identical to the pure-Python/numpy fallback
//     (distributeddeeplearning_tpu/native/__init__.py)
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// Build: g++ -O3 -std=c++17 -shared -fPIC -o libddl_native.so ddl_native.cc -lpthread

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32c
// Castagnoli polynomial 0x1EDC6F41 (reflected 0x82F63B78), slicing-by-8.
uint32_t kCrcTable[8][256];
bool kCrcInit = []() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = kCrcTable[0][i];
    for (int t = 1; t < 8; t++) {
      c = kCrcTable[0][c & 0xff] ^ (c >> 8);
      kCrcTable[t][i] = c;
    }
  }
  return true;
}();

uint32_t Crc32c(const uint8_t* p, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    crc ^= static_cast<uint32_t>(v);
    uint32_t hi = static_cast<uint32_t>(v >> 32);
    crc = kCrcTable[7][crc & 0xff] ^ kCrcTable[6][(crc >> 8) & 0xff] ^
          kCrcTable[5][(crc >> 16) & 0xff] ^ kCrcTable[4][crc >> 24] ^
          kCrcTable[3][hi & 0xff] ^ kCrcTable[2][(hi >> 8) & 0xff] ^
          kCrcTable[1][(hi >> 16) & 0xff] ^ kCrcTable[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = kCrcTable[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

// TFRecord's CRC mask (tensorflow/core/lib/hash/crc32c.h semantics).
inline uint32_t MaskCrc(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

// ------------------------------------------------------------- splitmix64
inline uint64_t SplitMix64(uint64_t x) {
  uint64_t z = x + 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

uint32_t ddl_crc32c(const uint8_t* data, uint64_t n) { return Crc32c(data, n); }

uint32_t ddl_masked_crc32c(const uint8_t* data, uint64_t n) {
  return MaskCrc(Crc32c(data, n));
}

// Append `n_records` framed records to `path` (create/truncate unless
// `append`). `buf` holds the concatenated payloads; `lens[i]` their sizes.
// Returns 0, or -2 on IO error.
int ddl_tfrecord_write(const char* path, const uint8_t* buf,
                       const uint64_t* lens, uint64_t n_records, int append) {
  FILE* f = std::fopen(path, append ? "ab" : "wb");
  if (!f) return -2;
  uint64_t off = 0;
  for (uint64_t i = 0; i < n_records; i++) {
    uint8_t header[12];
    uint64_t len = lens[i];
    std::memcpy(header, &len, 8);  // little-endian (TPU/x86 hosts)
    uint32_t len_crc = MaskCrc(Crc32c(header, 8));
    std::memcpy(header + 8, &len_crc, 4);
    uint32_t data_crc = MaskCrc(Crc32c(buf + off, len));
    if (std::fwrite(header, 1, 12, f) != 12 ||
        std::fwrite(buf + off, 1, len, f) != len ||
        std::fwrite(&data_crc, 1, 4, f) != 4) {
      std::fclose(f);
      return -2;
    }
    off += len;
  }
  if (std::fclose(f) != 0) return -2;
  return 0;
}

// Scan a TFRecord file. Fills payload `offsets`/`lengths` (up to
// `capacity` entries; pass 0/NULL to only count). `verify` checks both
// CRCs per record. Returns the record count, -1 on framing/CRC error,
// -2 on IO error.
int64_t ddl_tfrecord_index(const char* path, uint64_t* offsets,
                           uint64_t* lengths, uint64_t capacity, int verify) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -2;
  // File size bounds every record length: a corrupt/garbage length field
  // must fail cleanly, not hang (negative fseek loop) or throw from
  // vector::resize across the C ABI.
  if (std::fseek(f, 0, SEEK_END) != 0) {
    std::fclose(f);
    return -2;
  }
  const uint64_t file_size = static_cast<uint64_t>(std::ftell(f));
  std::rewind(f);
  int64_t count = 0;
  uint64_t pos = 0;
  std::vector<uint8_t> payload;
  for (;;) {
    uint8_t header[12];
    size_t got = std::fread(header, 1, 12, f);
    if (got == 0) break;  // clean EOF
    if (got != 12) {
      std::fclose(f);
      return -1;
    }
    uint64_t len;
    std::memcpy(&len, header, 8);
    if (len > file_size - (pos + 12) || len + 4 > file_size - (pos + 12)) {
      std::fclose(f);
      return -1;  // length field runs past EOF: corrupt framing
    }
    if (verify) {
      uint32_t stored;
      std::memcpy(&stored, header + 8, 4);
      if (MaskCrc(Crc32c(header, 8)) != stored) {
        std::fclose(f);
        return -1;
      }
    }
    uint64_t payload_off = pos + 12;
    uint8_t footer[4];
    if (verify) {
      payload.resize(len);
      if (std::fread(payload.data(), 1, len, f) != len ||
          std::fread(footer, 1, 4, f) != 4) {
        std::fclose(f);
        return -1;
      }
      uint32_t stored;
      std::memcpy(&stored, footer, 4);
      if (MaskCrc(Crc32c(payload.data(), len)) != stored) {
        std::fclose(f);
        return -1;
      }
    } else {
      if (std::fseek(f, static_cast<long>(len + 4), SEEK_CUR) != 0) {
        std::fclose(f);
        return -1;
      }
    }
    if (offsets && static_cast<uint64_t>(count) < capacity) {
      offsets[count] = payload_off;
      lengths[count] = len;
    }
    pos = payload_off + len + 4;
    count++;
  }
  std::fclose(f);
  return count;
}

// out[i] = float32 in [0, 1) derived from SplitMix64(seed + i) — counter
// mode, so any thread count produces identical bits.
void ddl_fill_uniform_f32(float* out, uint64_t n, uint64_t seed,
                          int n_threads) {
  if (n_threads < 1) n_threads = 1;
  auto worker = [out, seed](uint64_t lo, uint64_t hi) {
    for (uint64_t i = lo; i < hi; i++) {
      uint32_t bits = static_cast<uint32_t>(SplitMix64(seed + i) >> 32);
      out[i] = static_cast<float>(bits) * (1.0f / 4294967296.0f);
    }
  };
  if (n_threads == 1 || n < 1u << 16) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> threads;
  uint64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    uint64_t lo = static_cast<uint64_t>(t) * chunk;
    uint64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
