# Build front-end — parity with the reference Makefile (Makefile:18-39)
# and its generic build helper (include/build.mk:12-16).
#
# Typical flow (reference notebook order):
#   make build smoke push      # 00_CreateImageAndTest
#   make provision setup       # 01_CreateResources
#   make submit stream         # 01_Train*
#   make teardown
#
# Registry/infra knobs come from the environment or .env (dotenv), like
# the reference's DOCKER_REPOSITORY/EXT_PWD exports (Makefile:22-29).

DOCKER_REPOSITORY ?= local
IMAGE             ?= $(DOCKER_REPOSITORY)/ddl-tpu
TAG               ?= latest
TPU               ?=
ZONE              ?=
BUCKET            ?=
ACCELERATOR_TYPE  ?= v5litepod-8
SCRIPT            ?= examples/imagenet_keras_tpu.py
JOB               ?= ddl-train
PY                ?= python

.PHONY: build login push run jupyter smoke test test-fast test-smoke check \
        lint \
        notebooks bench recertify decode-audit heavy-refresh obs-report \
        obs-watch trace-report bench-trend accum-memory fault-suite \
        elastic-drill \
        serve-bench serve-bench-spec fleet-bench chaos-bench coloc-bench \
        disagg-bench \
        stream-shards \
        stream-bench native \
        provision setup submit stream status stop teardown

## Image tier (reference 00_CreateImageAndTest + Makefile build/push)
build:
	docker build -t $(IMAGE):$(TAG) .

login:	## docker login from .env (DOCKER_USER/DOCKER_PASSWORD, reference cell-11 parity)
	$(PY) -c "import sys; from distributeddeeplearning_tpu.utils.env import docker_login; sys.exit(docker_login())"

push: login
	docker push $(IMAGE):$(TAG)

run:	## run the image's default smoke command locally
	docker run --rm -it $(IMAGE):$(TAG)

# Reference Makefile:22-29 parity: its `jupyter` target mounts PWD + data
# into the operator container and serves the notebooks.
jupyter:	## serve the notebook tier from the image
	docker run --rm -it -p 8888:8888 \
	    -v $(CURDIR):/workspace -v $(or $(DATA),/tmp/data):/data \
	    -e DOCKER_REPOSITORY=$(DOCKER_REPOSITORY) \
	    $(IMAGE):$(TAG) \
	    jupyter lab --ip=0.0.0.0 --port=8888 --allow-root --no-browser notebooks/

## Local verification (reference's mpirun -np 2 smoke, no docker needed)
smoke:
	$(PY) launch.py --num-processes 2 --devices-per-process 4 \
	    --platform cpu --timeout 540 \
	    --env FAKE=True --env FAKE_DATA_LENGTH=128 --env EPOCHS=1 \
	    --env BATCHSIZE=4 --env IMAGE_SIZE=32 --env NUM_CLASSES=8 \
	    --env MODEL=resnet18 $(SCRIPT)

test:	## full suite (~52 min on a 1-vCPU host; see docs/TESTING.md)
	$(PY) -m pytest tests/ -x -q

test-fast:	## deselect the measured-heavy oracles (tests/heavy_tests.txt)
	$(PY) -m pytest tests/ -x -q -m "not heavy"

lint:	## ddlint static-analysis suite (docs/ANALYSIS.md): AST host-sync/
	## tracer lint over the hot paths, HLO donation/collective/cache-key
	## audit of every engine step + the SlotEngine program set, and the
	## env/obs/protocol contract cross-checks. Writes lint.json. Single
	## rule: $(PY) scripts/ddlint.py --rule <name> (--list for the
	## catalogue)
	$(PY) scripts/ddlint.py

check:	## CI gate: heavy-list drift guard + the ddlint suite (one
	## command — heavy_refresh --check chains ddlint --changed-ok),
	## then the fast tier — a new slow test that skipped
	## tests/heavy_tests.txt fails here instead of silently bloating
	## every fast run (scripts/heavy_refresh.py)
	$(PY) scripts/heavy_refresh.py --check
	$(MAKE) test-fast

test-smoke:	## sub-minute loop: pure-host logic + mesh/collective semantics
	$(PY) -m pytest tests/test_collectives.py tests/test_config.py \
	    tests/test_timer.py tests/test_env_utils.py tests/test_schedules.py \
	    tests/test_synthetic_data.py tests/test_native.py -x -q

notebooks:	## execute the notebook tier headlessly; fails on any broken cell
	$(PY) scripts/run_notebooks.py

bench:
	$(PY) bench.py

recertify:	## all headline protocols at one HEAD -> RECERT.json (round 5)
	$(PY) scripts/recertify.py

decode-audit:	## decode-tier roofline + batch sweep (round 5; --kv-dtype/
	## --weight-dtype int8 audit the quantized floor, scales itemized)
	$(PY) scripts/decode_audit.py

serve-bench:	## continuous batching vs sequential generate under Poisson
	## load (docs/SERVING.md protocol; SERVE_*/BENCH_VOCAB knobs;
	## SERVE_KV_DTYPE/SERVE_WEIGHT_DTYPE=int8 run the quant compare;
	## SERVE_SPEC_K>0 runs the speculative compare)
	$(PY) scripts/serve_bench.py

serve-bench-spec:	## speculative-decode compare: greedy vs int8 self-draft
	## spec engine at K=4 on a decode-heavy backlog — gates bitwise
	## greedy parity + >=1.4x tokens/sec + closed program sets
	## (docs/SERVING.md speculative tier; serve_lm_spec recertify row)
	SERVE_SPEC_K=$(or $(SPEC_K),4) SERVE_SPEC_DRAFT=$(or $(SPEC_DRAFT),int8) \
	    SERVE_MAX_NEW=64 SERVE_REQUESTS=24 SERVE_RATE_RPS=0 \
	    SERVE_PREFILLS_PER_STEP=8 $(PY) scripts/serve_bench.py

fleet-bench:	## multi-replica fleet: 1 vs SERVE_REPLICAS(=2) replicas on a
	## seeded multi-tenant load — gates scaling (CPU-honest basis), flat
	## p99 TTFT, weighted fairness, bitwise per-request parity, closed
	## program sets per replica (docs/SERVING.md fleet tier;
	## serve_lm_fleet recertify row)
	$(PY) scripts/fleet_bench.py

chaos-bench:	## seeded mixed-verb fault storm over a closed 3-tenant
	## backlog on 2+ replicas: every non-shed request must finish with
	## bitwise splice parity, the corrupt injection detected+healed
	## (never delivered), the flap crash-loop must open the breaker,
	## program sets stay closed and p99 TTFT holds within the declared
	## multiple (docs/ROBUSTNESS.md serving failure model;
	## serve_lm_chaos recertify row; SERVE_CHAOS_PLAN/SERVE_CHAOS_SEED)
	$(PY) scripts/chaos_bench.py

disagg-bench:	## disaggregated prefill/decode pools vs the colocated
	## fleet at equal replica count on a bimodal storm with a hot
	## shared system prefix — gates strictly-better p99 TTFT, bounded
	## inter-token p99, bitwise parity vs sequential generate,
	## prefill-once-per-fleet via the prefix directory, one scheduled
	## zero-drop live migration, and closed program sets per pool
	## (docs/SERVING.md disaggregation; serve_lm_disagg recertify row)
	$(PY) scripts/disagg_bench.py

coloc-bench:	## combined fault+chaos storm over ONE device pool: a
	## serving surge drives the brownout ladder to exhaustion, the
	## arbiter shrinks training via the capacity file, the controller's
	## scale-up is lease-gated, then reclaim drains the leased replica
	## zero-drop and training grows back — training trajectory must
	## re-join the uninterrupted run at f32 ULP, p99 TTFT holds the
	## COLOC_TTFT_SLO_MS bound, zero dropped or mixed-version requests
	## (docs/ROBUSTNESS.md colocation; lm_coloc recertify row)
	$(PY) scripts/coloc_bench.py

accum-memory:	## host-side proof: compiled activation bytes vs ACCUM_STEPS (PROFILE.md)
	$(PY) scripts/accum_memory.py

stream-shards:	## local streamed-shard fixture: seeded token shards + index
	## under stream_fixture/tokens (DATA_FORMAT=stream smoke target;
	## scripts/streamgen.py builds real corpora the same way)
	$(PY) scripts/streamgen.py tokens --out stream_fixture/tokens \
	    --records 512 --seq-len 64 --vocab 256 --shard-records 128

stream-bench:	## streamed pretrain -> checkpoint -> SlotEngine serve e2e:
	## gates restored-params round trip, manifest data_cursor, and
	## served streams token-equal to inference.generate
	## (docs/DATA.md; lm_stream recertify row)
	$(PY) scripts/stream_bench.py

heavy-refresh:	## prune tests/heavy_tests.txt against --collect-only + print tier numbers
	$(PY) scripts/heavy_refresh.py

fault-suite:	## fast fault-injection battery: plan grammar, supervisor e2e,
	## heartbeat, NaN guard, checkpoint keying + corrupt-latest fallback
	## (the heavy resume-equivalence oracles run with the full suite)
	$(PY) -m pytest tests/test_faults.py tests/test_fault_tolerance.py \
	    -x -q -m "not heavy"

elastic-drill:	## fast elastic battery: shrink/restore grammar, capacity
	## probe, checkpoint portability across 1/4/8 devices, global data
	## topology, and the jax-light supervisor shrink->resume->grow e2e
	## (the heavy trajectory oracles run with the full suite;
	## docs/ROBUSTNESS.md elasticity section)
	$(PY) -m pytest tests/test_elastic.py -x -q -m "not heavy"

# Render the observability report for the most recent run directory
# (OBS_RUN=dir overrides; runs land under runs/ by convention — the
# launcher's --obs-dir, bench --events, or OBS_DIR on any entry point).
obs-report:	## event-bus run report for the newest runs/<dir> (docs/OBSERVABILITY.md)
	$(PY) scripts/obs_report.py $(or $(OBS_RUN),$(shell ls -td runs/*/ 2>/dev/null | head -1))

obs-watch:	## live dashboard for the newest runs/<dir>: rollups + SLO burn
	## rates, publishes rollup.json (OBS_RUN=dir, SLO_SPEC honored)
	$(PY) scripts/obs_watch.py $(or $(OBS_RUN),$(shell ls -td runs/*/ 2>/dev/null | head -1))

trace-report:	## per-request critical-path digest for the newest runs/<dir>:
	## top-K-slowest decomposed per phase vs fleet p50, chaos causes,
	## orphans, per-step training attribution (OBS_RUN=dir, TOP=K)
	$(PY) scripts/trace_report.py $(or $(OBS_RUN),$(shell ls -td runs/*/ 2>/dev/null | head -1)) --top $(or $(TOP),5)

bench-trend:	## regression sentinel over BENCH_r*.json: fails on a >10%
	## like-for-like drop; cpu/outage-tier rounds listed, never compared
	$(PY) scripts/bench_trend.py

## Native IO tier (built on demand by the Python bindings too)
native:
	g++ -O3 -std=c++17 -shared -fPIC -o native/libddl_native.so \
	    native/ddl_native.cc -lpthread

## Cluster tier (reference 01_CreateResources / 01_Train*)
# --tpu/--zone live on the PARENT parser (before the subcommand) and are
# only passed when set, so TPU_NAME/ZONE from .env keep working.
TPU_FLAGS = $(if $(TPU),--tpu $(TPU),) $(if $(ZONE),--zone $(ZONE),)

provision:
	$(PY) -m distributeddeeplearning_tpu.orchestration.provision \
	    $(TPU_FLAGS) pod-create --accelerator-type $(ACCELERATOR_TYPE)

setup:
	$(PY) -m distributeddeeplearning_tpu.orchestration.provision \
	    $(TPU_FLAGS) setup $(if $(BUCKET),--bucket $(BUCKET),)

submit:
	$(PY) -m distributeddeeplearning_tpu.orchestration.submit \
	    $(TPU_FLAGS) run --job $(JOB) --detach \
	    --manifest $(JOB).json $(SCRIPT)

stream:
	$(PY) -m distributeddeeplearning_tpu.orchestration.submit \
	    $(TPU_FLAGS) stream --job $(JOB)

status:
	$(PY) -m distributeddeeplearning_tpu.orchestration.submit \
	    $(TPU_FLAGS) status --job $(JOB)

stop:
	$(PY) -m distributeddeeplearning_tpu.orchestration.submit \
	    $(TPU_FLAGS) stop --job $(JOB)

teardown:
	$(PY) -m distributeddeeplearning_tpu.orchestration.provision \
	    $(TPU_FLAGS) pod-delete
