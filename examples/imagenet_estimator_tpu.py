"""Train ResNet50 on ImageNet or synthetic data — estimator-style front-end.

TPU-native counterpart of the reference's
``HorovodTF/src/imagenet_estimator_tf_horovod.py`` (459 LoC): same
env-var contract (docstring there, :1-9 — ``DISTRIBUTED``, ``FAKE``,
``FAKE_DATA_LENGTH``, ``EPOCHS``, ``VALIDATION``, ``AZ_BATCHAI_INPUT_
TRAIN``/``_TEST``, ``AZ_BATCHAI_OUTPUT_MODEL``), same mainline shape
(main() :413-455), one engine underneath.

Run locally (the reference's ``mpirun -np 2`` smoke, SURVEY.md §4.2)::

    FAKE=True FAKE_DATA_LENGTH=2048 EPOCHS=1 BATCHSIZE=32 \
        python examples/imagenet_estimator_tpu.py

On a TPU pod slice, launch with ``python -m distributeddeeplearning_tpu.
launch`` on every host (or let your job scheduler do it) — same script.
"""

# Allow `python examples/<name>.py` from a repo checkout without an
# install: put the repo root (this file's parent's parent) on sys.path.
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)


from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data import make_input_fn
from distributeddeeplearning_tpu.frontends import Estimator, RunConfig
from distributeddeeplearning_tpu.parallel import distributed
from distributeddeeplearning_tpu.utils.logging import get_logger


def main():
    distributed.maybe_initialize()  # hvd.init() equivalent (:417)
    config = TrainConfig.from_env(model="resnet50")
    logger = get_logger()
    logger.info("Estimator-style training: %s", config)

    estimator = Estimator(
        config.model,
        config,
        RunConfig(model_dir=config.model_dir),
    )
    estimator.train(make_input_fn(train=True), epochs=config.epochs)
    if config.validation:
        metrics = estimator.evaluate(make_input_fn(train=False))
        logger.info("validation: %s", metrics)


if __name__ == "__main__":
    main()
