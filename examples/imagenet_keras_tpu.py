"""Train ResNet50 — Keras-style front-end with the full callback set.

TPU-native counterpart of the reference's
``HorovodKeras/src/imagenet_keras_horovod.py`` (357 LoC): compile/fit
with the exact callback roster the reference assembles at :194-227 —
broadcast, metric averaging, 5-epoch LR warmup, x0.1 decay at 30/60/80
(arXiv:1706.02677, cited there at :40-42), per-epoch logger, rank-0
checkpointing with resume (:287-291, :316-341).

Run locally::

    FAKE=True FAKE_DATA_LENGTH=2048 EPOCHS=1 BATCHSIZE=32 \
        python examples/imagenet_keras_tpu.py
"""

# Allow `python examples/<name>.py` from a repo checkout without an
# install: put the repo root (this file's parent's parent) on sys.path.
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)


from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data import make_dataset
from distributeddeeplearning_tpu.frontends import Model
from distributeddeeplearning_tpu.parallel import distributed
from distributeddeeplearning_tpu.training.callbacks import (
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    LoggerCallback,
    MetricAverageCallback,
    ModelCheckpointCallback,
)
from distributeddeeplearning_tpu.utils.logging import get_logger


def main():
    distributed.maybe_initialize()
    config = TrainConfig.from_env(model="resnet50")
    logger = get_logger()
    logger.info("Keras-style training: %s", config)

    model = Model(config.model, config)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")

    callbacks = [
        # Reference callback roster (imagenet_keras_horovod.py:194-227):
        BroadcastGlobalVariablesCallback(0),
        MetricAverageCallback(),
        LearningRateWarmupCallback(warmup_epochs=config.warmup_epochs, verbose=True),
        LearningRateScheduleCallback(multiplier=0.1, start_epoch=30),
        LearningRateScheduleCallback(multiplier=0.01, start_epoch=60),
        LearningRateScheduleCallback(multiplier=0.001, start_epoch=80),
        LoggerCallback(),
    ]
    if config.model_dir:
        callbacks.append(ModelCheckpointCallback(config.model_dir))

    train_data = make_dataset(config, train=True)
    val_data = make_dataset(config, train=False) if config.validation else None
    result = model.fit(
        train_data,
        epochs=config.epochs,
        callbacks=callbacks,
        validation_data=val_data,
    )
    if config.validation and val_data is not None:
        # Reference averages the eval score across workers via
        # hvd.allreduce (:344-353); ours comes back already averaged.
        logger.info("final validation: %s", model.evaluate(val_data))
    logger.info("throughput: %.1f images/sec", result.images_per_sec)


if __name__ == "__main__":
    main()
