"""Train a decoder-only Transformer LM on synthetic tokens — explicit loop.

The long-context counterpart of the ImageNet examples: same engine, same
launcher, per-token cross-entropy, causal attention through the
configurable impl (``ATTN_IMPL=pallas`` runs the flash kernel).

Run locally (CPU mesh smoke)::

    FAKE_DATA_LENGTH=2048 EPOCHS=1 BATCHSIZE=4 MODEL=lm_tiny \
        SEQ_LEN=128 VOCAB=1024 python examples/lm_synthetic_tpu.py

or across 2 processes::

    python launch.py -n 2 --devices-per-process 4 --platform cpu \
        --env FAKE_DATA_LENGTH=512 --env BATCHSIZE=2 --env SEQ_LEN=64 \
        --env VOCAB=256 examples/lm_synthetic_tpu.py
"""

# Allow `python examples/<name>.py` from a repo checkout without an
# install: put the repo root (this file's parent's parent) on sys.path.
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)


import os

import jax.numpy as jnp

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokenDataset
from distributeddeeplearning_tpu.frontends import explicit
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import distributed
from distributeddeeplearning_tpu.utils.logging import get_logger, log_summary
from distributeddeeplearning_tpu.utils.timer import Timer


def main():
    distributed.maybe_initialize()
    import jax

    seq_len = int(os.environ.get("SEQ_LEN", "128"))
    vocab = int(os.environ.get("VOCAB", "32000"))
    # lm_tiny is only the default — MODEL=lm_base etc. must win (from_env
    # overrides beat the env, so don't pass model as an override).
    defaults = {} if "MODEL" in os.environ else {"model": "lm_tiny"}
    config = TrainConfig.from_env(num_classes=vocab, **defaults)
    logger = get_logger()
    logger.info("LM training: %s (seq_len=%d)", config.model, seq_len)

    model = get_model(
        config.model,
        **{**config.model_kwargs(), "num_classes": vocab},
        max_seq_len=seq_len,
    )
    data = SyntheticTokenDataset(
        length=config.fake_data_length,
        global_batch_size=config.global_batch_size,
        seq_len=seq_len,
        vocab_size=vocab,
        seed=config.seed,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
    )
    pieces, state = explicit.setup(
        model,
        config,
        steps_per_epoch=data.steps_per_epoch,
        input_shape=(1, seq_len),
        input_dtype=jnp.int32,
    )

    timer = Timer().start()
    for epoch in range(config.epochs):
        state = explicit.train_epoch(pieces, state, data, epoch)
    timer.stop()

    tokens = config.epochs * data.steps_per_epoch * config.global_batch_size
    log_summary(
        data_length=tokens,
        duration_s=timer.elapsed,
        batch_size_per_device=config.batch_size_per_device,
        num_devices=jax.device_count(),
        dataset_kind="synthetic-tokens",
    )


if __name__ == "__main__":
    main()
