"""Pipeline-parallel LM training on synthetic tokens.

The PP tier end-to-end: a decoder LM split into stages over a ``pipe``
mesh axis (each device holds one stage's weights), trained with the
GPipe fill-drain schedule (``training/pp_step.py``), composed with data
parallelism when the mesh has a ``data`` axis.

Env contract (the usual reference-style knobs plus PP's own)::

    PP_STAGES=4 PP_MICROBATCHES=8 MESH_SHAPE=2,4 \
    FAKE_DATA_LENGTH=4096 EPOCHS=1 BATCHSIZE=4 SEQ_LEN=128 \
    python examples/lm_pipeline_tpu.py

``MESH_SHAPE`` here is ``(data, pipe)``; it defaults to all devices on
``pipe``. Smoke (CPU): prefix with
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

# Allow `python examples/<name>.py` from a repo checkout without an
# install: put the repo root (this file's parent's parent) on sys.path.
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import os

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokenDataset
from distributeddeeplearning_tpu.models.pipeline_lm import PipelineLM
from distributeddeeplearning_tpu.parallel import distributed
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training.pp_step import (
    create_pp_state,
    make_pp_eval_step,
    make_pp_train_step,
)
from distributeddeeplearning_tpu.utils.logging import get_logger, log_summary
from distributeddeeplearning_tpu.utils.timer import Timer


def main():
    distributed.maybe_initialize()
    seq_len = int(os.environ.get("SEQ_LEN", "128"))
    vocab = int(os.environ.get("VOCAB_SIZE", "1024"))
    stages = int(os.environ.get("PP_STAGES", "0")) or len(jax.devices())
    microbatches = int(os.environ.get("PP_MICROBATCHES", "4"))
    config = TrainConfig.from_env(num_classes=vocab, model="lm_tiny")
    logger = get_logger()

    n_dev = len(jax.devices())
    if config.mesh_shape is not None:
        data_par, stages = config.mesh_shape
    else:
        data_par = n_dev // stages
    mesh = create_mesh(axes=("data", "pipe"), shape=(data_par, stages))
    from distributeddeeplearning_tpu.models.transformer_lm import _VARIANTS

    variant = config.model.replace("lm_", "")
    if variant not in _VARIANTS:
        raise SystemExit(
            f"MODEL={config.model!r}: the pipeline example supports the dense "
            f"LM family only (lm_{{{','.join(sorted(_VARIANTS))}}})"
        )
    depth = _VARIANTS[variant][1]
    # round the depth up to a stage multiple so every stage is equal
    n_layers = -(-depth // stages) * stages
    pl = PipelineLM(
        variant=variant, vocab_size=vocab, max_seq_len=seq_len,
        num_stages=stages, n_layers=n_layers, remat=config.remat,
    )
    logger.info(
        "PP LM: %s over %d stages x %d-way DP, %d microbatches",
        variant, stages, data_par, microbatches,
    )

    data = SyntheticTokenDataset(
        length=config.fake_data_length,
        global_batch_size=config.batch_size_per_device * data_par,
        seq_len=seq_len,
        vocab_size=vocab,
        seed=config.seed,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
    )
    tx = optax.sgd(config.base_lr * data_par, momentum=config.momentum)
    state = create_pp_state(pl, config, tx, mesh, seq_len)
    step = make_pp_train_step(
        pl, tx, mesh, config, num_microbatches=microbatches
    )
    spec = NamedSharding(mesh, P("data"))

    timer = Timer().start()
    seen = 0
    metrics = {}
    for epoch in range(config.epochs):
        for tokens, labels in data.epoch(epoch):
            batch = (jax.device_put(tokens, spec), jax.device_put(labels, spec))
            state, metrics = step(state, batch)
            seen += tokens.shape[0]
    jax.block_until_ready(metrics)
    timer.stop()
    logger.info(
        "final loss %.4f acc %.4f", float(metrics.get("loss", np.nan)),
        float(metrics.get("accuracy", np.nan)),
    )
    log_summary(
        data_length=seen,
        duration_s=timer.elapsed,
        batch_size_per_device=config.batch_size_per_device,
        num_devices=n_dev,
        dataset_kind="synthetic-tokens",
    )
    eval_step = make_pp_eval_step(pl, mesh)
    rows = next(iter(data.epoch(0)))
    m = eval_step(
        state, (jax.device_put(rows[0], spec), jax.device_put(rows[1], spec))
    )
    logger.info("eval: loss %.4f top1 %.4f", float(m["loss"]), float(m["top1"]))


if __name__ == "__main__":
    main()
