"""Autoregressive generation from a trained (or fresh) LM checkpoint.

The inference counterpart of ``lm_synthetic_tpu.py``: restores a
checkpoint if ``MODEL_DIR`` points at one (otherwise seeds fresh
params), then samples continuations through the KV-cache sampler
(``inference.generate`` — one jitted prefill+scan program; greedy /
temperature / top-k / top-p; EOS early-stop).

Env contract (the usual spellings plus the sampler's)::

    MODEL=lm_tiny VOCAB=32000 SEQ_LEN=128 BATCHSIZE=4 PROMPT_LEN=16 \
    MAX_NEW_TOKENS=64 TEMPERATURE=0.8 TOP_K=40 TOP_P=0.95 [EOS_TOKEN=2] \
    [MODEL_DIR=checkpoints/] python examples/lm_generate_tpu.py

Defaults (model, SEQ_LEN, seed) mirror ``lm_synthetic_tpu.py`` so its
default-trained checkpoint restores here with just ``MODEL_DIR=``.
"""

from __future__ import annotations

# Allow `python examples/<name>.py` from a repo checkout without an
# install: put the repo root (this file's parent's parent) on sys.path.
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import os

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.inference import generate
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.utils.logging import get_logger

    log = get_logger()
    # Defaults mirror examples/lm_synthetic_tpu.py so a default-trained
    # checkpoint restores here without extra env.
    vocab = int(os.environ.get("VOCAB", "32000"))
    seq_len = int(os.environ.get("SEQ_LEN", "128"))
    new_tokens = int(os.environ.get("MAX_NEW_TOKENS", "64"))
    prompt_len = int(os.environ.get("PROMPT_LEN", "16"))
    temperature = float(os.environ.get("TEMPERATURE", "0.8"))
    top_k = int(os.environ["TOP_K"]) if "TOP_K" in os.environ else None
    top_p = float(os.environ["TOP_P"]) if "TOP_P" in os.environ else None
    eos = int(os.environ["EOS_TOKEN"]) if "EOS_TOKEN" in os.environ else None
    defaults = {} if "MODEL" in os.environ else {"model": "lm_tiny"}
    cfg = TrainConfig.from_env(num_classes=vocab, **defaults)

    if cfg.model_dir and prompt_len + new_tokens > seq_len:
        # the checkpoint's pos_embed is sized by the TRAINING seq_len —
        # a longer table cannot be restored into
        raise SystemExit(
            f"PROMPT_LEN+MAX_NEW_TOKENS ({prompt_len + new_tokens}) exceeds "
            f"the checkpoint's SEQ_LEN ({seq_len}) — raise SEQ_LEN to the "
            "value the model was trained with"
        )
    model = get_model(
        cfg.model, **cfg.model_kwargs(),
        max_seq_len=seq_len if cfg.model_dir else max(
            seq_len, prompt_len + new_tokens
        ),
    )
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
    )

    # ONE construction point for the seeded params (jit init, unboxed
    # logical-partitioning metadata) — also the checkpoint-restore target.
    tx, _ = create_optimizer(cfg, steps_per_epoch=1)
    state = create_train_state(
        model, cfg, tx, input_shape=(1, seq_len), input_dtype=jnp.int32
    )
    if cfg.model_dir:
        from distributeddeeplearning_tpu.training.checkpoint import (
            CheckpointManager,
        )

        mgr = CheckpointManager(cfg.model_dir)
        latest = mgr.latest_epoch()
        if latest is None:
            mgr.close()
            raise SystemExit(
                f"MODEL_DIR={cfg.model_dir}: no checkpoint found — train "
                "first (examples/lm_synthetic_tpu.py) or unset MODEL_DIR "
                "to sample from fresh params"
            )
        state, _ = mgr.maybe_restore(state)
        mgr.close()
        log.info(
            "restored %s from %s (epoch %d)", cfg.model, cfg.model_dir, latest
        )
    else:
        log.info("no MODEL_DIR: sampling from fresh seeded params")
    params = state.params

    rng = np.random.RandomState(cfg.seed)
    batch = cfg.batch_size_per_device
    prompt = rng.randint(0, vocab, size=(batch, prompt_len)).astype(np.int32)
    out = generate(
        model, params, prompt,
        max_new_tokens=new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, eos_token=eos,
        rng=jax.random.PRNGKey(cfg.seed + 1),
    )
    out = np.asarray(out)
    for i, row in enumerate(out):
        log.info("sample %d: %s ...", i, " ".join(map(str, row[: prompt_len + 12])))
    log.info(
        "generated %d x %d tokens (%s)", batch, new_tokens,
        f"eos={eos}" if eos is not None else "no eos",
    )


if __name__ == "__main__":
    main()
