"""Train ResNet50 — explicit-loop front-end (you own the loop).

TPU-native counterpart of the reference's
``HorovodPytorch/src/imagenet_pytorch_horovod.py`` (363 LoC): the
hand-written epoch loop (main() :267-359, train() :204-221, validate()
:224-239), with checkpointing added — the reference PyTorch path has
none (SURVEY.md §5), which we treat as a defect, not a feature.

Run locally::

    FAKE=True FAKE_DATA_LENGTH=2048 EPOCHS=1 BATCHSIZE=32 \
        python examples/imagenet_explicit_tpu.py
"""

# Allow `python examples/<name>.py` from a repo checkout without an
# install: put the repo root (this file's parent's parent) on sys.path.
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)


import jax

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data import make_dataset
from distributeddeeplearning_tpu.frontends import explicit
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import distributed
from distributeddeeplearning_tpu.training.checkpoint import CheckpointManager
from distributeddeeplearning_tpu.utils.logging import get_logger, log_summary
from distributeddeeplearning_tpu.utils.timer import Timer


def main():
    distributed.maybe_initialize()
    config = TrainConfig.from_env(model="resnet50")
    logger = get_logger()
    logger.info("explicit-loop training: %s", config)

    model = get_model(config.model, **config.model_kwargs())
    train_data = make_dataset(config, train=True)
    pieces, state = explicit.setup(
        model, config, steps_per_epoch=train_data.steps_per_epoch
    )
    ckpt = CheckpointManager(
        config.model_dir, save_every_epochs=config.checkpoint_every_epochs
    )
    if config.resume and ckpt.enabled:
        state, start_epoch = ckpt.maybe_restore(state)
    else:
        start_epoch = 0

    timer = Timer().start()
    for epoch in range(start_epoch, config.epochs):
        state = explicit.train_epoch(pieces, state, train_data, epoch)
        if config.validation:
            metrics = explicit.validate(
                pieces, state, make_dataset(config, train=False)
            )
            logger.info("validation: %s", metrics, extra={"epoch": epoch})
        ckpt.save(epoch, state)
    timer.stop()
    ckpt.wait()

    epochs_run = config.epochs - start_epoch
    log_summary(
        data_length=epochs_run * train_data.steps_per_epoch * config.global_batch_size,
        duration_s=timer.elapsed,
        batch_size_per_device=config.batch_size_per_device,
        num_devices=jax.device_count(),
        dataset_kind="synthetic" if config.fake else "real",
    )


if __name__ == "__main__":
    main()
