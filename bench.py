"""Benchmark harness — emits ONE JSON line with the canonical metric.

Measures the reference's canonical metric (SURVEY.md §6): ``Total
images/sec`` for ResNet50 training on seeded synthetic ImageNet-shaped
data (the reference's ``FAKE=True`` IO-free upper-bound protocol,
``01_CreateResources.ipynb`` cell 2), on whatever devices are attached —
one v5e chip under the driver, 8 forced CPU devices in dev.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the reference-era per-GPU estimate for its exact stack
(ResNet50 fp32, per-GPU batch 64, Horovod/V100): ~325 images/sec/GPU.
``vs_baseline`` = our images/sec *per chip* / 325.

Every train-protocol line also carries ``compile_sec`` (AOT compile time,
measured apart from the hot loop — set ``COMPILATION_CACHE_DIR`` to make
re-runs deserialize instead of recompiling) and ``host_sync_count`` (host
materialisations inside the measured region; exactly 1 — the closing
fence — when the loop is sync-free).

``--events`` (or ``OBS_DIR`` in the env) additionally routes every
record and the compile/measure spans through the structured event bus
(``distributeddeeplearning_tpu/obs/``): the one JSON line on stdout
stays the driver protocol, but the same record lands in the run's
``events-p0.jsonl`` where ``scripts/obs_report.py`` can merge it with
training-loop and launcher events.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Optional

import jax
import numpy as np

REFERENCE_IMAGES_PER_SEC_PER_DEVICE = 325.0  # V100 fp32 ResNet50, reference stack
WARMUP_STEPS = 3
MEASURE_STEPS = 20

# Set by _guard_device_init when the TPU relay is down and the run fell
# back to CPU: merged into every record so the trajectory reads the
# round as an infra outage (tier: "cpu" + the probe diagnosis), not as a
# 100% perf regression (the BENCH_r04/r05 value: 0.0 lines).
_TIER_NOTE: Optional[dict] = None


def _emit_record(record: dict) -> None:
    """THE output path for every protocol record: the canonical JSON
    line on stdout (the driver's contract, unchanged) plus the same
    record as a ``bench_result`` event on the bus — ring-only when
    events mode is off, persisted when ``--events``/``OBS_DIR`` is on.
    Train-protocol records carrying accumulation fields also land as
    gauges so run reports can plot effective batch vs throughput."""
    if _TIER_NOTE:
        record = {**record, **{
            k: v for k, v in _TIER_NOTE.items() if k not in record
        }}
    print(json.dumps(record), flush=True)
    from distributeddeeplearning_tpu import obs

    bus = obs.get_bus()
    bus.point("bench_result", **record)
    if "accum_steps" in record:
        bus.gauge("bench.accum_steps", float(record["accum_steps"]))
    if "effective_batch" in record:
        bus.gauge("bench.effective_batch", float(record["effective_batch"]))
    bus.flush()


def _accum_steps_env() -> int:
    """ACCUM_STEPS for the bench protocols (in-step microbatched
    accumulation — the compiled step scans k microbatches per dispatch;
    activation memory ∝ microbatch). Resolved once so the JSON record
    can never disagree with the program that ran."""
    import os

    return max(int(os.environ.get("ACCUM_STEPS", "1")), 1)


def run_bench(
    per_device_batch: int,
    devices=None,
    profile_dir=None,
    *,
    model_name=None,
    depth: int = 50,
    image_size: int = 224,
):
    import jax.numpy as jnp
    import ml_dtypes
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    n_dev = devices if devices is not None else jax.device_count()
    global_batch = per_device_batch * n_dev
    cfg = TrainConfig(
        batch_size_per_device=per_device_batch, image_size=image_size,
        accum_steps=_accum_steps_env(),
    )
    # model_name (a vision-zoo registry name) measures that model under
    # the same protocol (BASELINE configs: vit_b16, efficientnet_b4);
    # default = the canonical ResNet50 line. All knobs are parsed once in
    # main() and passed through so the metric name can never desync from
    # the model actually benchmarked.
    if model_name:
        from distributeddeeplearning_tpu.models import get_model

        model = get_model(model_name, num_classes=1000, dtype=jnp.bfloat16)
    else:
        model = ResNet(depth=depth, num_classes=1000, dtype=jnp.bfloat16)
    mesh = data_parallel_mesh(n_dev)
    tx, _ = create_optimizer(cfg, steps_per_epoch=cfg.steps_per_epoch())
    state = replicate_state(create_train_state(model, cfg, tx), mesh)
    step = make_train_step(model, tx, mesh, cfg)

    from distributeddeeplearning_tpu.utils import hostsync

    rng = np.random.RandomState(42)
    host_batch = (
        # Staged bf16 (PROFILE.md): model compute dtype, half the transfer.
        rng.uniform(-1, 1, size=(global_batch, image_size, image_size, 3)).astype(
            ml_dtypes.bfloat16
        ),
        rng.randint(0, 1000, size=(global_batch,)).astype(np.int32),
    )
    batch = shard_batch(host_batch, mesh)

    # AOT compile, separately timed: compile cost must never smear into
    # the measured region, and with a persistent compilation cache
    # (COMPILATION_CACHE_DIR) re-runs deserialize instead of recompiling.
    from distributeddeeplearning_tpu import obs

    with obs.span("compile", what="bench_step"):
        _, compile_sec = step.aot_compile(state, batch)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    # host readback: drains the device queue
    float(hostsync.device_get(metrics["loss"], label="bench_fence"))

    # Fence with a host readback of a value that depends on every step in
    # the chain — block_until_ready alone does not reliably wait through
    # the axon loopback relay (it reported 165x hardware peak).
    import contextlib

    prof = (
        jax.profiler.trace(profile_dir)
        if profile_dir
        else contextlib.nullcontext()
    )
    sync0 = hostsync.accountant().count
    with prof, obs.span("bench_measure", steps=MEASURE_STEPS):
        t0 = time.perf_counter()
        for _ in range(MEASURE_STEPS):
            state, metrics = step(state, batch)
        assert np.isfinite(
            float(hostsync.device_get(metrics["loss"], label="bench_fence"))
        )
        dt = time.perf_counter() - t0

    images_per_sec = MEASURE_STEPS * global_batch / dt
    perf = {
        "compile_sec": round(compile_sec, 3),
        # syncs inside the measured region: exactly the closing fence
        "host_sync_count": int(hostsync.accountant().count - sync0),
        "accum_steps": cfg.accum_steps,
        "effective_batch": global_batch,
    }
    return images_per_sec, n_dev, perf


def run_lm_bench(
    model_name: str,
    per_device_batch: int,
    seq_len: int,
    attn_impl: str,
    profile_dir=None,
):
    """Long-context tier protocol: tokens/sec for a decoder LM (dense or
    MoE) on synthetic tokens, DP over all attached devices. Selected via
    ``BENCH_MODEL=lm_small`` etc.; the default ResNet50 protocol (the
    driver's canonical line) is untouched."""
    import contextlib
    import os

    import jax.numpy as jnp

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    vocab = int(os.environ.get("BENCH_VOCAB", "32000"))
    n_dev = jax.device_count()
    global_batch = per_device_batch * n_dev
    cfg = TrainConfig(
        model=model_name,
        batch_size_per_device=per_device_batch,
        attn_impl=attn_impl,
        num_classes=vocab,
        accum_steps=_accum_steps_env(),
    )
    model = get_model(model_name, **cfg.model_kwargs(), max_seq_len=seq_len)
    mesh = data_parallel_mesh(n_dev)
    tx, _ = create_optimizer(cfg, steps_per_epoch=64)
    state = replicate_state(
        create_train_state(
            model, cfg, tx, input_shape=(1, seq_len), input_dtype=jnp.int32
        ),
        mesh,
    )
    from distributeddeeplearning_tpu.utils import hostsync

    step = make_train_step(model, tx, mesh, cfg)
    rng = np.random.RandomState(42)
    rows = rng.randint(0, vocab, size=(global_batch, seq_len + 1)).astype(np.int32)
    batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh)

    from distributeddeeplearning_tpu import obs

    with obs.span("compile", what="bench_step"):
        _, compile_sec = step.aot_compile(state, batch)  # see run_bench

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    # fence (see run_bench)
    float(hostsync.device_get(metrics["loss"], label="bench_fence"))

    prof = (
        jax.profiler.trace(profile_dir) if profile_dir else contextlib.nullcontext()
    )
    sync0 = hostsync.accountant().count
    with prof, obs.span("bench_measure", steps=MEASURE_STEPS):
        t0 = time.perf_counter()
        for _ in range(MEASURE_STEPS):
            state, metrics = step(state, batch)
        assert np.isfinite(
            float(hostsync.device_get(metrics["loss"], label="bench_fence"))
        )
        dt = time.perf_counter() - t0
    tokens_per_sec = MEASURE_STEPS * global_batch * seq_len / dt
    perf = {
        "compile_sec": round(compile_sec, 3),
        "host_sync_count": int(hostsync.accountant().count - sync0),
        "accum_steps": cfg.accum_steps,
        "effective_batch": global_batch,
    }
    return tokens_per_sec, n_dev, perf


def run_decode_bench(model_name: str, batch: int, prompt_len: int, new_tokens: int):
    """Inference tier: generated tokens/sec through the KV-cache sampler
    (``inference.generate``) — selected via ``BENCH_DECODE=1``."""
    import os

    import flax.linen as nn
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.inference import generate
    from distributeddeeplearning_tpu.models import get_model

    vocab = int(os.environ.get("BENCH_VOCAB", "32000"))
    max_len = prompt_len + new_tokens
    model = get_model(model_name, num_classes=vocab, max_seq_len=max_len)
    variables = jax.jit(model.init, static_argnames=("train",))(
        jax.random.PRNGKey(0), jnp.zeros((batch, max_len), jnp.int32),
        train=False,
    )
    params = nn.unbox(variables["params"])
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, vocab, size=(batch, prompt_len)).astype(np.int32)
    kw = dict(max_new_tokens=new_tokens, temperature=0.8, top_k=40,
              rng=jax.random.PRNGKey(1))
    out = generate(model, params, prompt, **kw)  # compile + warmup
    int(np.asarray(out)[0, -1])
    t0 = time.perf_counter()
    reps = 3
    for i in range(reps):
        out = generate(model, params, prompt,
                       **{**kw, "rng": jax.random.PRNGKey(2 + i)})
    int(np.asarray(out)[0, -1])  # fence
    dt = time.perf_counter() - t0
    return reps * batch * new_tokens / dt


def decode_main():
    import os

    model_name = os.environ.get("BENCH_MODEL", "lm_small")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", "128"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
    try:
        tps = run_decode_bench(model_name, batch, prompt_len, new_tokens)
        _emit_record({
            "metric": f"{model_name}_decode_tokens_per_sec",
            "value": round(tps, 1),
            "unit": "tokens/sec",
            "vs_baseline": 0.0,  # the reference has no inference path
            "detail": {
                "batch": batch, "prompt_len": prompt_len,
                "new_tokens": new_tokens,
                "platform": jax.devices()[0].platform,
            },
        })
        return 0
    except Exception as e:
        _emit_record({
            "metric": f"{model_name}_decode_tokens_per_sec", "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": repr(e),
        })
        return 1


def lm_main():
    import os

    model_name = os.environ["BENCH_MODEL"]
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "1024"))
    attn_impl = os.environ.get(
        "ATTN_IMPL", "pallas" if jax.default_backend() == "tpu" else "xla"
    )
    batches = (8, 4, 2, 1)
    if "BENCH_BATCH" in os.environ:
        batches = (int(os.environ["BENCH_BATCH"]),)
    profile_dir = os.environ.get("BENCH_PROFILE") or None
    last_err = None
    for per_device_batch in batches:
        try:
            tps, n_dev, perf = run_lm_bench(
                model_name, per_device_batch, seq_len, attn_impl, profile_dir
            )
            _emit_record(
                {
                    "metric": f"{model_name}_synthetic_train_tokens_per_sec",
                    "value": round(tps, 1),
                    # no reference point: the reference is vision-only
                    "unit": "tokens/sec",
                    "vs_baseline": 0.0,
                    "compile_sec": perf["compile_sec"],
                    "host_sync_count": perf["host_sync_count"],
                    "accum_steps": perf["accum_steps"],
                    "effective_batch": perf["effective_batch"],
                    "detail": {
                        "devices": n_dev,
                        "per_device_batch": per_device_batch,
                        "seq_len": seq_len,
                        "attn_impl": attn_impl,
                        "tokens_per_sec_per_device": round(tps / n_dev, 1),
                        "platform": jax.devices()[0].platform,
                    },
                }
            )
            return 0
        except Exception as e:
            last_err = e
            continue
    _emit_record(
        {
            "metric": f"{model_name}_synthetic_train_tokens_per_sec",
            "value": 0.0,
            "unit": "tokens/sec",
            "vs_baseline": 0.0,
            "error": repr(last_err),
        }
    )
    return 1


def _vision_protocol():
    """Resolve the vision-mode knobs from env ONCE, for both the success
    path (main) and failure records (_intended_metric) — the metric name
    must be derived in exactly one place (ADVICE r4)."""
    import os

    depth = int(os.environ.get("BENCH_DEPTH", "50"))
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", "224"))
    vision_model = os.environ.get("BENCH_MODEL") or None
    if vision_model == "resnet50":
        # the canonical protocol by its registry name: keep the canonical
        # metric name + vs_baseline instead of demoting the run
        vision_model = None
    canonical = depth == 50 and image_size == 224 and not vision_model
    if canonical:
        metric = "resnet50_synthetic_train_images_per_sec"
    elif vision_model:
        metric = f"{vision_model}_{image_size}px_images_per_sec"
    else:
        metric = f"resnet{depth}_{image_size}px_smoke_images_per_sec"
    return vision_model, depth, image_size, canonical, metric


def _intended_metric():
    """(metric, unit) the active env selects — resolvable BEFORE any jax
    call, so failure records stay attributable to the protocol that was
    asked for (same derivation as the mode mains)."""
    import os

    model = os.environ.get("BENCH_MODEL", "")
    if os.environ.get("BENCH_DECODE", "") == "1":
        return f"{model or 'lm_small'}_decode_tokens_per_sec", "tokens/sec"
    if model.startswith("lm_"):
        return f"{model}_synthetic_train_tokens_per_sec", "tokens/sec"
    return _vision_protocol()[4], "images/sec"


def _probe_device_init(timeout_s: float) -> str:
    """Try backend init in a THROWAWAY subprocess.

    A hung ``jax.device_count()`` cannot be interrupted in-process (the
    axon plugin blocks in C++), so retrying requires each attempt to be a
    process we can kill. Returns ``"ok"`` (child saw ≥1 device),
    ``"timeout"`` (the relay-down signature — init hangs, never errors),
    or ``"error"`` (child exited nonzero: an import/env problem that the
    in-process attempt will reproduce with a real traceback — NOT a relay
    outage, so don't retry or misattribute it)."""
    import subprocess

    # The probe must honour an explicit JAX_PLATFORMS=cpu the same way
    # main() does (via config.update — the axon plugin pins platforms at
    # interpreter start, so the env var alone is ignored and a dead relay
    # would hang even a deliberate CPU run).
    probe_src = (
        "import os, jax\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "print(jax.device_count())\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe_src],
            timeout=timeout_s,
            capture_output=True,
        )
        return "ok" if r.returncode == 0 else "error"
    except subprocess.TimeoutExpired:
        return "timeout"


def _guard_device_init(
    attempts: Optional[int] = None,
    probe_timeout_s: Optional[float] = None,
    backoff_s: Optional[float] = None,
) -> None:
    """Bounded-retry device-init guard (round 5).

    A dead TPU relay makes ``jax.devices()`` block forever rather than
    error (observed end of round 4: the axon tunnel went down and every
    jax call hung) — and round 4's single-attempt fail-fast turned one
    transient relay flap into a 0.0 record for the whole round. Now: probe
    init in a killable subprocess, retry with backoff (relay flaps of a
    minute or two heal), and only after ``attempts`` straight failures
    emit the structured failure record. A watchdog still guards the real
    in-process init afterwards (the relay can die between probe and use).
    """
    import os
    import threading

    # Device-init retry policy: infra knobs, deliberately ambient across
    # a whole recertify battery (never part of any row's protocol).
    attempts = attempts or int(os.environ.get(
        "BENCH_INIT_PROBES", "3"
    ))  # ddlint: ok(protocol-vars): infra knob — relay probe count, deliberately ambient
    probe_timeout_s = probe_timeout_s or float(os.environ.get(
        "BENCH_INIT_TIMEOUT", "100"
    ))  # ddlint: ok(protocol-vars): infra knob — relay probe timeout, deliberately ambient
    backoff_s = backoff_s or float(os.environ.get(
        "BENCH_INIT_BACKOFF", "60"
    ))  # ddlint: ok(protocol-vars): infra knob — relay probe backoff, deliberately ambient
    metric, unit = _intended_metric()

    def _fail(msg: str) -> None:
        # _emit_record flushes the bus before the hard exit below (which
        # skips atexit handlers on purpose — the backend may be hung).
        _emit_record(
            {
                "metric": metric,
                "value": 0.0,
                "unit": unit,
                "vs_baseline": 0.0,
                # explicit outage marker: a 0.0 here is "nothing could
                # run", never a measured regression
                "tier": "outage",
                "error": msg,
            }
        )
        os._exit(1)

    for attempt in range(1, attempts + 1):
        outcome = _probe_device_init(probe_timeout_s)
        if outcome == "ok":
            break
        if outcome == "error":
            # Child exited with a real error (not a hang): fall through to
            # the in-process init so the actual traceback surfaces —
            # emitting a "relay down" record here would misattribute it.
            print(
                "# device-init probe errored (not a hang) — proceeding "
                "in-process for the real traceback",
                file=sys.stderr,
                flush=True,
            )
            break
        print(
            f"# device-init probe {attempt}/{attempts} timed out "
            f"({probe_timeout_s:.0f}s)",
            file=sys.stderr,
            flush=True,
        )
        if attempt == attempts:
            reason = (
                f"device init did not complete in {attempts} probes x "
                f"{probe_timeout_s:.0f}s (backoff {backoff_s:.0f}s) — "
                "accelerator attachment/relay down?"
            )
            # CPU-tier fallback (the BENCH_r04/r05 lesson): a dead relay
            # used to emit value: 0.0, which the trajectory reads as a
            # 100% regression instead of an infra outage. Run the same
            # protocol on CPU and tag every record tier: "cpu" so the
            # round stays attributable. BENCH_CPU_FALLBACK=0 restores
            # the hard-fail record (which now carries tier: "outage").
            if os.environ.get(
                "BENCH_CPU_FALLBACK", "1"
                # ddlint: ok(protocol-vars): infra knob — outage-tier fallback, deliberately ambient
            ) not in ("0", "false", "off"):
                os.environ["JAX_PLATFORMS"] = "cpu"
                if _probe_device_init(probe_timeout_s) == "ok":
                    jax.config.update("jax_platforms", "cpu")
                    global _TIER_NOTE
                    _TIER_NOTE = {"tier": "cpu", "tpu_outage": reason}
                    print(
                        "# TPU device init unreachable — falling back to "
                        "tier=cpu (records carry tier + tpu_outage)",
                        file=sys.stderr,
                        flush=True,
                    )
                    break
            _fail(reason)
        time.sleep(backoff_s)

    done = threading.Event()

    def watchdog():
        if not done.wait(probe_timeout_s * 2):
            _fail(
                "device init hung in-process after a successful probe — "
                "relay died between probe and use?"
            )

    threading.Thread(target=watchdog, daemon=True).start()
    jax.device_count()  # first backend touch — the call that can hang
    done.set()


def main():
    import os

    if "--events" in sys.argv[1:] or os.environ.get("OBS_DIR"):
        # Route the bus to OBS_DIR (or a fresh runs/bench-* dir): the
        # spans and the result record below then persist as JSONL.
        from distributeddeeplearning_tpu import obs

        if not os.environ.get("OBS_DIR"):
            os.environ["OBS_DIR"] = os.path.join(
                "runs", f"bench-{int(time.time())}"
            )
        obs.configure_from_env()
    if os.environ.get("JAX_PLATFORMS"):
        # Honour an explicit platform pick in-process: the axon plugin
        # pins jax_platforms at interpreter start, so without this a
        # deliberate CPU run still touches (and can hang on) the relay.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("COMPILATION_CACHE_DIR"):
        # Persistent XLA compilation cache: re-runs (and every protocol
        # of a recertify battery) deserialize instead of recompiling.
        from distributeddeeplearning_tpu.training.warmup import (
            enable_persistent_cache,
        )

        enable_persistent_cache(os.environ["COMPILATION_CACHE_DIR"])
    _guard_device_init()
    if os.environ.get("BENCH_DECODE", "") == "1":
        return decode_main()
    if os.environ.get("BENCH_MODEL", "").startswith("lm_"):
        return lm_main()

    last_err = None
    profile_dir = os.environ.get("BENCH_PROFILE") or None
    scaling = os.environ.get("BENCH_SCALING", "") == "1"
    batches = (256, 128, 64, 32)
    if "BENCH_BATCH" in os.environ:
        batches = (int(os.environ["BENCH_BATCH"]),)
    # ONE metric name for success and failure records — the protocol that
    # ran must be attributable either way (derivation shared with
    # _intended_metric via _vision_protocol).
    vision_model, depth, image_size, canonical, metric = _vision_protocol()
    bench_kw = dict(model_name=vision_model, depth=depth, image_size=image_size)
    for per_device_batch in batches:
        try:
            ips, n_dev, perf = run_bench(
                per_device_batch, profile_dir=profile_dir, **bench_kw
            )
            per_chip = ips / n_dev
            detail = {
                "devices": n_dev,
                # world_size mirrors devices for bench_trend's
                # world_change protocol skip: an elastic-era resize is a
                # new baseline, not a regression (scripts/bench_trend.py)
                "world_size": n_dev,
                "per_device_batch": per_device_batch,
                "images_per_sec_per_device": round(per_chip, 1),
                "platform": jax.devices()[0].platform,
                "image_size": image_size,
            }
            if vision_model:
                # no baseline field: the V100 number is a ResNet50
                # reference and means nothing for other architectures
                detail["model"] = vision_model
            else:
                detail["model_depth"] = depth
                detail["baseline_images_per_sec_per_device"] = (
                    REFERENCE_IMAGES_PER_SEC_PER_DEVICE
                )
                if not canonical:
                    detail["smoke_overrides"] = True
            if scaling and n_dev > 1:
                # Scaling-efficiency path (BASELINE >90% target, 8→64):
                # images/sec/chip at 1 device vs all attached devices. A
                # failed rerun must not discard the valid N-device result.
                try:
                    ips1, _, _ = run_bench(per_device_batch, devices=1, **bench_kw)
                    detail["images_per_sec_1_device"] = round(ips1, 1)
                    detail["scaling_efficiency"] = round(per_chip / ips1, 4)
                except Exception as e:
                    detail["scaling_error"] = repr(e)
            _emit_record(
                {
                    "metric": metric,
                    "value": round(ips, 1),
                    "unit": "images/sec",
                    # vs_baseline only means something for the
                    # canonical ResNet50@224 protocol
                    "vs_baseline": round(
                        per_chip / REFERENCE_IMAGES_PER_SEC_PER_DEVICE, 3
                    )
                    if canonical
                    else 0.0,
                    "compile_sec": perf["compile_sec"],
                    "host_sync_count": perf["host_sync_count"],
                    "accum_steps": perf["accum_steps"],
                    "effective_batch": perf["effective_batch"],
                    "detail": detail,
                }
            )
            return 0
        except Exception as e:  # OOM etc. → retry smaller batch
            last_err = e
            continue
    _emit_record({
        "metric": metric,
        "value": 0.0, "unit": "images/sec", "vs_baseline": 0.0,
        "error": repr(last_err),
    })
    return 1


if __name__ == "__main__":
    sys.exit(main())
