"""Benchmark harness — emits ONE JSON line with the canonical metric.

Measures the reference's canonical metric (SURVEY.md §6): ``Total
images/sec`` for ResNet50 training on seeded synthetic ImageNet-shaped
data (the reference's ``FAKE=True`` IO-free upper-bound protocol,
``01_CreateResources.ipynb`` cell 2), on whatever devices are attached —
one v5e chip under the driver, 8 forced CPU devices in dev.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the reference-era per-GPU estimate for its exact stack
(ResNet50 fp32, per-GPU batch 64, Horovod/V100): ~325 images/sec/GPU.
``vs_baseline`` = our images/sec *per chip* / 325.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np

REFERENCE_IMAGES_PER_SEC_PER_DEVICE = 325.0  # V100 fp32 ResNet50, reference stack
WARMUP_STEPS = 3
MEASURE_STEPS = 20


def run_bench(per_device_batch: int):
    import jax.numpy as jnp
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    n_dev = jax.device_count()
    global_batch = per_device_batch * n_dev
    cfg = TrainConfig(batch_size_per_device=per_device_batch)
    model = ResNet(depth=50, num_classes=1000, dtype=jnp.bfloat16)
    mesh = data_parallel_mesh()
    tx, _ = create_optimizer(cfg, steps_per_epoch=cfg.steps_per_epoch())
    state = replicate_state(create_train_state(model, cfg, tx), mesh)
    step = make_train_step(model, tx, mesh, cfg)

    rng = np.random.RandomState(42)
    host_batch = (
        rng.uniform(-1, 1, size=(global_batch, 224, 224, 3)).astype(np.float32),
        rng.randint(0, 1000, size=(global_batch,)).astype(np.int32),
    )
    batch = shard_batch(host_batch, mesh)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    float(metrics["loss"])  # host readback: drains the device queue

    # Fence with a host readback of a value that depends on every step in
    # the chain — block_until_ready alone does not reliably wait through
    # the axon loopback relay (it reported 165x hardware peak).
    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    dt = time.perf_counter() - t0

    images_per_sec = MEASURE_STEPS * global_batch / dt
    return images_per_sec, n_dev


def main():
    last_err = None
    for per_device_batch in (256, 128, 64, 32):
        try:
            ips, n_dev = run_bench(per_device_batch)
            per_chip = ips / n_dev
            print(
                json.dumps(
                    {
                        "metric": "resnet50_synthetic_train_images_per_sec",
                        "value": round(ips, 1),
                        "unit": "images/sec",
                        "vs_baseline": round(
                            per_chip / REFERENCE_IMAGES_PER_SEC_PER_DEVICE, 3
                        ),
                        "detail": {
                            "devices": n_dev,
                            "per_device_batch": per_device_batch,
                            "images_per_sec_per_device": round(per_chip, 1),
                            "platform": jax.devices()[0].platform,
                            "baseline_images_per_sec_per_device": REFERENCE_IMAGES_PER_SEC_PER_DEVICE,
                        },
                    }
                )
            )
            return 0
        except Exception as e:  # OOM etc. → retry smaller batch
            last_err = e
            continue
    print(json.dumps({"metric": "resnet50_synthetic_train_images_per_sec",
                      "value": 0.0, "unit": "images/sec", "vs_baseline": 0.0,
                      "error": repr(last_err)}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
