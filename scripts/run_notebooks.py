"""Execute the notebook tier headlessly and commit the outputs.

VERDICT r3 #6: the reference's notebooks are its primary UX and its
operator image serves them (``/root/reference/Docker/dockerfile:26-61``,
``jupyter_notebook_config.py:3-7``); ours must be executable and
*proven* executable, not decorative. This runner drives all three
through nbconvert's ExecutePreprocessor exactly as ``make notebooks``
and the test tier (``tests/test_notebooks.py``) do:

* ``00_BuildImageAndSmoke`` — docker cells print-only (DRY), the local
  2-process launcher smoke runs for real on forced CPU devices.
* ``01_ProvisionAndTrain`` — the orchestration CLIs in ``--dry-run``
  mode: argument validation and command synthesis execute end-to-end,
  no gcloud required.
* ``02_TrainFrontends`` — real training smokes for all front-ends on
  the in-process 8-device CPU mesh.

Executed notebooks are written back IN PLACE so the committed files
carry their outputs (the reference commits outputs too). Exit code is
non-zero on the first cell error.

Usage: python scripts/run_notebooks.py [notebook.ipynb ...]
"""

from __future__ import annotations

import os
import sys
import time

import nbformat
from nbconvert.preprocessors import ExecutePreprocessor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NOTEBOOKS = (
    "notebooks/00_BuildImageAndSmoke.ipynb",
    "notebooks/01_ProvisionAndTrain.ipynb",
    "notebooks/02_TrainFrontends.ipynb",
)


def run_notebook(path: str, timeout: int = 1800) -> None:
    """Execute one notebook in a fresh kernel (cwd = repo root, so the
    ``!python launch.py`` / ``!make`` cells resolve) and write it back
    with outputs. ``DDL_SCRATCH`` points the notebooks' working files
    (.env, job manifests) at a throwaway dir so execution never touches
    an operator's configured repo-root ``.env``. Raises on any cell
    error."""
    import tempfile

    nb = nbformat.read(path, as_version=4)
    ep = ExecutePreprocessor(timeout=timeout, kernel_name="python3")
    with tempfile.TemporaryDirectory() as scratch:
        prev = os.environ.get("DDL_SCRATCH")
        os.environ["DDL_SCRATCH"] = scratch  # kernel inherits our env
        try:
            ep.preprocess(nb, {"metadata": {"path": REPO}})
        finally:
            if prev is None:
                os.environ.pop("DDL_SCRATCH", None)
            else:
                os.environ["DDL_SCRATCH"] = prev
    nbformat.write(nb, path)


def main(argv=None) -> int:
    targets = argv if argv else [os.path.join(REPO, n) for n in NOTEBOOKS]
    for path in targets:
        t0 = time.perf_counter()
        print(f"executing {os.path.relpath(path, REPO)} ...", flush=True)
        try:
            run_notebook(path)
        except Exception as e:
            print(f"FAILED: {path}: {e}", file=sys.stderr)
            return 1
        print(f"  ok ({time.perf_counter() - t0:.0f}s)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
