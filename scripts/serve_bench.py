"""Continuous-batching serving benchmark — Poisson load vs sequential,
dense vs paged KV pool at a fixed byte budget.

The serving tier's certifiable protocol (BASELINE.md style, one JSON
line on stdout). A seeded Poisson arrival stream of mixed-length
requests is served by up to three configurations:

* **sequential baseline**: one request at a time through
  ``inference.generate`` (each distinct shape warmed first, so the
  comparison is pure steady-state throughput);
* **continuous batching** on the selected KV layout
  (``SERVE_KV_LAYOUT=dense|paged``): the same requests submitted to
  ``serving.Server`` on their arrival schedule, drained to completion;
* **compare** (``SERVE_KV_LAYOUT=compare``): dense AND paged engines at
  the SAME pool-byte budget — the dense pool holds
  ``SERVE_POOL_SLOT_BUDGET`` full ``max_len`` rows; the paged pool gets
  exactly those bytes as blocks (`budget_tokens / block_size` blocks +
  the trash block) but serves ``SERVE_SLOTS`` decode rows. On the
  long-tail length mix (``SERVE_PROFILE=longtail``) most requests need
  a fraction of ``max_len``, so block-granular admission sustains a
  multiple of the dense concurrency from the same HBM. The record
  carries both runs' throughput/concurrency and the script exits
  non-zero unless paged reaches ≥2× dense peak concurrency (or ≥1.5×
  tokens/sec) with bitwise per-request parity and zero mid-measure
  recompiles on BOTH engines.
* **quantization compare** (``SERVE_KV_DTYPE=int8|fp8`` and/or
  ``SERVE_WEIGHT_DTYPE=int8|fp8`` — docs/SERVING.md): the bf16
  (native) engine at ``SERVE_POOL_SLOT_BUDGET`` dense slots vs the
  quantized engine given the SAME KV-pool bytes — the 1-byte store
  tiers + scales pack ~2–3.5× the slots into the budget, so the quantized engine's capacity (and, with
  the per-step cost amortized over more co-resident requests, its
  tokens/sec) certifies the byte win. The load runs GREEDY; exact
  parity is mathematically unavailable under quantization (one flipped
  argmax re-conditions the whole suffix), so quality is gated by a
  **teacher-forced greedy token-match-rate oracle**: every reference
  stream is replayed through the quantized engine with the context
  forced to the bf16 tokens (``SlotEngine.force_token``) and per-step
  agreement must reach ``SERVE_QUANT_MATCH_MIN`` (0.95). The
  free-running positional match and the weight-quantization logit
  error are reported alongside, unGated (documented like the accum ULP
  note). Exits non-zero unless match ≥ threshold AND quantized
  tokens/sec ≥ bf16 with zero mid-measure recompiles and closed
  program sets on BOTH engines.
* **speculative compare** (``SERVE_SPEC_K > 0`` — docs/SERVING.md):
  plain greedy engine vs the speculative engine (``SERVE_SPEC_DRAFT``
  int8 self-draft or n-gram prompt lookup) on the same seeded greedy
  load. Speculation in the greedy regime is **lossless by
  construction**, so parity is gated bitwise; the script also gates
  speculative tokens/sec ≥ ``SERVE_SPEC_MIN_SPEEDUP`` (1.4) × the
  baseline, zero mid-measure recompiles, and both program sets closed
  at their static counts (the speculative set is enlarged — verify +
  draft programs — but still closed). Accept-rate p50/mean and
  draft/verify time are reported.

Env knobs (defaults in parentheses): ``SERVE_SLOTS`` (8),
``SERVE_BUCKETS`` ("8,16"; compare/longtail default covers the long
tail), ``SERVE_REQUESTS`` (32), ``SERVE_MAX_NEW`` (16),
``SERVE_RATE_RPS`` (200 — Poisson arrival rate; 0 = closed backlog,
all at t=0), ``SERVE_SEED`` (0), ``SERVE_PROFILE`` (mixed | longtail | disagg),
``SERVE_KV_LAYOUT`` (dense | paged | compare), ``SERVE_BLOCK_SIZE``
(16), ``SERVE_NUM_BLOCKS`` (0 = dense-equivalent),
``SERVE_POOL_SLOT_BUDGET`` (4 — the fixed byte budget, in dense slots),
``SERVE_KV_DTYPE`` / ``SERVE_WEIGHT_DTYPE`` (bf16 — int8/fp8 selects
the quantization compare; fp8 falls back to int8 off-TPU),
``SERVE_DECODE_KERNEL`` (xla — fused selects the Pallas paged-decode
kernel on every engine the run builds; threaded into the archived
record as ``detail.decode_kernel`` so bench_trend treats a kernel swap
as a protocol change), ``SERVE_QUANT_MATCH_MIN`` (0.95),
``SERVE_SPEC_K`` (0 — >0 selects the speculative compare),
``SERVE_SPEC_DRAFT`` (int8 | ngram), ``SERVE_SPEC_NGRAM_N`` (3),
``SERVE_SPEC_MIN_SPEEDUP`` (1.4),
``BENCH_MODEL`` (lm_tiny), ``BENCH_VOCAB`` (32000), plus the generic
``OBS_DIR``/``--events`` and ``COMPILATION_CACHE_DIR`` plumbing
bench.py uses. With ``SLO_SPEC`` set (and ``OBS_DIR``) the bench runs
under the live telemetry plane — rollups + SLO burn rates published to
``<OBS_DIR>/rollup.json`` while serving — and
``SERVE_ADMISSION_POLICY=adaptive`` closes the feedback loop: the
scheduler derates admission while a latency SLO burns
(docs/SERVING.md, docs/OBSERVABILITY.md).

Usage::

    python scripts/serve_bench.py [--events]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Shape mixes + seeded Poisson load + per-shape warmup live in
# serving/loadgen.py (shared with scripts/fleet_bench.py); the names
# are re-exported here because this module IS the serving bench's
# protocol surface.
from distributeddeeplearning_tpu.serving.loadgen import (  # noqa: E402
    MIXED_PROMPT_LENS,
    PROFILES,
    build_requests,
    percentile as _percentile,
    warm_shapes,
)


def _emit_record(record: dict) -> None:
    """bench.py's output contract: the canonical JSON line on stdout
    plus the same record on the event bus."""
    print(json.dumps(record), flush=True)
    from distributeddeeplearning_tpu import obs

    bus = obs.get_bus()
    bus.point("bench_result", **record)
    bus.flush()


def run_sequential(model, params, reqs, temperature, top_k):
    """One-at-a-time baseline through inference.generate; each distinct
    (prompt_len, max_new) shape is warmed first (loadgen.warm_shapes).
    Returns (tokens/sec, per-request outputs, distinct compiled
    shapes)."""
    import jax
    import numpy as np

    from distributeddeeplearning_tpu.inference import generate

    n_shapes = warm_shapes(model, params, reqs, temperature, top_k)
    outs = []
    t0 = time.perf_counter()
    for r in reqs:
        out = generate(
            model, params, r["prompt"][None], max_new_tokens=r["max_new"],
            temperature=temperature, top_k=top_k,
            rng=jax.random.PRNGKey(r["seed"]),
        )
        outs.append(np.asarray(out)[0])
    dt = time.perf_counter() - t0
    tokens = sum(r["max_new"] for r in reqs)
    return tokens / dt, outs, n_shapes


def run_continuous(server, reqs, temperature, top_k):
    """Replay the Poisson schedule against the serving loop: submit
    each request at its arrival offset, pumping the scheduler while
    waiting; drain. Returns (tokens/sec makespan throughput, handles,
    wall seconds)."""
    from distributeddeeplearning_tpu.serving import Request

    handles = []
    t0 = time.perf_counter()
    for r in reqs:
        while time.perf_counter() - t0 < r["arrival_s"]:
            server.step()  # keep decoding while the next arrival is due
        handles.append(server.submit(Request(
            prompt=r["prompt"], max_new_tokens=r["max_new"],
            temperature=temperature, top_k=top_k, rng=r["seed"],
        )))
    server.drain()
    dt = time.perf_counter() - t0
    tokens = sum(len(h.new_tokens) for h in handles)
    return tokens / dt, handles, dt


def serve_one_engine(model, params, reqs, seq_outs, *, engine_kwargs,
                     queue_depth, prefills_per_step, temperature, top_k,
                     admission_policy=None):
    """Build + warm one engine, replay the request schedule through it,
    and report throughput, concurrency, latency percentiles, parity
    against the sequential outputs (None skips the check — the quant
    compare has no bitwise reference) and the compile ledger. Returns
    ``(record, per-request new-token streams, engine)``."""
    import numpy as np

    from distributeddeeplearning_tpu.serving import Server, SlotEngine

    engine = SlotEngine(model, params, **engine_kwargs)
    engine.warmup()
    server = Server(
        engine, queue_depth=max(queue_depth, len(reqs)),
        prefills_per_step=prefills_per_step,
        admission_policy=admission_policy,
    )
    # Warm pass: one request end-to-end so first-dispatch overheads
    # (host transfers, executable load) stay out of the measurement.
    run_continuous(server, reqs[:1], temperature, top_k)
    compile_count_pre = engine.compile_count
    server.stats["peak_active"] = 0

    tps, handles, wall_s = run_continuous(server, reqs, temperature, top_k)

    parity = None if seq_outs is None else all(
        np.array_equal(h.tokens, seq_outs[i][: len(h.tokens)])
        for i, h in enumerate(handles)
    )
    ttft_ms = [h.ttft_s * 1e3 for h in handles if h.ttft_s is not None]
    qwait_ms = [
        h.queue_wait_s * 1e3 for h in handles
        if h.queue_wait_s is not None
    ]
    out = {
        "kv_layout": engine.kv_layout,
        "tokens_per_sec": round(tps, 1),
        "parity": None if parity is None else bool(parity),
        "slots": engine.num_slots,
        "peak_concurrent": server.stats["peak_active"],
        "ttft_p50_ms": round(_percentile(ttft_ms, 0.5), 2),
        "ttft_p99_ms": round(_percentile(ttft_ms, 0.99), 2),
        "queue_wait_p50_ms": round(_percentile(qwait_ms, 0.5), 2),
        "queue_wait_p99_ms": round(_percentile(qwait_ms, 0.99), 2),
        "slot_occupancy_mean": round(server.occupancy_mean, 3),
        "decode_steps": server.stats["decode_steps"],
        "compile_count": engine.compile_count,
        "programs_expected": engine.programs_expected,
        "compiles_during_measure": engine.compile_count - compile_count_pre,
        "wall_s": round(wall_s, 2),
    }
    if engine.allocator is not None:
        snap = engine.allocator.snapshot()
        out["pool"] = {
            "block_size": engine.block_size,
            "capacity_blocks": snap["capacity"],
            "prefix_hit_blocks": snap["prefix_hit_blocks"],
            "evicted": snap["evicted"],
            # utilization at peak demand: how much of the byte budget
            # actually held live KV when the pool was busiest
            "peak_live_blocks": snap["peak_live"],
            "peak_utilization": round(
                snap["peak_live"] / snap["capacity"], 3
            ) if snap["capacity"] else 0.0,
        }
    return out, [list(h.new_tokens) for h in handles], engine


def kv_slot_bytes(model, max_len: int, kv_dtype: str) -> int:
    """Per-slot KV bytes of a dense cache row at ``max_len`` — int8
    payload PLUS f32 scales when quantized (shape-only eval_shape; the
    quant compare sizes the quantized engine's slot count so both
    engines hold the SAME pool bytes)."""
    import math

    import numpy as np
    from flax import traverse_util

    from distributeddeeplearning_tpu.inference import (
        decode_cache_shapes,
        decode_variant,
    )

    shapes = decode_cache_shapes(
        decode_variant(model, kv_dtype=kv_dtype), 1, max_len
    )
    total = 0
    for path, leaf in traverse_util.flatten_dict(dict(shapes)).items():
        if path[-1] in ("cache_index", "pos_index"):
            continue
        total += math.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
    return total


def teacher_forced_match(engine, reqs, ref_streams):
    """The quantization quality oracle: per-step greedy agreement with
    the reference context FORCED (``SlotEngine.force_token``). Each
    reference stream replays through the quantized engine; at every
    step the engine answers "given this exact bf16-produced history,
    which token would I emit?" and agreement is counted. Free-running
    comparison would conflate per-step quality with divergence cascades
    (one flip re-conditions the suffix), which is why it is reported
    but not gated."""
    from distributeddeeplearning_tpu.serving import ReqSpec

    total = matched = 0
    i = 0
    active = {}  # slot -> (stream, next position to compare)
    while i < len(reqs) or active:
        for slot in engine.free_slots:
            if i >= len(reqs):
                break
            r, stream = reqs[i], ref_streams[i]
            i += 1
            first, _ = engine.prefill(slot, ReqSpec(
                prompt=r["prompt"], max_new_tokens=len(stream),
                temperature=0.0,
            ))
            total += 1
            matched += int(first == stream[0])
            if len(stream) == 1:
                engine.release(slot)
            else:
                engine.force_token(slot, int(stream[0]))
                active[slot] = (stream, 1)
        if not active:
            continue
        for slot, tok, _eos in engine.decode_step():
            if slot not in active:
                continue
            stream, c = active[slot]
            total += 1
            matched += int(tok == stream[c])
            c += 1
            if c >= len(stream):
                engine.release(slot)
                del active[slot]
            else:
                engine.force_token(slot, int(stream[c - 1]))
                active[slot] = (stream, c)
    return matched / max(total, 1)


def positional_match(ref_streams, q_streams):
    """Free-running positional agreement (reported, not gated)."""
    tot = hit = 0
    for a, b in zip(ref_streams, q_streams):
        tot += max(len(a), len(b))
        hit += sum(x == y for x, y in zip(a, b))
    return hit / max(tot, 1)


def weight_logit_err(model, params, reqs, ref_streams, n_seq: int = 2):
    """Per-step logit error of the weight quantization alone: a
    teacher-forced full forward over reference sequences with exact vs
    dequantized-int8 params (max over positions of max-abs logit
    delta). The KV-cache quantization's contribution is covered by the
    engine-level match oracle; this isolates the weights."""
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.ops import quant as quantlib

    dq = quantlib.dequantize_params(quantlib.quantize_params(params))
    err = 0.0
    for r, s in list(zip(reqs, ref_streams))[:n_seq]:
        toks = np.concatenate([r["prompt"], np.asarray(s, np.int32)])
        toks = jnp.asarray(toks[None, :])
        lr = model.apply({"params": params}, toks, train=False)
        lq = model.apply({"params": dq}, toks, train=False)
        err = max(err, float(jnp.max(jnp.abs(
            lr.astype(jnp.float32) - lq.astype(jnp.float32)
        ))))
    return err


def run_quant_compare(model, params, reqs, cfg, metric, *, budget_slots,
                      max_len, profile, rate_rps, match_min):
    """The quantized-decode certification: bf16 (native) engine at
    ``budget_slots`` dense slots vs the int8 engine holding the SAME
    KV-pool bytes (more slots — the byte win expressed as capacity),
    same seeded greedy load. Gates: teacher-forced greedy match rate ≥
    ``match_min``, quantized tokens/sec ≥ bf16, zero mid-measure
    recompiles and closed program sets on both engines (the quality
    replay reuses the warmed quantized engine, so it proves the oracle
    itself compiled nothing)."""
    import jax

    common = dict(
        queue_depth=cfg.queue_depth,
        prefills_per_step=cfg.prefills_per_step,
        temperature=0.0, top_k=None,
        admission_policy=cfg.build_admission_policy(),
    )
    ref_run, ref_streams, ref_engine = serve_one_engine(
        model, params, reqs, None,
        engine_kwargs=dict(
            num_slots=budget_slots, max_len=max_len, buckets=cfg.buckets,
            decode_kernel=cfg.decode_kernel,
        ),
        **common,
    )
    native_b = kv_slot_bytes(model, max_len, "bf16")
    quant_b = kv_slot_bytes(model, max_len, cfg.kv_dtype)
    slots_q = max(budget_slots, int(budget_slots * native_b // quant_b))
    q_run, q_streams, q_engine = serve_one_engine(
        model, params, reqs, None,
        engine_kwargs=dict(
            num_slots=slots_q, max_len=max_len, buckets=cfg.buckets,
            kv_dtype=cfg.kv_dtype, weight_dtype=cfg.weight_dtype,
            decode_kernel=cfg.decode_kernel,
        ),
        **common,
    )
    # Quality oracle on the SAME warmed quantized engine: the replay
    # must compile nothing (force_token is pure host data).
    compile_pre = q_engine.compile_count
    match = teacher_forced_match(q_engine, reqs, ref_streams)
    free_match = positional_match(ref_streams, q_streams)
    logit_err = (
        weight_logit_err(model, params, reqs, ref_streams)
        if cfg.weight_dtype != "bf16" else None
    )
    # Label the quantized side by its actual tier (int8 or fp8) so the
    # archived record says what ran; the kv tier names the engine when
    # both tiers are set.
    qlabel = cfg.kv_dtype if cfg.kv_dtype != "bf16" else cfg.weight_dtype
    tps_ratio = (
        q_run["tokens_per_sec"] / ref_run["tokens_per_sec"]
        if ref_run["tokens_per_sec"] else 0.0
    )
    capacity_ratio = (
        q_run["peak_concurrent"] / ref_run["peak_concurrent"]
        if ref_run["peak_concurrent"] else 0.0
    )
    detail = {
        "profile": profile,
        "requests": len(reqs),
        "buckets": list(cfg.buckets),
        "rate_rps": rate_rps,
        "max_len": max_len,
        "platform": jax.devices()[0].platform,
        "kv_dtype": cfg.kv_dtype,
        "weight_dtype": cfg.weight_dtype,
        "decode_kernel": cfg.decode_kernel,
        "pool_budget_slots": budget_slots,
        "kv_slot_bytes": {"bf16": int(native_b), qlabel: int(quant_b)},
        "kv_bytes_per_token": {
            "bf16": ref_engine.byte_accounting()["kv_bytes_per_token"],
            qlabel: q_engine.byte_accounting()["kv_bytes_per_token"],
        },
        "param_bytes": {
            "bf16": ref_engine.byte_accounting()["param_bytes"],
            qlabel: q_engine.byte_accounting()["param_bytes"],
        },
        "bf16": ref_run,
        qlabel: q_run,
        "tps_ratio": round(tps_ratio, 2),
        "capacity_ratio": round(capacity_ratio, 2),
        # Teacher-forced per-step agreement (GATED) vs free-running
        # positional agreement (reported): see docs/SERVING.md — exact
        # parity is mathematically unavailable under quantization.
        "match_rate": round(match, 4),
        "match_rate_min": match_min,
        "match_rate_freerun": round(free_match, 4),
        "weight_logit_err_max": (
            None if logit_err is None else round(logit_err, 5)
        ),
    }
    clean = (
        ref_run["compiles_during_measure"] == 0
        and q_run["compiles_during_measure"] == 0
        and q_engine.compile_count == compile_pre
    )
    closed = all(
        r["compile_count"] == r["programs_expected"]
        for r in (ref_run, q_run)
    )
    ok = (
        clean and closed and match >= match_min and tps_ratio >= 1.0
    )
    record = {
        "metric": metric,
        # headline: quantized throughput at the shared byte budget
        "value": q_run["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": round(tps_ratio, 2),
        "detail": detail,
    }
    _emit_record(record)
    return 0 if ok else 1


def run_spec_compare(model, params, reqs, cfg, metric, *, max_len,
                     profile, rate_rps, min_speedup):
    """The speculative-decode certification (``SERVE_SPEC_K > 0``):
    plain greedy engine vs the speculative engine (same slots, same
    seeded load, same pool geometry). Gates: **bitwise greedy parity**
    (every stream token-for-token equal — speculation must be lossless
    in the greedy regime), speculative tokens/sec >= ``min_speedup`` x
    the baseline, zero mid-measure recompiles and program sets closed
    at their static counts on BOTH engines. Accept-rate p50/mean are
    reported from the engine's per-tick tallies."""
    import jax
    import numpy as np

    common = dict(
        queue_depth=cfg.queue_depth,
        prefills_per_step=cfg.prefills_per_step,
        temperature=0.0, top_k=None,
        admission_policy=cfg.build_admission_policy(),
    )
    base_kwargs = dict(
        num_slots=cfg.num_slots, max_len=max_len, buckets=cfg.buckets,
        decode_kernel=cfg.decode_kernel,
    )
    ref_run, ref_streams, ref_engine = serve_one_engine(
        model, params, reqs, None, engine_kwargs=base_kwargs, **common,
    )
    spec_kwargs = dict(
        base_kwargs, spec_k=cfg.spec_k, spec_draft=cfg.spec_draft,
        spec_ngram_n=cfg.spec_ngram_n,
    )
    spec_run, spec_streams, spec_engine = serve_one_engine(
        model, params, reqs, None, engine_kwargs=spec_kwargs, **common,
    )
    parity = spec_streams == ref_streams  # bitwise, token for token
    st = spec_engine.spec_stats
    rates = st["accept_rates"]
    speedup = (
        spec_run["tokens_per_sec"] / ref_run["tokens_per_sec"]
        if ref_run["tokens_per_sec"] else 0.0
    )
    detail = {
        "profile": profile,
        "requests": len(reqs),
        "buckets": list(cfg.buckets),
        "rate_rps": rate_rps,
        "max_len": max_len,
        "platform": jax.devices()[0].platform,
        "spec_k": cfg.spec_k,
        "spec_draft": cfg.spec_draft,
        "decode_kernel": cfg.decode_kernel,
        "greedy": ref_run,
        "spec": spec_run,
        "speedup": round(speedup, 2),
        "min_speedup": min_speedup,
        "parity": bool(parity),
        "accept_rate_mean": round(float(np.mean(rates)), 4) if rates else None,
        "accept_rate_p50": round(_percentile(sorted(rates), 0.5), 4)
        if rates else None,
        "tokens_per_verify": round(
            st["tokens_committed"] / max(st["verify_ticks"], 1), 2
        ),
        "draft_ms_total": round(st["draft_s"] * 1e3, 1),
        "verify_ms_total": round(st["verify_s"] * 1e3, 1),
        "draft_bytes": {
            k: v for k, v in spec_engine.byte_accounting().items()
            if k.startswith("draft_")
        } or None,
    }
    clean = (
        ref_run["compiles_during_measure"] == 0
        and spec_run["compiles_during_measure"] == 0
    )
    closed = all(
        r["compile_count"] == r["programs_expected"]
        for r in (ref_run, spec_run)
    )
    ok = clean and closed and parity and speedup >= min_speedup
    record = {
        "metric": metric,
        # headline: speculative throughput on the same greedy load
        "value": spec_run["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": round(speedup, 2),
        "detail": detail,
    }
    _emit_record(record)
    return 0 if ok else 1


def start_live_plane(obs_dir):
    """Run the live telemetry plane (tail -> rollup -> SLO -> rollup.json)
    in a background thread for the duration of the bench — the thing an
    adaptive admission policy (SERVE_ADMISSION_POLICY=adaptive) reads.
    Returns (stop_event, thread), or (None, None) when SLO_SPEC is
    unset (no objectives = nothing to evaluate or feed back)."""
    import threading

    from distributeddeeplearning_tpu.obs.rollup import LivePlane
    from distributeddeeplearning_tpu.obs.slo import SloEngine

    slo = SloEngine.from_env()
    if slo is None:
        return None, None
    plane = LivePlane(obs_dir, slo_engine=slo)
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            plane.poll(now=time.time())
            stop.wait(0.2)
        plane.poll(now=time.time())

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return stop, t


def main() -> int:
    if "--events" in sys.argv[1:] or os.environ.get("OBS_DIR"):
        from distributeddeeplearning_tpu import obs

        if not os.environ.get("OBS_DIR"):
            os.environ["OBS_DIR"] = os.path.join(
                "runs", f"serve-bench-{int(time.time())}"
            )
        obs.configure_from_env()
    # Live plane (docs/OBSERVABILITY.md): with SLO_SPEC set the bench
    # runs under its own telemetry — rollup.json is published next to
    # the event files and SERVE_ADMISSION_POLICY=adaptive closes the
    # loop (shed-then-recover under a burning latency SLO).
    plane_stop = plane_thread = None
    if os.environ.get("OBS_DIR") and os.environ.get("SLO_SPEC"):
        plane_stop, plane_thread = start_live_plane(os.environ["OBS_DIR"])
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("COMPILATION_CACHE_DIR"):
        from distributeddeeplearning_tpu.training.warmup import (
            enable_persistent_cache,
        )

        enable_persistent_cache(os.environ["COMPILATION_CACHE_DIR"])

    import flax.linen as nn
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.serving import ServeConfig

    env = os.environ
    model_name = env.get("BENCH_MODEL", "lm_tiny")
    # Realistic LM vocab by default: decode is weight/KV-bandwidth-bound
    # (scripts/decode_audit.py), and the output projection over the full
    # vocab is the term continuous batching amortises across slots —
    # a toy vocab would benchmark dispatch overhead instead.
    vocab = int(env.get("BENCH_VOCAB", "32000"))
    n_requests = int(env.get("SERVE_REQUESTS", "32"))
    max_new = int(env.get("SERVE_MAX_NEW", "16"))
    rate_rps = float(env.get("SERVE_RATE_RPS", "200"))
    seed = int(env.get("SERVE_SEED", "0"))
    profile = env.get("SERVE_PROFILE", "mixed")
    layout = env.get("SERVE_KV_LAYOUT", "dense")
    budget_slots = int(env.get("SERVE_POOL_SLOT_BUDGET", "4"))
    if profile not in PROFILES:
        raise SystemExit(f"unknown SERVE_PROFILE {profile!r}")
    if layout not in ("dense", "paged", "compare"):
        raise SystemExit(f"unknown SERVE_KV_LAYOUT {layout!r}")
    shapes = PROFILES[profile] or [(tp, max_new) for tp in MIXED_PROMPT_LENS]
    cfg = ServeConfig.from_env()
    if cfg.buckets is None:
        cfg.buckets = (8, 16) if profile == "mixed" else (8, 16, 32, 64, 96)
    max_len = max(tp + n_new for tp, n_new in shapes)
    # Quantization compare (SERVE_KV_DTYPE / SERVE_WEIGHT_DTYPE=int8):
    # its own mode — greedy load (the match-rate oracle's regime),
    # engine-vs-engine at a fixed KV-pool byte budget.
    quant = cfg.kv_dtype != "bf16" or cfg.weight_dtype != "bf16"
    if quant and layout != "dense":
        raise SystemExit(
            "the quantization compare runs on the dense layout — unset "
            "SERVE_KV_LAYOUT or the quantized (int8/fp8) dtypes"
        )
    # Speculative compare (SERVE_SPEC_K > 0): greedy-vs-speculative,
    # bitwise greedy parity gated (docs/SERVING.md).
    spec = cfg.spec_k > 0
    if spec and (quant or layout != "dense"):
        raise SystemExit(
            "the speculative compare runs on the dense native-dtype "
            "engines — unset SERVE_KV_LAYOUT / the quantized dtypes or "
            "SERVE_SPEC_K"
        )
    match_min = float(env.get("SERVE_QUANT_MATCH_MIN", "0.95"))
    min_speedup = float(env.get("SERVE_SPEC_MIN_SPEEDUP", "1.4"))
    temperature, top_k = (0.0, None) if quant else (0.8, 40)
    metric = (
        "serve_spec_vs_greedy_tokens_per_sec" if spec
        else "serve_int8_vs_bf16_tokens_per_sec" if quant
        else "serve_paged_vs_dense_capacity" if layout == "compare"
        else "serve_continuous_tokens_per_sec"
    )

    if spec:
        # The verify window writes spec_k lookahead positions past a
        # request's last token; both engines get the same headroom so
        # the compare stays shape-for-shape fair.
        max_len += cfg.spec_k
    try:
        model = get_model(
            model_name, num_classes=vocab, max_seq_len=max_len,
            dtype=jnp.float32,
        )
        variables = jax.jit(model.init, static_argnames=("train",))(
            jax.random.PRNGKey(0), jnp.zeros((2, max_len), jnp.int32),
            train=False,
        )
        params = nn.unbox(variables["params"])
        reqs = build_requests(n_requests, rate_rps, seed, vocab, shapes)

        if spec:
            return run_spec_compare(
                model, params, reqs, cfg, metric, max_len=max_len,
                profile=profile, rate_rps=rate_rps,
                min_speedup=min_speedup,
            )

        if quant:
            return run_quant_compare(
                model, params, reqs, cfg, metric,
                budget_slots=budget_slots, max_len=max_len,
                profile=profile, rate_rps=rate_rps,
                match_min=match_min,
            )

        seq_tps, seq_outs, seq_shapes = run_sequential(
            model, params, reqs, temperature, top_k
        )

        budget_tokens = budget_slots * max_len
        paged_kwargs = dict(
            num_slots=cfg.num_slots, max_len=max_len, buckets=cfg.buckets,
            decode_kernel=cfg.decode_kernel,
            kv_layout="paged", block_size=cfg.block_size,
            num_blocks=(
                cfg.num_blocks or budget_tokens // cfg.block_size + 1
            ),
            prefix_cache=cfg.prefix_cache,
        )
        runs = {}
        if layout in ("dense", "compare"):
            runs["dense"], _, _ = serve_one_engine(
                model, params, reqs, seq_outs,
                engine_kwargs=dict(
                    num_slots=(
                        budget_slots if layout == "compare"
                        else cfg.num_slots
                    ),
                    max_len=max_len, buckets=cfg.buckets,
                    decode_kernel=cfg.decode_kernel,
                ),
                queue_depth=cfg.queue_depth,
                prefills_per_step=cfg.prefills_per_step,
                temperature=temperature, top_k=top_k,
                admission_policy=cfg.build_admission_policy(),
            )
        if layout in ("paged", "compare"):
            runs["paged"], _, _ = serve_one_engine(
                model, params, reqs, seq_outs,
                engine_kwargs=paged_kwargs,
                queue_depth=cfg.queue_depth,
                prefills_per_step=cfg.prefills_per_step,
                temperature=temperature, top_k=top_k,
                admission_policy=cfg.build_admission_policy(),
            )

        detail = {
            "profile": profile,
            "requests": n_requests,
            "buckets": list(cfg.buckets),
            "rate_rps": rate_rps,
            "max_len": max_len,
            "sequential_tokens_per_sec": round(seq_tps, 1),
            "sequential_compiled_shapes": seq_shapes,
            "platform": jax.devices()[0].platform,
            "decode_kernel": cfg.decode_kernel,
        }
        parity = all(r["parity"] for r in runs.values())
        clean = all(r["compiles_during_measure"] == 0 for r in runs.values())
        closed = all(
            r["compile_count"] == r["programs_expected"]
            for r in runs.values()
        )
        if layout == "compare":
            dense, paged = runs["dense"], runs["paged"]
            capacity_ratio = (
                paged["peak_concurrent"] / dense["peak_concurrent"]
                if dense["peak_concurrent"] else 0.0
            )
            tps_ratio = (
                paged["tokens_per_sec"] / dense["tokens_per_sec"]
                if dense["tokens_per_sec"] else 0.0
            )
            detail.update({
                "pool_budget_tokens": budget_tokens,
                "dense": dense,
                "paged": paged,
                "capacity_ratio": round(capacity_ratio, 2),
                "tps_ratio": round(tps_ratio, 2),
                "parity": parity,
            })
            record = {
                "metric": metric,
                # headline: paged throughput at the shared byte budget
                "value": paged["tokens_per_sec"],
                "unit": "tokens/sec",
                "vs_baseline": round(tps_ratio, 2),
            }
            ok = (
                parity and clean and closed
                and (capacity_ratio >= 2.0 or tps_ratio >= 1.5)
            )
        else:
            run = runs[layout]
            detail.update(run)
            detail["speedup_vs_sequential"] = (
                round(run["tokens_per_sec"] / seq_tps, 2) if seq_tps else 0.0
            )
            record = {
                "metric": metric,
                "value": run["tokens_per_sec"],
                "unit": "tokens/sec",
                "vs_baseline": detail["speedup_vs_sequential"],
            }
            ok = parity and clean and closed
        record["detail"] = detail
        _emit_record(record)
        return 0 if ok else 1
    except Exception as e:  # structured failure record, like bench.py
        _emit_record({
            "metric": metric, "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": repr(e),
        })
        raise
    finally:
        if plane_stop is not None:
            plane_stop.set()
            plane_thread.join(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
