"""Continuous-batching serving benchmark — Poisson load vs sequential.

The serving tier's certifiable protocol (BASELINE.md style, one JSON
line on stdout): a seeded Poisson arrival stream of mixed-length
requests is served twice —

* **sequential baseline**: one request at a time through
  ``inference.generate`` (each distinct shape warmed first, so the
  comparison is pure steady-state throughput — the per-shape compiles
  the slot engine avoids are reported separately, not smuggled into the
  denominator);
* **continuous batching**: the same requests submitted to
  ``serving.Server`` on their arrival schedule, drained to completion.

The record carries throughput (the headline ``value``), the sequential
baseline and speedup, TTFT/queue-wait percentiles, mean slot occupancy
and the engine's compile count — everything
``scripts/recertify.py``'s ``serve_lm`` row needs to re-certify the
protocol on hardware the moment the relay returns.

Env knobs (defaults in parentheses): ``SERVE_SLOTS`` (8),
``SERVE_BUCKETS`` ("8,16"), ``SERVE_REQUESTS`` (32),
``SERVE_MAX_NEW`` (16), ``SERVE_RATE_RPS`` (200 — Poisson arrival
rate; 0 = closed backlog, all at t=0), ``SERVE_SEED`` (0),
``BENCH_MODEL`` (lm_tiny), ``BENCH_VOCAB`` (256), plus the generic
``OBS_DIR``/``--events`` and ``COMPILATION_CACHE_DIR`` plumbing
bench.py uses.

Usage::

    python scripts/serve_bench.py [--events]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(vals, q):
    vals = sorted(vals)
    if not vals:
        return 0.0
    idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
    return vals[idx]


def _emit_record(record: dict) -> None:
    """bench.py's output contract: the canonical JSON line on stdout
    plus the same record on the event bus."""
    print(json.dumps(record), flush=True)
    from distributeddeeplearning_tpu import obs

    bus = obs.get_bus()
    bus.point("bench_result", **record)
    bus.flush()


def build_requests(n, rate_rps, max_new, seed, vocab, prompt_lens):
    """Seeded request set + Poisson arrival offsets (seconds). Mixed
    prompt lengths, per-request sampling seeds — the adversarial mix
    the parity oracle certifies, at load."""
    import numpy as np

    rng = np.random.RandomState(seed)
    reqs = []
    t = 0.0
    for i in range(n):
        if rate_rps > 0:
            t += float(rng.exponential(1.0 / rate_rps))
        tp = int(prompt_lens[i % len(prompt_lens)])
        reqs.append({
            "arrival_s": t,
            "prompt": rng.randint(0, vocab, size=(tp,)).astype(np.int32),
            "max_new": max_new,
            "seed": int(rng.randint(0, 2**31 - 1)),
        })
    return reqs


def run_sequential(model, params, reqs, temperature, top_k):
    """One-at-a-time baseline through inference.generate; each distinct
    (prompt_len, max_new) shape is warmed first. Returns (tokens/sec,
    per-request outputs, distinct compiled shapes)."""
    import jax
    import numpy as np

    from distributeddeeplearning_tpu.inference import generate

    shapes = sorted({(len(r["prompt"]), r["max_new"]) for r in reqs})
    for tp, n_new in shapes:  # warm per-shape samplers out of the timing
        generate(
            model, params, np.zeros((1, tp), np.int32),
            max_new_tokens=n_new, temperature=temperature, top_k=top_k,
            rng=jax.random.PRNGKey(0),
        )
    outs = []
    t0 = time.perf_counter()
    for r in reqs:
        out = generate(
            model, params, r["prompt"][None], max_new_tokens=r["max_new"],
            temperature=temperature, top_k=top_k,
            rng=jax.random.PRNGKey(r["seed"]),
        )
        outs.append(np.asarray(out)[0])
    dt = time.perf_counter() - t0
    tokens = sum(r["max_new"] for r in reqs)
    return tokens / dt, outs, len(shapes)


def run_continuous(server, reqs, temperature, top_k):
    """Replay the Poisson schedule against the serving loop: submit
    each request at its arrival offset, pumping the scheduler while
    waiting; drain. Returns (tokens/sec makespan throughput, handles,
    wall seconds)."""
    from distributeddeeplearning_tpu.serving import Request

    handles = []
    t0 = time.perf_counter()
    for r in reqs:
        while time.perf_counter() - t0 < r["arrival_s"]:
            server.step()  # keep decoding while the next arrival is due
        handles.append(server.submit(Request(
            prompt=r["prompt"], max_new_tokens=r["max_new"],
            temperature=temperature, top_k=top_k, rng=r["seed"],
        )))
    server.drain()
    dt = time.perf_counter() - t0
    tokens = sum(len(h.new_tokens) for h in handles)
    return tokens / dt, handles, dt


def main() -> int:
    if "--events" in sys.argv[1:] or os.environ.get("OBS_DIR"):
        from distributeddeeplearning_tpu import obs

        if not os.environ.get("OBS_DIR"):
            os.environ["OBS_DIR"] = os.path.join(
                "runs", f"serve-bench-{int(time.time())}"
            )
        obs.configure_from_env()
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("COMPILATION_CACHE_DIR"):
        from distributeddeeplearning_tpu.training.warmup import (
            enable_persistent_cache,
        )

        enable_persistent_cache(os.environ["COMPILATION_CACHE_DIR"])

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.serving import (
        Server, ServeConfig, SlotEngine,
    )

    env = os.environ
    model_name = env.get("BENCH_MODEL", "lm_tiny")
    # Realistic LM vocab by default: decode is weight/KV-bandwidth-bound
    # (scripts/decode_audit.py), and the output projection over the full
    # vocab is the term continuous batching amortises across slots —
    # a toy vocab would benchmark dispatch overhead instead.
    vocab = int(env.get("BENCH_VOCAB", "32000"))
    n_requests = int(env.get("SERVE_REQUESTS", "32"))
    max_new = int(env.get("SERVE_MAX_NEW", "16"))
    rate_rps = float(env.get("SERVE_RATE_RPS", "200"))
    seed = int(env.get("SERVE_SEED", "0"))
    prompt_lens = (4, 7, 12, 5, 16, 3, 9, 14)
    cfg = ServeConfig.from_env()
    if cfg.buckets is None:
        cfg.buckets = (8, 16)
    max_len = max(prompt_lens) + max_new
    temperature, top_k = 0.8, 40

    try:
        model = get_model(
            model_name, num_classes=vocab, max_seq_len=max_len,
            dtype=jnp.float32,
        )
        variables = jax.jit(model.init, static_argnames=("train",))(
            jax.random.PRNGKey(0), jnp.zeros((2, max_len), jnp.int32),
            train=False,
        )
        params = nn.unbox(variables["params"])
        reqs = build_requests(
            n_requests, rate_rps, max_new, seed, vocab, prompt_lens
        )

        seq_tps, seq_outs, seq_shapes = run_sequential(
            model, params, reqs, temperature, top_k
        )

        engine = SlotEngine(
            model, params, num_slots=cfg.num_slots, max_len=max_len,
            buckets=cfg.buckets,
        )
        engine.warmup()
        server = Server(
            engine, queue_depth=max(cfg.queue_depth, n_requests),
            prefills_per_step=cfg.prefills_per_step,
        )
        # Warm pass: one request end-to-end so first-dispatch overheads
        # (host transfers, executable load) stay out of the measurement.
        run_continuous(server, reqs[:1], temperature, top_k)
        compile_count_pre = engine.compile_count

        cont_tps, handles, wall_s = run_continuous(
            server, reqs, temperature, top_k
        )

        # Per-request parity against the sequential outputs — the bench
        # itself proves the speedup is not buying different tokens.
        parity = all(
            np.array_equal(h.tokens, seq_outs[i][: len(h.tokens)])
            for i, h in enumerate(handles)
        )
        ttft_ms = [h.ttft_s * 1e3 for h in handles if h.ttft_s is not None]
        qwait_ms = [
            h.queue_wait_s * 1e3 for h in handles
            if h.queue_wait_s is not None
        ]
        record = {
            "metric": "serve_continuous_tokens_per_sec",
            "value": round(cont_tps, 1),
            "unit": "tokens/sec",
            "vs_baseline": round(cont_tps / seq_tps, 2) if seq_tps else 0.0,
            "detail": {
                "sequential_tokens_per_sec": round(seq_tps, 1),
                "speedup_vs_sequential": round(cont_tps / seq_tps, 2)
                if seq_tps else 0.0,
                "parity": bool(parity),
                "requests": n_requests,
                "slots": cfg.num_slots,
                "buckets": list(cfg.buckets),
                "rate_rps": rate_rps,
                "max_new_tokens": max_new,
                "ttft_p50_ms": round(_percentile(ttft_ms, 0.5), 2),
                "ttft_p99_ms": round(_percentile(ttft_ms, 0.99), 2),
                "queue_wait_p50_ms": round(_percentile(qwait_ms, 0.5), 2),
                "queue_wait_p99_ms": round(_percentile(qwait_ms, 0.99), 2),
                "slot_occupancy_mean": round(server.occupancy_mean, 3),
                "decode_steps": server.stats["decode_steps"],
                "compile_count": engine.compile_count,
                "compiles_during_measure": engine.compile_count
                - compile_count_pre,
                "sequential_compiled_shapes": seq_shapes,
                "wall_s": round(wall_s, 2),
                "platform": jax.devices()[0].platform,
            },
        }
        _emit_record(record)
        return 0 if parity and record["detail"]["compiles_during_measure"] == 0 else 1
    except Exception as e:  # structured failure record, like bench.py
        _emit_record({
            "metric": "serve_continuous_tokens_per_sec", "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": repr(e),
        })
        raise


if __name__ == "__main__":
    sys.exit(main())
