"""GPipe vs 1F1B: measured step time + compiled memory (VERDICT r2 #3).

Runs both schedules on the 8-device forced-CPU mesh (S=4 stages x 2-way
DP, M=8 microbatches, lm_tiny) and prints wall-clock per step plus XLA's
``memory_analysis`` (argument/output/temp/generated-code bytes — temp
size is where the schedules differ: GPipe's AD keeps every microbatch's
stage activations live; 1F1B's ring buffer holds 2S stage inputs).

Usage: python scripts/pp_schedule_bench.py [stages] [microbatches]
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.models.pipeline_lm import PipelineLM
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training.pp_step import (
    create_pp_state,
    make_pp_train_step,
)

VOCAB, T, LAYERS_PER_STAGE = 256, 128, 2


def run(schedule: str, stages: int, microbatches: int, steps: int = 10):
    n_dev = len(jax.devices())
    data_par = n_dev // stages
    pl = PipelineLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T, num_stages=stages,
        n_layers=stages * LAYERS_PER_STAGE, dtype=jnp.float32,
    )
    cfg = TrainConfig(
        num_classes=VOCAB, batch_size_per_device=microbatches,
        weight_decay=0.0, compute_dtype="float32",
    )
    mesh = create_mesh(axes=("data", "pipe"), shape=(data_par, stages))
    tx = optax.sgd(0.01)
    state = create_pp_state(pl, cfg, tx, mesh, T)
    step = make_pp_train_step(
        pl, tx, mesh, cfg, num_microbatches=microbatches, schedule=schedule,
        donate_state=False,
    )
    rows = np.random.RandomState(0).randint(
        0, VOCAB, size=(microbatches * data_par, T + 1)
    ).astype(np.int32)
    spec = NamedSharding(mesh, P("data"))
    batch = (
        jax.device_put(rows[:, :-1], spec),
        jax.device_put(rows[:, 1:], spec),
    )
    # One AOT compile serves both memory_analysis and the timing loop
    # (calling the jitted wrapper would compile the program a second time).
    compiled = step.build(state).lower(state, batch).compile()
    try:
        mem = compiled.memory_analysis()
        temp_mb = mem.temp_size_in_bytes / 1e6
    except Exception:
        temp_mb = float("nan")

    state, metrics = compiled(state, batch)  # warmup
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = compiled(state, batch)
    loss = float(metrics["loss"])  # fence
    dt = (time.perf_counter() - t0) / steps
    print(
        f"{schedule:6s} S={stages} M={microbatches}: "
        f"step={dt * 1e3:8.1f} ms  temp={temp_mb:10.1f} MB  loss={loss:.4f}",
        flush=True,
    )
    return dt


def main():
    stages = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    microbatches = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    for schedule in ("gpipe", "1f1b"):
        run(schedule, stages, microbatches)


if __name__ == "__main__":
    main()
