"""Attention fwd / fwd+bwd timing at long T (VERDICT r2 #8).

Times the compiled forward and the compiled forward+backward (grad wrt
q,k,v) for the selected attention impls at the selected sequence
lengths, bf16 causal, d=128. Prints ms per call per configuration and
finishes with the usual ONE JSON record line (bench.py's contract:
``metric``/``value``/``unit``/``detail``) so the run is archivable and
machine-checkable. The train step pays the fwd+bwd number every step.

Usage::

    python scripts/attn_bench.py [--seq-lens 8192,32768]
        [--impls pallas,xla] [--batch 1] [--heads 8] [--head-dim 128]
        [--steps 5] [--decode-verify K]

The xla impl materializes the [T, T] score matrix, so it is skipped
above 8k (OOM) unless it is the only impl requested.

``--decode-verify K`` adds the serving tier's speculative-verify shape
to the sweep: a ``[B, K+1]`` query window against the full static KV
cache with per-row position masks — the attention view
(``models/vit.Attention._masked_decode_scores``) every ``SlotEngine``
spec tick runs (docs/SERVING.md). Forward-only (an inference path);
failures are captured per row like the impl sweep.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# xla materializes [T, T] scores; beyond this it OOMs rather than runs.
XLA_MAX_T = 8192


def bench(impl: str, t: int, b: int = 1, h: int = 8, d: int = 128,
          steps: int = 5) -> dict:
    """One (impl, T) timing. Returns a result row; failures are
    recorded (not raised) so one broken impl can't kill the sweep."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.ops.attention import (
        dot_product_attention,
    )

    rng = np.random.RandomState(0)
    shape = (b, t, h, d)  # BTHD layout
    q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    k = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    v = jnp.asarray(rng.randn(*shape), jnp.bfloat16)

    def fwd(q, k, v):
        return dot_product_attention(q, k, v, causal=True, impl=impl)

    def loss(q, k, v):
        return jnp.sum(fwd(q, k, v).astype(jnp.float32))

    row = {"impl": impl, "seq_len": t, "batch": b, "heads": h,
           "head_dim": d}
    for name, fn in (
        ("fwd", jax.jit(fwd)),
        ("fwd_bwd", jax.jit(jax.grad(loss, argnums=(0, 1, 2)))),
    ):
        try:
            out = fn(q, k, v)
            leaf = jax.tree.leaves(out)[0]
            float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))  # fence
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(q, k, v)
            leaf = jax.tree.leaves(out)[0]
            float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
            ms = (time.perf_counter() - t0) / steps * 1e3
            row[f"{name}_ms"] = round(ms, 2)
            print(f"{impl:7s} T={t:6d} {name:8s} {ms:9.1f} ms", flush=True)
        except Exception as e:
            row[f"{name}_error"] = f"{type(e).__name__}: {e}"
            print(f"{impl:7s} T={t:6d} {name:8s} FAILED: "
                  f"{type(e).__name__}: {e}", flush=True)
    if "fwd_ms" in row and "fwd_bwd_ms" in row:
        bwd = row["fwd_bwd_ms"] - row["fwd_ms"]
        print(
            f"{impl:7s} T={t:6d} bwd-only {bwd:9.1f} ms "
            f"(bwd/fwd = {bwd / row['fwd_ms']:.1f}x)" if row["fwd_ms"]
            else f"{impl:7s} T={t:6d}", flush=True,
        )
    return row


def bench_decode_verify(t: int, k: int, b: int = 1, h: int = 8,
                        d: int = 128, steps: int = 5) -> dict:
    """One decode-verify timing: a [B, K+1, H, D] query window against
    a [B, T, H, D] cache view, position-masked per row — the math of
    ``models/vit.Attention._masked_decode_scores`` at the serving
    tier's speculative-verify shape. Failures are recorded, not raised,
    like :func:`bench`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(0)
    window = k + 1
    q = jnp.asarray(rng.randn(b, window, h, d), jnp.bfloat16)
    k_all = jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16)
    v_all = jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16)
    # per-row window start: queries sit at the cache's tail
    pos = jnp.full((b,), t - window, jnp.int32)[:, None] + jnp.arange(window)

    def fwd(q, k_all, v_all, q_pos):
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", (q * d ** -0.5), k_all
        ).astype(jnp.float32)
        k_pos = jnp.arange(t)
        mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None]
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v_all)

    row = {"impl": "decode_verify", "seq_len": t, "batch": b, "heads": h,
           "head_dim": d, "window": window}
    try:
        fn = jax.jit(fwd)
        out = fn(q, k_all, v_all, pos)
        float(jnp.asarray(out).ravel()[0].astype(jnp.float32))  # fence
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(q, k_all, v_all, pos)
        float(jnp.asarray(out).ravel()[0].astype(jnp.float32))
        ms = (time.perf_counter() - t0) / steps * 1e3
        row["fwd_ms"] = round(ms, 2)
        print(f"verify  T={t:6d} fwd      {ms:9.1f} ms (window {window})",
              flush=True)
    except Exception as e:
        row["fwd_error"] = f"{type(e).__name__}: {e}"
        print(f"verify  T={t:6d} fwd      FAILED: "
              f"{type(e).__name__}: {e}", flush=True)
    return row


def bench_paged_fused(t: int, b: int = 1, h: int = 8, d: int = 128,
                      steps: int = 5, block_size: int = 128) -> dict:
    """One fused paged-decode timing: a [B, 1, H, D] query row against a
    [num_blocks, block_size, H, D] block pool walked through per-row
    block tables — the serving tier's SERVE_DECODE_KERNEL=fused hot path
    (``ops/pallas/paged_decode.py``). Forward-only (a decode kernel has
    no backward); failures are recorded per row like :func:`bench`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.ops.pallas.paged_decode import (
        fused_decode_attention,
    )

    rng = np.random.RandomState(0)
    bs = min(block_size, t)
    mb = -(-t // bs)
    nb = b * mb + 1  # + trash block 0, the pool convention
    q = jnp.asarray(rng.randn(b, 1, h, d), jnp.bfloat16)
    k_pool = jnp.asarray(rng.randn(nb, bs, h, d), jnp.bfloat16)
    v_pool = jnp.asarray(rng.randn(nb, bs, h, d), jnp.bfloat16)
    # each row owns a contiguous run of blocks (block 0 stays trash)
    table = jnp.asarray(
        1 + np.arange(b * mb).reshape(b, mb), jnp.int32
    )
    pos = jnp.full((b, 1), t - 1, jnp.int32)  # queries at the tail

    def fwd(q, k_pool, v_pool, pos, table):
        return fused_decode_attention(
            q, k_pool, v_pool, pos, block_table=table, block_size=bs,
        )

    row = {"impl": "paged_fused", "seq_len": t, "batch": b, "heads": h,
           "head_dim": d, "block_size": bs}
    try:
        fn = jax.jit(fwd)
        out = fn(q, k_pool, v_pool, pos, table)
        float(jnp.asarray(out).ravel()[0].astype(jnp.float32))  # fence
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(q, k_pool, v_pool, pos, table)
        float(jnp.asarray(out).ravel()[0].astype(jnp.float32))
        ms = (time.perf_counter() - t0) / steps * 1e3
        row["fwd_ms"] = round(ms, 2)
        print(f"pgfused T={t:6d} fwd      {ms:9.1f} ms "
              f"(bs {bs}, {mb} blocks/row)", flush=True)
    except Exception as e:
        row["fwd_error"] = f"{type(e).__name__}: {e}"
        print(f"pgfused T={t:6d} fwd      FAILED: "
              f"{type(e).__name__}: {e}", flush=True)
    return row


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-lens", default="8192,32768",
                   help="comma-separated sequence lengths")
    p.add_argument("--impls", default="pallas,xla",
                   help="comma-separated attention impls "
                        "(pallas | xla | auto | paged_fused — the "
                        "serving tier's fused decode kernel, fwd-only)")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--steps", type=int, default=5,
                   help="timed calls per configuration")
    p.add_argument("--decode-verify", type=int, default=0, metavar="K",
                   help="also time the [B, K+1]-window decode-verify "
                        "view at each T (0 = off)")
    args = p.parse_args(argv)
    seq_lens = [int(t) for t in args.seq_lens.split(",") if t.strip()]
    impls = [i.strip() for i in args.impls.split(",") if i.strip()]
    if not seq_lens or not impls:
        p.error("--seq-lens and --impls must be non-empty")

    import jax

    rows, skipped = [], []
    for t in seq_lens:
        for impl in impls:
            if impl == "paged_fused":
                rows.append(bench_paged_fused(
                    t, b=args.batch, h=args.heads, d=args.head_dim,
                    steps=args.steps,
                ))
                continue
            if impl == "xla" and t > XLA_MAX_T and len(impls) > 1:
                print(f"xla     T={t:6d} skipped "
                      f"([T,T] materialization OOMs)", flush=True)
                skipped.append({"impl": impl, "seq_len": t,
                                "reason": "xla_oom"})
                continue
            rows.append(bench(impl, t, b=args.batch, h=args.heads,
                              d=args.head_dim, steps=args.steps))
        if args.decode_verify > 0:
            rows.append(bench_decode_verify(
                t, args.decode_verify, b=args.batch, h=args.heads,
                d=args.head_dim, steps=args.steps,
            ))
    # Headline: the fwd+bwd ms of the last successful row (the largest
    # T of the preferred impl — what the train step pays per step).
    timed = [r for r in rows if "fwd_bwd_ms" in r]
    record = {
        "metric": "attn_fwd_bwd_ms",
        "value": timed[-1]["fwd_bwd_ms"] if timed else 0.0,
        "unit": "ms",
        "detail": {
            "platform": jax.devices()[0].platform,
            "rows": rows,
            "skipped": skipped,
        },
    }
    print(json.dumps(record), flush=True)
    return 0 if timed else 1


if __name__ == "__main__":
    sys.exit(main())
