"""Attention fwd / fwd+bwd timing at long T (VERDICT r2 #8).

Times the compiled forward and the compiled forward+backward (grad wrt
q,k,v) for the flash (Pallas) and xla attention impls at T ∈ {8k, 32k},
bf16 causal, d=128. Prints ms per call; the train step pays the
fwd+bwd number every step.

Usage: python scripts/attn_bench.py [T ...]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributeddeeplearning_tpu.ops.attention import dot_product_attention


def bench(impl: str, t: int, b: int = 1, h: int = 8, d: int = 128, steps: int = 5):
    rng = np.random.RandomState(0)
    shape = (b, t, h, d)  # BTHD layout
    q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    k = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
    v = jnp.asarray(rng.randn(*shape), jnp.bfloat16)

    def fwd(q, k, v):
        return dot_product_attention(q, k, v, causal=True, impl=impl)

    def loss(q, k, v):
        return jnp.sum(fwd(q, k, v).astype(jnp.float32))

    results = {}
    for name, fn in (("fwd", jax.jit(fwd)), ("fwd+bwd", jax.jit(jax.grad(loss, argnums=(0, 1, 2))))):
        try:
            out = fn(q, k, v)
            leaf = jax.tree.leaves(out)[0]
            float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))  # fence
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(q, k, v)
            leaf = jax.tree.leaves(out)[0]
            float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
            ms = (time.perf_counter() - t0) / steps * 1e3
            results[name] = ms
            print(f"{impl:7s} T={t:6d} {name:8s} {ms:9.1f} ms", flush=True)
        except Exception as e:
            print(f"{impl:7s} T={t:6d} {name:8s} FAILED: {type(e).__name__}: {e}",
                  flush=True)
    if "fwd" in results and "fwd+bwd" in results:
        print(
            f"{impl:7s} T={t:6d} bwd-only {results['fwd+bwd'] - results['fwd']:9.1f} ms "
            f"(bwd/fwd = {(results['fwd+bwd'] - results['fwd']) / results['fwd']:.1f}x)",
            flush=True,
        )


def main():
    ts = [int(a) for a in sys.argv[1:]] or [8192, 32768]
    for t in ts:
        for impl in ("pallas", "xla"):
            if impl == "xla" and t > 8192:
                print(f"xla     T={t:6d} skipped ([T,T] materialization OOMs)")
                continue
            bench(impl, t)


if __name__ == "__main__":
    main()
