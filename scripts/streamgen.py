"""Build stream shard sets (tokens or vision records) + their index.

The shard-writer CLI of the streamed data plane (docs/DATA.md "Streamed
shards"): produces the ``stream_index.json`` + ``shard-*.{field}.bin``
layout that ``DATA_FORMAT=stream`` reads. Deliberately jax-free — shard
preparation is host tooling that must run on any machine.

Usage::

    # LM token shards from a byte-level corpus (vocab 256):
    python scripts/streamgen.py tokens --out /data/stream/wiki \
        --corpus corpus1.txt corpus2.txt --seq-len 1024

    # ... or synthetically (seeded; test fixtures, benches):
    python scripts/streamgen.py tokens --out /tmp/shards \
        --records 4096 --seq-len 128 --vocab 32000 --seed 42

    # Vision record shards (synthetic; real ImageNet rides
    # data/prepare.py's TFRecord path until the streamed ingest lands):
    python scripts/streamgen.py records --out /tmp/imgshards \
        --records 4096 --image-size 64 --classes 100

    make stream-shards       # the repo's small local fixture

Prints one JSON summary line (shards, records, bytes, out) — the same
one-line protocol every repo script speaks.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _emit(meta: dict, out_dir: str) -> None:
    payload = sum(
        os.path.getsize(os.path.join(out_dir, f))
        for f in os.listdir(out_dir)
        if f.endswith(".bin")
    )
    print(
        json.dumps(
            {
                "out": out_dir,
                "kind": meta["kind"],
                "shards": len(meta["shards"]),
                "records": meta["total_records"],
                "bytes": payload,
            }
        )
    )


def gen_tokens(args) -> int:
    from distributeddeeplearning_tpu.data.stream import (
        corpus_to_rows,
        synthetic_rows,
        write_token_shards,
    )

    if args.corpus:
        vocab = 256  # byte-level

        def chunks():
            for path in args.corpus:
                with open(path, "rb") as f:
                    yield corpus_to_rows(
                        f.read(), seq_len=args.seq_len, stride=args.stride
                    )

        rows = chunks()
    else:
        if not args.records:
            print(
                "ERROR: need --corpus FILE... or --records N",
                file=sys.stderr,
            )
            return 2
        vocab = args.vocab
        rows = [
            synthetic_rows(
                args.records,
                seq_len=args.seq_len,
                vocab_size=vocab,
                seed=args.seed,
            )
        ]
    meta = write_token_shards(
        args.out,
        rows,
        seq_len=args.seq_len,
        vocab_size=vocab,
        shard_records=args.shard_records,
    )
    _emit(meta, args.out)
    return 0


def gen_records(args) -> int:
    from distributeddeeplearning_tpu.data.stream import (
        synthetic_records,
        write_record_shards,
    )

    images, labels = synthetic_records(
        args.records,
        image_size=args.image_size,
        num_classes=args.classes,
        seed=args.seed,
    )
    meta = write_record_shards(
        args.out,
        (images, labels),
        image_size=args.image_size,
        num_classes=args.classes,
        shard_records=args.shard_records,
    )
    _emit(meta, args.out)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("tokens", help="LM token shards ([seq_len+1] int32)")
    t.add_argument("--out", required=True)
    t.add_argument(
        "--corpus", nargs="+", default=None,
        help="byte-level corpus file(s) (vocab 256)",
    )
    t.add_argument("--records", type=int, default=0,
                   help="synthetic row count (no --corpus)")
    t.add_argument("--seq-len", type=int, default=128)
    t.add_argument("--stride", type=int, default=None,
                   help="corpus window stride (default: seq-len)")
    t.add_argument("--vocab", type=int, default=32_000,
                   help="synthetic vocab (corpus mode is byte-level 256)")
    t.add_argument("--shard-records", type=int, default=8192)
    t.add_argument("--seed", type=int, default=42)
    t.set_defaults(fn=gen_tokens)

    r = sub.add_parser(
        "records", help="vision record shards (uint8 image + int32 label)"
    )
    r.add_argument("--out", required=True)
    r.add_argument("--records", type=int, required=True)
    r.add_argument("--image-size", type=int, default=64)
    r.add_argument("--classes", type=int, default=100)
    r.add_argument("--shard-records", type=int, default=1024)
    r.add_argument("--seed", type=int, default=42)
    r.set_defaults(fn=gen_records)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
