"""ddlint — run the repo's static-analysis suite (docs/ANALYSIS.md).

Three analyzer families over one finding/suppression substrate
(``distributeddeeplearning_tpu/analysis/``):

    ast       host-sync, tracer-bool       (AST over the hot paths)
    hlo       hlo-donation, hlo-collectives, hlo-cache-key
              (lowers every engine step + the SlotEngine program set
              on the forced-8-CPU-device mesh)
    contract  env-docs, obs-registry, protocol-vars

Usage::

    python scripts/ddlint.py                  # everything; writes lint.json
    python scripts/ddlint.py --rule env-docs  # one rule, fast iteration
    python scripts/ddlint.py --family ast     # one family
    python scripts/ddlint.py --list           # rule catalogue
    python scripts/ddlint.py --check          # CI drift guard: no write,
                                              # fail if lint.json is stale
    python scripts/ddlint.py --changed-ok     # gate mode (make check):
                                              # run everything, refresh
                                              # lint.json, fail only on
                                              # unsuppressed findings

Exit code 1 on any unsuppressed finding (or, under ``--check``, a stale
``lint.json``). The summary line counts suppressions — they are visible
budget, not silence.
"""

from __future__ import annotations

# The HLO family lowers real programs: force the CPU backend and the
# 8-device test mesh BEFORE anything imports jax (the package __init__
# does, via the compat shim).
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()

import argparse  # noqa: E402
import json  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import time  # noqa: E402

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from distributeddeeplearning_tpu.analysis import (  # noqa: E402
    FAMILIES,
    REPO_ROOT,
    apply_suppressions,
    package_sources,
    rules,
)

LINT_JSON = os.path.join(REPO_ROOT, "lint.json")


def _summary(findings, names) -> dict:
    per_rule = {
        n: {"findings": 0, "suppressed": 0} for n in names
    }
    for f in findings:
        row = per_rule.setdefault(
            f.rule, {"findings": 0, "suppressed": 0}
        )
        row["suppressed" if f.suppressed else "findings"] += 1
    commit = subprocess.run(
        ["git", "-C", REPO_ROOT, "rev-parse", "--short", "HEAD"],
        capture_output=True, text=True,
    ).stdout.strip()
    open_n = sum(r["findings"] for r in per_rule.values())
    supp_n = sum(r["suppressed"] for r in per_rule.values())
    return {
        "commit": commit,
        "date": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "ok": open_n == 0,
        "rules": per_rule,
        "findings_total": open_n,
        "suppressions_total": supp_n,
        "findings": [
            {
                "rule": f.rule, "path": f.path, "line": f.line,
                "message": f.message, "suppressed": f.suppressed,
                **({"reason": f.reason} if f.reason else {}),
            }
            for f in sorted(
                findings, key=lambda f: (f.rule, f.path, f.line)
            )
        ],
    }


def _comparable(summary: dict) -> dict:
    """lint.json minus the volatile stamp fields (drift = same commit
    basis, different verdict/findings)."""
    return {
        k: v for k, v in summary.items() if k not in ("commit", "date")
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--rule", action="append", default=None,
                   help="run one rule (repeatable) — local iteration")
    p.add_argument("--family", choices=FAMILIES, default=None,
                   help="run one analyzer family")
    p.add_argument("--list", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--check", action="store_true",
                   help="drift guard: run, don't write, exit 1 if "
                        "lint.json on disk is stale")
    p.add_argument("--changed-ok", action="store_true",
                   help="gate mode: refresh lint.json whatever it held; "
                        "only unsuppressed findings fail")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the summary here (default: lint.json at "
                        "the repo root for full runs; off for --rule/"
                        "--family runs)")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)

    catalogue = rules(args.family)
    if args.list:
        for name, (family, desc, _) in sorted(
            catalogue.items(), key=lambda kv: (kv[1][0], kv[0])
        ):
            print(f"{name:16s} [{family:8s}] {desc}")
        return 0

    if args.rule:
        unknown = [r for r in args.rule if r not in catalogue]
        if unknown:
            print(f"unknown rule(s) {unknown}; have {sorted(catalogue)}")
            return 2
        catalogue = {r: catalogue[r] for r in args.rule}

    partial = bool(args.rule or args.family)
    findings = []
    for name, (family, _, runner) in sorted(
        catalogue.items(), key=lambda kv: (kv[1][0], kv[0])
    ):
        t0 = time.perf_counter()
        found = runner()
        if not args.quiet:
            print(
                f"ddlint: {name}: {len(found)} raw finding(s) "
                f"in {time.perf_counter() - t0:.1f}s",
                flush=True,
            )
        findings.extend(found)
    findings = apply_suppressions(findings, package_sources())
    # bad-suppression findings ride along on every run; in partial runs
    # keep only the selected rules' results plus those markers.
    if partial:
        keep = set(catalogue) | {"bad-suppression"}
        findings = [f for f in findings if f.rule in keep]

    summary = _summary(findings, list(catalogue))
    for f in summary["findings"]:
        if not f["suppressed"] or not args.quiet:
            tag = " [suppressed]" if f["suppressed"] else ""
            print(f"{f['path']}:{f['line']}: {f['rule']}: "
                  f"{f['message']}{tag}")

    stale = False
    if args.check and not partial:
        try:
            with open(LINT_JSON) as fh:
                on_disk = json.load(fh)
        except (OSError, json.JSONDecodeError):
            on_disk = None
        stale = on_disk is None or _comparable(on_disk) != _comparable(
            summary
        )
        if stale:
            print("STALE: lint.json does not match this run "
                  "(python scripts/ddlint.py to refresh)")
    elif not partial or args.json:
        path = args.json or LINT_JSON
        with open(path, "w") as fh:
            json.dump(summary, fh, indent=1)
            fh.write("\n")

    n_rules = len(catalogue)
    print(
        f"ddlint: {n_rules} rule(s), "
        f"{summary['findings_total']} finding(s), "
        f"{summary['suppressions_total']} suppression(s)"
        + (" [STALE lint.json]" if stale else "")
    )
    return 0 if summary["ok"] and not stale else 1


if __name__ == "__main__":
    sys.exit(main())
