"""Multi-replica serving fleet benchmark — 1 vs N replicas on one
seeded multi-tenant load.

The fleet tier's certifiable protocol (BASELINE.md style, one JSON line
on stdout). One seeded multi-tenant request stream
(``serving/loadgen.py`` — tenants cycled round-robin so every tenant
offers the same work mix) is served twice through the fleet router
(``serving/fleet/``): once by a single replica, once by
``SERVE_REPLICAS`` replicas, each replica a warmed SlotEngine + Server
on its own pump thread and event stream. Gates (exit non-zero unless
ALL hold):

* **scaling** — aggregate fleet tokens/sec ≥
  ``SERVE_FLEET_MIN_SCALING`` (1.8) × the single-replica run… on a
  host with at least ``SERVE_REPLICAS`` usable cores. **CPU-honest
  basis** (the decode_audit convention): N pump threads on ONE core
  time-slice — linear replica scaling is *physically unattainable
  there*, so a single-core host derates the gate to
  ``SERVE_FLEET_SINGLE_CORE_MIN`` (0.9; routing/fan-out must cost
  ~nothing) and the record carries ``scaling_basis: "single_core"`` so
  no consumer misreads the ratio as the hardware claim. All other
  gates stay fully enforced either way.
* **flat TTFT** — fleet p99 TTFT ≤ ``SERVE_FLEET_TTFT_MAX_RATIO``
  (1.25) × single-replica p99. TTFT here is the *fleet-level*
  first-token time measured at the client handle via the streaming
  path (submission → first streamed token, queueing + routing +
  prefill included) — a real end-to-end number, not a server-side
  proxy.
* **fairness** — at the moment the contended phase ends (the first
  instant any tenant's backlog empties), every tenant's share of
  delivered tokens is within ``SERVE_FLEET_FAIRNESS_TOL`` (0.15,
  relative) of its weight share — the router's deficit-weighted fair
  queueing holding under a hot-neighbour load.
* **per-request parity** — every request's token stream is bitwise
  identical between the 1-replica and N-replica runs (the serving
  tier's determinism contract surviving routing, placement and
  co-scheduling).
* **closed programs** — every replica in both runs ends with
  ``compile_count == programs_expected`` and zero mid-measure
  recompiles.

Env knobs (defaults): ``SERVE_REPLICAS`` (2), ``SERVE_TENANT_WEIGHTS``
("gold:3,silver:2,bronze:1"), ``SERVE_PLACEMENT`` (affinity),
``SERVE_SLOTS`` (4 per replica), ``SERVE_BUCKETS`` ("8,16"),
``SERVE_REQUESTS`` (48), ``SERVE_MAX_NEW`` (16), ``SERVE_RATE_RPS``
(0 = closed backlog — fairness needs a backlog well past fleet
capacity, or the contended window certifies nothing), ``SERVE_SEED``
(0),
``SERVE_PROFILE`` (mixed), ``SERVE_FLEET_MIN_SCALING`` (1.8),
``SERVE_FLEET_SINGLE_CORE_MIN`` (0.9), ``SERVE_FLEET_TTFT_MAX_RATIO``
(1.25), ``SERVE_FLEET_FAIRNESS_TOL`` (0.15), ``BENCH_MODEL``
(lm_tiny), ``BENCH_VOCAB`` (32000), plus ``OBS_DIR`` (per-replica
``events-p0-s<k>.jsonl`` streams + the ``serve.fleet_pressure`` gauge
land there; ``scripts/obs_watch.py`` renders the per-replica view).

Usage::

    python scripts/fleet_bench.py [--events]
    make fleet-bench
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.serving.loadgen import (  # noqa: E402
    build_tenant_requests,
    percentile,
    profile_shapes,
)


def _emit_record(record: dict) -> None:
    print(json.dumps(record), flush=True)
    from distributeddeeplearning_tpu import obs

    bus = obs.get_bus()
    bus.point("bench_result", **record)
    bus.flush()


def usable_cores() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def fairness_snapshot(handles_by_tenant) -> dict:
    """Delivered-token share per tenant at this instant."""
    tokens = {
        t: sum(len(fh.new_tokens) for fh in hs)
        for t, hs in handles_by_tenant.items()
    }
    total = sum(tokens.values())
    return {
        t: {"tokens": n, "share": (n / total if total else 0.0)}
        for t, n in tokens.items()
    }


def run_fleet(model, params, reqs, scfg, fcfg, n_replicas, max_len,
              tenants):
    """Build an n-replica fleet, replay the seeded schedule through the
    router (main thread pumps the router; each replica pumps itself),
    and report throughput / TTFT / fairness-at-contention / parity
    streams / per-replica compile ledgers."""
    from distributeddeeplearning_tpu.serving import (
        Replica,
        Request,
        Router,
    )

    router = Router(config=dataclasses.replace(fcfg, replicas=n_replicas))
    obs_dir = os.environ.get("OBS_DIR") or None
    for k in range(n_replicas):
        router.add_replica(
            Replica(k, model, params, scfg, max_len=max_len,
                    obs_dir=obs_dir),
            start=True, threaded=True,
        )
    t0 = time.perf_counter()
    while not all(r.state == "ready" for r in router.replicas):
        if time.perf_counter() - t0 > 600:
            raise TimeoutError("fleet warmup timed out")
        time.sleep(0.01)
    # Warm pass: one request end-to-end per replica (round-robin
    # placement for the warm pass only) so first-dispatch overheads
    # stay out of the measurement.
    warm_router_placement = router.config.placement
    router.config.placement = "rr"
    for i in range(n_replicas):
        router.submit(Request(
            prompt=reqs[0]["prompt"], max_new_tokens=2, temperature=0.0,
        ))
    router.drain(timeout=300)
    router.config.placement = warm_router_placement

    compile_pre = {
        r.rid: r.engine.compile_count for r in router.replicas
    }
    completed_pre = router.stats["completed"]  # the warm pass
    handles = []
    handles_by_tenant = {t: [] for t in tenants}
    fairness = None
    steady_base = None
    pressure_peak = 0.0
    total_slots = sum(r.engine.num_slots for r in router.replicas)

    def pump_once() -> bool:
        nonlocal fairness, steady_base, pressure_peak
        busy = router.step()
        pressure_peak = max(pressure_peak, router.last_pressure)
        if len(handles) != len(reqs):
            return busy
        if fairness is None and steady_base is None:
            # Steady state reached: every slot busy with backlog behind
            # it — delivery shares are pinned by the router's weights
            # from here until the first tenant's backlog empties. The
            # fairness window measures exactly that span, excluding the
            # ramp-up ticks where slots filled in first-cycle order.
            occupied = sum(
                r.server.active_count for r in router.replicas
                if r.server is not None
            )
            if occupied >= total_slots:
                steady_base = fairness_snapshot(handles_by_tenant)
        if fairness is None:
            stats = router.tenant_stats()
            # only the measured tenants — the warm pass's "default"
            # tenant queue is empty by construction
            if any(stats[t]["queued"] == 0 for t in tenants if t in stats):
                # Contended phase over for at least one tenant. No
                # steady-state base (backlog never filled the fleet)
                # means the load never contended: the snapshot is
                # marked unusable and the fairness gate fails, pushing
                # the protocol toward a genuinely contended backlog
                # instead of a vacuous pass.
                snap = fairness_snapshot(handles_by_tenant)
                base = steady_base or {}
                window = {}
                for t in tenants:
                    got = snap[t]["tokens"] - (
                        base[t]["tokens"] if t in base else 0
                    )
                    window[t] = {"tokens": got}
                total = sum(row["tokens"] for row in window.values())
                for t, row in window.items():
                    row["share"] = row["tokens"] / total if total else 0.0
                window["_contended"] = steady_base is not None and total > 0
                fairness = window
        return busy

    t0 = time.perf_counter()
    for r in reqs:
        while time.perf_counter() - t0 < r["arrival_s"]:
            pump_once()
        fh = router.submit(Request(
            prompt=r["prompt"], max_new_tokens=r["max_new"],
            temperature=0.0,
        ), tenant=r["tenant"])
        handles.append(fh)
        handles_by_tenant[r["tenant"]].append(fh)
    while pump_once():
        pass
    dt = time.perf_counter() - t0
    if fairness is None:  # trigger never fired (open-loop light load)
        fairness = fairness_snapshot(handles_by_tenant)
        fairness["_contended"] = False

    tokens = sum(len(fh.new_tokens) for fh in handles)
    ttft_ms = [
        fh.ttft_s * 1e3 for fh in handles if fh.ttft_s is not None
    ]
    ledger = [
        {
            "replica": r.rid,
            "compile_count": r.engine.compile_count,
            "programs_expected": r.engine.programs_expected,
            "compiles_during_measure":
                r.engine.compile_count - compile_pre[r.rid],
            "dispatched": r.dispatched,
            "occupancy_mean": round(r.server.occupancy_mean, 3),
        }
        for r in router.replicas
    ]
    run = {
        "replicas": n_replicas,
        "tokens_per_sec": round(tokens / dt, 1),
        "wall_s": round(dt, 2),
        "tokens": tokens,
        "completed": router.stats["completed"] - completed_pre,
        "requeued": router.stats["requeued"],
        "ttft_p50_ms": round(percentile(ttft_ms, 0.5), 2),
        "ttft_p99_ms": round(percentile(ttft_ms, 0.99), 2),
        "pressure_peak": round(pressure_peak, 3),
        "fairness_at_contention": fairness,
        "per_replica": ledger,
    }
    streams = [list(fh.new_tokens) for fh in handles]
    statuses = [fh.finish_reason for fh in handles]
    router.close()
    return run, streams, statuses


def main() -> int:
    if "--events" in sys.argv[1:] or os.environ.get("OBS_DIR"):
        from distributeddeeplearning_tpu import obs

        if not os.environ.get("OBS_DIR"):
            os.environ["OBS_DIR"] = os.path.join(
                "runs", f"fleet-bench-{int(time.time())}"
            )
        obs.configure_from_env()
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("COMPILATION_CACHE_DIR"):
        from distributeddeeplearning_tpu.training.warmup import (
            enable_persistent_cache,
        )

        enable_persistent_cache(os.environ["COMPILATION_CACHE_DIR"])

    import flax.linen as nn
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.serving import FleetConfig, ServeConfig
    from distributeddeeplearning_tpu.serving.fleet.router import (
        parse_tenant_weights,
    )

    env = os.environ
    model_name = env.get("BENCH_MODEL", "lm_tiny")
    vocab = int(env.get("BENCH_VOCAB", "32000"))
    n_requests = int(env.get("SERVE_REQUESTS", "48"))
    max_new = int(env.get("SERVE_MAX_NEW", "16"))
    rate_rps = float(env.get("SERVE_RATE_RPS", "0"))
    seed = int(env.get("SERVE_SEED", "0"))
    profile = env.get("SERVE_PROFILE", "mixed")
    weights = parse_tenant_weights(
        env.get("SERVE_TENANT_WEIGHTS", "gold:3,silver:2,bronze:1")
    )
    min_scaling = float(env.get("SERVE_FLEET_MIN_SCALING", "1.8"))
    single_core_min = float(env.get("SERVE_FLEET_SINGLE_CORE_MIN", "0.9"))
    ttft_max_ratio = float(env.get("SERVE_FLEET_TTFT_MAX_RATIO", "1.25"))
    fairness_tol = float(env.get("SERVE_FLEET_FAIRNESS_TOL", "0.15"))

    scfg = ServeConfig.from_env()
    if env.get("SERVE_SLOTS") is None:
        scfg.num_slots = 4  # per REPLICA — the fleet scales by adding pools
    if scfg.buckets is None:
        scfg.buckets = (8, 16)
    fcfg = FleetConfig.from_env()
    fcfg.tenant_weights = weights
    n_replicas = fcfg.replicas

    shapes = profile_shapes(profile, max_new)
    max_len = max(tp + n_new for tp, n_new in shapes)
    tenants = sorted(weights)
    metric = "serve_fleet_scaling_tokens_per_sec"
    try:
        model = get_model(
            model_name, num_classes=vocab, max_seq_len=max_len,
            dtype=jnp.float32,
        )
        variables = jax.jit(model.init, static_argnames=("train",))(
            jax.random.PRNGKey(0), jnp.zeros((2, max_len), jnp.int32),
            train=False,
        )
        params = nn.unbox(variables["params"])
        reqs = build_tenant_requests(
            tenants, n_requests, rate_rps, seed, vocab, shapes
        )

        single, single_streams, single_status = run_fleet(
            model, params, reqs, scfg, fcfg, 1, max_len, tenants
        )
        fleet, fleet_streams, fleet_status = run_fleet(
            model, params, reqs, scfg, fcfg, n_replicas, max_len, tenants
        )

        parity = (
            fleet_streams == single_streams
            and fleet_status == single_status
        )
        scaling = (
            fleet["tokens_per_sec"] / single["tokens_per_sec"]
            if single["tokens_per_sec"] else 0.0
        )
        ttft_ratio = (
            fleet["ttft_p99_ms"] / single["ttft_p99_ms"]
            if single["ttft_p99_ms"] else 0.0
        )
        cores = usable_cores()
        basis = "multi_core" if cores >= n_replicas else "single_core"
        scaling_min = min_scaling if basis == "multi_core" else (
            single_core_min
        )
        weight_total = sum(weights.values())
        fairness_rows = {}
        contended = bool(
            fleet["fairness_at_contention"].get("_contended", True)
        )
        fair_ok = contended  # an uncontended snapshot certifies nothing
        for t, w in weights.items():
            want = w / weight_total
            got = fleet["fairness_at_contention"][t]["share"]
            rel_err = abs(got - want) / want
            within = rel_err <= fairness_tol
            fair_ok = fair_ok and within
            fairness_rows[t] = {
                "weight_share": round(want, 4),
                "token_share": round(got, 4),
                "rel_err": round(rel_err, 4),
                "within_tol": within,
            }
        fairness_rows["_contended"] = contended
        clean = all(
            row["compiles_during_measure"] == 0
            for run in (single, fleet) for row in run["per_replica"]
        )
        closed = all(
            row["compile_count"] == row["programs_expected"]
            for run in (single, fleet) for row in run["per_replica"]
        )
        no_drops = (
            single["completed"] == len(reqs)
            and fleet["completed"] == len(reqs)
        )
        ok = (
            parity and clean and closed and no_drops and fair_ok
            and scaling >= scaling_min
            and (ttft_ratio <= ttft_max_ratio or fleet["ttft_p99_ms"]
                 <= single["ttft_p99_ms"])
        )
        detail = {
            "profile": profile,
            "requests": n_requests,
            "rate_rps": rate_rps,
            "max_len": max_len,
            "buckets": list(scfg.buckets),
            "slots_per_replica": scfg.num_slots,
            "replicas": n_replicas,
            "placement": fcfg.placement,
            "tenant_weights": weights,
            "platform": jax.devices()[0].platform,
            "cores": cores,
            # CPU-honest scaling semantics (docs/SERVING.md): on a host
            # with fewer cores than replicas the pumps time-slice one
            # core and linear scaling is physically unattainable; the
            # gate derates to "fleet overhead costs ~nothing" and this
            # field says so instead of letting the ratio masquerade as
            # a hardware claim.
            "scaling_basis": basis,
            "scaling_min_applied": scaling_min,
            "scaling_min_multi_core": min_scaling,
            "single": single,
            "fleet": fleet,
            "scaling": round(scaling, 2),
            "ttft_p99_ratio": round(ttft_ratio, 2),
            "ttft_max_ratio": ttft_max_ratio,
            "fairness": fairness_rows,
            "fairness_tol": fairness_tol,
            "parity": bool(parity),
            "no_drops": no_drops,
        }
        record = {
            "metric": metric,
            "value": fleet["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": round(scaling, 2),
            "detail": detail,
        }
        _emit_record(record)
        return 0 if ok else 1
    except Exception as e:  # structured failure record, like bench.py
        _emit_record({
            "metric": metric, "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": repr(e),
        })
        raise


if __name__ == "__main__":
    sys.exit(main())
