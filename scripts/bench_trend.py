"""Regression sentinel over the bench trajectory (``BENCH_r*.json``).

Every round the driver re-runs ``bench.py`` and archives the record as
``BENCH_r<k>.json``. This script reads that trajectory and answers the
one question a perf-focused repo must keep answering: **did a
like-for-like headline regress?** — while refusing to be fooled by
infra outages. Rounds 4–5 taught the lesson: a dead accelerator relay
used to emit ``value: 0.0``, which a naive diff reads as a 100%
regression. Records now carry a ``tier`` (``bench.py``): ``"cpu"`` =
relay down, protocol re-run on the CPU fallback; ``"outage"`` = nothing
could run. Neither is comparable to a TPU round, so both are **listed
but skipped** — as are legacy outage records (``error`` / value ≤ 0
with no tier), cross-platform pairs, pairs whose
``kv_dtype``/``weight_dtype`` changed (a re-quantized protocol is a new
baseline, not a regression; records predating the quantized tier count
as the native "bf16" config), pairs whose ``spec_k`` changed (a
re-speculated protocol likewise — records predating the speculative
tier count as ``spec_k=0``), pairs whose ``data_format`` changed
(synthetic pool vs streamed shards is a different input pipeline —
``data_change`` skip; records predating the streamed tier count as the
native synthetic reader), pairs whose ``chaos_plan`` differs (a
fault storm is part of the protocol — ``chaos_change`` skip;
chaos-free records normalize to no plan), pairs whose ``coloc``
knob string differs (a re-arbitrated pool — different geometry,
shrink step, or surge window — is a new colocation protocol —
``coloc_change`` skip; non-colocated records normalize to none),
pairs whose disaggregation ``pool_split`` differs (re-drawing the
prefill/decode pool boundary is a new serving protocol —
``disagg_change`` skip; colocated records normalize to none),
and pairs whose
``decode_kernel`` changed (the fused Pallas decode path vs the stitched
XLA lowering is a different machine program per token —
``kernel_change`` skip; records predating the kernel tier count as the
native ``xla`` lowering).

A drop > ``--threshold`` (default 10%) between *consecutive comparable*
records of the same metric+platform exits nonzero — the CI tripwire
``make bench-trend`` wires up.

Usage::

    python scripts/bench_trend.py [--glob 'BENCH_r*.json']
        [--threshold 0.10] [--json]
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def load_round(path: str) -> Dict[str, Any]:
    """One trajectory entry: round number + the parsed bench record
    (may be absent when the round's output was unparseable)."""
    with open(path) as fh:
        d = json.load(fh)
    m = _ROUND_RE.search(os.path.basename(path))
    n = d.get("n") if isinstance(d, dict) else None
    if n is None and m:
        n = int(m.group(1))
    record = d.get("parsed") if isinstance(d, dict) else None
    return {
        "path": path,
        "round": n,
        "rc": d.get("rc") if isinstance(d, dict) else None,
        "record": record if isinstance(record, dict) else None,
    }


def classify(entry: Dict[str, Any]) -> Optional[str]:
    """Why this round is NOT comparable (None = comparable).

    ``tier: cpu/outage`` records are deliberate infra annotations;
    legacy outage rounds (pre-tier) show up as an error field or a
    non-positive value. Reporting any of them as a regression would be
    exactly the 100%-drop misread this sentinel exists to kill."""
    rec = entry["record"]
    if rec is None:
        return "unparsed"
    tier = rec.get("tier") or (rec.get("detail") or {}).get("tier")
    if tier in ("cpu", "outage"):
        return f"tier:{tier}"
    if rec.get("error"):
        return "error"
    try:
        if float(rec.get("value", 0.0)) <= 0.0:
            return "zero_value"
    except (TypeError, ValueError):
        return "bad_value"
    return None


def analyze(
    paths: List[str], threshold: float = 0.10
) -> Dict[str, Any]:
    """The trajectory verdict: per-round rows + like-for-like drops."""
    entries = sorted(
        (load_round(p) for p in paths),
        key=lambda e: (e["round"] is None, e["round"] or 0),
    )
    rows: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    # metric -> last comparable (round, value, platform, dtypes)
    last: Dict[str, Dict[str, Any]] = {}
    for e in entries:
        rec = e["record"] or {}
        skip = classify(e)
        detail = rec.get("detail") or {}
        row = {
            "round": e["round"],
            "metric": rec.get("metric"),
            "value": rec.get("value"),
            "unit": rec.get("unit"),
            "platform": detail.get("platform"),
            # A kv_dtype/weight_dtype change is a protocol change, not a
            # regression — same treatment as a platform change. Records
            # predating the quantized tier carry no dtype fields; they
            # ran the native ("bf16") engines, so absent normalizes to
            # that and stays comparable.
            "dtypes": (
                detail.get("kv_dtype") or "bf16",
                detail.get("weight_dtype") or "bf16",
            ),
            # A spec_k change re-shapes the whole protocol (draft +
            # verify programs, commits per tick) — a new baseline, not
            # a regression; records predating the speculative tier ran
            # spec_k=0 and stay comparable. Same treatment as dtypes.
            "spec_k": int(detail.get("spec_k") or 0),
            # A data-format change (synthetic pool -> streamed shards,
            # or any reader swap) re-shapes the input side of a train
            # protocol — different bytes, different host pipeline — so
            # it is a protocol skip, not a regression. Records predating
            # the streamed tier carry no field and normalize to the
            # native synthetic reader.
            "data_format": detail.get("data_format") or "native",
            # A replica-count change re-shapes the fleet protocol the
            # same way (aggregate throughput over N pools is a new
            # baseline); non-fleet records normalize to 1 replica.
            "replicas": int(detail.get("replicas") or 1),
            # A decode-kernel swap (stitched XLA lowering <-> fused
            # Pallas paged-decode) replaces the per-token machine
            # program outright — a new baseline, not a regression.
            # Records predating the kernel tier carry no field and ran
            # the native "xla" lowering.
            "kernel": detail.get("decode_kernel") or "xla",
            # A chaos plan's presence (or a different storm) re-shapes
            # the whole run — faults, rebuilds and brownout windows are
            # part of the protocol, not noise around it — so any
            # chaos-plan difference is a protocol skip, never a
            # regression. Chaos-free records normalize to "".
            "chaos": str(detail.get("chaos_plan") or ""),
            # The colocation knob string (pool geometry, shrink step,
            # brownout stages, surge window — coloc_bench's `coloc`
            # detail) re-shapes the arbitrated storm the same way: a
            # different arbitration protocol is a new baseline
            # (``coloc_change`` skip), never a regression.
            # Non-colocated records normalize to "".
            "coloc": str(detail.get("coloc") or ""),
            # The disaggregation pool split (disagg_bench's
            # `pool_split` detail, e.g. "prefill:2,decode:2"): moving
            # replicas between the prefill and decode pools re-shapes
            # which phase each engine serves — a new serving protocol
            # (``disagg_change`` skip), never a regression. Colocated
            # records normalize to "".
            "pools": str(detail.get("pool_split") or ""),
            # An elastic world resize is the training-side analog: the
            # same metric over a different device count is a new
            # baseline (``world_change`` skip). Pre-elastic records
            # carry no world_size but always recorded ``devices`` — the
            # same number — so they normalize to it and stay comparable
            # across the field's introduction; records with neither
            # normalize to 0 ("unspecified").
            "world": int(
                detail.get("world_size") or detail.get("devices") or 0
            ),
            "skip": skip,
            "delta_pct": None,
        }
        if skip is None:
            metric = rec["metric"]
            value = float(rec["value"])
            prev = last.get(metric)
            if (
                prev is not None
                and prev["platform"] == row["platform"]
                and prev["dtypes"] == row["dtypes"]
                and prev["spec_k"] == row["spec_k"]
                and prev["kernel"] == row["kernel"]
                and prev["replicas"] == row["replicas"]
                and prev["world"] == row["world"]
                and prev["data_format"] == row["data_format"]
                and prev["chaos"] == row["chaos"]
                and prev["coloc"] == row["coloc"]
                and prev["pools"] == row["pools"]
            ):
                delta = (value - prev["value"]) / prev["value"]
                row["delta_pct"] = round(delta * 100.0, 2)
                if delta < -threshold:
                    regressions.append({
                        "metric": metric,
                        "from_round": prev["round"],
                        "to_round": e["round"],
                        "from_value": prev["value"],
                        "to_value": value,
                        "drop_pct": round(-delta * 100.0, 2),
                    })
            elif prev is not None and prev["platform"] != row["platform"]:
                row["skip"] = (
                    f"platform_change:{prev['platform']}->{row['platform']}"
                )
            elif prev is not None and prev["dtypes"] != row["dtypes"]:
                row["skip"] = (
                    f"dtype_change:{'/'.join(prev['dtypes'])}"
                    f"->{'/'.join(row['dtypes'])}"
                )
            elif prev is not None and prev["spec_k"] != row["spec_k"]:
                row["skip"] = (
                    f"spec_change:k={prev['spec_k']}->k={row['spec_k']}"
                )
            elif prev is not None and prev["kernel"] != row["kernel"]:
                row["skip"] = (
                    f"kernel_change:{prev['kernel']}->{row['kernel']}"
                )
            elif prev is not None and prev["replicas"] != row["replicas"]:
                row["skip"] = (
                    f"replica_change:{prev['replicas']}"
                    f"->{row['replicas']}"
                )
            elif prev is not None and prev["data_format"] != row["data_format"]:
                row["skip"] = (
                    f"data_change:{prev['data_format']}"
                    f"->{row['data_format']}"
                )
            elif prev is not None and prev["chaos"] != row["chaos"]:
                row["skip"] = (
                    f"chaos_change:"
                    f"{prev['chaos'] or 'none'}->{row['chaos'] or 'none'}"
                )
            elif prev is not None and prev["coloc"] != row["coloc"]:
                row["skip"] = (
                    f"coloc_change:"
                    f"{prev['coloc'] or 'none'}->{row['coloc'] or 'none'}"
                )
            elif prev is not None and prev["pools"] != row["pools"]:
                row["skip"] = (
                    f"disagg_change:"
                    f"{prev['pools'] or 'none'}->{row['pools'] or 'none'}"
                )
            elif prev is not None:
                row["skip"] = (
                    f"world_change:{prev['world'] or 'unspecified'}"
                    f"->{row['world'] or 'unspecified'}"
                )
            if row["skip"] is None or "_change" in str(row["skip"]):
                # A protocol/platform transition row is not COMPARED,
                # but it IS the new baseline — otherwise one permanent
                # dtype/spec change would skip every later round forever
                # and the sentinel would go blind for that metric.
                last[metric] = {
                    "round": e["round"], "value": value,
                    "platform": row["platform"], "dtypes": row["dtypes"],
                    "spec_k": row["spec_k"], "kernel": row["kernel"],
                    "replicas": row["replicas"],
                    "world": row["world"],
                    "data_format": row["data_format"],
                    "chaos": row["chaos"],
                    "coloc": row["coloc"],
                    "pools": row["pools"],
                }
        rows.append(row)
    return {
        "rows": rows,
        "regressions": regressions,
        "threshold_pct": threshold * 100.0,
        "ok": not regressions,
    }


def render(result: Dict[str, Any]) -> str:
    out = []
    add = out.append
    add(f"{'round':>5s} {'metric':42s} {'value':>12s} {'Δ%':>8s}  note")
    for r in result["rows"]:
        val = "-" if r["value"] is None else f"{r['value']:.1f}"
        delta = "-" if r["delta_pct"] is None else f"{r['delta_pct']:+.1f}"
        note = r["skip"] or (r["platform"] or "")
        add(
            f"{r['round'] if r['round'] is not None else '?':>5} "
            f"{(r['metric'] or '<unparsed>'):42s} {val:>12s} {delta:>8s}"
            f"  {note}"
        )
    if result["regressions"]:
        add("")
        for g in result["regressions"]:
            add(
                f"REGRESSION: {g['metric']} dropped {g['drop_pct']:.1f}% "
                f"(round {g['from_round']}: {g['from_value']:.1f} -> "
                f"round {g['to_round']}: {g['to_value']:.1f}; "
                f"threshold {result['threshold_pct']:.0f}%)"
            )
    else:
        add("")
        add(
            f"OK: no like-for-like drop > {result['threshold_pct']:.0f}% "
            f"(outage/cpu-tier rounds skipped, not misread)"
        )
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--glob", default=os.path.join(REPO, "BENCH_r*.json"),
        help="trajectory files (default: repo-root BENCH_r*.json)",
    )
    p.add_argument("--threshold", type=float, default=0.10,
                   help="like-for-like drop that fails (fraction)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    paths = sorted(globlib.glob(args.glob))
    if not paths:
        print(f"ERROR: no trajectory files match {args.glob}",
              file=sys.stderr)
        return 2
    result = analyze(paths, threshold=args.threshold)
    if args.json:
        print(json.dumps(result))
    else:
        print(render(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
