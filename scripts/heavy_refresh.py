"""Keep ``tests/heavy_tests.txt`` (and the TESTING.md tier table) honest.

The fast/full test split is data: ``heavy_tests.txt`` lists nodeids
measured >= ~10 s, and ``conftest`` tags them ``heavy``+``slow`` at
collection. Two ways that data rots (VERDICT r5 items 5/7): tests get
renamed/removed and the list keeps stale nodeids, and the TESTING.md
tier table's collected/deselected counts drift from reality. This
script closes both:

* default mode (``make heavy-refresh``) — runs ``pytest
  --collect-only``, prunes heavy entries that no longer collect, and
  prints the tier numbers (collected / heavy / fast) that belong in the
  TESTING.md table;
* ``--from-durations LOG`` — full regeneration from a measured
  ``pytest --durations=N`` run log (every ``call`` >= ``--threshold``
  seconds becomes heavy), replacing the fragile grep/awk recipe the doc
  used to carry.

``--check`` additionally runs the ddlint static-analysis suite
(``scripts/ddlint.py --changed-ok``, docs/ANALYSIS.md) so ``make
check``'s gate is ONE command: heavy-list drift, lint invariants, then
the fast tier.

Exit code 1 when the pruned list differs from what was on disk (or the
lint gate fails) and ``--check`` was passed; always writes otherwise.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
from typing import List, Set

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEAVY_FILE = os.path.join(REPO, "tests", "heavy_tests.txt")

# `12.34s call tests/test_x.py::test_y` — pytest --durations line
_DURATION_RE = re.compile(r"^\s*([0-9.]+)s\s+call\s+(\S+)")


def collected_nodeids() -> List[str]:
    """Every nodeid pytest currently collects (CPU platform forced —
    collection imports test modules, which import jax)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/", "-q",
            "--collect-only", "-p", "no:cacheprovider",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
    )
    ids = [
        ln.strip() for ln in res.stdout.splitlines()
        if "::" in ln and not ln.startswith(("=", "<", " "))
    ]
    if not ids:
        raise SystemExit(
            f"pytest --collect-only produced no nodeids (rc={res.returncode}):\n"
            + res.stdout[-2000:] + res.stderr[-2000:]
        )
    return ids


def parse_durations_log(lines, threshold_s: float) -> List[str]:
    """Nodeids whose measured ``call`` duration >= threshold (the awk
    filter from the old TESTING.md recipe, kept exact: without it every
    top-N test lands in the heavy list and the fast tier silently
    shrinks)."""
    out = []
    for ln in lines:
        m = _DURATION_RE.match(ln)
        if m and float(m.group(1)) >= threshold_s:
            out.append(m.group(2))
    return out


def read_heavy() -> List[str]:
    try:
        with open(HEAVY_FILE) as f:
            return [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return []


def write_heavy(ids: List[str]) -> None:
    with open(HEAVY_FILE, "w") as f:
        f.write("\n".join(ids) + ("\n" if ids else ""))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--from-durations", metavar="LOG", default=None,
        help="regenerate the whole list from a measured --durations log",
    )
    # 10 s on the 1-vCPU reference host: the fast tier must fit the
    # driver's 870 s tier-1 timeout with margin; the suite outgrew the
    # original 25 s cut (613 fast tests measured 1131 s total).
    p.add_argument("--threshold", type=float, default=10.0)
    p.add_argument(
        "--check", action="store_true",
        help="don't write; exit 1 if the list on disk is stale",
    )
    args = p.parse_args(argv)

    lint_rc = 0
    if args.check:
        # The lint gate rides the same CI entry point (`make check`).
        # --changed-ok: a refreshed lint.json is fine; only unsuppressed
        # findings (printed by ddlint itself) fail the gate.
        lint_rc = subprocess.call(
            [sys.executable, os.path.join(REPO, "scripts", "ddlint.py"),
             "--changed-ok"],
            cwd=REPO,
        )

    current: Set[str] = set(collected_nodeids())
    heavy = read_heavy()

    if args.from_durations:
        with open(args.from_durations) as f:
            measured = parse_durations_log(f, args.threshold)
        new = sorted(set(measured) & current)
        dropped_uncollected = sorted(set(measured) - current)
        if dropped_uncollected:
            print(f"ignored {len(dropped_uncollected)} measured-but-not-"
                  f"collected nodeids: {dropped_uncollected}")
    else:
        new = [nid for nid in heavy if nid in current]
        stale = [nid for nid in heavy if nid not in current]
        if stale:
            print(f"pruning {len(stale)} stale heavy entries:")
            for nid in stale:
                print(f"  - {nid}")

    n_total, n_heavy = len(current), len(new)
    print(f"tier numbers for docs/TESTING.md: {n_total} collected, "
          f"{n_heavy} heavy/slow (deselected by fast tiers), "
          f"{n_total - n_heavy} fast")

    if new == heavy:
        print(f"{HEAVY_FILE} is current ({n_heavy} entries)")
        return 1 if lint_rc else 0
    if args.check:
        print(f"STALE: {HEAVY_FILE} needs refreshing (run make heavy-refresh)")
        return 1
    write_heavy(new)
    print(f"wrote {HEAVY_FILE} ({len(heavy)} -> {n_heavy} entries)")
    return 1 if lint_rc else 0


if __name__ == "__main__":
    sys.exit(main())
