"""lm_stream recertify row: pretrain on token shards -> checkpoint ->
serve the trained artifact.

The first end-to-end pretrain→serve pipeline in the repo (ROADMAP
item 5): every prior serving number decoded from *randomly initialised*
params, and the trained artifact the training tier produces had never
crossed into the serving tier. This protocol closes the loop on the
streamed data plane (docs/DATA.md):

1. build a seeded synthetic token shard set (``data/stream``) in a
   temp dir — the same writer path ``scripts/streamgen.py`` exposes;
2. pretrain ``BENCH_MODEL`` on it via ``DATA_FORMAT=stream`` semantics
   (TokenStreamDataset + host prefetch + checkpointable shuffle
   cursor), step-granular checkpoints ON so every manifest carries the
   ``data_cursor``;
3. restore the final checkpoint from disk into a fresh buffer tree
   (portability: the restore path, not the in-memory state, feeds
   serving) and **gate** that the restored params match the trained
   ones bitwise and the manifest carries the stream cursor;
4. load the restored params into a ``SlotEngine`` and serve greedy
   continuations — **gate**: token streams match ``inference.generate``
   on the same restored params exactly.

JSON line: ``lm_stream_pretrain_tokens_per_sec`` (training throughput
on the streamed reader), with the serve-match + cursor gates and the
data-plane detail. Non-zero exit on any gate failure — recertify treats
that as a failed row.

Knobs (env): ``BENCH_MODEL`` (lm_tiny), ``STREAM_RECORDS`` (512),
``STREAM_SEQ_LEN`` (64), ``STREAM_VOCAB`` (256), ``STREAM_SHARD_RECORDS``
(128), ``STREAM_SHUFFLE_BLOCK`` (64), ``STREAM_BATCH`` (8, per device),
``STREAM_EPOCHS`` (2), ``PREFETCH_HOST_BATCHES`` (2), ``SERVE_MAX_NEW``
(16), ``SERVE_SLOTS`` (4), ``SERVE_PROMPT_LEN`` (8).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def main() -> int:
    import jax
    import numpy as np

    model_name = os.environ.get("BENCH_MODEL", "lm_tiny")
    records = _env_int("STREAM_RECORDS", 512)
    seq_len = _env_int("STREAM_SEQ_LEN", 64)
    vocab = _env_int("STREAM_VOCAB", 256)
    shard_records = _env_int("STREAM_SHARD_RECORDS", 128)
    shuffle_block = _env_int("STREAM_SHUFFLE_BLOCK", 64)
    batch = _env_int("STREAM_BATCH", 8)
    epochs = _env_int("STREAM_EPOCHS", 2)
    host_prefetch = _env_int("PREFETCH_HOST_BATCHES", 2)
    max_new = _env_int("SERVE_MAX_NEW", 16)
    slots = _env_int("SERVE_SLOTS", 4)
    prompt_len = _env_int("SERVE_PROMPT_LEN", 8)

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.stream import (
        TokenStreamDataset,
        synthetic_rows,
        write_token_shards,
    )
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop
    from distributeddeeplearning_tpu.training.checkpoint import (
        CheckpointManager,
    )

    with tempfile.TemporaryDirectory(prefix="lm_stream_") as tmp:
        shard_dir = os.path.join(tmp, "shards")
        write_token_shards(
            shard_dir,
            synthetic_rows(records, seq_len=seq_len, vocab_size=vocab,
                           seed=42),
            seq_len=seq_len,
            vocab_size=vocab,
            shard_records=shard_records,
        )
        ckpt_dir = os.path.join(tmp, "ckpt")
        cfg = TrainConfig(
            model=model_name,
            num_classes=vocab,
            batch_size_per_device=batch,
            epochs=epochs,
            compute_dtype="float32",
            weight_decay=0.0,
            log_every_steps=0,
            data_format="stream",
            data_dir=shard_dir,
            fake=False,
            stream_shuffle_block=shuffle_block,
            prefetch_host_batches=host_prefetch,
            model_dir=ckpt_dir,
            checkpoint_every_steps=2,
            checkpoint_async=False,
        )
        data = TokenStreamDataset(
            shard_dir,
            global_batch_size=cfg.global_batch_size,
            seed=cfg.seed,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            shuffle_block=shuffle_block,
        )
        model = get_model(
            model_name,
            num_classes=vocab,
            dtype="float32",
            max_seq_len=max(seq_len, prompt_len + max_new),
        )
        result = loop.fit(model, cfg, data, add_default_logger=False)
        train_tps = result.images_per_sec * seq_len  # rows/s x tokens/row

        # Portability leg: restore the artifact FROM DISK and gate the
        # round trip + the manifest's stream cursor.
        mgr = CheckpointManager(ckpt_dir, save_every_steps=2)
        restored = mgr.restore(
            jax.tree.map(lambda x: jax.numpy.zeros_like(x), result.state)
        )
        manifest = mgr.last_manifest or {}
        cursor = manifest.get("data_cursor")
        mgr.close()
        roundtrip_ok = all(
            bool(np.array_equal(np.asarray(a), np.asarray(b)))
            for a, b in zip(
                jax.tree.leaves(jax.device_get(result.state.params)),
                jax.tree.leaves(jax.device_get(restored.params)),
            )
        )

        # Serve the trained artifact: greedy through the slot engine vs
        # the sequential reference on the SAME restored params.
        from distributeddeeplearning_tpu.inference import generate
        from distributeddeeplearning_tpu.serving import SlotEngine

        prompts = data.index.read(
            "tokens", np.arange(slots)
        )[:, :prompt_len].astype(np.int32)
        engine = SlotEngine(
            model, restored.params, num_slots=slots,
            max_len=prompt_len + max_new,
        )
        served = np.asarray(
            generate(
                model, restored.params, prompts,
                max_new_tokens=max_new, engine=engine,
            )
        )
        reference = np.asarray(
            generate(
                model, restored.params, jax.numpy.asarray(prompts),
                max_new_tokens=max_new,
            )
        )
        serve_match = bool(np.array_equal(served, reference))

    ok = roundtrip_ok and serve_match and cursor is not None and train_tps > 0
    record = {
        "metric": "lm_stream_pretrain_tokens_per_sec",
        "value": round(train_tps, 1) if ok else 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # new scenario: no reference point
        "host_sync_count": result.perf.get("host_sync_count"),
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": jax.device_count(),
            "data_format": "stream",
            "records": records,
            "seq_len": seq_len,
            "vocab": vocab,
            "shuffle_block": shuffle_block,
            "epochs": epochs,
            "per_device_batch": batch,
            "prefetch_host_batches": host_prefetch,
            "serve_match": serve_match,
            "restore_roundtrip": roundtrip_ok,
            "manifest_data_cursor": cursor,
            "serve_max_new": max_new,
            "serve_slots": slots,
        },
    }
    print(json.dumps(record), flush=True)
    if not ok:
        print(
            f"FAIL: roundtrip={roundtrip_ok} serve_match={serve_match} "
            f"cursor={'present' if cursor else 'MISSING'} tps={train_tps}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
