"""Live telemetry dashboard — watch a running world's rollups + SLOs.

The read loop of the live plane (docs/OBSERVABILITY.md): tail the run
directory's ``events-*.jsonl`` part files incrementally, aggregate them
into rolling-window rollups (counter rates, last-value gauges, span
p50/p95/p99 from fixed-bucket log histograms), evaluate the ``SLO_SPEC``
objectives with multi-window burn rates, publish the snapshot as an
atomically-replaced ``rollup.json`` (the file an adaptive admission
policy reads — docs/SERVING.md), and render it to the terminal.

Deliberately jax-free: it must run against a live TPU world from any
machine that can see the run directory, including one with no
accelerator stack at all.

Usage::

    python scripts/obs_watch.py [RUN_DIR] [--once] [--json]
        [--interval S] [--window S] [--slo SPEC_OR_FILE] [--no-write]
    make obs-watch                    # newest runs/<dir>, live

``--once`` renders a single snapshot and exits (scriptable / CI);
without it the dashboard refreshes every ``--interval`` seconds until
interrupted. ``--slo`` overrides the ``SLO_SPEC`` env (inline spec or a
spec file path).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def newest_run_dir(base: str = "runs") -> str:
    """The most recently modified run directory under ``runs/`` — the
    Makefile's obs-report convention."""
    dirs = [d for d in glob.glob(os.path.join(base, "*")) if os.path.isdir(d)]
    if not dirs:
        raise SystemExit(
            f"ERROR: no run directories under {base}/ — pass one "
            f"(launch.py --obs-dir, bench --events, or OBS_DIR)"
        )
    return max(dirs, key=os.path.getmtime)


def _fmt_val(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


#: A serving-fleet replica's private event stream (obs/bus.py
#: bound_bus): the process proc tag with "-s<k>" appended.
_REPLICA_PROC_RE = re.compile(r"-s\d+$")


def replica_rows(snapshot: dict):
    """Per-replica (stream, gauges) rows when the run carries 2+ fleet
    replica streams (``events-p0-s<k>.jsonl``); None otherwise — a
    single-engine run keeps the flat gauge table."""
    procs = snapshot.get("procs") or {}
    rows = [
        (proc, gauges) for proc, gauges in sorted(procs.items())
        if _REPLICA_PROC_RE.search(str(proc))
    ]
    return rows if len(rows) >= 2 else None


def render(snapshot: dict) -> str:
    """Human-readable dashboard frame from one rollup snapshot."""
    out = []
    add = out.append
    win = snapshot.get("window_s")
    add(
        f"run dir: {snapshot.get('run_dir', '?')}   "
        f"files: {snapshot.get('files', '?')}   "
        f"events: {snapshot.get('events_total', 0)}   "
        f"window: {win:g}s"
    )
    slo = snapshot.get("slo")
    if slo is not None:
        add("")
        add("SLO objectives (burn = value/threshold; >1 = failing):")
        for st in slo:
            flag = "BURNING" if st["burning"] else (
                "ok" if st["burn"] <= 1.0 else "hot"
            )
            value = st.get("value")
            add(
                f"  [{flag:7s}] {st['objective']:44s} "
                f"burn {st['burn']:8.2f}x (long {st['burn_long']:.2f}x)  "
                f"value {_fmt_val(value) if value is not None else 'n/a':>10s}"
                f"  worst {st['worst_burn']:.2f}x  "
                f"breaches {st['breaches']}"
            )
    spans = snapshot.get("spans") or {}
    if spans:
        add("")
        add(f"{'span (window)':32s} {'count':>7s} {'p50 ms':>9s} "
            f"{'p95 ms':>9s} {'p99 ms':>9s} {'max ms':>9s}")
        for name, s in sorted(
            spans.items(), key=lambda kv: -kv[1]["count"]
        ):
            add(
                f"{name:32s} {s['count']:7d} {s['p50_ms']:9.3f} "
                f"{s['p95_ms']:9.3f} {s['p99_ms']:9.3f} {s['max_ms']:9.3f}"
            )
    counters = snapshot.get("counters") or {}
    if counters:
        add("")
        add(f"{'counter (window)':32s} {'sum':>10s} {'rate/s':>10s}")
        for name, c in sorted(counters.items()):
            add(f"{name:32s} {c['sum']:10.0f} {c['rate_per_s']:10.3f}")
    # Data-plane line (streamed shards / host prefetch, docs/DATA.md):
    # consumer wait p50/p99 over the window + the live buffer depth and
    # delivery rate — is the pipeline keeping up with the step?
    wait = spans.get("data.wait")
    g = snapshot.get("gauges") or {}
    depth = (g.get("data.buffer_depth") or {}).get("value")
    rate = (g.get("data.bytes_per_s") or {}).get("value")
    if wait or depth is not None or rate is not None:
        parts = []
        if wait:
            parts.append(
                f"wait p50 {wait['p50_ms']:.2f}ms p99 {wait['p99_ms']:.2f}ms"
                f" (n={wait['count']})"
            )
        if depth is not None:
            parts.append(f"buffer {depth:.0f}")
        if rate is not None:
            parts.append(f"{rate / 2**20:.1f} MiB/s")
        add("")
        add("data plane: " + "  ".join(parts))
    replicas = replica_rows(snapshot)
    gauges = snapshot.get("gauges") or {}
    if gauges:
        add("")
        add(f"{'gauge (last value)':32s} {'value':>12s} {'age s':>8s}")
        # With a serving fleet present, the per-replica serve gauges are
        # rendered as rows below instead of collapsed last-writer-wins.
        skip = (
            {"serve.slot_occupancy", "serve.queue_depth"}
            if replicas else set()
        )
        for name, g in sorted(gauges.items()):
            if name in skip:
                continue
            age = g.get("age_s")
            add(
                f"{name:32s} {_fmt_val(g['value']):>12s} "
                f"{age if age is not None else '?':>8}"
            )
    # Fleet health row (chaos/self-healing tier, docs/ROBUSTNESS.md):
    # rendered whenever the router publishes the health gauges.
    health = []
    for label, name in (
        ("quarantined", "fleet.quarantined"),
        ("breakers open", "fleet.breaker_open"),
        ("brownout stage", "fleet.brownout_stage"),
    ):
        cell = (gauges or {}).get(name)
        if cell is not None and cell.get("value") is not None:
            health.append((label, cell["value"]))
    if health and any(v for _, v in health):
        add("")
        add("fleet health: " + "  ".join(
            f"{label} {v:.0f}" for label, v in health
        ))
    # Disaggregation row (docs/SERVING.md): the live prefill/decode
    # pool split plus the handoff seam and directory-hit counters.
    # Absent on colocated fleets, which emit none of these.
    disagg = []
    for label, name in (
        ("prefill", "fleet.prefill_replicas"),
        ("decode", "fleet.decode_replicas"),
        ("handoff ms", "serve.handoff_ms"),
    ):
        cell = (gauges or {}).get(name)
        if cell is not None and cell.get("value") is not None:
            disagg.append((label, cell["value"]))
    if disagg:
        for label, name in (
            ("directory hits", "serve.directory_hits"),
            ("migrations", "serve.migrations"),
        ):
            cell = counters.get(name)
            if cell and cell.get("sum"):
                disagg.append((label, cell["sum"]))
        add("")
        add("disaggregation: " + "  ".join(
            f"{label} {_fmt_val(v)}" for label, v in disagg
        ))
    # Pool-ownership row (train/serve colocation, serving/arbiter.py +
    # docs/ROBUSTNESS.md colocation): who holds the ONE device pool
    # right now — training's world size vs the replicas serving holds
    # leases for. Rendered whenever an arbiter publishes the gauges.
    pool = []
    for label, name in (
        ("train world", "pool.train_world"),
        ("serve replicas", "pool.serve_replicas"),
    ):
        cell = (gauges or {}).get(name)
        if cell is not None and cell.get("value") is not None:
            pool.append((label, cell["value"]))
    if pool:
        add("")
        add("pool ownership: " + "  ".join(
            f"{label} {v:.0f}" for label, v in pool
        ))
    # Trace-plane row (obs/traces.py): distinct request traces active
    # in the window + chaos re-routes by cause. Absent on untraced runs.
    tr = snapshot.get("traces")
    if tr:
        parts = [f"{tr.get('distinct', 0)} active trace(s)"]
        reroutes = tr.get("reroutes") or {}
        if reroutes:
            parts.append("reroutes " + ", ".join(
                f"{cause} x{n}" for cause, n in sorted(reroutes.items())
            ))
        add("")
        add("traces (window): " + "  ".join(parts))
    if replicas:
        add("")
        add("serving replicas (one row per events-*-s<k> stream):")
        add(
            f"  {'stream':16s} {'occupancy':>10s} {'queue':>7s} "
            f"{'programs':>9s} {'pool free':>10s} {'kv B/token':>11s}"
        )
        for proc, g in replicas:
            def val(name, default="-"):
                cell = g.get(name)
                return _fmt_val(cell["value"]) if cell and cell.get(
                    "value"
                ) is not None else default
            add(
                f"  {proc:16s} {val('serve.slot_occupancy'):>10s} "
                f"{val('serve.queue_depth'):>7s} "
                f"{val('serve.programs'):>9s} "
                f"{val('serve.block_pool_free'):>10s} "
                f"{val('serve.kv_bytes_per_token'):>11s}"
            )
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "path", nargs="?", default=None,
        help="run directory (default: newest runs/<dir>)",
    )
    p.add_argument("--once", action="store_true",
                   help="render one snapshot and exit")
    p.add_argument("--json", action="store_true",
                   help="emit the snapshot as JSON (implies --once)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in live mode (s)")
    p.add_argument("--window", type=float, default=60.0,
                   help="rollup window (s)")
    p.add_argument(
        "--slo", default=None,
        help="SLO spec (inline or file path; default: $SLO_SPEC)",
    )
    p.add_argument(
        "--no-write", action="store_true",
        help="don't publish rollup.json (read-only observer)",
    )
    args = p.parse_args(argv)

    from distributeddeeplearning_tpu.obs.rollup import LivePlane
    from distributeddeeplearning_tpu.obs.slo import SloEngine

    directory = args.path or newest_run_dir()
    if not os.path.isdir(directory):
        print(f"ERROR: {directory} is not a run directory", file=sys.stderr)
        return 2
    env = dict(os.environ)
    if args.slo is not None:
        env["SLO_SPEC"] = args.slo
    slo = SloEngine.from_env(env)
    plane = LivePlane(directory, window_s=args.window, slo_engine=slo)
    write = not args.no_write

    if args.once or args.json:
        snap = plane.poll(now=time.time(), write=write)
        if args.json:
            print(json.dumps(snap, default=str))
        else:
            print(render(snap))
        return 0
    try:
        while True:
            snap = plane.poll(now=time.time(), write=write)
            # ANSI home+clear: one flicker-free frame per interval.
            sys.stdout.write("\x1b[H\x1b[2J")
            print(time.strftime("%H:%M:%S"), "obs-watch", directory)
            print(render(snap))
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
