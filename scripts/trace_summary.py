"""Summarize a jax.profiler trace: device-lane op durations grouped by
fusion-name prefix, so PROFILE.md's per-op tables can be reproduced.

Usage: python scripts/trace_summary.py /tmp/trace_dir [top_n]
Finds the newest ``*.trace.json.gz`` under the directory, keeps complete
events on TensorCore/XLA-op tracks, strips trailing digits/dots from op
names (``fusion.123`` → ``fusion``), and prints total ms and counts per
group, normalized per step when the number of profiled steps is known
(``TRACE_STEPS``, default bench.py's 20).
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import re
import sys


def load_events(trace_dir: str):
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(paths[-1], "rt") as fh:
        return json.load(fh), paths[-1]


def main():
    trace_dir = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    steps = int(os.environ.get("TRACE_STEPS", "20"))
    data, path = load_events(trace_dir)
    events = data["traceEvents"]

    # pid -> process name; keep TensorCore-ish lanes (XLA ops run there).
    proc = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc[e["pid"]] = e["args"].get("name", "")
    device_pids = {
        p for p, n in proc.items()
        if "TPU" in n or "Tensor" in n or "/device" in n.lower()
    }

    groups = collections.defaultdict(lambda: [0.0, 0])
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        name = e.get("name", "")
        # thread-level lanes include steps/modules; skip the module-level
        # envelope events (they'd double-count their children)
        if name.startswith("jit_") or name.startswith("Steps"):
            continue
        dur = e.get("dur", 0) / 1e3  # us -> ms
        key = re.sub(r"[.\d]+$", "", name)
        groups[key][0] += dur
        groups[key][1] += 1
        total += dur

    print(f"# {path}")
    print(f"# total device op time: {total:.1f} ms "
          f"({total / steps:.1f} ms/step over {steps} steps)")
    print(f"{'group':55s} {'ms/step':>9s} {'count':>7s} {'%':>6s}")
    for key, (ms, cnt) in sorted(groups.items(), key=lambda kv: -kv[1][0])[:top_n]:
        print(f"{key:55s} {ms / steps:9.2f} {cnt:7d} {100 * ms / total:5.1f}%")


if __name__ == "__main__":
    main()
