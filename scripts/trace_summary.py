"""Summarize a jax.profiler trace: device-lane op durations grouped by
fusion-name prefix, so PROFILE.md's per-op tables can be reproduced.

Usage: python scripts/trace_summary.py /tmp/trace_dir [top_n]
Finds the newest ``*.trace.json.gz`` under the directory, keeps complete
events on TensorCore/XLA-op tracks, strips trailing digits/dots from op
names (``fusion.123`` → ``fusion``), and prints total ms and counts per
group, normalized per step when the number of profiled steps is known
(``TRACE_STEPS``, default bench.py's 20).
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import re
import sys


def load_events(trace_dir: str):
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(paths[-1], "rt") as fh:
        return json.load(fh), paths[-1]


def device_pids(events) -> set:
    """pid set of TensorCore/XLA-op lanes (where device ops run)."""
    proc = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc[e["pid"]] = e["args"].get("name", "")
    return {
        p for p, n in proc.items()
        if "TPU" in n or "Tensor" in n or "/device" in n.lower()
    }


def summarize_trace(data):
    """Group complete device-lane events by fusion-name prefix.

    Returns ``(groups, total_ms)`` where ``groups`` maps op-group name
    (trailing digits/dots stripped: ``fusion.123`` → ``fusion``) to
    ``[total_ms, count]``. Envelope events (``jit_*``/``Steps*``) are
    skipped — they'd double-count their children."""
    events = data["traceEvents"]
    pids = device_pids(events)
    groups = collections.defaultdict(lambda: [0.0, 0])
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in pids:
            continue
        name = e.get("name", "")
        if name.startswith("jit_") or name.startswith("Steps"):
            continue
        dur = e.get("dur", 0) / 1e3  # us -> ms
        key = re.sub(r"[.\d]+$", "", name)
        groups[key][0] += dur
        groups[key][1] += 1
        total += dur
    return dict(groups), total


def render(groups, total, steps, path, top_n=30) -> str:
    lines = [
        f"# {path}",
        f"# total device op time: {total:.1f} ms "
        f"({total / steps:.1f} ms/step over {steps} steps)",
        f"{'group':55s} {'ms/step':>9s} {'count':>7s} {'%':>6s}",
    ]
    for key, (ms, cnt) in sorted(
        groups.items(), key=lambda kv: -kv[1][0]
    )[:top_n]:
        lines.append(
            f"{key:55s} {ms / steps:9.2f} {cnt:7d} "
            f"{100 * ms / max(total, 1e-12):5.1f}%"
        )
    return "\n".join(lines)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    trace_dir = argv[0]
    top_n = int(argv[1]) if len(argv) > 1 else 30
    steps = int(os.environ.get("TRACE_STEPS", "20"))
    data, path = load_events(trace_dir)
    groups, total = summarize_trace(data)
    print(render(groups, total, steps, path, top_n=top_n))


if __name__ == "__main__":
    main()
