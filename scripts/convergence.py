"""Convergence-to-accuracy runs on REAL data (VERDICT r3 #2).

Every training test in the suite is a few-step loss-decrease or an
engine-equality oracle; nothing had ever been trained to a stated
target. These two runs close that: the full recipe — augmentation,
warmup + step/cosine decay, L2/decoupled weight decay, per-replica BN,
exact full-set eval — engaged end to end on the attached chip, on real
data available in-image (the environment has no network egress):

* ``vision`` — ResNet18 through the KERAS front-end (compile/fit/
  evaluate with the reference-style warmup + schedule callbacks) on an
  ImageFolder built from scikit-learn's bundled *handwritten digits*
  scans (1,797 real 8×8 images; the classic test-set half of NIST's
  UCI digits) — train 1,497 / held-out 300, JPEG files on disk through
  the real ``ImageFolderDataset`` decode+augment path.
  Stated target: ≥ 95 % top-1. (BASELINE.md records the result.)
* ``lm`` — byte-level ``lm_small`` on a real code corpus: the CPython
  standard library's own ``.py`` sources (~25 MB of text), 95/5
  train/held-out split, AdamW + warmup/cosine, exact full-coverage
  eval perplexity-per-byte. Stated target: eval ppl ≤ 3.0 (≈1.6
  bits/byte — compact for a from-scratch 512-wide model, far below the
  8.0 ppl of a byte-uniform... enormous gap to random ≈ 256).

Usage::

    python scripts/convergence.py vision [--epochs 40]
    python scripts/convergence.py lm [--steps 2000]

Each prints ONE JSON line with the final metric vs its target.
"""

from __future__ import annotations

import argparse
import glob
import io
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python scripts/convergence.py` from anywhere
    sys.path.insert(0, REPO)
DATA_ROOT = os.path.join(REPO, ".benchdata")

VISION_TARGET_TOP1 = 0.95
LM_TARGET_PPL = 3.0


def build_digits_imagefolder(root: str, image_size: int = 32):
    """scikit-learn digits → ImageFolder JPEGs (train/ + val/), built
    once. Real scanned handwriting, 10 classes, stratified 300-image
    holdout (every 6th image of each class)."""
    from PIL import Image
    from sklearn.datasets import load_digits

    root = f"{root}{image_size}"  # cache key: the built resolution
    train_dir, val_dir = os.path.join(root, "train"), os.path.join(root, "val")
    if os.path.exists(os.path.join(root, ".done")):
        return train_dir, val_dir
    digits = load_digits()
    counters = {}
    for img8, label in zip(digits.images, digits.target):
        idx = counters.get(int(label), 0)
        counters[int(label)] = idx + 1
        split = val_dir if idx % 6 == 5 else train_dir
        d = os.path.join(split, f"digit_{label}")
        os.makedirs(d, exist_ok=True)
        arr = (img8 / 16.0 * 255).astype(np.uint8)
        rgb = np.stack([arr] * 3, axis=-1)
        Image.fromarray(rgb).resize(
            (image_size, image_size), Image.BILINEAR
        ).save(os.path.join(d, f"img_{idx:04d}.jpeg"), quality=95)
    with open(os.path.join(root, ".done"), "w") as f:
        f.write("ok\n")
    return train_dir, val_dir


def run_vision(epochs: int = 40, batch: int = 128) -> dict:
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.imagenet import ImageFolderDataset
    from distributeddeeplearning_tpu.frontends.keras_style import Model
    from distributeddeeplearning_tpu.training.callbacks import (
        LearningRateScheduleCallback,
        LearningRateWarmupCallback,
    )

    train_dir, val_dir = build_digits_imagefolder(
        os.path.join(DATA_ROOT, "digits")
    )
    cfg = TrainConfig(
        model="resnet18",
        num_classes=10,
        image_size=32,
        batch_size_per_device=batch,
        epochs=epochs,
        base_lr=0.02,
        weight_decay=5e-5,  # the reference Keras L2 surgery constant
        validation=True,
    )
    train = ImageFolderDataset(
        train_dir, global_batch_size=batch, image_size=32, train=True,
        num_workers=4,
    )
    val = ImageFolderDataset(
        val_dir, global_batch_size=batch, image_size=32, train=False,
        num_workers=4,
    )
    model = Model("resnet18", cfg).compile(optimizer="momentum")
    t0 = time.perf_counter()
    model.fit(
        train,
        epochs=epochs,
        callbacks=[
            # reference-style declarative schedule (Keras :211-224):
            # 3 warmup epochs, ×0.1 at 50 %, ×0.01 at 80 % of the run
            LearningRateWarmupCallback(warmup_epochs=3),
            LearningRateScheduleCallback(
                start_epoch=epochs // 2, multiplier=0.1
            ),
            LearningRateScheduleCallback(
                start_epoch=int(epochs * 0.8), multiplier=0.01
            ),
        ],
    )
    metrics = model.evaluate(val)  # exact full-set eval (pad + mask)
    return {
        "run": "vision_digits_resnet18",
        "top1": round(float(metrics["top1"]), 4),
        "target_top1": VISION_TARGET_TOP1,
        "met": bool(metrics["top1"] >= VISION_TARGET_TOP1),
        "val_samples": int(metrics["samples"]),
        "epochs": epochs,
        "minutes": round((time.perf_counter() - t0) / 60, 1),
    }


def load_stdlib_corpus(max_bytes: int = 48 * 2**20) -> bytes:
    """The CPython standard library's .py sources, concatenated in
    sorted-path order (deterministic)."""
    import sysconfig

    stdlib = sysconfig.get_paths()["stdlib"]
    chunks, total = [], 0
    for path in sorted(glob.glob(os.path.join(stdlib, "**", "*.py"),
                                 recursive=True)):
        if "site-packages" in path:
            continue
        try:
            data = open(path, "rb").read()
        except OSError:
            continue
        chunks.append(data)
        total += len(data)
        if total >= max_bytes:
            break
    return b"\n".join(chunks)[:max_bytes]


def run_lm(
    steps: int = 2000,
    batch: int = 16,
    seq_len: int = 512,
    model_name: str = "lm_small",
    target_ppl: float = LM_TARGET_PPL,
    max_mb: int = 48,
    **model_kw,
) -> dict:
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import (
        make_eval_step,
        replicate_state,
    )

    corpus = load_stdlib_corpus(max_bytes=max_mb * 2**20)
    data = np.frombuffer(corpus, np.uint8)
    n_rows = len(data) // (seq_len + 1)
    rows = data[: n_rows * (seq_len + 1)].reshape(n_rows, seq_len + 1)
    rng = np.random.RandomState(0)
    order = rng.permutation(n_rows)
    n_eval = max(n_rows // 20, batch)  # 5 % held out
    eval_rows = rows[order[:n_eval]].astype(np.int32)
    train_rows = rows[order[n_eval:]].astype(np.int32)

    # "epochs" for the schedule: warmup 10 %, cosine to 0 over the run.
    steps_per_epoch = max(steps // 10, 1)
    cfg = TrainConfig(
        model=model_name,
        num_classes=256,
        batch_size_per_device=batch,
        epochs=10,
        warmup_epochs=1,
        lr_schedule="cosine",
        optimizer="adamw",
        base_lr=3e-4,
        scale_lr_by_world_size=False,
        weight_decay=0.0,
        decoupled_weight_decay=0.1,
    )
    model = get_model(
        model_name, num_classes=256, max_seq_len=seq_len, attn_impl="fused"
        if jax.default_backend() == "tpu" else "xla", **model_kw,
    )
    mesh = data_parallel_mesh(jax.device_count())
    tx, _ = create_optimizer(cfg, steps_per_epoch)
    state = replicate_state(
        create_train_state(
            model, cfg, tx, input_shape=(1, seq_len), input_dtype=jnp.int32
        ),
        mesh,
    )
    step = make_train_step(model, tx, mesh, cfg)
    t0 = time.perf_counter()
    for i in range(steps):
        take = rng.randint(0, len(train_rows) - batch + 1)
        b = train_rows[take : take + batch]
        state, metrics = step(
            state, shard_batch((b[:, :-1], b[:, 1:]), mesh)
        )
        if i % 200 == 0:
            print(
                f"step {i}: loss {float(metrics['loss']):.3f}", flush=True
            )
    train_minutes = (time.perf_counter() - t0) / 60

    # exact full-coverage eval: every held-out row once, tail padded+masked
    eval_step = make_eval_step(model, mesh)
    sums = {"loss": 0.0, "count": 0.0}
    for start in range(0, len(eval_rows), batch):
        b = eval_rows[start : start + batch]
        weights = np.ones(len(b), np.float32)
        if len(b) < batch:
            pad = batch - len(b)
            b = np.concatenate([b, np.zeros((pad, seq_len + 1), np.int32)])
            weights = np.concatenate([weights, np.zeros(pad, np.float32)])
        m = eval_step(
            state, shard_batch((b[:, :-1], b[:, 1:], weights), mesh)
        )
        count = float(m["count"])
        sums["loss"] += float(m["loss"]) * count
        sums["count"] += count
    eval_loss = sums["loss"] / sums["count"]
    ppl = float(np.exp(eval_loss))
    return {
        "run": f"{model_name}_stdlib_bytes",
        "eval_ppl_per_byte": round(ppl, 3),
        "eval_bits_per_byte": round(eval_loss / np.log(2), 3),
        "target_ppl": target_ppl,
        "met": bool(ppl <= target_ppl),
        "steps": steps,
        "train_tokens": steps * batch * seq_len,
        "eval_rows": int(n_eval),
        "minutes": round(train_minutes, 1),
        **({"model_kw": model_kw} if model_kw else {}),
    }


MOE_TARGET_PPL = 2.85  # within ~4 % of the dense twin's 2.749 r4 result


def run_moe(
    steps: int = 2000,
    batch: int = 16,
    seq_len: int = 512,
    experts: int = 8,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    with_dense: bool = True,
    max_mb: int = 48,
) -> dict:
    """Dense-vs-MoE QUALITY at equal step budget (VERDICT r4 #4).

    The EP tier has routing-equality oracles and an exact cost audit
    (``scripts/moe_audit.py``, PROFILE.md) but no evidence the routed
    model *learns* competitively. This trains ``lm_moe_small`` and its
    dense twin on the same stdlib byte corpus with the same optimizer,
    schedule, and step budget, and reports both eval perplexities. The
    stated target: MoE eval-ppl ≤ 2.85 per byte (within ~4 % of the
    dense twin's round-4 2.749 — routed capacity must not cost quality
    at this scale, where experts see ~1/8 of the gradient signal each).
    """
    moe = run_lm(
        steps, batch, seq_len,
        model_name="lm_moe_small",
        target_ppl=MOE_TARGET_PPL,
        max_mb=max_mb,
        moe_experts=experts,
        moe_top_k=top_k,
        moe_capacity_factor=capacity_factor,
    )
    out = {
        "run": "moe_vs_dense_stdlib_bytes",
        "moe": moe,
        "experts": experts,
        "top_k": top_k,
        "capacity_factor": capacity_factor,
        "met": moe["met"],
    }
    if with_dense:
        dense = run_lm(
            steps, batch, seq_len, model_name="lm_small", max_mb=max_mb
        )
        out["dense"] = dense
        out["ppl_gap_pct"] = round(
            100.0
            * (moe["eval_ppl_per_byte"] - dense["eval_ppl_per_byte"])
            / dense["eval_ppl_per_byte"],
            2,
        )
    return out


def run_cluster(epochs: int = 40, batch: int = 128) -> dict:
    """Convergence through the FLAGSHIP CLUSTER STACK (VERDICT r4 #3):
    ``prepare.py``-written TFRecord shards → ``TFRecordImageNetDataset``
    → ``ENGINE=pjit`` (GSPMD, batch-split per-replica BN,
    ``models/norm.py``) → ``INPUT_STAGING=uint8`` (on-device normalize)
    → exact full-set eval. This is the exact stack
    ``docs/ORCHESTRATION.md`` submits to a pod (reference anchor: the
    ``01_Train*.ipynb`` cell-15 command line is the reference's
    flagship path); the vision target is unchanged: ≥ 95 % top-1 on the
    held-out digits."""
    import jax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.imagenet import TFRecordImageNetDataset
    from distributeddeeplearning_tpu.data.prepare import write_tfrecords
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training.callbacks import (
        LearningRateScheduleCallback,
        LearningRateWarmupCallback,
    )
    from distributeddeeplearning_tpu.training.loop import evaluate, fit

    train_dir, val_dir = build_digits_imagefolder(
        os.path.join(DATA_ROOT, "digits")
    )
    shard_root = os.path.join(DATA_ROOT, "digits32_tfrec")
    # Sentinel = the LAST artifact written: an interrupted first run must
    # not leave a half-built cache that every later run trusts.
    if not os.path.exists(os.path.join(shard_root, "val", "count.txt")):
        # Same shard writer `prepare.py ingest` ends in (native TFRecord
        # framing + first-party Example codec).
        write_tfrecords(
            train_dir, os.path.join(shard_root, "train"),
            num_shards=8, prefix="digits",
        )
        write_tfrecords(
            val_dir, os.path.join(shard_root, "val"),
            num_shards=2, prefix="digits",
        )
    cfg = TrainConfig(
        model="resnet18",
        engine="pjit",
        input_staging="uint8",
        num_classes=10,
        image_size=32,
        batch_size_per_device=batch,
        epochs=epochs,
        base_lr=0.02,
        weight_decay=5e-5,
        validation=True,
        fake=False,
    )
    train = TFRecordImageNetDataset(
        os.path.join(shard_root, "train", "digits-*"),
        global_batch_size=batch, image_size=32, train=True,
        image_dtype=np.uint8,
    )
    val = TFRecordImageNetDataset(
        os.path.join(shard_root, "val", "digits-*"),
        global_batch_size=batch, image_size=32, train=False,
        image_dtype=np.uint8,
    )
    model = get_model("resnet18", num_classes=10)
    t0 = time.perf_counter()
    result = fit(
        model, cfg, train,
        epochs=epochs,
        callbacks=[
            LearningRateWarmupCallback(warmup_epochs=3),
            LearningRateScheduleCallback(
                start_epoch=epochs // 2, multiplier=0.1
            ),
            LearningRateScheduleCallback(
                start_epoch=int(epochs * 0.8), multiplier=0.01
            ),
        ],
    )
    metrics = evaluate(
        model, cfg, val, state=result.state
    )  # exact full-set eval (record-sharded, pad + mask)
    return {
        "run": "cluster_digits_resnet18_pjit_uint8_tfrecord",
        "stack": "prepare.write_tfrecords + TFRecordImageNetDataset + "
                 "ENGINE=pjit(per-replica BN) + INPUT_STAGING=uint8",
        "top1": round(float(metrics["top1"]), 4),
        "target_top1": VISION_TARGET_TOP1,
        "met": bool(metrics["top1"] >= VISION_TARGET_TOP1),
        "val_samples": int(metrics["samples"]),
        "epochs": epochs,
        "minutes": round((time.perf_counter() - t0) / 60, 1),
    }


def main(argv=None) -> int:
    if os.environ.get("JAX_PLATFORMS"):
        # Honour an explicit platform pick (CPU smoke runs): the axon
        # plugin pins jax_platforms at interpreter start, so the env var
        # alone is ignored — and hangs when the relay is down.
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("vision")
    v.add_argument("--epochs", type=int, default=40)
    v.add_argument("--batch", type=int, default=128)
    l = sub.add_parser("lm")
    l.add_argument("--steps", type=int, default=2000)
    l.add_argument("--batch", type=int, default=16)
    l.add_argument("--seq-len", type=int, default=512)
    m = sub.add_parser("moe", help="dense-vs-MoE quality at equal budget")
    m.add_argument("--steps", type=int, default=2000)
    m.add_argument("--batch", type=int, default=16)
    m.add_argument("--seq-len", type=int, default=512)
    m.add_argument("--experts", type=int, default=8)
    m.add_argument("--top-k", type=int, default=2)
    m.add_argument("--cf", type=float, default=1.25)
    m.add_argument("--no-dense", action="store_true",
                   help="skip the paired dense run")
    m.add_argument("--max-mb", type=int, default=48,
                   help="corpus cap in MiB (small for CPU smoke)")
    c = sub.add_parser("cluster", help="flagship pjit+TFRecord+uint8 stack")
    c.add_argument("--epochs", type=int, default=40)
    c.add_argument("--batch", type=int, default=128)
    args = p.parse_args(argv)
    if args.cmd == "vision":
        out = run_vision(args.epochs, args.batch)
    elif args.cmd == "lm":
        out = run_lm(args.steps, args.batch, args.seq_len)
    elif args.cmd == "moe":
        out = run_moe(
            args.steps, args.batch, args.seq_len,
            experts=args.experts, top_k=args.top_k,
            capacity_factor=args.cf, with_dense=not args.no_dense,
            max_mb=args.max_mb,
        )
    else:
        out = run_cluster(args.epochs, args.batch)
    print(json.dumps(out))
    return 0 if out["met"] else 1


if __name__ == "__main__":
    sys.exit(main())
