"""Per-request critical-path digest from trace-stamped event files.

The trace plane's CLI (``distributeddeeplearning_tpu/obs/traces.py``):
point it at a run directory (``OBS_DIR``) or any set of
``events*.jsonl`` files and it reconstructs every request's critical
path — queue wait → prefill → decode ticks → delivery, with chaos
re-routes attributed by cause — then renders the top-K-slowest digest:
each slow request decomposed per phase against the fleet p50, naming
the dominant culprit. Training runs get the same treatment per step
(data wait vs dispatch vs collective).

Usage::

    python scripts/trace_report.py RUN_DIR_OR_FILES... [--json] [--top K]
    make trace-report                 # newest runs/<dir>

Gap accounting is first-class: each request's phases must sum to its
measured end-to-end latency within ``max(GAP_TOL_S, GAP_TOL_FRAC *
e2e)`` (docs/OBSERVABILITY.md); the unattributed remainder is printed,
never hidden. Orphan traces (admission point without a terminal
outcome) are listed — a healthy run has zero.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _ms(v) -> str:
    return f"{(v or 0.0) * 1e3:.1f}ms"


def render(recon: dict, training, top_k: int) -> str:
    from distributeddeeplearning_tpu.obs import traces

    out: List[str] = []
    add = out.append
    add(
        f"trace digest: {recon['count']} request(s), "
        f"{recon['within_tolerance']} within gap tolerance "
        f"(max({traces.GAP_TOL_S:g}s, {traces.GAP_TOL_FRAC:.0%} of e2e)), "
        f"{recon['sheds']} shed, {recon['orphan_count']} orphan(s)"
    )
    if recon["causes"]:
        add("interventions: " + ", ".join(
            f"{c} x{n}" for c, n in sorted(recon["causes"].items())
        ))
    reqs = recon["requests"]
    if reqs:
        p50s = traces.phase_p50s(reqs)
        add("")
        add("fleet p50 per phase: " + "  ".join(
            f"{p} {_ms(p50s[p])}" for p in traces.PHASES
        ) + f"  gap {_ms(p50s['gap'])}  e2e {_ms(p50s['e2e'])}")
        add("")
        add(f"top {top_k} slowest (phase / +excess vs fleet p50):")
        for r in traces.top_slow(reqs, k=top_k, p50s=p50s):
            add(
                f"  req={r.get('req', '?')} tenant={r.get('tenant', '?')} "
                f"e2e {_ms(r['e2e_s'])} outcome={r['outcome']} "
                f"attempts={r['attempts']}"
                f"  <- culprit: {r['culprit']} "
                f"(+{_ms(r['culprit_excess_s'])})"
            )
            cells = []
            for p in traces.PHASES:
                v = r["phases"].get(p, 0.0)
                if v or r["excess"].get(p):
                    cells.append(f"{p} {_ms(v)} (+{_ms(r['excess'][p])})")
            cells.append(
                f"gap {_ms(max(r['gap_s'], 0.0))}"
                + ("" if r["within_tolerance"] else " OVER TOLERANCE")
            )
            add("      " + "  ".join(cells))
            for iv in r["interventions"]:
                add(
                    f"      intervention: {iv['what']} "
                    f"cause={iv.get('cause', '?')}"
                    + (f" from-replica={iv['src']}"
                       if iv.get("src") is not None else "")
                    + (f" replica={iv['replica']}"
                       if iv.get("replica") is not None else "")
                    + (f" dur {_ms(iv['dur_s'])}"
                       if iv.get("dur_s") else "")
                )
    for o in recon["orphans"]:
        add(
            f"ORPHAN trace {o['trace']}: admission seen, no terminal "
            f"outcome ({o['events']} event(s), last wall {o['end_wall']})"
        )
    if training:
        add("")
        add(
            f"training attribution ({training['steps']} step(s), "
            f"{training['procs']} proc(s)): "
            f"wall {training['wall_s']:.3f}s = "
            f"dispatch {training['dispatch_s']:.3f}s + "
            f"data wait {training['data_wait_s']:.3f}s + "
            f"collective {training['collective_s']:.3f}s + "
            f"other {training['other_s']:.3f}s"
        )
        for s in training["slowest"]:
            add(
                f"  slow step p={s['p']} epoch={s.get('epoch', '?')}: "
                f"wall {s['wall_s']:.3f}s (dispatch {s['dispatch_s']:.3f}s, "
                f"data wait {s['data_wait_s']:.3f}s, "
                f"other {s['other_s']:.3f}s)"
            )
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="+", help="run dir(s) and/or events*.jsonl")
    p.add_argument("--json", action="store_true", help="emit digest JSON")
    p.add_argument("--top", type=int, default=5, help="slowest requests shown")
    args = p.parse_args(argv)

    from distributeddeeplearning_tpu.obs import report, traces

    try:
        loaded = report.load(args.paths)
    except FileNotFoundError as e:
        print(f"ERROR: no event files under {e}", file=sys.stderr)
        return 2
    recon = traces.reconstruct(loaded)
    training = traces.training_attribution(loaded)
    if args.json:
        out = dict(recon)
        out["top_slow"] = traces.top_slow(recon["requests"], k=args.top)
        out["training"] = training
        print(json.dumps(out, default=str))
    elif not recon["count"] and not recon["orphan_count"] and not training:
        print(
            "no trace-stamped request events found (run predates the "
            "trace plane, or nothing was served)"
        )
    else:
        print(render(recon, training, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
