"""Serving chaos bench — a seeded mixed-verb fault storm, gated.

The fleet tier's robustness protocol (BASELINE.md style, one JSON line
on stdout; recertify row ``serve_lm_chaos``). One seeded multi-tenant
closed backlog (``serving/loadgen.py``) is served twice by the SAME
fleet geometry (``SERVE_REPLICAS`` >= 2 router-fronted replicas, 3
weighted tenants):

1. **undisturbed** — no chaos, the reference run;
2. **storm** — the same backlog under a seeded ``SERVE_CHAOS_PLAN``
   mixing the fleet verbs (default: one of each —
   crash + hang + slow + corrupt + flap, ``chaos.storm_plan``), with a
   brownout ladder armed (``SERVE_BROWNOUT_STAGES``, default
   ``spec_off,shed:1``) and driven by a deterministic injected burn
   window, so degradation is part of the drill.

Gates (exit non-zero unless ALL hold):

* **zero-drop + splice parity** — every non-shed request finishes with
  a token stream BITWISE identical to the undisturbed run (the
  re-route/replay/splice machinery surviving the whole storm); every
  shed request carries the distinct ``brownout`` outcome — nothing is
  silently dropped.
* **corrupt detect-and-heal** — the storm's ``corrupt`` injection is
  caught by the splice verifier (>= 1 ``splice_mismatch``), the
  offending replica is hard-faulted, and the healed streams still gate
  bitwise — the flipped token is never delivered (parity proves it).
* **breaker budget respected** — the ``flap`` verb's crash-loop burns
  through ``SERVE_REPLICA_MAX_RESTARTS`` and MUST open the circuit
  breaker (``breaker_open`` >= 1, the replica removed); every other
  faulted replica rejoins inside its budget.
* **closed program sets** — every replica that survived untouched ends
  with zero mid-measure compiles; replicas rebuilt by the breaker path
  re-close at exactly ``programs_expected`` (rebuild compiles are
  itemized, never silently folded into "zero").
* **bounded TTFT** — storm p99 TTFT (fleet-level, streaming-measured)
  <= ``SERVE_CHAOS_TTFT_MAX_RATIO`` (8.0) x the undisturbed p99.

Env knobs (defaults): ``SERVE_REPLICAS`` (2), ``SERVE_TENANT_WEIGHTS``
("gold:3,silver:2,bronze:1"), ``SERVE_SLOTS`` (4), ``SERVE_BUCKETS``
("8,16"), ``SERVE_REQUESTS`` (36), ``SERVE_MAX_NEW`` (16),
``SERVE_SEED`` (0), ``SERVE_CHAOS_PLAN`` (storm_plan(replicas,
SERVE_CHAOS_SEED)), ``SERVE_CHAOS_SEED`` (0),
``SERVE_REPLICA_MAX_RESTARTS`` (2), ``SERVE_REPLICA_RESTART_BACKOFF``
(0.05), ``SERVE_STRAGGLER_FACTOR`` (4.0), ``SERVE_STRAGGLER_TICKS``
(5), ``SERVE_QUARANTINE_TICKS`` (60), ``SERVE_PUMP_HEARTBEAT_S``
(0.75), ``SERVE_BROWNOUT_STAGES`` ("spec_off,shed:1"),
``SERVE_CHAOS_TTFT_MAX_RATIO`` (8.0), ``BENCH_MODEL`` (lm_tiny),
``BENCH_VOCAB`` (32000), plus ``OBS_DIR`` for the per-replica event
streams and the fleet-health gauges.

Usage::

    python scripts/chaos_bench.py [--events]
    make chaos-bench
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.serving.loadgen import (  # noqa: E402
    build_tenant_requests,
    percentile,
    profile_shapes,
)


def _emit_record(record: dict) -> None:
    print(json.dumps(record), flush=True)
    from distributeddeeplearning_tpu import obs

    bus = obs.get_bus()
    bus.point("bench_result", **record)
    bus.flush()


def run_storm(model, params, reqs, scfg, fcfg, max_len, tenants, *,
              chaos_plan, brownout_stages, burn_window):
    """Serve the backlog through an n-replica fleet; with a chaos plan
    the storm runs with the brownout ladder driven by a deterministic
    injected burn window (router ticks [a, b) read as burning)."""
    from distributeddeeplearning_tpu.serving import (
        BrownoutLadder,
        ChaosInjector,
        Replica,
        Request,
        Router,
        parse_brownout_stages,
        parse_chaos_plan,
    )

    fcfg = dataclasses.replace(
        fcfg, chaos_plan="", brownout_stages="",
    )
    router = Router(config=fcfg)
    obs_dir = os.environ.get("OBS_DIR") or None
    for k in range(fcfg.replicas):
        router.add_replica(
            Replica(k, model, params, scfg, max_len=max_len,
                    obs_dir=obs_dir),
            start=True, threaded=True,
        )
    t0 = time.perf_counter()
    while not all(r.state == "ready" for r in router.replicas):
        if time.perf_counter() - t0 > 600:
            raise TimeoutError("fleet warmup timed out")
        time.sleep(0.01)
    # Warm pass (round-robin) so first-dispatch overheads stay out of
    # the measurement, exactly like fleet_bench.
    warm_placement = router.config.placement
    router.config.placement = "rr"
    for _ in range(fcfg.replicas):
        router.submit(Request(
            prompt=reqs[0]["prompt"], max_new_tokens=2, temperature=0.0,
        ))
    router.drain(timeout=300)
    router.config.placement = warm_placement

    # Arm the drill AFTER the warm pass so the chaos clock (and the
    # injected burn window) start at storm tick 0, not somewhere inside
    # the warm drain's tick stream.
    router._ticks = 0
    chaos = None
    if chaos_plan:
        chaos = ChaosInjector(
            parse_chaos_plan(chaos_plan), seed=fcfg.chaos_seed
        )
        router.chaos = chaos
        for r in router.replicas:
            r.chaos = chaos
    brownout = None
    if brownout_stages:
        # Deterministic burn driver: the ladder sees "burning" exactly
        # inside the declared router-tick window — the drill's stand-in
        # for a live plane reporting a latency SLO on fire.
        def reader():
            a, b = burn_window
            burning = a <= router._ticks < b
            return {
                "slo": [
                    {"objective": "chaos_drill_ttft", "stat": "p99",
                     "metric": "serve.ttft", "burning": burning}
                ]
            }

        brownout = BrownoutLadder(
            parse_brownout_stages(brownout_stages),
            reader=reader, refresh_s=0.0, escalate_ticks=2,
            recover_ticks=4,
        )
        router.brownout = brownout

    engines_pre = {
        r.rid: (id(r.engine), r.engine.compile_count)
        for r in router.replicas
    }
    handles = []
    t0 = time.perf_counter()
    for r in reqs:
        handles.append((r, router.submit(Request(
            prompt=r["prompt"], max_new_tokens=r["max_new"],
            temperature=0.0,
        ), tenant=r["tenant"])))
    # Paced router ticks (the chaos clock): 5 ms per tick keeps the
    # storm's tick-indexed verbs landing mid-flight instead of all
    # firing before the first prefill, and both runs pace identically.
    while router.step():
        time.sleep(0.005)
    # Run the storm to quiescence: the flap crash-loop must burn its
    # whole cycle count through rejoin/backoff so the breaker verdict
    # is real, and mid-rebuild replicas must settle. Hard cap so an
    # undeliverable directive cannot wedge the bench.
    t_q = time.perf_counter()
    while time.perf_counter() - t_q < 30.0:
        router.step()
        settled = not any(
            r.state in ("faulted", "starting") for r in router.replicas
        )
        if settled and (chaos is None or chaos.quiescent()):
            break
        time.sleep(0.01)
    dt = time.perf_counter() - t0

    tokens = sum(len(fh.new_tokens) for _, fh in handles)
    ttft_ms = [
        fh.ttft_s * 1e3 for _, fh in handles if fh.ttft_s is not None
    ]
    ledger = []
    for r in router.replicas:
        pre = engines_pre.get(r.rid)
        rebuilt = pre is None or pre[0] != id(r.engine)
        ledger.append({
            "replica": r.rid,
            "state": r.state,
            "rebuilt": rebuilt,
            "compile_count": r.engine.compile_count if r.engine else 0,
            "programs_expected":
                r.engine.programs_expected if r.engine else 0,
            "compiles_during_measure": (
                0 if rebuilt or pre is None
                else r.engine.compile_count - pre[1]
            ),
            "leaked_threads": r.leaked_threads,
        })
    run = {
        "replicas": fcfg.replicas,
        "tokens_per_sec": round(tokens / dt, 1) if dt else 0.0,
        "wall_s": round(dt, 2),
        "tokens": tokens,
        "ttft_p50_ms": round(percentile(ttft_ms, 0.5), 2),
        "ttft_p99_ms": round(percentile(ttft_ms, 0.99), 2),
        "stats": dict(router.stats),
        "per_replica": ledger,
        "chaos_fired": list(chaos.fired) if chaos else [],
        "brownout_transitions":
            list(brownout.transitions) if brownout else [],
        "final_replica_count": len(router.replicas),
    }
    streams = [list(fh.new_tokens) for _, fh in handles]
    outcomes = [fh.finish_reason for _, fh in handles]
    splice_ok = all(fh.restart_consistent for _, fh in handles)
    mismatches = sum(fh.splice_mismatches for _, fh in handles)
    # Trace plane: each backlog request's trace id + dispatch count, so
    # the trace-verification gate can key the reconstructed critical
    # paths back to what the storm actually did to each request.
    trace_info = [
        {"trace": fh.trace, "attempts": fh.attempts,
         "outcome": fh.finish_reason}
        for _, fh in handles
    ]
    router.close()
    return run, streams, outcomes, splice_ok, mismatches, trace_info


def trace_gates(storm_traces, obs_dir):
    """Trace-verification gate (docs/OBSERVABILITY.md trace plane):
    reconstruct critical paths from the storm's event files and check

    * every backlog request's trace reconstructs (admission + terminal
      — no orphan);
    * every re-routed (hedged/spliced/migrated) request's trace carries
      the ``fleet.reroute`` child span with a correct ``cause``;
    * every non-shed request's phase sum matches its measured
      end-to-end latency within the documented gap tolerance.

    Returns the gate dict; the caller folds ``*_ok`` values into the
    bench verdict."""
    from distributeddeeplearning_tpu import obs
    from distributeddeeplearning_tpu.obs import report, traces

    obs.flush()  # the router-side (process-global) stream
    loaded = report.load([obs_dir])
    recon = traces.reconstruct(loaded)
    # The run dir may also hold warm-pass (and stale) traces — gate on
    # the storm backlog's trace ids only.
    ids = {t["trace"] for t in storm_traces}
    by_trace = {
        r["trace"]: r for r in recon["requests"] + recon["orphans"]
        if r["trace"] in ids
    }
    orphans = [
        r["trace"] for r in recon["orphans"] if r["trace"] in ids
    ]
    missing = sorted(ids - set(by_trace))
    rerouted = [
        t for t in storm_traces
        if t["attempts"] >= 2 and t["outcome"] != "brownout"
    ]
    bad_reroutes = []
    for t in rerouted:
        r = by_trace.get(t["trace"])
        spans = [
            iv for iv in (r["interventions"] if r else [])
            if iv["what"] == "fleet.reroute"
        ]
        if not spans or any(
            iv.get("cause") not in ("hedge", "splice", "migration")
            for iv in spans
        ):
            bad_reroutes.append(t["trace"])
    over_tolerance = [
        r["trace"] for tid, r in sorted(by_trace.items())
        if r["outcome"] not in ("brownout", "orphan")
        and not r["within_tolerance"]
    ]
    return {
        "traces_reconstructed": len(by_trace),
        "traces_expected": len(ids),
        "all_reconstructed_ok": not missing and not orphans,
        "trace_orphans": len(orphans),
        "rerouted_requests": len(rerouted),
        "reroute_cause_ok": not bad_reroutes,
        "bad_reroute_traces": bad_reroutes,
        "phase_sum_ok": not over_tolerance,
        "over_tolerance_traces": over_tolerance,
    }


def main() -> int:
    if "--events" in sys.argv[1:] or os.environ.get("OBS_DIR"):
        from distributeddeeplearning_tpu import obs

        if not os.environ.get("OBS_DIR"):
            os.environ["OBS_DIR"] = os.path.join(
                "runs", f"chaos-bench-{int(time.time())}"
            )
        obs.configure_from_env()
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("COMPILATION_CACHE_DIR"):
        from distributeddeeplearning_tpu.training.warmup import (
            enable_persistent_cache,
        )

        enable_persistent_cache(os.environ["COMPILATION_CACHE_DIR"])

    import flax.linen as nn
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.serving import FleetConfig, ServeConfig
    from distributeddeeplearning_tpu.serving.chaos import storm_plan
    from distributeddeeplearning_tpu.serving.fleet.router import (
        parse_tenant_weights,
    )

    env = os.environ
    model_name = env.get("BENCH_MODEL", "lm_tiny")
    vocab = int(env.get("BENCH_VOCAB", "32000"))
    n_requests = int(env.get("SERVE_REQUESTS", "36"))
    max_new = int(env.get("SERVE_MAX_NEW", "16"))
    seed = int(env.get("SERVE_SEED", "0"))
    profile = env.get("SERVE_PROFILE", "mixed")
    weights = parse_tenant_weights(
        env.get("SERVE_TENANT_WEIGHTS", "gold:3,silver:2,bronze:1")
    )
    ttft_max_ratio = float(env.get("SERVE_CHAOS_TTFT_MAX_RATIO", "8.0"))

    scfg = ServeConfig.from_env()
    if env.get("SERVE_SLOTS") is None:
        scfg.num_slots = 4
    if scfg.buckets is None:
        scfg.buckets = (8, 16)
    fcfg = FleetConfig.from_env()
    fcfg.tenant_weights = weights
    # Drill-tempo robustness knobs unless the operator pinned them.
    if env.get("SERVE_REPLICA_MAX_RESTARTS") is None:
        fcfg.max_restarts = 2
    if env.get("SERVE_REPLICA_RESTART_BACKOFF") is None:
        fcfg.restart_backoff_s = 0.05
    if env.get("SERVE_STRAGGLER_FACTOR") is None:
        # 4x, not lower: N pump threads time-slicing one core (GIL)
        # show sustained latency asymmetry that a tighter factor reads
        # as a straggler even in the undisturbed run.
        fcfg.straggler_factor = 4.0
    if env.get("SERVE_STRAGGLER_TICKS") is None:
        fcfg.straggler_ticks = 5
    if env.get("SERVE_QUARANTINE_TICKS") is None:
        fcfg.quarantine_ticks = 60
    if env.get("SERVE_PUMP_HEARTBEAT_S") is None:
        fcfg.heartbeat_timeout_s = 0.75
    chaos_plan = env.get("SERVE_CHAOS_PLAN") or storm_plan(
        fcfg.replicas, seed=fcfg.chaos_seed
    )
    brownout_stages = env.get("SERVE_BROWNOUT_STAGES", "spec_off,shed:1")
    burn_window = (20, 40)  # router ticks the injected SLO burn spans

    shapes = profile_shapes(profile, max_new)
    max_len = max(tp + n_new for tp, n_new in shapes)
    tenants = sorted(weights)
    metric = "serve_lm_chaos_tokens_per_sec"
    try:
        model = get_model(
            model_name, num_classes=vocab, max_seq_len=max_len,
            dtype=jnp.float32,
        )
        variables = jax.jit(model.init, static_argnames=("train",))(
            jax.random.PRNGKey(0), jnp.zeros((2, max_len), jnp.int32),
            train=False,
        )
        params = nn.unbox(variables["params"])
        reqs = build_tenant_requests(
            tenants, n_requests, 0.0, seed, vocab, shapes
        )

        base, base_streams, base_outcomes, _, _, _ = run_storm(
            model, params, reqs, scfg, fcfg, max_len, tenants,
            chaos_plan="", brownout_stages="", burn_window=burn_window,
        )
        (storm, storm_streams, storm_outcomes, splice_ok, mismatches,
         storm_traces) = run_storm(
            model, params, reqs, scfg, fcfg, max_len, tenants,
            chaos_plan=chaos_plan, brownout_stages=brownout_stages,
            burn_window=burn_window,
        )

        shed_idx = [
            i for i, o in enumerate(storm_outcomes) if o == "brownout"
        ]
        kept_idx = [
            i for i in range(len(reqs)) if i not in set(shed_idx)
        ]
        parity = all(
            storm_streams[i] == base_streams[i] for i in kept_idx
        )
        completed_ok = all(
            storm_outcomes[i] in ("eos", "length") for i in kept_idx
        )
        shed_marked = all(
            storm_outcomes[i] == "brownout" for i in shed_idx
        )
        corrupt_armed = any(
            f["kind"] == "corrupt" for f in storm["chaos_fired"]
        )
        corrupt_detected = (
            storm["stats"]["splice_mismatch"] >= 1 and mismatches >= 1
        )
        corrupt_healed = corrupt_detected and splice_ok and parity
        flap_count = next(
            (f.count for f in _parse(chaos_plan) if f.kind == "flap"), 0
        )
        expect_breaker = flap_count > fcfg.max_restarts
        breaker_ok = (
            storm["stats"]["breaker_open"] >= 1 if expect_breaker
            else storm["stats"]["breaker_open"] == 0
        )
        closed = all(
            row["compile_count"] == row["programs_expected"]
            for run in (base, storm) for row in run["per_replica"]
            if row["compile_count"]
        )
        clean = all(
            row["compiles_during_measure"] == 0
            for run in (base, storm) for row in run["per_replica"]
        )
        ttft_ratio = (
            storm["ttft_p99_ms"] / base["ttft_p99_ms"]
            if base["ttft_p99_ms"] else 0.0
        )
        ttft_ok = (
            ttft_ratio <= ttft_max_ratio
            or storm["ttft_p99_ms"] <= base["ttft_p99_ms"]
        )
        brownout_down = any(
            t["direction"] == "down"
            for t in storm["brownout_transitions"]
        )
        brownout_up = any(
            t["direction"] == "up" for t in storm["brownout_transitions"]
        )
        # Trace-verification gate — only when the event streams were
        # captured (OBS_DIR); without files there is nothing to audit.
        tgates = None
        if os.environ.get("OBS_DIR"):
            tgates = trace_gates(storm_traces, os.environ["OBS_DIR"])
        trace_ok = tgates is None or (
            tgates["all_reconstructed_ok"]
            and tgates["reroute_cause_ok"]
            and tgates["phase_sum_ok"]
        )
        ok = (
            parity and completed_ok and shed_marked and closed and clean
            and (corrupt_detected and corrupt_healed if corrupt_armed
                 else True)
            and breaker_ok and ttft_ok and brownout_down and brownout_up
            and trace_ok
        )
        detail = {
            "profile": profile,
            "requests": n_requests,
            "replicas": fcfg.replicas,
            "slots_per_replica": scfg.num_slots,
            "tenant_weights": weights,
            "platform": jax.devices()[0].platform,
            "chaos_plan": chaos_plan,
            "chaos_seed": fcfg.chaos_seed,
            "brownout_stages": brownout_stages,
            "burn_window_ticks": list(burn_window),
            "max_restarts": fcfg.max_restarts,
            "undisturbed": base,
            "storm": storm,
            "ttft_p99_ratio": round(ttft_ratio, 2),
            "ttft_max_ratio": ttft_max_ratio,
            "gates": {
                "parity_non_shed": parity,
                "completed_non_shed": completed_ok,
                "shed_marked_brownout": shed_marked,
                "shed_count": len(shed_idx),
                "corrupt_detected": corrupt_detected,
                "corrupt_healed": corrupt_healed,
                "splice_mismatches": mismatches,
                "breaker_respected": breaker_ok,
                "breaker_opened": storm["stats"]["breaker_open"],
                "programs_closed": closed,
                "zero_untouched_recompiles": clean,
                "ttft_bounded": ttft_ok,
                "brownout_step_down": brownout_down,
                "brownout_step_up": brownout_up,
                "trace_plane_ok": trace_ok,
            },
        }
        if tgates is not None:
            detail["trace_gates"] = tgates
        record = {
            "metric": metric,
            "value": storm["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": round(
                storm["tokens_per_sec"] / base["tokens_per_sec"], 2
            ) if base["tokens_per_sec"] else 0.0,
            "detail": detail,
        }
        _emit_record(record)
        if not ok:
            failed = [k for k, v in detail["gates"].items()
                      if v is False]
            print(f"CHAOS GATES FAILED: {failed}", file=sys.stderr)
        return 0 if ok else 1
    except Exception as e:  # structured failure record, like bench.py
        _emit_record({
            "metric": metric, "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": repr(e),
        })
        raise


def _parse(plan: str):
    from distributeddeeplearning_tpu.serving.chaos import parse_chaos_plan

    return parse_chaos_plan(plan)


if __name__ == "__main__":
    sys.exit(main())
