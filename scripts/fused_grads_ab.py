"""A/B the fused dW+db dense backward (VERDICT r4 #5) on the bench
protocols it targets: ViT-B/16 (the trace that named the ~12 ms of
bias-grad reduction passes) and lm_small @1k (same reduction class).

Runs each protocol twice — stock, then ``FUSED_DENSE_GRAD=1`` — through
``scripts/recertify.py``'s own protocol table and subprocess runner
(ONE definition of each certified protocol; this script must measure
exactly what the battery certifies), and prints the paired numbers +
delta. The kernel is kept only if this says it wins (PROFILE.md
protocol, like the depthwise/fused-block write-ups).

Usage::

    python scripts/fused_grads_ab.py [--timeout 900]
        [--only vit_b16,lm_small_1k] [--set BENCH_BATCH=2 ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts.recertify import PROTOCOLS, run_protocol  # noqa: E402

AB_PROTOCOLS = ("vit_b16", "lm_small_1k")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--only", default=None)
    p.add_argument(
        "--set", action="append", default=[], metavar="KEY=VAL",
        help="override protocol env (e.g. --set BENCH_BATCH=2 for smoke)",
    )
    args = p.parse_args(argv)
    names = (
        [n.strip() for n in args.only.split(",") if n.strip()] if args.only
        else list(AB_PROTOCOLS)
    )
    unknown = [n for n in names if n not in PROTOCOLS]
    if unknown:
        p.error(f"unknown protocol(s) {unknown}; valid: {sorted(PROTOCOLS)}")
    bad = [kv for kv in args.set if "=" not in kv]
    if bad:
        p.error(f"--set needs KEY=VAL, got {bad}")
    overrides = dict(kv.split("=", 1) for kv in args.set)

    results = {}
    failed = False
    for name in names:
        row = {}
        for label, flag in (("stock", ""), ("fused", "1")):
            rec = run_protocol(
                name,
                {**PROTOCOLS[name], **overrides, "FUSED_DENSE_GRAD": flag},
                args.timeout,
            )
            row[label] = rec.get("value", 0.0)
            if row[label] <= 0:
                # surface the failure — a fabricated 0.0 baseline would
                # silently decide the keep-or-drop question
                row[f"{label}_error"] = rec.get("error", rec)
                failed = True
            print(f"{name} {label}: {row[label]}"
                  + (f"  ERROR: {row.get(label + '_error')}"
                     if row[label] <= 0 else ""),
                  flush=True)
        if row["stock"] > 0 and row["fused"] > 0:
            row["delta_pct"] = round(
                100.0 * (row["fused"] - row["stock"]) / row["stock"], 2
            )
        results[name] = row
    print(json.dumps(results))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
