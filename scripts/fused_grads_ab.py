"""A/B the fused dW+db dense backward (VERDICT r4 #5) on the bench
protocols it targets: ViT-B/16 (the trace that named the ~12 ms of
bias-grad reduction passes) and lm_small @1k (same reduction class).

Runs each protocol twice in fresh subprocesses — stock, then
``FUSED_DENSE_GRAD=1`` — and prints the paired numbers + delta. The
kernel is kept only if this says it wins (PROFILE.md protocol, like the
depthwise/fused-block write-ups).

Usage::

    python scripts/fused_grads_ab.py [--timeout 900]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROTOCOLS = {
    "vit_b16": {"BENCH_MODEL": "vit_b16", "BENCH_BATCH": "256"},
    "lm_small_1k": {
        "BENCH_MODEL": "lm_small", "BENCH_SEQ_LEN": "1024", "BENCH_BATCH": "8",
    },
}


def run_once(env_over: dict, timeout_s: float) -> dict:
    env = dict(os.environ)
    env.update(env_over)
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timeout {timeout_s:.0f}s"}
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    return json.loads(lines[-1]) if lines else {
        "error": f"no JSON; rc={r.returncode}", "stderr": r.stderr[-300:],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--only", default=None)
    p.add_argument(
        "--set", action="append", default=[], metavar="KEY=VAL",
        help="override protocol env (e.g. --set BENCH_BATCH=2 for smoke)",
    )
    args = p.parse_args(argv)
    names = (
        [n.strip() for n in args.only.split(",")] if args.only
        else list(PROTOCOLS)
    )
    overrides = dict(kv.split("=", 1) for kv in args.set)
    results = {}
    for name in names:
        row = {}
        for label, extra in (("stock", {"FUSED_DENSE_GRAD": ""}),
                             ("fused", {"FUSED_DENSE_GRAD": "1"})):
            rec = run_once(
                {**PROTOCOLS[name], **overrides, **extra}, args.timeout
            )
            row[label] = rec.get("value", 0.0)
            row[f"{label}_rec"] = rec
            print(f"{name} {label}: {row[label]}", flush=True)
        if row["stock"] > 0 and row["fused"] > 0:
            row["delta_pct"] = round(
                100.0 * (row["fused"] - row["stock"]) / row["stock"], 2
            )
        results[name] = row
    print(json.dumps({
        n: {k: v for k, v in r.items() if not k.endswith("_rec")}
        for n, r in results.items()
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
