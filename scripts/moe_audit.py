"""MoE efficiency audit (VERDICT r3 #7): where lm_moe's ~25 % vs dense goes.

Measures, on the attached chip, tokens/sec + ``cost_analysis`` bytes and
FLOPs per step for dense ``lm_small`` vs ``lm_moe_small`` across the
routing design space — top-1 vs top-2, capacity factor sweep — and
prints the per-component byte account of the routing machinery (the
dispatch/combine one-hot tensors and the expert-major activation
buffers are the structural overhead: they exist in the MoE step and not
the dense one).

Usage: python scripts/moe_audit.py [--seq-len 1024] [--batch 8]
One table row per variant; PROFILE.md's MoE section records the result.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def measure(model_name, seq_len, batch, steps=20, **model_kw):
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    vocab = 32_000
    cfg = TrainConfig(
        model=model_name, batch_size_per_device=batch, num_classes=vocab,
        attn_impl="pallas" if jax.default_backend() == "tpu" else "xla",
    )
    model = get_model(
        model_name, num_classes=vocab, max_seq_len=seq_len,
        attn_impl=cfg.attn_impl, **model_kw,
    )
    mesh = data_parallel_mesh(jax.device_count())
    tx, _ = create_optimizer(cfg, steps_per_epoch=64)
    state = replicate_state(
        create_train_state(
            model, cfg, tx, input_shape=(1, seq_len), input_dtype=jnp.int32
        ),
        mesh,
    )
    step = make_train_step(model, tx, mesh, cfg, donate_state=False)
    rng = np.random.RandomState(42)
    rows = rng.randint(0, vocab, size=(batch, seq_len + 1)).astype(np.int32)
    b = shard_batch((rows[:, :-1], rows[:, 1:]), mesh)

    compiled = step.lower(state, b).compile()
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    gb = cost.get("bytes accessed", float("nan")) / 1e9
    tf = cost.get("flops", float("nan")) / 1e12
    for _ in range(3):
        state, metrics = compiled(state, b)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = compiled(state, b)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    return {
        "tok_s": batch * seq_len / dt,
        "ms": dt * 1e3,
        "gb": gb,
        "tflops": tf,
    }


def routing_bytes(batch, seq_len, experts, top_k, cf, hidden=512):
    """Analytic bytes of the routing machinery itself (f32 dispatch +
    combine [b,s,e,c] plus bf16 expert-major in/out [e,b,c,d]), one
    write + one read each, fwd + symmetric bwd (×2)."""
    c = int(np.ceil(top_k * seq_len / experts * cf))
    onehot = batch * seq_len * experts * c * 4 * 2  # dispatch + combine
    expert_io = experts * batch * c * hidden * 2 * 2  # in + out, bf16
    return 2 * 2 * (onehot + expert_io), c  # r+w, fwd+bwd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()
    t, b = args.seq_len, args.batch

    dense = measure("lm_small", t, b)
    print(
        f"{'variant':28s} {'tok/s':>9s} {'ms':>7s} {'GB':>7s} {'TF':>6s} "
        f"{'vs dense':>8s} {'cap':>4s}"
    )
    print(
        f"{'lm_small (dense)':28s} {dense['tok_s']:9.0f} {dense['ms']:7.1f} "
        f"{dense['gb']:7.2f} {dense['tflops']:6.2f} {'1.000':>8s} {'-':>4s}"
    )
    for label, kw in (
        ("moe top2 cf1.25 (default)", dict(moe_top_k=2, moe_capacity_factor=1.25)),
        ("moe top1 cf1.25", dict(moe_top_k=1, moe_capacity_factor=1.25)),
        ("moe top2 cf1.0", dict(moe_top_k=2, moe_capacity_factor=1.0)),
        ("moe top2 cf2.0", dict(moe_top_k=2, moe_capacity_factor=2.0)),
        ("moe top1 cf2.0", dict(moe_top_k=1, moe_capacity_factor=2.0)),
    ):
        r = measure("lm_moe_small", t, b, **kw)
        route_gb, cap = routing_bytes(
            b, t, 8, kw["moe_top_k"], kw["moe_capacity_factor"]
        )
        print(
            f"{label:28s} {r['tok_s']:9.0f} {r['ms']:7.1f} {r['gb']:7.2f} "
            f"{r['tflops']:6.2f} {r['tok_s'] / dense['tok_s']:8.3f} {cap:4d}"
            f"   (routing-machinery est {route_gb / 1e9:.2f} GB)"
        )


if __name__ == "__main__":
    sys.exit(main())
