"""Disaggregated serving bench — split pools vs colocated, gated.

The disaggregation protocol (BASELINE.md style, one JSON line on
stdout; recertify row ``serve_lm_disagg``). One seeded bimodal backlog
(``SERVE_PROFILE=disagg``: long-prefill and long-decode requests, every
prompt opening with the same hot system prefix — ``loadgen.hot_prompt``)
is served twice at EQUAL replica count:

1. **coloc** — the colocated fleet (every replica prefills + decodes);
2. **disagg** — the same fleet split into prefill and decode pools
   (``SERVE_DISAGG=1``): prefill replicas export each slot's block
   table after the first token (the handoff unit — blocks, not a
   replay), the router seats exports on decode replicas, greedy
   prefixes land in the fleet-wide prefix directory, and one scheduled
   live migration moves a running stream between decode replicas
   mid-decode.

Gates (exit non-zero unless ALL hold):

* **TTFT wins** — disagg p99 TTFT (streaming-measured) strictly below
  coloc p99 at the same replica count: prefill slots recycle per
  prefill instead of being held for a whole decode.
* **decode cadence bounded** — disagg p99 inter-token latency (gaps
  after the handoff seam; the seam is reported separately) <=
  ``BENCH_DISAGG_ITL_FACTOR`` x the coloc p99.
* **bitwise parity** — every request's token stream, in BOTH runs,
  is bitwise identical to sequential ``inference.generate`` — the
  handoff/import/migration seams never change a token.
* **prefill once per fleet** — after the storm, the second tenant
  re-sends a prompt the directory already holds: it must complete
  bitwise with ZERO prefill-program executions anywhere in the fleet
  (adopted from the directory) and bump ``serve.directory_hits``.
* **live migration, zero drops** — the scheduled mid-stream migration
  transplants >= 1 running stream (``stats["migrations"]``), and every
  request still finishes (eos/length) with bitwise parity.
* **closed program sets** — zero mid-measure compiles in both runs;
  every engine ends at exactly ``programs_expected`` (prefill-pool
  engines close over the prefill buckets, decode-pool engines over the
  single decode program).

Env knobs (defaults): ``SERVE_REPLICAS`` (4), ``SERVE_POOL_PREFILL`` /
``SERVE_POOL_DECODE`` (0 = auto half/half split),
``SERVE_DISAGG_DIRECTORY`` (1), ``SERVE_DISAGG_PREFETCH`` (1),
``SERVE_SLOTS`` (4), ``SERVE_PREFILLS_PER_STEP`` (2),
``SERVE_REQUESTS`` (24), ``SERVE_PROFILE`` (disagg), ``SERVE_MAX_NEW``
(16 — mixed profile only), ``SERVE_SEED`` (0),
``SERVE_TENANT_WEIGHTS`` ("alpha:1,beta:1"),
``BENCH_DISAGG_PREFIX_LEN`` (32 — hot shared system-prefix tokens),
``BENCH_DISAGG_ITL_FACTOR`` (1.5), ``BENCH_DISAGG_MIGRATE_TICK`` (6 —
earliest router tick the scheduled migration may fire),
``BENCH_MODEL`` (lm_tiny), ``BENCH_VOCAB`` (32000), plus ``OBS_DIR``
for the per-replica event streams and pool gauges.

Usage::

    python scripts/disagg_bench.py [--events]
    make disagg-bench
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.serving.loadgen import (  # noqa: E402
    build_tenant_requests,
    hot_prompt,
    percentile,
    profile_shapes,
)


def _emit_record(record: dict) -> None:
    print(json.dumps(record), flush=True)
    from distributeddeeplearning_tpu import obs

    bus = obs.get_bus()
    bus.point("bench_result", **record)
    bus.flush()


def run_fleet(model, params, reqs, scfg, fcfg, max_len, *,
              migrate_tick=0, probe=None):
    """Serve the backlog through the fleet ``fcfg`` describes. With
    ``migrate_tick`` > 0 (disagg only) the bench schedules one live
    migration off a busy decode replica once that router tick passes
    and another decode replica has room. ``probe`` re-sends one
    directory-resident prompt AFTER the storm and reports the fleet's
    prefill-execution delta (the prefill-once-per-fleet oracle)."""
    import numpy as np

    from distributeddeeplearning_tpu.serving import Replica, Request, Router

    router = Router(config=fcfg)
    obs_dir = os.environ.get("OBS_DIR") or None
    npre, _ = fcfg.pool_split()
    for k in range(fcfg.replicas):
        pool = "mixed"
        if fcfg.disagg:
            pool = "prefill" if k < npre else "decode"
        router.add_replica(
            Replica(k, model, params, scfg, max_len=max_len,
                    obs_dir=obs_dir, pool=pool),
            start=True, threaded=True,
        )
    t0 = time.perf_counter()
    while not all(r.state == "ready" for r in router.replicas):
        if time.perf_counter() - t0 > 600:
            raise TimeoutError("fleet warmup timed out")
        time.sleep(0.01)
    # Warm pass (round-robin over the placeable pool) so first-dispatch
    # overheads — and, disaggregated, the first handoff/import seam —
    # stay out of the measurement. Engines precompile their closed
    # program sets at build; this warms the dispatch path, not code.
    warm_placement = router.config.placement
    router.config.placement = "rr"
    for _ in range(fcfg.replicas):
        router.submit(Request(
            prompt=reqs[0]["prompt"], max_new_tokens=2, temperature=0.0,
        ))
    router.drain(timeout=600)
    router.config.placement = warm_placement
    router._ticks = 0

    engines_pre = {
        r.rid: (id(r.engine), r.engine.compile_count)
        for r in router.replicas
    }
    # Client-side wall clock per committed token: TTFT is the first
    # stamp, the inter-token gaps are the decode cadence the ITL gate
    # compares (the first gap — the handoff seam — is split out).
    token_t = [[] for _ in reqs]
    handles = []
    t0 = time.perf_counter()
    for i, r in enumerate(reqs):
        def cb(_h, toks, i=i):
            now = time.perf_counter()
            token_t[i].extend([now] * len(toks))
        handles.append((r, router.submit(Request(
            prompt=r["prompt"], max_new_tokens=r["max_new"],
            temperature=0.0, on_token=cb,
        ), tenant=r["tenant"])))
    migrated = 0
    migrate_tries = 0
    while router.step():
        if (
            fcfg.disagg and migrate_tick and not migrated
            and router._ticks >= migrate_tick and migrate_tries < 64
        ):
            migrated += _try_migrate(router)
            migrate_tries += 1
        if time.perf_counter() - t0 > 600:
            raise TimeoutError("storm drain timed out")
        time.sleep(0.005)
    dt = time.perf_counter() - t0

    tokens = sum(len(fh.new_tokens) for _, fh in handles)
    ttft_ms = [
        fh.ttft_s * 1e3 for _, fh in handles if fh.ttft_s is not None
    ]
    seam_ms, itl_ms = [], []
    for ts in token_t:
        gaps = [
            (b - a) * 1e3 for a, b in zip(ts, ts[1:])
        ]
        if gaps:
            seam_ms.append(gaps[0])
            itl_ms.extend(gaps[1:])

    probe_out = None
    if probe is not None:
        pre_execs = {
            r.rid: r.engine.prefill_execs for r in router.replicas
        }
        hits0 = router.stats["directory_hits"]
        pfh = router.submit(Request(
            prompt=probe["prompt"], max_new_tokens=probe["max_new"],
            temperature=0.0,
        ), tenant=probe["tenant"])
        t_p = time.perf_counter()
        while router.step():
            if time.perf_counter() - t_p > 120:
                raise TimeoutError("directory probe timed out")
            time.sleep(0.002)
        probe_out = {
            "tokens": [int(t) for t in pfh.new_tokens],
            "outcome": pfh.finish_reason,
            "prefill_execs_delta": sum(
                r.engine.prefill_execs - pre_execs[r.rid]
                for r in router.replicas
            ),
            "directory_hits_delta":
                router.stats["directory_hits"] - hits0,
        }

    ledger = []
    for r in router.replicas:
        pre = engines_pre.get(r.rid)
        ledger.append({
            "replica": r.rid,
            "pool": r.pool,
            "state": r.state,
            "compile_count": r.engine.compile_count if r.engine else 0,
            "programs_expected":
                r.engine.programs_expected if r.engine else 0,
            "compiles_during_measure": (
                0 if pre is None or pre[0] != id(r.engine)
                else r.engine.compile_count - pre[1]
            ),
            "prefill_execs": r.engine.prefill_execs if r.engine else 0,
        })
    run = {
        "disagg": bool(fcfg.disagg),
        "replicas": fcfg.replicas,
        "pools": dict(zip(("prefill", "decode"), fcfg.pool_split()))
        if fcfg.disagg else {"mixed": fcfg.replicas},
        "tokens_per_sec": round(tokens / dt, 1) if dt else 0.0,
        "wall_s": round(dt, 2),
        "tokens": tokens,
        "ttft_p50_ms": round(percentile(ttft_ms, 0.5), 2),
        "ttft_p99_ms": round(percentile(ttft_ms, 0.99), 2),
        "itl_p50_ms": round(percentile(itl_ms, 0.5), 2),
        "itl_p99_ms": round(percentile(itl_ms, 0.99), 2),
        "seam_p99_ms": round(percentile(seam_ms, 0.99), 2),
        "migrated_streams": migrated,
        "stats": dict(router.stats),
        "per_replica": ledger,
    }
    if router.directory is not None:
        run["directory"] = router.directory.snapshot()
    streams = [
        [int(t) for t in fh.new_tokens] for _, fh in handles
    ]
    outcomes = [fh.finish_reason for _, fh in handles]
    router.close()
    return run, streams, outcomes, probe_out


def _try_migrate(router) -> int:
    """One scheduled-migration attempt: pick a decode replica with a
    live imported stream while a sibling decode replica has room, and
    transplant one stream. Returns streams moved (0 when the moment
    isn't right yet — the bench retries next tick)."""
    decode = [r for r in router.replicas if r.pool == "decode"]
    for src in decode:
        with router._lock:
            live = any(
                fh.replica_id == src.rid and fh._sub is not None
                and not fh.done.is_set()
                for fh in router._inflight
            )
        if not live:
            continue
        room = any(
            d.rid != src.rid and d.placeable and d.free_slot_count() > 0
            for d in decode
        )
        if not room:
            continue
        try:
            return router.migrate(src.rid)
        except TimeoutError:
            return 0
    return 0


def main() -> int:
    if "--events" in sys.argv[1:] or os.environ.get("OBS_DIR"):
        from distributeddeeplearning_tpu import obs

        if not os.environ.get("OBS_DIR"):
            os.environ["OBS_DIR"] = os.path.join(
                "runs", f"disagg-bench-{int(time.time())}"
            )
        obs.configure_from_env()
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("COMPILATION_CACHE_DIR"):
        from distributeddeeplearning_tpu.training.warmup import (
            enable_persistent_cache,
        )

        enable_persistent_cache(os.environ["COMPILATION_CACHE_DIR"])

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.inference import generate
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.serving import FleetConfig, ServeConfig
    from distributeddeeplearning_tpu.serving.fleet.router import (
        parse_tenant_weights,
    )

    env = os.environ
    model_name = env.get("BENCH_MODEL", "lm_tiny")
    vocab = int(env.get("BENCH_VOCAB", "32000"))
    n_requests = int(env.get("SERVE_REQUESTS", "24"))
    max_new = int(env.get("SERVE_MAX_NEW", "16"))
    seed = int(env.get("SERVE_SEED", "0"))
    profile = env.get("SERVE_PROFILE", "disagg")
    prefix_len = int(env.get("BENCH_DISAGG_PREFIX_LEN", "32"))
    itl_factor = float(env.get("BENCH_DISAGG_ITL_FACTOR", "1.5"))
    migrate_tick = int(env.get("BENCH_DISAGG_MIGRATE_TICK", "6"))
    weights = parse_tenant_weights(
        env.get("SERVE_TENANT_WEIGHTS", "alpha:1,beta:1")
    )
    tenants = sorted(weights)

    scfg = ServeConfig.from_env()
    if scfg.kv_layout != "paged":
        scfg.kv_layout = "paged"  # the block table is the handoff unit
    if env.get("SERVE_SLOTS") is None:
        scfg.num_slots = 4
    if env.get("SERVE_PREFILLS_PER_STEP") is None:
        # A prefill-pool replica's whole job is prefills; two per tick
        # keeps the split fleet's admission rate from bottlenecking on
        # the pump cadence (the colocated run gets the same setting —
        # its TTFT is slot-bound, not admission-bound).
        scfg.prefills_per_step = 2
    fcfg = FleetConfig.from_env()
    if env.get("SERVE_REPLICAS") is None:
        fcfg.replicas = 4
    fcfg.tenant_weights = weights
    fcfg = dataclasses.replace(fcfg, chaos_plan="", brownout_stages="")
    fcfg_coloc = dataclasses.replace(fcfg, disagg=False)
    fcfg_disagg = dataclasses.replace(fcfg, disagg=True)
    fcfg_disagg.validate()

    shapes = profile_shapes(profile, max_new)
    prefix = hot_prompt(vocab, prefix_len, seed=seed + 1)
    plens = sorted({tp + prefix_len for tp, _ in shapes})
    max_len = max(
        tp + prefix_len + n_new for tp, n_new in shapes
    )
    if scfg.buckets is None:
        bmax = plens[-1]
        bshort = max(
            [p for p in plens if p <= bmax // 2] or [bmax]
        )
        scfg.buckets = (bshort, bmax) if bshort < bmax else (bmax,)
    metric = "serve_lm_disagg_tokens_per_sec"
    try:
        model = get_model(
            model_name, num_classes=vocab, max_seq_len=max_len,
            dtype=jnp.float32,
        )
        variables = jax.jit(model.init, static_argnames=("train",))(
            jax.random.PRNGKey(0), jnp.zeros((2, max_len), jnp.int32),
            train=False,
        )
        params = nn.unbox(variables["params"])
        reqs = build_tenant_requests(
            tenants, n_requests, 0.0, seed, vocab, shapes,
            shared_prefix=prefix,
        )
        # The prefill-once probe: tenant B re-sends the exact prompt
        # tenant A's longest prefill published to the directory.
        donor_i = max(
            (i for i, r in enumerate(reqs) if r["tenant"] == tenants[0]),
            key=lambda i: len(reqs[i]["prompt"]),
        )
        donor = reqs[donor_i]
        probe = {
            "prompt": donor["prompt"], "max_new": donor["max_new"],
            "tenant": tenants[-1],
        }

        # Sequential oracle — greedy ``inference.generate`` per request
        # (rng-free at temperature 0): the bitwise reference both fleet
        # geometries must reproduce through every seam.
        oracle = []
        for r in reqs:
            out = np.asarray(generate(
                model, params, np.asarray(r["prompt"])[None, :],
                max_new_tokens=r["max_new"], temperature=0.0,
            ))
            oracle.append(
                [int(t) for t in out[0, len(r["prompt"]):]]
            )
        probe_oracle = oracle[donor_i]

        coloc, coloc_streams, coloc_outcomes, _ = run_fleet(
            model, params, reqs, scfg, fcfg_coloc, max_len,
        )
        disagg, dis_streams, dis_outcomes, probe_out = run_fleet(
            model, params, reqs, scfg, fcfg_disagg, max_len,
            migrate_tick=migrate_tick, probe=probe,
        )

        parity_coloc = coloc_streams == oracle
        parity_disagg = dis_streams == oracle
        completed_ok = all(
            o in ("eos", "length")
            for o in coloc_outcomes + dis_outcomes
        )
        ttft_ok = disagg["ttft_p99_ms"] < coloc["ttft_p99_ms"]
        itl_ok = (
            disagg["itl_p99_ms"] <= coloc["itl_p99_ms"] * itl_factor
        )
        prefill_once = (
            probe_out is not None
            and probe_out["prefill_execs_delta"] == 0
            and probe_out["directory_hits_delta"] >= 1
            and probe_out["tokens"] == probe_oracle
            and probe_out["outcome"] in ("eos", "length")
        )
        migration_ok = (
            disagg["migrated_streams"] >= 1
            and disagg["stats"]["migrations"] >= 1
        )
        handoffs_ok = disagg["stats"]["handoffs"] >= 1
        closed = all(
            row["compile_count"] == row["programs_expected"]
            for run in (coloc, disagg) for row in run["per_replica"]
        )
        clean = all(
            row["compiles_during_measure"] == 0
            for run in (coloc, disagg) for row in run["per_replica"]
        )
        ok = (
            parity_coloc and parity_disagg and completed_ok and ttft_ok
            and itl_ok and prefill_once and migration_ok and handoffs_ok
            and closed and clean
        )
        detail = {
            "profile": profile,
            "requests": n_requests,
            "replicas": fcfg.replicas,
            "slots_per_replica": scfg.num_slots,
            "buckets": list(scfg.buckets),
            "prefix_len": prefix_len,
            "platform": jax.devices()[0].platform,
            "pool_split": "prefill:{},decode:{}".format(
                *fcfg_disagg.pool_split()
            ),
            "disagg": disagg,
            "coloc": coloc,
            "ttft_p99_speedup": round(
                coloc["ttft_p99_ms"] / disagg["ttft_p99_ms"], 2
            ) if disagg["ttft_p99_ms"] else 0.0,
            "itl_factor_max": itl_factor,
            "probe": probe_out,
            "gates": {
                "parity_coloc": parity_coloc,
                "parity_disagg": parity_disagg,
                "completed_all": completed_ok,
                "ttft_p99_wins": ttft_ok,
                "itl_p99_bounded": itl_ok,
                "prefill_once_per_fleet": prefill_once,
                "migration_zero_drop": migration_ok,
                "handoffs_flowed": handoffs_ok,
                "programs_closed": closed,
                "zero_midmeasure_recompiles": clean,
            },
        }
        record = {
            "metric": metric,
            "value": disagg["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": round(
                disagg["tokens_per_sec"] / coloc["tokens_per_sec"], 2
            ) if coloc["tokens_per_sec"] else 0.0,
            "detail": detail,
        }
        _emit_record(record)
        if not ok:
            failed = [k for k, v in detail["gates"].items()
                      if v is False]
            print(f"DISAGG GATES FAILED: {failed}", file=sys.stderr)
        return 0 if ok else 1
    except Exception as e:  # structured failure record, like bench.py
        _emit_record({
            "metric": metric, "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": repr(e),
        })
        raise


if __name__ == "__main__":
    sys.exit(main())
