"""Decode-path audit (VERDICT r4 #6): roofline position + batch sweep.

The decode tier's 11.7k tok/s (b=8) was the only number in BASELINE.md
with no PROFILE.md account behind it. This script gives it one, using the
same method as the trainer audits: an analytic byte floor, a measured
sweep, and (optionally) a trace.

**Byte floor.** Autoregressive decode is memory-bound: each step must
stream (a) every parameter and (b) the KV cache past. With this repo's
static cache design (``inference.py`` — buffers allocated at the
request length prompt+new, position mask hides the unwritten tail), the
attention reads the FULL buffer every step regardless of how many
tokens are valid yet, so with max_len = prompt_len + new_tokens:

    bytes/step  =  param_bytes + kv_cache_bytes(max_len)
    tok/s floor =  batch * HBM_BW / bytes_per_step

Batch amortizes the parameter (and, less obviously, nothing else: the KV
cache scales WITH batch, so at large b the cache term dominates and
tok/s/seq degrades). The sweep shows exactly where that crossover sits.

**Paged mode** (``--kv-layout paged``, the serving tier's
``SERVE_KV_LAYOUT=paged`` — docs/SERVING.md): decode runs through the
block-pool ``SlotEngine`` instead of ``inference.generate``, and the
floor accounts what that path actually streams per step: the
table-gathered K/V view (``blocks_per_slot * block_size`` rows per
sequence — block-rounded, so ≥ the dense ``max_len``) PLUS the per-slot
int32 block tables the gather indexes through. Leaving the table bytes
out would overstate ``pct_of_floor`` in paged mode; they are itemized as
``block_table_bytes`` in each row.

**Speculative mode** (``--spec-k K`` [``--spec-draft int8|ngram``], the
serving tier's ``SERVE_SPEC_K`` — docs/SERVING.md): every surviving
byte buys MORE than one token. A verify tick streams the target's
params + cache ONCE for K+1 candidate positions and commits
``1..K+1`` tokens, so the audited unit becomes **bytes per accepted
token** (tick bytes ÷ measured commits per verify) and the rows carry a
``floor_multiplier`` against the non-speculative floor. The draft's
costs are itemized honestly, never netted out: the int8 self-draft adds
a second dense KV pool (``draft_cache_mb``) streamed once per draft
step, the resident int8+scale weight tree read once per tick, and K
reads of the dequantized (native-dtype) weight view the draft scan
hoists (``serving/engine._spec_draft_fn``); the n-gram draft adds
nothing. The accept rate is MEASURED through a real speculative
``SlotEngine`` loop, not assumed.

**Quantized mode** (``--kv-dtype int8`` / ``--weight-dtype int8``, the
serving tier's ``SERVE_KV_DTYPE``/``SERVE_WEIGHT_DTYPE``): the floor is
recomputed from the bytes the quantized programs actually stream — int8
K/V + the f32 per-head scale buffers (itemized ``kv_scale_bytes``), and
int8 kernels/embedding + their per-channel scales (itemized
``param_scale_bytes``). Scales are *in* the floor, never hidden:
claiming the bf16 floor with int8 bytes would overstate
``pct_of_floor``. Measurement then runs through a real quantized
``SlotEngine`` decode loop (``inference.generate`` has no quantized
path — the serving engine is the product surface for it).

**Kernel compare** (``--kernel xla|fused|both``, the serving tier's
``SERVE_DECODE_KERNEL`` — docs/SERVING.md): ``fused`` measures through
the Pallas online-softmax decode kernel
(``ops/pallas/paged_decode.py``); ``both`` emits one row per kernel per
batch so the impls are compared against the SAME analytic floor basis.
The per-kernel bytes are itemized honestly: under a quantized cache the
stitched (xla) path materialises full-length compute-dtype K/V buffers
— the gather→dequant round-trip the fused kernel performs in-register —
charged to the xla rows as ``dequant_roundtrip_bytes`` (write + read of
both tensors). The fused rows never pay it, which is exactly the
bytes/step gap serve_bench's compare gate asserts. ``pct_of_floor``
stays ``None`` off-TPU for every kernel (CPU interpret-mode measures
dispatch correctness, not roofline position).

Usage::

    python scripts/decode_audit.py [--model lm_small] [--prompt-len 128]
        [--new-tokens 128] [--batches 1,2,4,8,16,32,64]
        [--kv-layout dense|paged] [--block-size 16]
        [--kv-dtype bf16|int8|fp8] [--weight-dtype bf16|int8|fp8]
        [--kernel xla|fused|both]
        [--spec-k 4] [--spec-draft int8|ngram]
        [--profile-dir /tmp/decode_trace]

Prints a per-batch table and ONE summary JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# The chip constants live in ONE shared module (utils/roofline.py) so a
# chip swap is a single edit; re-exported here for existing importers.
from distributeddeeplearning_tpu.utils.roofline import (  # noqa: E402
    FLOOR_BASIS,
    HBM_GBPS,
)


def tree_bytes(tree) -> int:
    import jax

    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree)
    )


def sweep_row(b: int, tps: float, kv_bytes: int, bytes_per_step: int,
              floor: float, on_tpu: bool, table_bytes: int = 0,
              kv_scale_bytes: int = 0) -> dict:
    """One sweep record. VERDICT r5 item 8: the byte floor is a v5e HBM
    roofline — off-chip (CPU smoke) it is NOT a position, so
    ``pct_of_floor`` is emitted as None there and the analytic floor is
    kept under an explicitly-labelled key instead. ``table_bytes`` (paged
    mode) and ``kv_scale_bytes`` (int8 mode: the f32 per-head scale
    buffers) are already inside ``bytes_per_step``; they are itemized so
    the floor's overheads stay auditable."""
    row = {
        "batch": b,
        "tokens_per_sec": round(tps, 1),
        "tokens_per_sec_per_seq": round(tps / b, 1),
        "bytes_per_step_mb": round(bytes_per_step / 2**20, 1),
        "kv_cache_mb": round(kv_bytes / 2**20, 1),
        "analytic_floor_tokens_per_sec": round(floor, 1),
        "pct_of_floor": round(100.0 * tps / floor, 1) if on_tpu else None,
    }
    if table_bytes:
        row["block_table_bytes"] = int(table_bytes)
    if kv_scale_bytes:
        row["kv_scale_bytes"] = int(kv_scale_bytes)
    return row


def format_row(row: dict) -> str:
    pct = row["pct_of_floor"]
    pct_str = f"{pct:>9.1f}%" if pct is not None else f"{'n/a':>10}"
    return (f"  {row['batch']:>4} {row['tokens_per_sec']:>10.1f} "
            f"{row['tokens_per_sec_per_seq']:>10.1f} "
            f"{row['analytic_floor_tokens_per_sec']:>12.1f} "
            f"{pct_str} {row['kv_cache_mb']:>10.1f}")


def paged_step_bytes(model, b: int, max_len: int, block_size: int,
                     kv_dtype: str = "bf16"):
    """Per-decode-step streamed KV bytes of the PAGED layout for ``b``
    co-resident sequences: the table-gathered K/V view (each sequence
    reads its ``blocks_per_slot`` blocks — block-rounded ``max_len``)
    plus the int32 block tables the gather routes through, plus — under
    ``kv_dtype="int8"`` — the f32 per-head scale pools gathered beside
    the payload (itemized as scale bytes). Shape-only (``eval_shape`` of
    the paged decode clone's init — exactly how the serving engine sizes
    its pool). Returns (view_bytes, table_bytes, scale_bytes); the view
    EXCLUDES scales so callers can itemize."""
    import jax
    import jax.numpy as jnp
    from flax import traverse_util

    from distributeddeeplearning_tpu.inference import decode_variant

    mb = -(-max_len // block_size)
    paged_model = decode_variant(
        model, paged_blocks=b * mb + 1, paged_block_size=block_size,
        kv_dtype=kv_dtype,
    )
    shapes = jax.eval_shape(
        lambda r: paged_model.init(
            r, jnp.zeros((b, max_len), jnp.int32), train=False
        ),
        jax.random.PRNGKey(0),
    )["cache"]
    view_bytes = table_bytes = scale_bytes = 0
    for path, leaf in traverse_util.flatten_dict(dict(shapes)).items():
        if path[-1] == "block_table":
            table_bytes += math.prod(leaf.shape) * 4
        elif path[-1] in ("paged_k", "paged_v", "paged_k_scale",
                          "paged_v_scale"):
            _, bs, heads, tail = leaf.shape
            n = b * mb * bs * heads * tail * np.dtype(leaf.dtype).itemsize
            if path[-1].endswith("_scale"):
                scale_bytes += n
            else:
                view_bytes += n
    return view_bytes, table_bytes, scale_bytes


def measure_engine(model, params, b: int, prompt_len: int, new_tokens: int,
                   vocab: int, reps: int = 3, *, kv_layout: str = "dense",
                   block_size: int = 16, kv_dtype: str = "bf16",
                   weight_dtype: str = "bf16",
                   decode_kernel: str = "xla") -> float:
    """Measured engine-decode throughput: ``b`` requests co-resident in
    a SlotEngine (dense or block-pool layout, native or quantized
    dtypes, stitched or fused decode kernel), timing the batched decode
    steps (the path the byte floor describes; prefill is the one-off
    outside it). The quantized/fused configurations only exist on this
    path — ``inference.generate`` stays native-dtype XLA."""
    from distributeddeeplearning_tpu.serving import ReqSpec, SlotEngine

    max_len = prompt_len + new_tokens
    paged_kw = (
        dict(block_size=block_size, prefix_cache=False)
        if kv_layout == "paged" else {}
    )
    engine = SlotEngine(
        model, params, num_slots=b, max_len=max_len,
        buckets=(prompt_len,), kv_layout=kv_layout,
        kv_dtype=kv_dtype, weight_dtype=weight_dtype,
        decode_kernel=decode_kernel, **paged_kw,
    )
    engine.warmup()
    rng = np.random.RandomState(0)
    total = t_meas = 0.0
    for rep in range(reps + 1):  # rep 0 = warmup, untimed
        for slot in list(engine.active_slots):
            engine.release(slot)
        for slot in range(b):
            spec = ReqSpec(
                prompt=rng.randint(0, vocab, size=(prompt_len,)).astype(
                    np.int32
                ),
                max_new_tokens=new_tokens,
                temperature=0.8, top_k=40, rng=rep * b + slot,
            )
            engine.validate_spec(spec)
            engine.prefill(slot, spec)
        engine.decode_step()  # fence: first batched step dispatched
        t0 = time.perf_counter()
        # prefill + the fence step emitted 2 of new_tokens already
        steps = max(new_tokens - 2, 1)
        for _ in range(steps):
            engine.decode_step()
        dt = time.perf_counter() - t0
        if rep:
            total += b * steps
            t_meas += dt
    return total / t_meas


def measure_engine_spec(model, params, b: int, prompt_len: int,
                        new_tokens: int, vocab: int, reps: int = 3, *,
                        spec_k: int = 4, spec_draft: str = "int8",
                        kv_dtype: str = "bf16",
                        decode_kernel: str = "xla"):
    """Measured speculative throughput: ``b`` greedy requests
    co-resident in a spec SlotEngine, timing the draft+verify ticks to
    completion. Returns ``(tokens/sec, accept_rate, commits_per_verify)``
    — the accept rate is what the analytic bytes-per-accepted-token
    figure divides by, so it is measured, never assumed."""
    from distributeddeeplearning_tpu.serving import ReqSpec, SlotEngine

    max_len = prompt_len + new_tokens + spec_k  # verify lookahead headroom
    engine = SlotEngine(
        model, params, num_slots=b, max_len=max_len,
        buckets=(prompt_len,), kv_dtype=kv_dtype,
        decode_kernel=decode_kernel,
        spec_k=spec_k, spec_draft=spec_draft,
    )
    engine.warmup()
    rng = np.random.RandomState(0)
    total = t_meas = 0.0
    for rep in range(reps + 1):  # rep 0 = warmup, untimed
        for slot in list(engine.active_slots):
            engine.release(slot)
        for slot in range(b):
            spec = ReqSpec(
                prompt=rng.randint(0, vocab, size=(prompt_len,)).astype(
                    np.int32
                ),
                max_new_tokens=new_tokens,
            )
            engine.validate_spec(spec)
            engine.prefill(slot, spec)
        t0 = time.perf_counter()
        tokens = 0
        while engine.active_slots:
            for slot, toks, _eos in engine.spec_step():
                tokens += len(toks)
                if engine._cursor[slot] >= engine._max_new[slot]:
                    engine.release(slot)
        dt = time.perf_counter() - t0
        if rep:
            total += tokens
            t_meas += dt
    st = engine.spec_stats
    proposed = st["tokens_accepted"] + st["tokens_rejected"]
    accept_rate = st["tokens_accepted"] / max(proposed, 1)
    commits_per_verify = (
        st["tokens_committed"] * spec_k / max(proposed, 1)
    )
    return total / t_meas, accept_rate, commits_per_verify


def audit(model_name: str, prompt_len: int, new_tokens: int,
          batches, profile_dir=None, vocab: int = 32000,
          kv_layout: str = "dense", block_size: int = 16,
          kv_dtype: str = "bf16", weight_dtype: str = "bf16",
          kernel: str = "xla",
          spec_k: int = 0, spec_draft: str = "int8"):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from flax import traverse_util

    from distributeddeeplearning_tpu.inference import decode_variant, generate
    from distributeddeeplearning_tpu.models import get_model

    max_len = prompt_len + new_tokens
    # Speculative rows write spec_k lookahead positions past the last
    # token — the model (and the spec engine's cache) carries the
    # headroom; non-spec paths keep auditing the max_len view.
    model_len = max_len + spec_k
    model = get_model(model_name, num_classes=vocab, max_seq_len=model_len)
    variables = jax.jit(model.init, static_argnames=("train",))(
        jax.random.PRNGKey(0), jnp.zeros((1, model_len), jnp.int32),
        train=False,
    )
    params = nn.unbox(variables["params"])
    # Param bytes a decode step streams, dtype-aware: with int8 weights
    # the floor charges the quantized kernels/embedding PLUS their f32
    # per-channel scales (itemized — a bf16 floor quoted over int8
    # bytes would overstate pct_of_floor). Shape-only eval_shape of the
    # quantization pass; nothing is materialized here.
    param_scale_bytes = 0
    if weight_dtype == "int8":
        from distributeddeeplearning_tpu.ops import quant as quantlib

        split = quantlib.tree_byte_split(
            jax.eval_shape(quantlib.quantize_params, params)
        )
        param_bytes = split["int8"] + split["scale"] + split["other"]
        param_scale_bytes = split["scale"]
    else:
        param_bytes = tree_bytes(params)

    # KV-cache bytes for batch b: shape-only trace of the decode clone's
    # init (exactly how inference.generate / the engine size buffers);
    # int8 mode's f32 scale buffers come back itemized.
    decode_model = decode_variant(model, kv_dtype=kv_dtype)

    def cache_byte_split(b: int, length: int = max_len):
        shapes = jax.eval_shape(
            lambda r: decode_model.init(
                r, jnp.zeros((b, length), jnp.int32), train=False
            ),
            jax.random.PRNGKey(0),
        )["cache"]
        kv = scale = 0
        for path, leaf in traverse_util.flatten_dict(dict(shapes)).items():
            n = math.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
            if path[-1].endswith("_scale"):
                scale += n
            else:
                kv += n
        return kv, scale

    quantized = kv_dtype != "bf16" or weight_dtype != "bf16"
    kernels = ("xla", "fused") if kernel == "both" else (kernel,)

    def native_kv_bytes(b: int) -> int:
        """Full-length K/V bytes in the COMPUTE dtype for batch ``b`` —
        the dequantized buffers the stitched kernel materialises under a
        quantized cache (shape-only; the fused kernel never builds
        them)."""
        if kv_layout == "paged":
            return paged_step_bytes(model, b, max_len, block_size,
                                    "bf16")[0]
        native_model = decode_variant(model)
        shapes = jax.eval_shape(
            lambda r: native_model.init(
                r, jnp.zeros((b, max_len), jnp.int32), train=False
            ),
            jax.random.PRNGKey(0),
        )["cache"]
        return sum(
            math.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
            for path, leaf in traverse_util.flatten_dict(
                dict(shapes)
            ).items()
            if path[-1] in ("cached_k", "cached_v")
        )

    rows = []
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    print(f"# {model_name} decode audit on {platform}: params "
          f"{param_bytes / 2**20:.1f} MiB "
          f"(weights {weight_dtype}, kv {kv_dtype}), max_len {max_len}",
          flush=True)
    if not on_tpu:
        print(f"# NOTE: floor column is the ANALYTIC v5e byte floor "
              f"({FLOOR_BASIS}); on {platform} it is not a roofline "
              "position — % of floor suppressed", flush=True)
    print(f"# {'b':>4} {'tok/s':>10} {'tok/s/seq':>10} {'floor tok/s':>12} "
          f"{'% of floor':>10} {'cache MiB':>10}", flush=True)
    import contextlib

    for i, b in enumerate(batches):
        table_bytes = scale_bytes = 0
        if spec_k:
            # Speculative rows: the audited unit is bytes per ACCEPTED
            # token — one verify tick's streamed bytes over the
            # measured commits per verify. The cache view carries the
            # spec_k lookahead positions the verify writes into.
            kv, scale_bytes = cache_byte_split(b, max_len + spec_k)
            verify_bytes = param_bytes + kv + scale_bytes
            draft_cache = draft_resident = 0
            if spec_draft == "int8":
                from distributeddeeplearning_tpu.ops import (
                    quant as quantlib,
                )

                dsplit = quantlib.tree_byte_split(
                    jax.eval_shape(quantlib.quantize_params, params)
                )
                draft_resident = (
                    dsplit["int8"] + dsplit["scale"] + dsplit["other"]
                )
                dkv, dkv_scale = cache_byte_split(b, max_len + spec_k)
                draft_cache = dkv + dkv_scale
            native_bytes = tree_bytes(params)
            draft_tick = (
                draft_resident + spec_k * (native_bytes + draft_cache)
                if spec_draft == "int8" else 0
            )
            bytes_per_tick = verify_bytes + draft_tick
            tps, accept_rate, commits = measure_engine_spec(
                model, params, b, prompt_len, new_tokens, vocab,
                spec_k=spec_k, spec_draft=spec_draft, kv_dtype=kv_dtype,
                decode_kernel=kernels[0],
            )
            commits = max(commits, 1e-9)
            floor = b * commits * HBM_GBPS * 1e9 / bytes_per_tick
            base_kv, base_scale = cache_byte_split(b)
            base_bytes = param_bytes + base_kv + base_scale
            row = sweep_row(b, tps, kv, bytes_per_tick, floor, on_tpu,
                            kv_scale_bytes=scale_bytes)
            row.update({
                "kernel": kernels[0],
                "spec_k": spec_k,
                "accept_rate": round(accept_rate, 4),
                "commits_per_verify": round(commits, 2),
                "bytes_per_accepted_token_mb": round(
                    bytes_per_tick / (b * commits) / 2**20, 2
                ),
                "draft_cache_mb": round(draft_cache / 2**20, 1),
                "draft_param_mb": round(draft_resident / 2**20, 1),
                # tokens a surviving byte buys vs the non-spec floor
                "floor_multiplier": round(
                    commits * base_bytes / bytes_per_tick, 2
                ),
            })
            rows.append(row)
            print(format_row(row) + f"  x{row['floor_multiplier']:.2f} "
                  f"floor (accept {accept_rate:.2f})", flush=True)
            continue
        # The engine path serves paged layouts, quantized dtypes AND any
        # non-default kernel (inference.generate has none of the three —
        # the serving engine is the product surface for them).
        use_engine = (
            kv_layout == "paged" or quantized or kernels != ("xla",)
        )
        if use_engine:
            if kv_layout == "paged":
                kv, table_bytes, scale_bytes = paged_step_bytes(
                    model, b, max_len, block_size, kv_dtype
                )
            else:
                kv, scale_bytes = cache_byte_split(b)
            base_bytes = param_bytes + kv + scale_bytes + table_bytes
            dequant_extra = (
                2 * native_kv_bytes(b) if kv_dtype != "bf16" else 0
            )
            for kern in kernels:
                # Stitched kernel under a quantized cache: the gather
                # dequantizes full-length K/V into compute-dtype HBM
                # buffers (write) the score math reads back (read) —
                # traffic the fused kernel does in-register. Charged to
                # the xla rows, itemized; the fused floor is the bare
                # pool stream.
                extra = dequant_extra if kern == "xla" else 0
                bytes_per_step = base_bytes + extra
                floor = b * HBM_GBPS * 1e9 / bytes_per_step
                tps = measure_engine(
                    model, params, b, prompt_len, new_tokens, vocab,
                    kv_layout=kv_layout, block_size=block_size,
                    kv_dtype=kv_dtype, weight_dtype=weight_dtype,
                    decode_kernel=kern,
                )
                row = sweep_row(
                    b, tps, kv, bytes_per_step, floor, on_tpu,
                    table_bytes=table_bytes, kv_scale_bytes=scale_bytes,
                )
                row["kernel"] = kern
                if extra:
                    row["dequant_roundtrip_bytes"] = int(extra)
                rows.append(row)
                suffix = f"  [{kern}]" if len(kernels) > 1 else ""
                print(format_row(row) + suffix, flush=True)
            continue
        else:
            kv, _ = cache_byte_split(b)
            bytes_per_step = param_bytes + kv
            floor = b * HBM_GBPS * 1e9 / bytes_per_step
            rng = np.random.RandomState(0)
            prompt = rng.randint(0, vocab, size=(b, prompt_len)).astype(
                np.int32
            )
            kw = dict(max_new_tokens=new_tokens, temperature=0.8, top_k=40,
                      rng=jax.random.PRNGKey(1))
            out = generate(model, params, prompt, **kw)  # compile + warmup
            int(np.asarray(out)[0, -1])
            prof = (
                jax.profiler.trace(os.path.join(profile_dir, f"b{b}"))
                if profile_dir else contextlib.nullcontext()
            )
            reps = 3
            with prof:
                t0 = time.perf_counter()
                for r in range(reps):
                    out = generate(model, params, prompt,
                                   **{**kw, "rng": jax.random.PRNGKey(2 + r)})
                int(np.asarray(out)[0, -1])  # host readback fence
                dt = time.perf_counter() - t0
            tps = reps * b * new_tokens / dt
        row = sweep_row(b, tps, kv, bytes_per_step, floor, on_tpu,
                        table_bytes=table_bytes, kv_scale_bytes=scale_bytes)
        rows.append(row)
        print(format_row(row), flush=True)
    out = {
        "audit": f"{model_name}_decode",
        "platform": platform,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "kv_layout": kv_layout,
        "kv_dtype": kv_dtype,
        "weight_dtype": weight_dtype,
        "decode_kernel": kernel,
        "param_bytes_mb": round(param_bytes / 2**20, 1),
        "hbm_gbps": HBM_GBPS,
        "floor_basis": FLOOR_BASIS,
        # the roofline claim is only a measured position on the chip the
        # floor constant describes
        "floor_applicable": on_tpu,
        "sweep": rows,
    }
    if param_scale_bytes:
        out["param_scale_bytes"] = int(param_scale_bytes)
    if kv_layout == "paged":
        out["block_size"] = block_size
    if spec_k:
        out["spec_k"] = spec_k
        out["spec_draft"] = spec_draft
    return out


def main(argv=None) -> int:
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="lm_small")
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=128)
    p.add_argument("--batches", default="1,2,4,8,16,32,64")
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--kv-layout", choices=("dense", "paged"),
                   default="dense")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--kv-dtype", choices=("bf16", "int8", "fp8"),
                   default="bf16")
    p.add_argument("--weight-dtype", choices=("bf16", "int8", "fp8"),
                   default="bf16")
    p.add_argument("--kernel", choices=("xla", "fused", "both"),
                   default="xla",
                   help="decode attention lowering to audit "
                        "(SERVE_DECODE_KERNEL); 'both' emits one row "
                        "per kernel per batch for the compare gate")
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative lookahead (0 = off); rows become "
                        "bytes per ACCEPTED token at the measured "
                        "accept rate")
    p.add_argument("--spec-draft", choices=("int8", "ngram"),
                   default="int8")
    p.add_argument("--profile-dir", default=None)
    args = p.parse_args(argv)
    if args.spec_k and (args.kv_layout == "paged"
                        or args.weight_dtype != "bf16"):
        p.error("--spec-k rows audit the dense native-weight engine "
                "(the serving tier's spec-compare regime)")
    if args.spec_k and args.kernel == "both":
        p.error("--spec-k audits one kernel per run "
                "(--kernel xla or --kernel fused)")
    batches = [int(b) for b in args.batches.split(",") if b.strip()]
    out = audit(args.model, args.prompt_len, args.new_tokens, batches,
                profile_dir=args.profile_dir, vocab=args.vocab,
                kv_layout=args.kv_layout, block_size=args.block_size,
                kv_dtype=args.kv_dtype, weight_dtype=args.weight_dtype,
                kernel=args.kernel,
                spec_k=args.spec_k, spec_draft=args.spec_draft)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
