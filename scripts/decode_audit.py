"""Decode-path audit (VERDICT r4 #6): roofline position + batch sweep.

The decode tier's 11.7k tok/s (b=8) was the only number in BASELINE.md
with no PROFILE.md account behind it. This script gives it one, using the
same method as the trainer audits: an analytic byte floor, a measured
sweep, and (optionally) a trace.

**Byte floor.** Autoregressive decode is memory-bound: each step must
stream (a) every parameter and (b) the KV cache past. With this repo's
static cache design (``inference.py`` — buffers allocated at the
request length prompt+new, position mask hides the unwritten tail), the
attention reads the FULL buffer every step regardless of how many
tokens are valid yet, so with max_len = prompt_len + new_tokens:

    bytes/step  =  param_bytes + kv_cache_bytes(max_len)
    tok/s floor =  batch * HBM_BW / bytes_per_step

Batch amortizes the parameter (and, less obviously, nothing else: the KV
cache scales WITH batch, so at large b the cache term dominates and
tok/s/seq degrades). The sweep shows exactly where that crossover sits.

**Paged mode** (``--kv-layout paged``, the serving tier's
``SERVE_KV_LAYOUT=paged`` — docs/SERVING.md): decode runs through the
block-pool ``SlotEngine`` instead of ``inference.generate``, and the
floor accounts what that path actually streams per step: the
table-gathered K/V view (``blocks_per_slot * block_size`` rows per
sequence — block-rounded, so ≥ the dense ``max_len``) PLUS the per-slot
int32 block tables the gather indexes through. Leaving the table bytes
out would overstate ``pct_of_floor`` in paged mode; they are itemized as
``block_table_bytes`` in each row.

Usage::

    python scripts/decode_audit.py [--model lm_small] [--prompt-len 128]
        [--new-tokens 128] [--batches 1,2,4,8,16,32,64]
        [--kv-layout dense|paged] [--block-size 16]
        [--profile-dir /tmp/decode_trace]

Prints a per-batch table and ONE summary JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

HBM_GBPS = 819.0  # v5e (PROFILE.md constant used by every trainer audit)
FLOOR_BASIS = f"v5e-hbm-{HBM_GBPS:.0f}GBps"


def tree_bytes(tree) -> int:
    import jax

    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree)
    )


def sweep_row(b: int, tps: float, kv_bytes: int, bytes_per_step: int,
              floor: float, on_tpu: bool, table_bytes: int = 0) -> dict:
    """One sweep record. VERDICT r5 item 8: the byte floor is a v5e HBM
    roofline — off-chip (CPU smoke) it is NOT a position, so
    ``pct_of_floor`` is emitted as None there and the analytic floor is
    kept under an explicitly-labelled key instead. ``table_bytes`` (paged
    mode) is already inside ``bytes_per_step``; it is itemized so the
    floor's paged overhead stays auditable."""
    row = {
        "batch": b,
        "tokens_per_sec": round(tps, 1),
        "tokens_per_sec_per_seq": round(tps / b, 1),
        "bytes_per_step_mb": round(bytes_per_step / 2**20, 1),
        "kv_cache_mb": round(kv_bytes / 2**20, 1),
        "analytic_floor_tokens_per_sec": round(floor, 1),
        "pct_of_floor": round(100.0 * tps / floor, 1) if on_tpu else None,
    }
    if table_bytes:
        row["block_table_bytes"] = int(table_bytes)
    return row


def format_row(row: dict) -> str:
    pct = row["pct_of_floor"]
    pct_str = f"{pct:>9.1f}%" if pct is not None else f"{'n/a':>10}"
    return (f"  {row['batch']:>4} {row['tokens_per_sec']:>10.1f} "
            f"{row['tokens_per_sec_per_seq']:>10.1f} "
            f"{row['analytic_floor_tokens_per_sec']:>12.1f} "
            f"{pct_str} {row['kv_cache_mb']:>10.1f}")


def paged_step_bytes(model, b: int, max_len: int, block_size: int):
    """Per-decode-step streamed KV bytes of the PAGED layout for ``b``
    co-resident sequences: the table-gathered K/V view (each sequence
    reads its ``blocks_per_slot`` blocks — block-rounded ``max_len``)
    plus the int32 block tables the gather routes through. Shape-only
    (``eval_shape`` of the paged decode clone's init — exactly how the
    serving engine sizes its pool)."""
    import jax
    import jax.numpy as jnp
    from flax import traverse_util

    from distributeddeeplearning_tpu.inference import decode_variant

    mb = -(-max_len // block_size)
    paged_model = decode_variant(
        model, paged_blocks=b * mb + 1, paged_block_size=block_size
    )
    shapes = jax.eval_shape(
        lambda r: paged_model.init(
            r, jnp.zeros((b, max_len), jnp.int32), train=False
        ),
        jax.random.PRNGKey(0),
    )["cache"]
    view_bytes = table_bytes = 0
    for path, leaf in traverse_util.flatten_dict(dict(shapes)).items():
        if path[-1] == "block_table":
            table_bytes += math.prod(leaf.shape) * 4
        elif path[-1] in ("paged_k", "paged_v"):
            _, bs, heads, dh = leaf.shape
            view_bytes += (
                b * mb * bs * heads * dh * np.dtype(leaf.dtype).itemsize
            )
    return view_bytes, table_bytes


def measure_paged(model, params, b: int, prompt_len: int, new_tokens: int,
                  block_size: int, vocab: int, reps: int = 3) -> float:
    """Measured paged-decode throughput: ``b`` requests co-resident in a
    block-pool SlotEngine, timing the batched decode steps (the path the
    byte floor describes; prefill is the one-off outside it)."""
    from distributeddeeplearning_tpu.serving import ReqSpec, SlotEngine

    max_len = prompt_len + new_tokens
    engine = SlotEngine(
        model, params, num_slots=b, max_len=max_len,
        buckets=(prompt_len,), kv_layout="paged", block_size=block_size,
        prefix_cache=False,
    )
    engine.warmup()
    rng = np.random.RandomState(0)
    total = t_meas = 0.0
    for rep in range(reps + 1):  # rep 0 = warmup, untimed
        for slot in list(engine.active_slots):
            engine.release(slot)
        for slot in range(b):
            spec = ReqSpec(
                prompt=rng.randint(0, vocab, size=(prompt_len,)).astype(
                    np.int32
                ),
                max_new_tokens=new_tokens,
                temperature=0.8, top_k=40, rng=rep * b + slot,
            )
            engine.validate_spec(spec)
            engine.prefill(slot, spec)
        engine.decode_step()  # fence: first batched step dispatched
        t0 = time.perf_counter()
        # prefill + the fence step emitted 2 of new_tokens already
        steps = max(new_tokens - 2, 1)
        for _ in range(steps):
            engine.decode_step()
        dt = time.perf_counter() - t0
        if rep:
            total += b * steps
            t_meas += dt
    return total / t_meas


def audit(model_name: str, prompt_len: int, new_tokens: int,
          batches, profile_dir=None, vocab: int = 32000,
          kv_layout: str = "dense", block_size: int = 16):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.inference import generate
    from distributeddeeplearning_tpu.models import get_model

    max_len = prompt_len + new_tokens
    model = get_model(model_name, num_classes=vocab, max_seq_len=max_len)
    variables = jax.jit(model.init, static_argnames=("train",))(
        jax.random.PRNGKey(0), jnp.zeros((1, max_len), jnp.int32),
        train=False,
    )
    params = nn.unbox(variables["params"])
    param_bytes = tree_bytes(params)

    # KV-cache bytes for batch b: shape-only trace of the decode clone's
    # init (exactly how inference.generate sizes its buffers).
    decode_model = model.clone(decode=True, attn_impl="xla", seq_axis=None)

    def cache_bytes(b: int) -> int:
        shapes = jax.eval_shape(
            lambda r: decode_model.init(
                r, jnp.zeros((b, max_len), jnp.int32), train=False
            ),
            jax.random.PRNGKey(0),
        )["cache"]
        return sum(
            math.prod(s.shape) * np.dtype(s.dtype).itemsize
            for s in jax.tree.leaves(shapes)
        )

    rows = []
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    print(f"# {model_name} decode audit on {platform}: params "
          f"{param_bytes / 2**20:.1f} MiB, max_len {max_len}", flush=True)
    if not on_tpu:
        print(f"# NOTE: floor column is the ANALYTIC v5e byte floor "
              f"({FLOOR_BASIS}); on {platform} it is not a roofline "
              "position — % of floor suppressed", flush=True)
    print(f"# {'b':>4} {'tok/s':>10} {'tok/s/seq':>10} {'floor tok/s':>12} "
          f"{'% of floor':>10} {'cache MiB':>10}", flush=True)
    import contextlib

    for i, b in enumerate(batches):
        table_bytes = 0
        if kv_layout == "paged":
            kv, table_bytes = paged_step_bytes(model, b, max_len, block_size)
            bytes_per_step = param_bytes + kv + table_bytes
            floor = b * HBM_GBPS * 1e9 / bytes_per_step
            tps = measure_paged(
                model, params, b, prompt_len, new_tokens, block_size, vocab
            )
        else:
            kv = cache_bytes(b)
            bytes_per_step = param_bytes + kv
            floor = b * HBM_GBPS * 1e9 / bytes_per_step
            rng = np.random.RandomState(0)
            prompt = rng.randint(0, vocab, size=(b, prompt_len)).astype(
                np.int32
            )
            kw = dict(max_new_tokens=new_tokens, temperature=0.8, top_k=40,
                      rng=jax.random.PRNGKey(1))
            out = generate(model, params, prompt, **kw)  # compile + warmup
            int(np.asarray(out)[0, -1])
            prof = (
                jax.profiler.trace(os.path.join(profile_dir, f"b{b}"))
                if profile_dir else contextlib.nullcontext()
            )
            reps = 3
            with prof:
                t0 = time.perf_counter()
                for r in range(reps):
                    out = generate(model, params, prompt,
                                   **{**kw, "rng": jax.random.PRNGKey(2 + r)})
                int(np.asarray(out)[0, -1])  # host readback fence
                dt = time.perf_counter() - t0
            tps = reps * b * new_tokens / dt
        row = sweep_row(b, tps, kv, bytes_per_step, floor, on_tpu,
                        table_bytes=table_bytes)
        rows.append(row)
        print(format_row(row), flush=True)
    out = {
        "audit": f"{model_name}_decode",
        "platform": platform,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "kv_layout": kv_layout,
        "param_bytes_mb": round(param_bytes / 2**20, 1),
        "hbm_gbps": HBM_GBPS,
        "floor_basis": FLOOR_BASIS,
        # the roofline claim is only a measured position on the chip the
        # floor constant describes
        "floor_applicable": on_tpu,
        "sweep": rows,
    }
    if kv_layout == "paged":
        out["block_size"] = block_size
    return out


def main(argv=None) -> int:
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="lm_small")
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=128)
    p.add_argument("--batches", default="1,2,4,8,16,32,64")
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--kv-layout", choices=("dense", "paged"),
                   default="dense")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--profile-dir", default=None)
    args = p.parse_args(argv)
    batches = [int(b) for b in args.batches.split(",") if b.strip()]
    out = audit(args.model, args.prompt_len, args.new_tokens, batches,
                profile_dir=args.profile_dir, vocab=args.vocab,
                kv_layout=args.kv_layout, block_size=args.block_size)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
