"""Render a run report from event-bus JSONL files.

The read side of the flight-recorder/event-bus layer
(``distributeddeeplearning_tpu/obs/``): point it at a run directory
(``OBS_DIR``) or any set of ``events*.jsonl`` files — local-mode runs
are merged by the launcher into ``<dir>/events.jsonl`` already; this
also merges on the fly when only part files exist.

Usage::

    python scripts/obs_report.py RUN_DIR_OR_FILES... [--json] [--top N]

Prints the timeline, span duration p50/p99, host-sync counts by call
site, compile vs step time, and per-host epoch skew. ``--json`` emits
the summary as one JSON object for machine consumption (the bench/
recertify successor to ad-hoc line protocols).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="+", help="run dir(s) and/or events*.jsonl")
    p.add_argument("--json", action="store_true", help="emit summary JSON")
    p.add_argument("--top", type=int, default=20, help="span table rows")
    args = p.parse_args(argv)

    from distributeddeeplearning_tpu.obs import report

    try:
        loaded = report.load(args.paths)
    except FileNotFoundError as e:
        print(f"ERROR: no event files under {e}", file=sys.stderr)
        return 2
    summary = report.summarize(loaded)
    if args.json:
        summary = dict(summary)
        print(json.dumps(summary, default=str))
    else:
        print(report.render(summary, top_n=args.top))
        # A crashed/preempted process's last moments live in its flight
        # dump — surface their existence so nobody greps for them.
        dumps = []
        for path in args.paths:
            if os.path.isdir(path):
                dumps += sorted(glob.glob(os.path.join(path, "flight-*.jsonl")))
        if dumps:
            print("\nflight-recorder dumps (crash black boxes):")
            for d in dumps:
                with open(d) as fh:
                    first = fh.readline()
                try:
                    reason = json.loads(first).get("reason", "?")
                except json.JSONDecodeError:
                    reason = "?"
                print(f"  {d}  (reason: {reason})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
