"""Re-certify every headline number at ONE commit (VERDICT r4 #2).

Runs the full BASELINE.md measurement battery back-to-back in fresh
subprocesses (one per protocol — separate processes keep compile caches
and allocator state from bleeding between rows) and writes
``RECERT.json`` with (commit, date, row) for each. BASELINE.md rows are
then refreshed from that file in one edit.

Protocols (all via bench.py's existing modes — no new measurement code):

    resnet50      BENCH_BATCH=256                      images/sec
    vit_b16       BENCH_MODEL=vit_b16 BENCH_BATCH=256  images/sec
    efficientnet  BENCH_MODEL=efficientnet_b4 ...      images/sec
    lm_small @1k  BENCH_MODEL=lm_small SEQ=1024        tokens/sec
    lm_small @8k  ... SEQ=8192 (flash kernel regime)   tokens/sec
    lm_small @32k ... SEQ=32768 BATCH=1                tokens/sec
    lm_moe_small  BENCH_MODEL=lm_moe_small             tokens/sec
    decode        BENCH_DECODE=1 (b=8, 128+128)        tokens/sec
    serve_lm      scripts/serve_bench.py (32k vocab)   tokens/sec
    serve_lm_paged  serve_bench dense-vs-paged A/B at  tokens/sec
                    a fixed pool-byte budget (longtail)
    serve_lm_int8   serve_bench bf16-vs-int8 (KV +     tokens/sec
                    weights) at a fixed byte budget,
                    teacher-forced match-rate oracle
    serve_lm_spec   serve_bench greedy-vs-speculative  tokens/sec
                    (int8 self-draft, K=4), bitwise
                    greedy parity + accept-rate stats
    serve_lm_fleet  fleet_bench 1-vs-2 router-fronted  tokens/sec
                    replicas, multi-tenant closed
                    backlog: scaling + flat TTFT +
                    weighted fairness + bitwise parity
    serve_lm_disagg disagg_bench split prefill/decode  tokens/sec
                    pools vs colocated at equal
                    replica count: TTFT win, parity,
                    prefill-once directory, live
                    migration, closed sets per pool
    serve_lm_chaos  chaos_bench seeded mixed-verb      tokens/sec
                    fault storm (crash/hang/slow/
                    corrupt/flap) + brownout ladder:
                    splice parity, corrupt healed,
                    breaker budget, bounded TTFT
    lm_coloc        coloc_bench train/serve pool       tokens/sec
                    arbitration under a combined
                    fault+chaos storm: ULP re-join,
                    zero-drop, lease/capacity cycle
    lm_stream       stream_bench pretrain-on-shards    tokens/sec
                    (streamed reader, cursor manifest)
                    -> restore -> SlotEngine greedy
                    serve, gated vs inference.generate

Usage::

    python scripts/recertify.py [--only resnet50,vit_b16] [--timeout 900]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROTOCOLS = {
    "resnet50": {"BENCH_BATCH": "256"},
    # In-step gradient accumulation A/B at the bench batch: one dispatch
    # scans 4 microbatches of 64 — certifies the on-chip cost of the
    # ACCUM_STEPS scan the moment hardware returns (PROFILE.md carries
    # the host-side memory proof meanwhile). Shares the battery's
    # compilation cache with the plain resnet50 row SAFELY: ACCUM_STEPS
    # changes the lowered HLO (the scan + accumulator), so the XLA
    # persistent-cache key — a hash of the HLO module — cannot collide
    # between rows that differ only in this env var (guarded by
    # tests/test_grad_accum.py::test_accum_changes_compiled_program).
    "resnet50_accum4": {"BENCH_BATCH": "256", "ACCUM_STEPS": "4"},
    "vit_b16": {"BENCH_MODEL": "vit_b16", "BENCH_BATCH": "256"},
    "efficientnet_b4": {"BENCH_MODEL": "efficientnet_b4", "BENCH_BATCH": "64"},
    "lm_small_1k": {
        "BENCH_MODEL": "lm_small", "BENCH_SEQ_LEN": "1024", "BENCH_BATCH": "8",
    },
    "lm_small_8k": {
        "BENCH_MODEL": "lm_small", "BENCH_SEQ_LEN": "8192", "BENCH_BATCH": "1",
    },
    "lm_small_32k": {
        "BENCH_MODEL": "lm_small", "BENCH_SEQ_LEN": "32768", "BENCH_BATCH": "1",
    },
    "lm_moe_small": {
        "BENCH_MODEL": "lm_moe_small", "BENCH_SEQ_LEN": "1024",
        "BENCH_BATCH": "8",
    },
    "decode": {"BENCH_DECODE": "1", "BENCH_MODEL": "lm_small"},
    # Serving tier: continuous batching vs sequential generate at 32k
    # vocab under Poisson load (scripts/serve_bench.py — its own
    # entrypoint, not a bench.py mode; the row's JSON line carries
    # speedup, TTFT p50/p99, occupancy and the compile count, and the
    # script exits non-zero on parity loss or a mid-measure recompile).
    "serve_lm": {
        "_script": "scripts/serve_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "32000",
        "SERVE_REQUESTS": "32", "SERVE_MAX_NEW": "16",
        "SERVE_RATE_RPS": "200", "SERVE_SLOTS": "8", "SERVE_BUCKETS": "8,16",
    },
    # Paged KV pool headline (docs/SERVING.md): dense vs paged at the
    # SAME pool-byte budget on the long-tail length mix — the row's JSON
    # line carries both runs, capacity_ratio and tps_ratio, and the
    # script exits non-zero unless paged reaches >=2x concurrency (or
    # >=1.5x tokens/sec) with bitwise parity and zero recompiles.
    "serve_lm_paged": {
        "_script": "scripts/serve_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "32000",
        "SERVE_KV_LAYOUT": "compare", "SERVE_PROFILE": "longtail",
        "SERVE_REQUESTS": "32", "SERVE_RATE_RPS": "0",
        "SERVE_SLOTS": "16", "SERVE_POOL_SLOT_BUDGET": "4",
        "SERVE_BLOCK_SIZE": "16",
    },
    # Quantized decode tier (docs/SERVING.md): bf16 vs int8 KV+weights
    # engines at the SAME KV-pool byte budget on a decode-heavy greedy
    # load — the row's JSON line carries both runs, tps/capacity ratios
    # and the teacher-forced greedy match rate, and the script exits
    # non-zero unless match >= 0.95 AND int8 tokens/sec >= bf16 with
    # zero recompiles and closed program sets on both engines.
    "serve_lm_int8": {
        "_script": "scripts/serve_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "32000",
        "SERVE_KV_DTYPE": "int8", "SERVE_WEIGHT_DTYPE": "int8",
        "SERVE_PROFILE": "mixed", "SERVE_MAX_NEW": "32",
        "SERVE_REQUESTS": "48", "SERVE_RATE_RPS": "0",
        "SERVE_POOL_SLOT_BUDGET": "4", "SERVE_PREFILLS_PER_STEP": "4",
    },
    # Speculative decode tier (docs/SERVING.md): plain greedy vs the
    # int8 self-draft speculative engine on a decode-heavy closed
    # backlog — the row's JSON line carries both runs, the accept-rate
    # p50/mean and draft/verify time split, and the script exits
    # non-zero unless spec tokens/sec >= 1.4x the greedy baseline with
    # BITWISE greedy parity, zero mid-measure recompiles, and both
    # program sets closed at their static counts.
    "serve_lm_spec": {
        "_script": "scripts/serve_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "32000",
        "SERVE_SPEC_K": "4", "SERVE_SPEC_DRAFT": "int8",
        "SERVE_PROFILE": "mixed", "SERVE_MAX_NEW": "64",
        "SERVE_REQUESTS": "24", "SERVE_RATE_RPS": "0",
        "SERVE_SLOTS": "8", "SERVE_PREFILLS_PER_STEP": "8",
    },
    # Fleet tier (docs/SERVING.md): one seeded multi-tenant closed
    # backlog served by 1 vs 2 router-fronted replicas — the row's JSON
    # line carries both runs, the scaling ratio and its basis
    # (single-core hosts CANNOT scale linearly and say so instead of
    # faking it), p99-TTFT ratio, per-tenant fairness at contention and
    # the per-replica compile ledgers; the script exits non-zero unless
    # scaling >= the basis floor AND p99 TTFT holds AND every tenant's
    # token share is within 15% of its weight share AND streams are
    # bitwise identical across runs with closed program sets.
    "serve_lm_fleet": {
        "_script": "scripts/fleet_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "32000",
        "SERVE_REPLICAS": "2", "SERVE_SLOTS": "4",
        "SERVE_TENANT_WEIGHTS": "gold:3,silver:2,bronze:1",
        "SERVE_PLACEMENT": "affinity",
        "SERVE_REQUESTS": "48", "SERVE_MAX_NEW": "16",
        "SERVE_RATE_RPS": "0", "SERVE_BUCKETS": "8,16",
    },
    # Serving chaos plane (docs/ROBUSTNESS.md serving failure model):
    # one seeded mixed-verb fault storm (crash+hang+slow+corrupt+flap,
    # chaos.storm_plan) over a closed 3-tenant backlog on 2 replicas,
    # with the brownout ladder driven through a deterministic burn
    # window — the row's JSON line carries the undisturbed and storm
    # runs, the fired-fault ledger and every gate verdict, and the
    # script exits non-zero unless every non-shed request completes
    # with BITWISE splice parity, the corrupt injection is detected
    # and healed (never delivered), the flap opens the breaker inside
    # its declared budget, program sets stay closed (rebuilds
    # itemized), p99 TTFT holds within the declared multiple, and the
    # brownout ladder steps down AND back up.
    "serve_lm_chaos": {
        "_script": "scripts/chaos_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "32000",
        "SERVE_REPLICAS": "2", "SERVE_SLOTS": "4",
        "SERVE_TENANT_WEIGHTS": "gold:3,silver:2,bronze:1",
        "SERVE_REQUESTS": "36", "SERVE_MAX_NEW": "16",
        "SERVE_RATE_RPS": "0", "SERVE_BUCKETS": "8,16",
        "SERVE_CHAOS_SEED": "0",
    },
    # Disaggregation tier (docs/SERVING.md disaggregation): the same
    # bimodal hot-prefix backlog served by a colocated fleet and by the
    # SAME replica count split into prefill/decode pools with the
    # fleet-wide prefix directory on — the row's JSON line carries both
    # runs, the p99-TTFT speedup, the handoff/migration/directory
    # ledgers and every gate verdict, and the script exits non-zero
    # unless disagg p99 TTFT strictly beats coloc, inter-token p99
    # stays inside its factor, every stream is bitwise equal to
    # sequential generate, the directory probe re-serves a shared
    # prompt with ZERO fleet-wide prefill executions, one scheduled
    # live migration lands with zero drops, and program sets stay
    # closed per pool.
    "serve_lm_disagg": {
        "_script": "scripts/disagg_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "32000",
        "SERVE_REPLICAS": "4", "SERVE_SLOTS": "4",
        "SERVE_TENANT_WEIGHTS": "alpha:1,beta:1",
        "SERVE_REQUESTS": "24", "SERVE_RATE_RPS": "0",
        "SERVE_PROFILE": "disagg", "SERVE_SEED": "0",
    },
    # Colocation tier (docs/ROBUSTNESS.md colocation): ONE device pool
    # shared by training and serving under a combined fault+chaos storm
    # — a serving surge drives the brownout ladder to exhaustion, the
    # PoolArbiter shrinks training through the capacity file
    # (owner="arbiter"), the FleetController's scale-up is lease-gated
    # (denied -> backoff, granted -> second replica), then reclaim
    # drains the leased replica zero-drop and training grows back; the
    # script exits non-zero unless the training trajectory re-joins the
    # uninterrupted reference at f32 ULP, serving p99 TTFT holds the
    # COLOC_TTFT_SLO_MS bound, every request completes with bitwise
    # stream parity (zero dropped, zero mixed-version), program sets
    # stay closed, and the full shrink -> lease -> reclaim -> grow
    # cycle is observed with the capacity file round-tripping.
    "lm_coloc": {
        "_script": "scripts/coloc_bench.py",
        "BENCH_MODEL": "lm_tiny", "BENCH_VOCAB": "64",
        "SERVE_REQUESTS": "24", "SERVE_MAX_NEW": "12",
        "SERVE_TENANT_WEIGHTS": "gold:3,silver:2,bronze:1",
        "SERVE_CHAOS_SEED": "0",
        "COLOC_POOL_DEVICES": "8", "COLOC_SHRINK_STEP": "6",
    },
    # Streamed data plane + the first pretrain->serve artifact
    # (docs/DATA.md): pretrain lm_tiny on seeded token shards through
    # the stream reader (checkpointable shuffle cursor + host prefetch),
    # restore the final checkpoint FROM DISK, serve it greedily through
    # a SlotEngine — the row's JSON line carries training tokens/sec on
    # the streamed reader plus the three gates (restored params bitwise
    # == trained, manifest carries the data_cursor, served streams
    # token-equal to inference.generate), and the script exits non-zero
    # if any gate fails.
    "lm_stream": {
        "_script": "scripts/stream_bench.py",
        "BENCH_MODEL": "lm_tiny",
        "STREAM_RECORDS": "512", "STREAM_SEQ_LEN": "64",
        "STREAM_VOCAB": "256", "STREAM_SHARD_RECORDS": "128",
        "STREAM_SHUFFLE_BLOCK": "64", "STREAM_BATCH": "8",
        "STREAM_EPOCHS": "2", "PREFETCH_HOST_BATCHES": "2",
        "SERVE_MAX_NEW": "16", "SERVE_SLOTS": "4",
    },
}


# Every var a protocol row may define: ambient values are dropped before
# a row's own env applies, so an exported BENCH_MODEL/ACCUM_STEPS can
# never leak into rows that deliberately leave it unset (the rows are
# the protocol — the environment only supplies infra knobs like
# COMPILATION_CACHE_DIR/JAX_PLATFORMS).
_PROTOCOL_VARS = (
    "BENCH_MODEL", "BENCH_BATCH", "BENCH_SEQ_LEN", "BENCH_DECODE",
    "BENCH_DEPTH", "BENCH_IMAGE_SIZE", "BENCH_SCALING", "ACCUM_STEPS",
    # Overlap toggle (training/overlap.py): an ambient
    # ASYNC_COLLECTIVES=0 would silently re-lower every train row's
    # gradient all-reduces without the overlap tag.
    "ASYNC_COLLECTIVES",
    # Decode-row geometry + the profile-capture dir (a leaked
    # BENCH_PROFILE would trace-capture every row's measured region).
    "BENCH_PROMPT_LEN", "BENCH_NEW_TOKENS", "BENCH_PROFILE",
    "BENCH_VOCAB", "SERVE_REQUESTS", "SERVE_MAX_NEW", "SERVE_RATE_RPS",
    "SERVE_SLOTS", "SERVE_BUCKETS", "SERVE_QUEUE_DEPTH", "SERVE_SEED",
    "SERVE_DEADLINE_MS", "SERVE_PREFILLS_PER_STEP", "SERVE_TOP_K_CAP",
    "SERVE_KV_LAYOUT", "SERVE_PROFILE", "SERVE_BLOCK_SIZE",
    "SERVE_NUM_BLOCKS", "SERVE_PREFIX_CACHE", "SERVE_POOL_SLOT_BUDGET",
    "SERVE_KV_DTYPE", "SERVE_WEIGHT_DTYPE", "SERVE_DECODE_KERNEL",
    "SERVE_QUANT_MATCH_MIN",
    "SERVE_SPEC_K", "SERVE_SPEC_DRAFT", "SERVE_SPEC_NGRAM_N",
    "SERVE_SPEC_MIN_SPEEDUP",
    # Telemetry-feedback knobs (docs/SERVING.md adaptive admission): an
    # ambient adaptive policy (or a stale rollup path) must never derate
    # a protocol row's admission mid-measurement.
    "SERVE_ADMISSION_POLICY", "SERVE_ROLLUP_PATH",
    "SERVE_REPLICAS", "SERVE_TENANT_WEIGHTS", "SERVE_PLACEMENT",
    "SERVE_FLEET_QUEUE_DEPTH", "SERVE_FLEET_QUANTUM",
    "SERVE_FLEET_MIN_SCALING", "SERVE_FLEET_SINGLE_CORE_MIN",
    "SERVE_FLEET_TTFT_MAX_RATIO", "SERVE_FLEET_FAIRNESS_TOL",
    # Chaos plane + self-healing knobs (serve_lm_chaos row,
    # docs/ROBUSTNESS.md): a leaked SERVE_CHAOS_PLAN must never storm
    # the other serving rows.
    "SERVE_CHAOS_PLAN", "SERVE_CHAOS_SEED", "SERVE_CHAOS_TTFT_MAX_RATIO",
    "SERVE_STRAGGLER_FACTOR", "SERVE_STRAGGLER_TICKS",
    "SERVE_QUARANTINE_TICKS", "SERVE_PUMP_HEARTBEAT_S",
    "SERVE_REPLICA_MAX_RESTARTS", "SERVE_REPLICA_RESTART_BACKOFF",
    "SERVE_FAULT_JOIN_S", "SERVE_BROWNOUT_STAGES",
    # Disaggregation plane (serve_lm_disagg row, docs/SERVING.md): a
    # leaked SERVE_DISAGG (or pool split / bench tuning) must never
    # split the other serving rows' fleets or reshape the disagg gates.
    "SERVE_DISAGG", "SERVE_POOL_PREFILL", "SERVE_POOL_DECODE",
    "SERVE_DISAGG_DIRECTORY", "SERVE_DISAGG_PREFETCH",
    "BENCH_DISAGG_PREFIX_LEN", "BENCH_DISAGG_ITL_FACTOR",
    "BENCH_DISAGG_MIGRATE_TICK",
    # Streamed data plane (lm_stream row + the DATA_* data-factory
    # knobs, docs/DATA.md): joined here so an exported DATA_FORMAT or
    # stream geometry can never leak into rows that leave it unset.
    "STREAM_RECORDS", "STREAM_SEQ_LEN", "STREAM_VOCAB",
    "STREAM_SHARD_RECORDS", "STREAM_SHUFFLE_BLOCK", "STREAM_BATCH",
    "STREAM_EPOCHS", "SERVE_PROMPT_LEN",
    "PREFETCH_HOST_BATCHES", "DATA_FORMAT", "DATA_TOPOLOGY",
    # Colocation arbiter plane (lm_coloc row, serving/arbiter.py +
    # docs/ROBUSTNESS.md colocation): a leaked pool geometry or stale
    # capacity TTL must never arbitrate the other rows' devices.
    "COLOC_POOL_DEVICES", "COLOC_SHRINK_STEP", "COLOC_TTFT_SLO_MS",
    "COLOC_BROWNOUT_STAGES", "COLOC_SURGE_WINDOW",
    "ARBITER_POOL_DEVICES", "ARBITER_MIN_TRAIN_WORLD",
    "ARBITER_DEVICES_PER_REPLICA", "ARBITER_SHRINK_TICKS",
    "ARBITER_GROW_TICKS", "ARBITER_HIGH_PRESSURE",
    "ARBITER_LOW_PRESSURE", "ARBITER_LEASE_TTL_S",
    "ARBITER_WATCH_PREFIX", "CAPACITY_STALE_S",
)


def run_protocol(name: str, env_over: dict, timeout_s: float) -> dict:
    env = dict(os.environ)
    for var in _PROTOCOL_VARS:
        env.pop(var, None)
    env_over = dict(env_over)
    script = env_over.pop("_script", "bench.py")
    env.update(env_over)
    # One persistent compilation cache across the whole battery (and
    # across re-runs at the same commit): every protocol subprocess
    # deserializes executables instead of recompiling. Opt out with
    # COMPILATION_CACHE_DIR="" (bench.py treats empty as off).
    env.setdefault("COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    # One fast retry per protocol: distinguishes a transient relay flap
    # from a real regression (bench.py itself retries device init).
    for attempt in (1, 2):
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, script)],
                env=env, timeout=timeout_s, capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            rec = {"error": f"timeout after {timeout_s:.0f}s"}
            continue
        lines = [
            ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")
        ]
        if lines:
            try:
                rec = json.loads(lines[-1])
            except json.JSONDecodeError as e:
                # A killed child can leave a partial line that starts
                # with '{' — record a failed row, don't abort the battery.
                rec = {"error": f"unparseable JSON line ({e}); "
                                f"rc={r.returncode}",
                       "stdout_tail": r.stdout[-300:]}
                continue
            rec["wall_s"] = round(time.perf_counter() - t0, 1)
            if rec.get("value", 0) > 0:
                return rec
        else:
            rec = {"error": f"no JSON line; rc={r.returncode}",
                   "stderr_tail": r.stderr[-500:]}
    return rec


def lint_verdict(commit: str) -> dict:
    """The ddlint verdict recorded beside the bench rows (docs/
    ANALYSIS.md): read ``lint.json`` (``make lint`` writes it) and note
    staleness against this battery's commit — so a static-invariant
    regression shows up in the recert trajectory, not only in CI."""
    try:
        with open(os.path.join(REPO, "lint.json")) as f:
            lint = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"missing": True}
    return {
        "ok": bool(lint.get("ok")),
        "commit": lint.get("commit"),
        "stale": lint.get("commit") != commit,
        "findings": lint.get("findings_total", 0),
        "suppressions": lint.get("suppressions_total", 0),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--only", default=None,
                   help="comma-separated protocol subset")
    p.add_argument("--timeout", type=float, default=900.0)
    args = p.parse_args(argv)
    names = (
        [n.strip() for n in args.only.split(",")] if args.only
        else list(PROTOCOLS)
    )
    commit = subprocess.run(
        ["git", "-C", REPO, "rev-parse", "--short", "HEAD"],
        capture_output=True, text=True,
    ).stdout.strip()
    out = {
        "commit": commit,
        "date": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "lint": lint_verdict(commit),
        "rows": {},
    }
    for name in names:
        print(f"=== {name} ===", flush=True)
        rec = run_protocol(name, PROTOCOLS[name], args.timeout)
        out["rows"][name] = rec
        print(json.dumps(rec), flush=True)
        # Incremental write: a crash mid-battery keeps completed rows.
        with open(os.path.join(REPO, "RECERT.json"), "w") as f:
            json.dump(out, f, indent=1)
    ok = all(r.get("value", 0) > 0 for r in out["rows"].values())
    print(json.dumps({"recertified": ok, "commit": commit,
                      "rows": len(out["rows"]),
                      "lint_ok": out["lint"].get("ok", False)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
