"""Host-side memory proof for in-step gradient accumulation.

The claim ACCUM_STEPS exists to make true — *compiled activation memory
scales with the microbatch, not the effective batch* — is certifiable
without any accelerator: XLA's ``compiled.memory_analysis()`` reports
the temp (activation/workspace) allocation of the exact program a TPU
would run, and the CPU backend computes it at full batch sizes in
seconds-to-minutes of compile time with zero execution.

For each requested ``accum_steps`` this script AOT-compiles the dp
engine's train step against an abstract (ShapeDtypeStruct — nothing is
materialised) global batch and tabulates:

* ``temp_bytes``   — XLA temp allocation: activations + workspace, the
  number that caps per-chip batch on HBM;
* ``arg_bytes`` / ``out_bytes`` — parameter+input / output buffers
  (invariant in ``accum_steps`` — the accumulator is scan-local);
* the per-leaf eval_shape of the staged batch (what the host ships).

Usage::

    python scripts/accum_memory.py                     # resnet50 b=256
    python scripts/accum_memory.py --model vit_b16 --batch 256
    python scripts/accum_memory.py --model lm_small --batch 8 --seq-len 1024
    python scripts/accum_memory.py --accum 1,2,4,8 --json

The markdown table is what PROFILE.md's "Microbatched accumulation"
subsection records; the on-chip step-time A/B rides the recertify
battery (``resnet50_accum4``) when hardware returns.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_step(model_name: str, batch: int, accum_steps: int,
               image_size: int, seq_len: int, vocab: int, dtype: str):
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    is_lm = model_name.startswith("lm_")
    cfg = TrainConfig(
        model=model_name,
        batch_size_per_device=batch,
        image_size=image_size,
        compute_dtype=dtype,
        num_classes=vocab if is_lm else 1000,
        accum_steps=accum_steps,
    )
    mesh = data_parallel_mesh(1)  # one chip's view: the HBM question
    tx, _ = create_optimizer(cfg, steps_per_epoch=64)
    kw = dict(num_classes=cfg.num_classes, dtype=cfg.compute_dtype)
    if is_lm:
        model = get_model(model_name, **kw, max_seq_len=seq_len)
        state = create_train_state(
            model, cfg, tx, input_shape=(1, seq_len), input_dtype=jnp.int32
        )
        batch_struct = (
            jax.ShapeDtypeStruct((batch, seq_len), jnp.int32),
            jax.ShapeDtypeStruct((batch, seq_len), jnp.int32),
        )
    else:
        model = get_model(model_name, **kw)
        state = create_train_state(model, cfg, tx)
        batch_struct = (
            jax.ShapeDtypeStruct(
                (batch, image_size, image_size, 3),
                jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
            ),
            jax.ShapeDtypeStruct((batch,), jnp.int32),
        )
    state = replicate_state(state, mesh)
    step = make_train_step(model, tx, mesh, cfg)
    return step, state, batch_struct


def measure(model_name: str, batch: int, accum_steps: int, *,
            image_size: int, seq_len: int, vocab: int, dtype: str) -> dict:
    import time

    step, state, batch_struct = build_step(
        model_name, batch, accum_steps, image_size, seq_len, vocab, dtype
    )
    t0 = time.perf_counter()
    compiled = step.lower(state, batch_struct).compile()
    compile_sec = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    row = {
        "accum_steps": accum_steps,
        "micro_batch": batch // accum_steps,
        "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
        "arg_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
        "out_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
        "compile_sec": round(compile_sec, 1),
    }
    return row


def _mb(n: int) -> str:
    return f"{n / 1e6:,.1f}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch", type=int, default=256,
                   help="effective (per-chip) batch — constant across rows")
    p.add_argument("--accum", default="1,2,4,8",
                   help="comma-separated ACCUM_STEPS values")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    ks = [int(s) for s in args.accum.split(",") if s.strip()]
    rows = []
    for k in ks:
        if args.batch % k:
            print(f"# skipping accum_steps={k}: does not divide batch "
                  f"{args.batch}", file=sys.stderr)
            continue
        rows.append(
            measure(
                args.model, args.batch, k,
                image_size=args.image_size, seq_len=args.seq_len,
                vocab=args.vocab, dtype=args.dtype,
            )
        )
        print(f"# accum_steps={k}: temp {_mb(rows[-1]['temp_bytes'])} MB "
              f"(compiled in {rows[-1]['compile_sec']}s)", file=sys.stderr)

    out = {
        "model": args.model,
        "batch": args.batch,
        "dtype": args.dtype,
        "platform": "cpu-hlo",  # the HLO is backend-shaped on CPU; the
        # on-chip numbers come from the recertify battery on hardware
        "rows": rows,
    }
    if args.json:
        print(json.dumps(out, indent=1))
        return 0
    base = rows[0]["temp_bytes"] if rows else 1
    print(f"\n{args.model} effective batch {args.batch} ({args.dtype}) — "
          "compiled memory vs ACCUM_STEPS (CPU-lowered HLO)\n")
    print("| accum_steps | microbatch | temp (activations) MB | vs k=1 | "
          "args MB | outputs MB |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        print(
            f"| {r['accum_steps']} | {r['micro_batch']} | "
            f"{_mb(r['temp_bytes'])} | "
            f"{r['temp_bytes'] / base:.2f}x | {_mb(r['arg_bytes'])} | "
            f"{_mb(r['out_bytes'])} |"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
