"""Deterministic fault-injection toolkit — the FAULT_PLAN CLI.

Companion to ``distributeddeeplearning_tpu/faults.py`` (grammar,
injector) and ``docs/ROBUSTNESS.md`` (failure model). Three actions:

* ``validate "PLAN"`` — parse a ``FAULT_PLAN`` string and print the
  per-process fault schedule it encodes (exit 2 on a grammar error,
  with the offending directive named) — dry-run a plan before spending
  a pod run on it.
* ``corrupt-latest CKPT_DIR`` — truncate every file of the newest
  committed checkpoint step: the exact on-disk state a preemption
  mid-write leaves behind, driving ``CheckpointManager``'s
  fall-back-to-previous-valid restore.
* ``exit-codes`` — print the exit-code taxonomy the restart supervisor
  enforces (which world exits are retried, which are terminal).

Usage::

    python scripts/faultgen.py validate "kill:step=3,rank=1;nan:step=2"
    python scripts/faultgen.py corrupt-latest /path/to/model_dir
    python scripts/faultgen.py exit-codes
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from distributeddeeplearning_tpu import faults  # noqa: E402


def _cmd_validate(args) -> int:
    try:
        plan = faults.parse_fault_plan(args.plan)
    except ValueError as e:
        print(f"invalid FAULT_PLAN: {e}", file=sys.stderr)
        return 2
    if not plan:
        print("empty plan (no faults)")
        return 0
    print(f"{len(plan)} fault(s):")
    for f in plan:
        who = "every process" if f.rank is None else f"process {f.rank}"
        detail = ""
        if f.kind == "hang":
            detail = f" for {f.secs:g}s"
        elif f.kind == "exit":
            detail = f" with code {f.code}"
        print(
            f"  {f.kind:<5s} {who} after optimizer step {f.step}{detail}"
        )
    return 0


def _cmd_corrupt_latest(args) -> int:
    steps = faults.checkpoint_steps(args.directory)
    if not steps:
        print(
            f"no committed checkpoints under {args.directory}",
            file=sys.stderr,
        )
        return 1
    target = faults.corrupt_latest_checkpoint(args.directory)
    print(
        f"truncated checkpoint step {steps[-1]} at {target} "
        f"(remaining valid steps: {steps[:-1] or 'none'})"
    )
    return 0


def _cmd_exit_codes(args) -> int:
    rows = [
        faults.classify_exit(rc)
        for rc in (
            faults.EXIT_OK,
            faults.EXIT_NONFINITE,
            faults.EXIT_TIMEOUT,
            faults.EXIT_HUNG,
            faults.EXIT_INTERRUPTED,
            -9,   # SIGKILL (preemption / OOM-kill)
            -15,  # SIGTERM
            1,    # generic crash
        )
    ]
    print(f"{'rc':>5s}  {'retryable':<9s}  reason")
    for v in rows:
        print(f"{v.rc:>5d}  {str(v.retryable).lower():<9s}  {v.reason}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="faultgen", description=__doc__.splitlines()[0]
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    v = sub.add_parser("validate", help="parse + pretty-print a FAULT_PLAN")
    v.add_argument("plan")
    v.set_defaults(fn=_cmd_validate)

    c = sub.add_parser(
        "corrupt-latest",
        help="truncate the newest checkpoint (partial-write fault)",
    )
    c.add_argument("directory")
    c.set_defaults(fn=_cmd_corrupt_latest)

    e = sub.add_parser("exit-codes", help="print the exit-code taxonomy")
    e.set_defaults(fn=_cmd_exit_codes)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
