"""Deterministic fault-injection toolkit — the FAULT_PLAN CLI.

Companion to ``distributeddeeplearning_tpu/faults.py`` (grammar,
injector) and ``docs/ROBUSTNESS.md`` (failure model). Three actions:

* ``validate "PLAN"`` — parse a ``FAULT_PLAN`` string and print the
  per-process fault schedule it encodes (exit 2 on a grammar error,
  with the offending directive named) — dry-run a plan before spending
  a pod run on it.
* ``corrupt-latest CKPT_DIR`` — truncate every file of the newest
  committed checkpoint step: the exact on-disk state a preemption
  mid-write leaves behind, driving ``CheckpointManager``'s
  fall-back-to-previous-valid restore.
* ``exit-codes`` — print the exit-code taxonomy the restart supervisor
  enforces (which world exits are retried, which are terminal).
* ``elastic-drill`` — emit a canned shrink→resume→grow ``FAULT_PLAN``
  for the elastic supervisor (``launch.py --elastic``): a ``shrink``
  preemption at ``--step`` losing ``--ranks`` processes, with capacity
  restored either ``--restore-secs`` later (wall clock) or once the
  shrunken world completes ``--restore-step`` (deterministic drills).
* ``chaos-drill`` — emit a canned seeded serving-fleet storm
  (``SERVE_CHAOS_PLAN``, ``serving/chaos.py``): one directive per
  ``--verbs`` entry over ``--replicas`` replicas, ticks drawn from
  ``--storm-seed`` — the plan ``scripts/chaos_bench.py`` replays.
* ``coloc-drill`` — emit the paired surge/shrink/storm/restore recipe
  for the train/serve colocation drill (``serving/arbiter.py``,
  ``scripts/coloc_bench.py``): a training-side ``FAULT_PLAN``
  (``shrink`` preemption + capacity restore) and a seeded serving-side
  ``SERVE_CHAOS_PLAN`` storm, one ``KEY=plan`` line each — the
  combined file ``validate`` understands.

``validate`` speaks BOTH dialects: a plan whose directives carry
``tick=`` (or use the fleet verbs crash/slow/corrupt/flap) validates
against the serving chaos grammar; everything else against the
training ``FAULT_PLAN`` grammar. A *combined* plan — ``KEY=plan``
lines (``coloc-drill`` output, also accepted as a file path) or one
``;``-joined string mixing both dialects — is split per directive and
each subset validated against its own grammar.

Usage::

    python scripts/faultgen.py validate "kill:step=3,rank=1;nan:step=2"
    python scripts/faultgen.py validate "crash:tick=4,replica=0;slow:tick=6,replica=1,factor=6"
    python scripts/faultgen.py validate combined_plan.txt
    python scripts/faultgen.py corrupt-latest /path/to/model_dir
    python scripts/faultgen.py exit-codes
    python scripts/faultgen.py elastic-drill --step 3 --restore-step 6
    python scripts/faultgen.py chaos-drill --replicas 2 --storm-seed 7
    python scripts/faultgen.py coloc-drill --replicas 2 --storm-seed 7
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from distributeddeeplearning_tpu import faults  # noqa: E402
from distributeddeeplearning_tpu.serving import chaos  # noqa: E402


def _is_fleet_plan(text: str) -> bool:
    """Dialect sniff: fleet directives are tick-indexed (``tick=``) or
    use a verb only the fleet grammar knows (``hang`` is shared — its
    keys disambiguate)."""
    fleet_only = set(chaos.FLEET_FAULT_KINDS) - set(faults.FAULT_KINDS)
    for raw in (text or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        kind = raw.partition(":")[0].strip()
        if kind in fleet_only or "tick=" in raw.replace(" ", ""):
            return True
    return False


def _print_fleet_plan(plan) -> None:
    print(f"{len(plan)} fleet fault(s) (serving chaos plane):")
    for f in plan:
        detail = ""
        if f.kind == "hang":
            detail = f" for {f.secs:g}s (heartbeat goes stale)"
        elif f.kind == "slow":
            detail = (
                f" (+{f.factor:g}x{chaos.SLOW_UNIT_S * 1e3:g}ms per pump "
                f"for {f.secs:g}s — straggler bait)"
            )
        elif f.kind == "corrupt":
            detail = " (replay-token flip; splice verifier must catch it)"
        elif f.kind == "flap":
            detail = f" x{f.count} crash->rejoin cycles (breaker bait)"
        print(
            f"  {f.kind:<7s} replica {f.replica} after router tick "
            f"{f.tick}{detail}"
        )


def _split_dialects(text: str):
    """Split a (possibly combined) plan into ``(fault_text,
    fleet_text)``. Handles the ``coloc-drill`` output — ``FAULT_PLAN=``
    / ``SERVE_CHAOS_PLAN=`` lines — and a single ``;``-joined string
    mixing directives of both dialects (per-directive sniff)."""
    fault_parts, fleet_parts = [], []
    keyed = False
    for line in (text or "").splitlines():
        line = line.strip()
        if line.startswith("FAULT_PLAN="):
            fault_parts.append(line.partition("=")[2])
            keyed = True
        elif line.startswith("SERVE_CHAOS_PLAN="):
            fleet_parts.append(line.partition("=")[2])
            keyed = True
    if not keyed:
        for raw in (text or "").replace("\n", ";").split(";"):
            raw = raw.strip()
            if not raw:
                continue
            (fleet_parts if _is_fleet_plan(raw) else fault_parts).append(raw)
    return ";".join(fault_parts), ";".join(fleet_parts)


def _validate_fleet_text(text: str) -> int:
    try:
        plan = chaos.parse_chaos_plan(text)
    except ValueError as e:
        print(f"invalid SERVE_CHAOS_PLAN: {e}", file=sys.stderr)
        return 2
    if not plan:
        print("empty plan (no faults)")
        return 0
    _print_fleet_plan(plan)
    return 0


def _cmd_validate(args) -> int:
    text = args.plan
    if text and os.path.isfile(text):
        # A combined plan file (coloc-drill output saved to disk).
        with open(text) as fh:
            text = fh.read()
    fault_text, fleet_text = _split_dialects(text)
    if fault_text and fleet_text:
        print("combined plan (both dialects):")
        rc = _validate_fault_text(fault_text)
        return rc or _validate_fleet_text(fleet_text)
    if fleet_text and not fault_text:
        return _validate_fleet_text(fleet_text)
    return _validate_fault_text(fault_text or text)


def _validate_fault_text(text: str) -> int:
    try:
        plan = faults.parse_fault_plan(text)
    except ValueError as e:
        print(f"invalid FAULT_PLAN: {e}", file=sys.stderr)
        return 2
    if not plan:
        print("empty plan (no faults)")
        return 0
    print(f"{len(plan)} fault(s):")
    for f in plan:
        who = "every process" if f.rank is None else f"process {f.rank}"
        detail = ""
        if f.kind == "hang":
            detail = f" for {f.secs:g}s"
        elif f.kind == "exit":
            detail = f" with code {f.code}"
        elif f.kind == "shrink":
            who = f"the top {f.ranks} process(es)"
            detail = " (capacity file updated, casualties SIGKILLed)"
        elif f.kind == "restore_capacity":
            if f.step == 0:
                print(
                    f"  {'restore_capacity':<7s} full capacity {f.secs:g}s "
                    f"after the shrink (wall clock)"
                )
                continue
            detail = " (full capacity announced; run continues)"
        print(
            f"  {f.kind:<7s} {who} after optimizer step {f.step}{detail}"
        )
    return 0


def _cmd_corrupt_latest(args) -> int:
    steps = faults.checkpoint_steps(args.directory)
    if not steps:
        print(
            f"no committed checkpoints under {args.directory}",
            file=sys.stderr,
        )
        return 1
    target = faults.corrupt_latest_checkpoint(args.directory)
    print(
        f"truncated checkpoint step {steps[-1]} at {target} "
        f"(remaining valid steps: {steps[:-1] or 'none'})"
    )
    return 0


def _cmd_elastic_drill(args) -> int:
    """Emit (and validate) the canned shrink→resume→grow plan."""
    if args.restore_step is not None:
        restore = f"restore_capacity:step={args.restore_step}"
    else:
        restore = f"restore_capacity:secs={args.restore_secs:g}"
    plan = f"shrink:step={args.step},ranks={args.ranks};{restore}"
    try:
        faults.parse_fault_plan(plan)
    except ValueError as e:  # defensive: bad --step/--ranks combos
        print(f"invalid drill plan {plan!r}: {e}", file=sys.stderr)
        return 2
    print(plan)
    if args.verbose:
        print(
            "# run under the elastic supervisor, e.g.:\n"
            "#   python launch.py -n 2 --elastic --max-restarts 2 \\\n"
            "#       --grow-check-every-s 1 --obs-dir runs/drill \\\n"
            f"#       --env FAULT_PLAN='{plan}' \\\n"
            "#       --env CHECKPOINT_EVERY_STEPS=1 --env "
            "CHECKPOINT_ASYNC=0 \\\n"
            "#       --env DATA_TOPOLOGY=global train.py",
            file=sys.stderr,
        )
    return 0


def _cmd_chaos_drill(args) -> int:
    """Emit (and validate) a canned seeded serving-fleet storm."""
    verbs = tuple(
        v.strip() for v in args.verbs.split(",") if v.strip()
    )
    try:
        plan = chaos.storm_plan(
            args.replicas, seed=args.storm_seed, verbs=verbs,
        )
    except ValueError as e:
        print(f"invalid drill spec: {e}", file=sys.stderr)
        return 2
    print(plan)
    if args.verbose:
        print(
            "# replay the storm through the gated bench, e.g.:\n"
            f"#   SERVE_CHAOS_PLAN='{plan}' \\\n"
            f"#       SERVE_REPLICAS={args.replicas} "
            f"SERVE_CHAOS_SEED={args.storm_seed} \\\n"
            "#       python scripts/chaos_bench.py",
            file=sys.stderr,
        )
    return 0


def _cmd_coloc_drill(args) -> int:
    """Emit (and validate) the paired colocation recipe: a training
    shrink/restore FAULT_PLAN and a seeded serving storm
    SERVE_CHAOS_PLAN — the surge that shrinks training, the storm the
    fleet self-heals through, and the restore that grows it back."""
    if args.restore_step is not None:
        restore = f"restore_capacity:step={args.restore_step}"
    else:
        restore = f"restore_capacity:secs={args.restore_secs:g}"
    fault_plan = f"shrink:step={args.shrink_step},ranks={args.ranks};{restore}"
    verbs = tuple(v.strip() for v in args.verbs.split(",") if v.strip())
    try:
        faults.parse_fault_plan(fault_plan)
        chaos_plan = chaos.storm_plan(
            args.replicas, seed=args.storm_seed, verbs=verbs,
        )
    except ValueError as e:
        print(f"invalid drill spec: {e}", file=sys.stderr)
        return 2
    print(f"FAULT_PLAN={fault_plan}")
    print(f"SERVE_CHAOS_PLAN={chaos_plan}")
    if args.verbose:
        print(
            "# replay the combined storm through the gated bench, e.g.:\n"
            f"#   FAULT_PLAN='{fault_plan}' \\\n"
            f"#       SERVE_CHAOS_PLAN='{chaos_plan}' \\\n"
            f"#       SERVE_CHAOS_SEED={args.storm_seed} \\\n"
            "#       python scripts/coloc_bench.py",
            file=sys.stderr,
        )
    return 0


def _cmd_exit_codes(args) -> int:
    rows = [
        faults.classify_exit(rc)
        for rc in (
            faults.EXIT_OK,
            faults.EXIT_NONFINITE,
            faults.EXIT_TIMEOUT,
            faults.EXIT_HUNG,
            faults.EXIT_INTERRUPTED,
            faults.EXIT_RESIZE,  # elastic world-resize handover
            -9,   # SIGKILL (preemption / OOM-kill)
            -15,  # SIGTERM
            1,    # generic crash
        )
    ]
    print(f"{'rc':>5s}  {'retryable':<9s}  reason")
    for v in rows:
        print(f"{v.rc:>5d}  {str(v.retryable).lower():<9s}  {v.reason}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="faultgen", description=__doc__.splitlines()[0]
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    v = sub.add_parser(
        "validate",
        help="parse + pretty-print a FAULT_PLAN or SERVE_CHAOS_PLAN "
        "(dialect auto-detected)",
    )
    v.add_argument("plan")
    v.set_defaults(fn=_cmd_validate)

    c = sub.add_parser(
        "corrupt-latest",
        help="truncate the newest checkpoint (partial-write fault)",
    )
    c.add_argument("directory")
    c.set_defaults(fn=_cmd_corrupt_latest)

    e = sub.add_parser("exit-codes", help="print the exit-code taxonomy")
    e.set_defaults(fn=_cmd_exit_codes)

    d = sub.add_parser(
        "elastic-drill",
        help="emit a canned shrink->resume->grow FAULT_PLAN "
        "(launch.py --elastic)",
    )
    d.add_argument(
        "--step", type=int, default=3,
        help="global step after which the shrink preemption fires",
    )
    d.add_argument(
        "--ranks", type=int, default=1, help="processes lost by the shrink"
    )
    d.add_argument(
        "--restore-step", type=int, default=None,
        help="global step at which the shrunken world announces restored "
        "capacity (deterministic; wins over --restore-secs)",
    )
    d.add_argument(
        "--restore-secs", type=float, default=30.0,
        help="wall-clock seconds after the shrink until capacity returns "
        "(default 30)",
    )
    d.add_argument(
        "--verbose", action="store_true",
        help="also print the launch.py invocation recipe to stderr",
    )
    d.set_defaults(fn=_cmd_elastic_drill)

    k = sub.add_parser(
        "chaos-drill",
        help="emit a canned seeded serving-fleet storm "
        "(SERVE_CHAOS_PLAN; scripts/chaos_bench.py)",
    )
    k.add_argument(
        "--replicas", type=int, default=2,
        help="fleet size the storm targets (default 2)",
    )
    k.add_argument(
        "--storm-seed", type=int, default=0,
        help="seed drawing the directive ticks/targets (default 0)",
    )
    k.add_argument(
        "--verbs", default=",".join(chaos.FLEET_FAULT_KINDS),
        help="comma-separated fleet verbs to include "
        f"(default: {','.join(chaos.FLEET_FAULT_KINDS)})",
    )
    k.add_argument(
        "--verbose", action="store_true",
        help="also print the chaos_bench invocation recipe to stderr",
    )
    k.set_defaults(fn=_cmd_chaos_drill)

    x = sub.add_parser(
        "coloc-drill",
        help="emit the paired FAULT_PLAN + SERVE_CHAOS_PLAN colocation "
        "recipe (serving/arbiter.py; scripts/coloc_bench.py)",
    )
    x.add_argument(
        "--shrink-step", type=int, default=6,
        help="global step after which training's shrink preemption fires",
    )
    x.add_argument(
        "--ranks", type=int, default=1,
        help="training processes freed for serving by the shrink",
    )
    x.add_argument(
        "--restore-step", type=int, default=None,
        help="global step at which capacity restores (deterministic; "
        "wins over --restore-secs)",
    )
    x.add_argument(
        "--restore-secs", type=float, default=30.0,
        help="wall-clock seconds until capacity returns (default 30)",
    )
    x.add_argument(
        "--replicas", type=int, default=2,
        help="fleet size the serving storm targets (default 2)",
    )
    x.add_argument(
        "--storm-seed", type=int, default=0,
        help="seed drawing the storm ticks/targets (default 0)",
    )
    x.add_argument(
        "--verbs", default=",".join(chaos.FLEET_FAULT_KINDS),
        help="comma-separated fleet verbs for the storm "
        f"(default: {','.join(chaos.FLEET_FAULT_KINDS)})",
    )
    x.add_argument(
        "--verbose", action="store_true",
        help="also print the coloc_bench invocation recipe to stderr",
    )
    x.set_defaults(fn=_cmd_coloc_drill)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
