"""Train/serve colocation bench — one pool, combined storm, gated.

The colocation tier's robustness protocol (BASELINE.md style, one JSON
line on stdout; recertify row ``lm_coloc``; docs/ROBUSTNESS.md
colocation section). One seeded drill exercises the whole
``PoolArbiter`` cycle (serving/arbiter.py) end to end:

1. **uninterrupted training reference** — an elastic mesh-``POOL`` LM
   run with per-step checkpoints: the trajectory every storm leg must
   re-join at f32 ULP.
2. **serving surge + arbitration storm** — a multi-tenant backlog hits
   a 1-replica fleet while a seeded ``SERVE_CHAOS_PLAN`` storms it and
   a deterministic surge window drives ``serve.fleet_pressure`` + an
   SLO burn. The brownout ladder escalates first (shed tiers); only
   once it is *exhausted* does the arbiter shrink training through the
   capacity file (``owner="arbiter"``); the ``FleetController``'s
   scale-up is lease-gated (denied → ``fleet.scaleup_denied`` +
   backoff; granted → second replica). When the surge passes the
   arbiter reclaims: the leased replica drains (zero-drop), the lease
   releases, full capacity is restored.
3. **training storm legs** — the shrink/grow the arbiter decided is
   replayed against the reference checkpoints exactly as the elastic
   supervisor would: resume at the shrink boundary on the
   half-size mesh with the BATCHSIZE x ``ACCUM_STEPS`` rescale, then
   grow back to the full mesh for the remainder.

Gates (exit non-zero unless ALL hold): training losses + final params
(and the shrunken midpoint) f32-ULP-equal to the uninterrupted
reference; serving p99 TTFT within ``COLOC_TTFT_SLO_MS`` through the
whole cycle; zero dropped and zero mixed-version requests (every
stream completes AND is bitwise-identical to an undisturbed serving
baseline; splices verified); closed program sets per replica; the
arbiter's shrink → lease-deny → lease-grant → reclaim → drain → grow
sequence observed with the capacity file round-tripping
8 → 4 → 8 under ``owner="arbiter"``.

Env knobs (defaults): ``COLOC_POOL_DEVICES`` (8),
``COLOC_SHRINK_STEP`` (6), ``COLOC_TTFT_SLO_MS`` (30000),
``COLOC_BROWNOUT_STAGES`` ("spec_off,max_new:8" — no shed stage: the
zero-drop gate is absolute), ``COLOC_SURGE_WINDOW`` ("8:60" router
ticks), ``SERVE_CHAOS_PLAN`` (early-tick crash/hang/slow/corrupt
recipe on replica 0), ``SERVE_CHAOS_SEED`` (0), ``SERVE_REQUESTS``
(24), ``SERVE_MAX_NEW`` (12), ``SERVE_TENANT_WEIGHTS``
("gold:3,silver:2,bronze:1"), ``BENCH_MODEL`` (lm_tiny),
``BENCH_VOCAB`` (64), plus ``OBS_DIR`` for the event streams the
pool-ownership timeline renders.

Usage::

    python scripts/coloc_bench.py [--events]
    make coloc-bench
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddeeplearning_tpu.serving.loadgen import (  # noqa: E402
    build_tenant_requests,
    percentile,
    profile_shapes,
)

#: Sequence length of the training legs (mirrors tests/test_elastic.py's
#: in-process oracle — tiny shapes, exact math).
TRAIN_SEQ_LEN = 16
#: Constant effective batch at every world size.
GLOBAL_BATCH = 16

#: Default serving-side storm: early-tick verbs on replica 0 only (the
#: scale-up replica must survive to drain zero-drop; a flap would burn
#: the breaker and remove the fleet's only pre-surge replica).
DEFAULT_CHAOS_PLAN = (
    "crash:tick=12,replica=0;hang:tick=24,replica=0,secs=0.5;"
    "slow:tick=36,replica=0,factor=6,secs=0.5;corrupt:tick=48,replica=0"
)


def _emit_record(record: dict) -> None:
    print(json.dumps(record), flush=True)
    from distributeddeeplearning_tpu import obs

    bus = obs.get_bus()
    bus.point("bench_result", **record)
    bus.flush()


def _ulp_close(tree_a, tree_b) -> bool:
    """tests/test_elastic.py's f32-ULP criterion as a predicate."""
    import jax
    import numpy as np

    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(tree_a)),
        jax.tree_util.tree_leaves(jax.device_get(tree_b)),
    ):
        try:
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-7)
        except AssertionError:
            return False
    return True


def _train_cfg(vocab: int, **kw):
    from distributeddeeplearning_tpu.config import TrainConfig

    base = dict(
        model="lm_tiny",
        num_classes=vocab,
        batch_size_per_device=2,
        fake_data_length=64,
        epochs=3,
        compute_dtype="float32",
        weight_decay=0.0,
        log_every_steps=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _train_fit(cfg, mesh, vocab: int):
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    data = SyntheticTokenDataset(
        length=cfg.fake_data_length,
        global_batch_size=GLOBAL_BATCH,
        seq_len=TRAIN_SEQ_LEN,
        vocab_size=vocab,
    )
    model = get_model(
        "lm_tiny", num_classes=vocab, dtype="float32",
        max_seq_len=TRAIN_SEQ_LEN,
    )
    return loop.fit(model, cfg, data, mesh=mesh, add_default_logger=False)


def run_serving(model, params, reqs, scfg, fcfg, max_len, *,
                chaos_plan, brownout_stages, surge_window, arbiter_kw,
                cap_file):
    """Serve the backlog once. With ``arbiter_kw`` the full colocation
    control plane is armed: chaos injector, brownout ladder,
    PoolArbiter, and a lease-gated FleetController, all driven by a
    deterministic surge window over router ticks (pressure high + SLO
    burning inside ``[a, b)``, calm outside)."""
    from distributeddeeplearning_tpu.serving import (
        BrownoutLadder,
        ChaosInjector,
        ControllerConfig,
        FleetController,
        Replica,
        Request,
        Router,
        parse_brownout_stages,
        parse_chaos_plan,
    )
    from distributeddeeplearning_tpu.serving.arbiter import (
        ArbiterConfig,
        PoolArbiter,
    )

    fcfg = dataclasses.replace(fcfg, chaos_plan="", brownout_stages="")
    router = Router(config=fcfg)
    obs_dir = os.environ.get("OBS_DIR") or None

    def make_replica(rid: int) -> Replica:
        return Replica(
            rid, model, params, scfg, max_len=max_len, obs_dir=obs_dir,
        )

    router.add_replica(make_replica(0), start=True, threaded=True)
    t0 = time.perf_counter()
    while not all(r.state == "ready" for r in router.replicas):
        if time.perf_counter() - t0 > 600:
            raise TimeoutError("fleet warmup timed out")
        time.sleep(0.01)
    # Warm pass so first-dispatch overheads stay out of the measurement.
    warm_placement = router.config.placement
    router.config.placement = "rr"
    router.submit(Request(
        prompt=reqs[0]["prompt"], max_new_tokens=2, temperature=0.0,
    ))
    router.drain(timeout=300)
    router.config.placement = warm_placement

    # Arm the drill AFTER the warm pass: chaos clock and surge window
    # both start at storm tick 0.
    router._ticks = 0
    chaos = None
    if chaos_plan:
        chaos = ChaosInjector(
            parse_chaos_plan(chaos_plan), seed=fcfg.chaos_seed
        )
        router.chaos = chaos
        for r in router.replicas:
            r.chaos = chaos

    arbiter = controller = ladder = None
    if arbiter_kw is not None:
        a, b = surge_window

        def surging() -> bool:
            return a <= router._ticks < b

        def slo_reader():
            return {
                "gauges": {
                    "serve.fleet_pressure": {
                        "value": 2.0 if surging() else 0.0
                    },
                },
                "slo": [
                    {"objective": "coloc_drill_ttft", "stat": "p99",
                     "metric": "serve.ttft", "burning": surging()}
                ] if surging() else [],
            }

        ladder = BrownoutLadder(
            parse_brownout_stages(brownout_stages),
            reader=slo_reader, refresh_s=0.0, escalate_ticks=2,
            recover_ticks=4,
        )
        router.brownout = ladder
        arbiter = PoolArbiter(
            ArbiterConfig(**arbiter_kw), cap_file, reader=slo_reader,
            ladder=ladder,
        )
        controller = FleetController(
            router, make_replica,
            ControllerConfig(
                min_replicas=1, max_replicas=2, up_ticks=2, down_ticks=4,
                denied_backoff_ticks=6,
            ),
            reader=lambda: 2.0 if surging() else 0.0,
            threaded_replicas=True,
            arbiter=arbiter,
        )

    engines_pre = {
        r.rid: (id(r.engine), r.engine.compile_count)
        for r in router.replicas
    }
    handles = []
    t0 = time.perf_counter()
    for r in reqs:
        handles.append((r, router.submit(Request(
            prompt=r["prompt"], max_new_tokens=r["max_new"],
            temperature=0.0,
        ), tenant=r["tenant"])))
    while router.step():
        if controller is not None:
            controller.tick()
            arbiter.tick()
        time.sleep(0.005)
    # Quiescence: the storm must settle AND — in the arbitrated run —
    # training must have reclaimed the whole pool (replica drained,
    # lease released, capacity restored). Hard cap so an undeliverable
    # directive cannot wedge the bench.
    t_q = time.perf_counter()
    while time.perf_counter() - t_q < 60.0:
        router.step()
        if controller is not None:
            controller.tick()
            arbiter.tick()
        settled = not any(
            r.state in ("faulted", "starting") for r in router.replicas
        )
        reclaimed = arbiter is None or (
            arbiter.train_world == arbiter.config.pool_devices
            and not arbiter.leases
        )
        if settled and reclaimed and (chaos is None or chaos.quiescent()):
            break
        time.sleep(0.01)
    dt = time.perf_counter() - t0

    tokens = sum(len(fh.new_tokens) for _, fh in handles)
    ttft_ms = [
        fh.ttft_s * 1e3 for _, fh in handles if fh.ttft_s is not None
    ]
    ledger = []
    for r in router.replicas:
        pre = engines_pre.get(r.rid)
        rebuilt = pre is None or pre[0] != id(r.engine)
        ledger.append({
            "replica": r.rid,
            "state": r.state,
            "rebuilt": rebuilt,
            "compile_count": r.engine.compile_count if r.engine else 0,
            "programs_expected":
                r.engine.programs_expected if r.engine else 0,
            "compiles_during_measure": (
                0 if rebuilt or pre is None
                else r.engine.compile_count - pre[1]
            ),
        })
    run = {
        "tokens_per_sec": round(tokens / dt, 1) if dt else 0.0,
        "wall_s": round(dt, 2),
        "tokens": tokens,
        "ttft_p50_ms": round(percentile(ttft_ms, 0.5), 2),
        "ttft_p99_ms": round(percentile(ttft_ms, 0.99), 2),
        "stats": dict(router.stats),
        "per_replica": ledger,
        "chaos_fired": list(chaos.fired) if chaos else [],
        "brownout_transitions":
            list(ladder.transitions) if ladder else [],
        "arbiter_decisions":
            list(arbiter.decisions) if arbiter else [],
        "controller_actions":
            list(controller.actions) if controller else [],
        "final_replica_count": len(router.replicas),
    }
    streams = [list(fh.new_tokens) for _, fh in handles]
    outcomes = [fh.finish_reason for _, fh in handles]
    splice_ok = all(fh.restart_consistent for _, fh in handles)
    mismatches = sum(fh.splice_mismatches for _, fh in handles)
    router.close()
    return run, streams, outcomes, splice_ok, mismatches, arbiter


def main() -> int:
    # The training legs need the full virtual pool BEFORE jax
    # initialises a backend (tests/conftest.py does the same).
    pool = int(os.environ.get("COLOC_POOL_DEVICES", "8"))
    flag = f"--xla_force_host_platform_device_count={pool}"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()
    if "--events" in sys.argv[1:] or os.environ.get("OBS_DIR"):
        from distributeddeeplearning_tpu import obs

        if not os.environ.get("OBS_DIR"):
            os.environ["OBS_DIR"] = os.path.join(
                "runs", f"coloc-bench-{int(time.time())}"
            )
        obs.configure_from_env()
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("COMPILATION_CACHE_DIR"):
        from distributeddeeplearning_tpu.training.warmup import (
            enable_persistent_cache,
        )

        enable_persistent_cache(os.environ["COMPILATION_CACHE_DIR"])

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu import faults
    from distributeddeeplearning_tpu.launch import _elastic_world
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.serving import FleetConfig, ServeConfig
    from distributeddeeplearning_tpu.serving.fleet.router import (
        parse_tenant_weights,
    )

    env = os.environ
    model_name = env.get("BENCH_MODEL", "lm_tiny")
    vocab = int(env.get("BENCH_VOCAB", "64"))
    n_requests = int(env.get("SERVE_REQUESTS", "24"))
    max_new = int(env.get("SERVE_MAX_NEW", "12"))
    seed = int(env.get("SERVE_SEED", "0"))
    profile = env.get("SERVE_PROFILE", "mixed")
    weights = parse_tenant_weights(
        env.get("SERVE_TENANT_WEIGHTS", "gold:3,silver:2,bronze:1")
    )
    shrink_step = int(env.get("COLOC_SHRINK_STEP", "6"))
    ttft_slo_ms = float(env.get("COLOC_TTFT_SLO_MS", "30000"))
    brownout_stages = env.get(
        "COLOC_BROWNOUT_STAGES", "spec_off,max_new:8"
    )
    surge_raw = env.get("COLOC_SURGE_WINDOW", "8:60")
    surge_window = tuple(int(x) for x in surge_raw.split(":"))
    chaos_plan = env.get("SERVE_CHAOS_PLAN") or DEFAULT_CHAOS_PLAN

    scfg = ServeConfig.from_env()
    if env.get("SERVE_SLOTS") is None:
        scfg.num_slots = 4
    if scfg.buckets is None:
        scfg.buckets = (8, 16)
    fcfg = FleetConfig.from_env()
    fcfg.replicas = 1  # training holds the pool; serving starts minimal
    fcfg.tenant_weights = weights
    if env.get("SERVE_REPLICA_MAX_RESTARTS") is None:
        fcfg.max_restarts = 2
    if env.get("SERVE_REPLICA_RESTART_BACKOFF") is None:
        fcfg.restart_backoff_s = 0.05
    if env.get("SERVE_STRAGGLER_FACTOR") is None:
        fcfg.straggler_factor = 4.0
    if env.get("SERVE_STRAGGLER_TICKS") is None:
        fcfg.straggler_ticks = 5
    if env.get("SERVE_QUARANTINE_TICKS") is None:
        fcfg.quarantine_ticks = 60
    if env.get("SERVE_PUMP_HEARTBEAT_S") is None:
        fcfg.heartbeat_timeout_s = 0.75

    workdir = env.get("OBS_DIR") or tempfile.mkdtemp(prefix="coloc-bench-")
    cap_file = os.path.join(workdir, "capacity.json")
    ckpt_dir = os.path.join(workdir, "ckpt")
    coloc_knobs = (
        f"pool={pool};shrink_step={shrink_step};stages={brownout_stages};"
        f"surge={surge_raw}"
    )

    shapes = profile_shapes(profile, max_new)
    serve_max_len = max(tp + n_new for tp, n_new in shapes)
    tenants = sorted(weights)
    metric = "lm_coloc_tokens_per_sec"
    try:
        devices = jax.devices()
        if len(devices) < pool:
            raise RuntimeError(
                f"pool needs {pool} devices, host has {len(devices)}"
            )
        mesh_full = create_mesh(devices=devices[:pool])

        # -- 1. uninterrupted training reference (the ULP oracle) ------
        steps_per_epoch = 64 // GLOBAL_BATCH
        epochs = 3
        ref = _train_fit(
            _train_cfg(
                vocab, model_dir=ckpt_dir, checkpoint_every_steps=1,
                checkpoint_async=False, lr_world_size=pool, elastic=True,
                checkpoint_keep=20, epochs=epochs,
            ),
            mesh_full, vocab,
        )
        ref_mid = _train_fit(
            _train_cfg(
                vocab, lr_world_size=pool,
                epochs=shrink_step // steps_per_epoch + 1,
            ),
            mesh_full, vocab,
        )

        # -- 2. serving surge + arbitration storm ----------------------
        model = get_model(
            model_name, num_classes=vocab, max_seq_len=serve_max_len,
            dtype=jnp.float32,
        )
        variables = jax.jit(model.init, static_argnames=("train",))(
            jax.random.PRNGKey(0),
            jnp.zeros((2, serve_max_len), jnp.int32),
            train=False,
        )
        params = nn.unbox(variables["params"])
        reqs = build_tenant_requests(
            tenants, n_requests, 0.0, seed, vocab, shapes
        )

        base, base_streams, base_outcomes, _, _, _ = run_serving(
            model, params, reqs, scfg, fcfg, serve_max_len,
            chaos_plan="", brownout_stages="", surge_window=surge_window,
            arbiter_kw=None, cap_file=cap_file,
        )
        min_train = _elastic_world(pool, pool // 2, 1)
        arbiter_kw = dict(
            pool_devices=pool,
            min_train_world=min_train,
            devices_per_replica=pool - min_train,
            shrink_ticks=2,
            grow_ticks=4,
            lease_ttl_s=600.0,
        )
        cap_probes = {}
        (storm, storm_streams, storm_outcomes, splice_ok, mismatches,
         arbiter) = run_serving(
            model, params, reqs, scfg, fcfg, serve_max_len,
            chaos_plan=chaos_plan, brownout_stages=brownout_stages,
            surge_window=surge_window, arbiter_kw=arbiter_kw,
            cap_file=cap_file,
        )
        decisions = storm["arbiter_decisions"]
        shrinks = [d for d in decisions if d["action"] == "shrink"]
        grows = [d for d in decisions if d["action"] == "grow"]
        cap_probes["final"] = faults.probe_capacity(cap_file, pool)
        with open(cap_file) as fh:
            cap_owner = json.load(fh).get("owner")

        # -- 3. training storm legs (replay the arbiter's decisions) ---
        shrunk_world = (
            shrinks[0]["to_world"] if shrinks else min_train
        )
        scale = pool // shrunk_world
        for s in faults.checkpoint_steps(ckpt_dir):
            if s > shrink_step:
                shutil.rmtree(os.path.join(ckpt_dir, str(s)))
        mesh_small = create_mesh(devices=devices[:shrunk_world])
        shrunk = _train_fit(
            _train_cfg(
                vocab, model_dir=ckpt_dir, checkpoint_every_steps=1,
                checkpoint_async=False,
                batch_size_per_device=2 * scale, accum_steps=scale,
                lr_world_size=pool, elastic=True,
                epochs=shrink_step // steps_per_epoch + 1,
                checkpoint_keep=20,
            ),
            mesh_small, vocab,
        )
        grown = _train_fit(
            _train_cfg(
                vocab, model_dir=ckpt_dir, checkpoint_every_steps=1,
                checkpoint_async=False, lr_world_size=pool, elastic=True,
                checkpoint_keep=20, epochs=epochs,
            ),
            mesh_full, vocab,
        )

        # -- gates ------------------------------------------------------
        completed = all(o in ("eos", "length") for o in storm_outcomes)
        parity = storm_streams == base_streams
        corrupt_armed = any(
            f["kind"] == "corrupt" for f in storm["chaos_fired"]
        )
        corrupt_detected = (not corrupt_armed) or (
            storm["stats"]["splice_mismatch"] >= 1
        )
        closed = all(
            row["compile_count"] == row["programs_expected"]
            for run in (base, storm) for row in run["per_replica"]
            if row["compile_count"]
        )
        clean = all(
            row["compiles_during_measure"] == 0
            for run in (base, storm) for row in run["per_replica"]
        )
        ttft_ok = storm["ttft_p99_ms"] <= ttft_slo_ms
        brownout_down = any(
            t["direction"] == "down"
            for t in storm["brownout_transitions"]
        )
        brownout_up = any(
            t["direction"] == "up"
            for t in storm["brownout_transitions"]
        )
        denies = [
            d for d in decisions if d["action"] == "lease_deny"
        ]
        grants = [
            d for d in decisions if d["action"] == "lease_grant"
        ]
        releases = [
            d for d in decisions if d["action"] == "lease_release"
        ]
        ctl_denied = [
            a for a in storm["controller_actions"]
            if a["action"] == "scaleup_denied"
        ]
        ctl_scaled = [
            a for a in storm["controller_actions"]
            if a["action"] == "scale_up"
        ]
        arbitration_ok = (
            len(shrinks) >= 1 and len(grows) >= 1
            and shrinks[0]["from_world"] == pool
            and shrinks[0]["to_world"] == shrunk_world
            and bool(grants) and bool(releases)
            and bool(ctl_scaled)
            and arbiter.train_world == pool
            and not arbiter.leases
        )
        capacity_ok = (
            cap_probes["final"] == pool and cap_owner == "arbiter"
        )
        mid_epoch_steps = (
            shrink_step // steps_per_epoch + 1
        ) * steps_per_epoch
        ulp_mid = (
            _ulp_close(ref_mid.state.params, shrunk.state.params)
            and shrunk.history[-1]["global_step"] == mid_epoch_steps
        )
        ulp_final = (
            _ulp_close(ref.state.params, grown.state.params)
            and _ulp_close(ref.state.opt_state, grown.state.opt_state)
            and grown.history[-1]["global_step"]
            == epochs * steps_per_epoch
        )
        loss_ok = bool(np.isclose(
            grown.history[-1]["loss"], ref.history[-1]["loss"],
            rtol=1e-4, atol=1e-6,
        ))
        ok = (
            completed and parity and splice_ok and corrupt_detected
            and closed and clean and ttft_ok
            and brownout_down and brownout_up
            and arbitration_ok and capacity_ok
            and ulp_mid and ulp_final and loss_ok
        )
        detail = {
            "profile": profile,
            "requests": n_requests,
            "pool_devices": pool,
            "shrunk_world": shrunk_world,
            "slots_per_replica": scfg.num_slots,
            "tenant_weights": weights,
            "platform": jax.devices()[0].platform,
            "coloc": coloc_knobs,
            "chaos_plan": chaos_plan,
            "chaos_seed": fcfg.chaos_seed,
            "brownout_stages": brownout_stages,
            "surge_window_ticks": list(surge_window),
            "undisturbed": base,
            "storm": storm,
            "ttft_slo_ms": ttft_slo_ms,
            "gates": {
                "zero_dropped": completed,
                "stream_parity": parity,
                "splice_verified": splice_ok,
                "splice_mismatches": mismatches,
                "corrupt_detected": corrupt_detected,
                "programs_closed": closed,
                "zero_untouched_recompiles": clean,
                "ttft_within_slo": ttft_ok,
                "brownout_step_down": brownout_down,
                "brownout_step_up": brownout_up,
                "arbitration_cycle": arbitration_ok,
                "lease_denied_then_granted": (
                    bool(denies or ctl_denied) and bool(grants)
                ),
                "capacity_roundtrip": capacity_ok,
                "ulp_midpoint": ulp_mid,
                "ulp_final": ulp_final,
                "loss_match": loss_ok,
            },
        }
        record = {
            "metric": metric,
            "value": storm["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": round(
                storm["tokens_per_sec"] / base["tokens_per_sec"], 2
            ) if base["tokens_per_sec"] else 0.0,
            "detail": detail,
        }
        _emit_record(record)
        if not ok:
            failed = [k for k, v in detail["gates"].items()
                      if v is False]
            print(f"COLOC GATES FAILED: {failed}", file=sys.stderr)
        return 0 if ok else 1
    except Exception as e:  # structured failure record, like bench.py
        _emit_record({
            "metric": metric, "value": 0.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "error": repr(e),
        })
        raise


if __name__ == "__main__":
    sys.exit(main())
