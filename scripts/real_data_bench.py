"""Real-data input-pipeline throughput (VERDICT r2 Missing #2 / Next #4).

Stages a real-JPEG dataset (synthetic images re-encoded to JPEG — it is
DECODE throughput that matters), then measures:

1. host-only decode+augment rate for each reader (ImageFolder threaded
   PIL, tf.data TFRecord, native TFRecord reader) — img/s and
   img/s/core;
2. end-to-end training img/s on the attached device with the real
   pipeline feeding the DP train step, vs the synthetic upper bound.

Usage::

    python scripts/real_data_bench.py prepare [--images 2048] [--root DIR]
    python scripts/real_data_bench.py host    [--root DIR] [--steps 8]
    python scripts/real_data_bench.py e2e     [--root DIR] [--batch 256]

Default root: ``.benchdata/`` (gitignored).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # `python scripts/real_data_bench.py` from anywhere
    sys.path.insert(0, _REPO)

DEFAULT_ROOT = os.path.join(_REPO, ".benchdata")


def prepare(root: str, n_images: int, image_size: int = 224, classes: int = 8):
    """ImageFolder tree of JPEGs (smooth low-frequency content — random
    noise would be unrealistically slow to decode) + TFRecord shards."""
    from PIL import Image

    from distributeddeeplearning_tpu.data.prepare import write_tfrecords

    folder = os.path.join(root, "imagefolder")
    rng = np.random.RandomState(42)
    for c in range(classes):
        os.makedirs(os.path.join(folder, f"class{c:03d}"), exist_ok=True)
    t0 = time.perf_counter()
    for i in range(n_images):
        c = i % classes
        # low-frequency pattern + mild noise ≈ natural-image entropy
        yy, xx = np.mgrid[0:image_size, 0:image_size].astype(np.float32)
        base = (
            127
            + 80 * np.sin(xx / (7 + c) + i)[..., None]
            * np.cos(yy / (11 + c))[..., None]
            + rng.normal(0, 12, (image_size, image_size, 3))
        )
        img = Image.fromarray(np.clip(base, 0, 255).astype(np.uint8))
        img.save(
            os.path.join(folder, f"class{c:03d}", f"img{i:06d}.jpg"),
            quality=85,
        )
    dt = time.perf_counter() - t0
    n, _ = write_tfrecords(folder, os.path.join(root, "tfrecords"), num_shards=8)
    sizes = []
    for dirpath, _, files in os.walk(folder):
        sizes += [os.path.getsize(os.path.join(dirpath, f)) for f in files]
    print(
        f"prepared {n} JPEGs ({np.mean(sizes) / 1024:.1f} KB avg) in {dt:.1f}s "
        f"+ 8 TFRecord shards under {root}"
    )


def _rate(name: str, it, steps: int, warmup: int = 2):
    n, t0 = 0, None
    for i, item in enumerate(it):
        if i == warmup:
            t0 = time.perf_counter()
            n = 0
        if i >= warmup:
            n += item[0].shape[0]
        if i >= warmup + steps:
            break
    if t0 is None or n == 0:
        raise SystemExit(
            f"{name}: dataset too small for warmup={warmup} + measurement "
            "— lower --batch or add --images"
        )
    dt = time.perf_counter() - t0
    cores = os.cpu_count() or 1
    print(
        f"{name:32s} {n / dt:8.1f} img/s host-only "
        f"({n / dt / cores:.1f} img/s/core, {cores} cores)"
    )
    return n / dt


def host(root: str, steps: int, batch: int, workers: int, worker_mode: str):
    from distributeddeeplearning_tpu.data.imagenet import (
        ImageFolderDataset,
        TFRecordImageNetDataset,
    )

    folder = os.path.join(root, "imagefolder")
    pattern = os.path.join(root, "tfrecords", "imagenet-*")
    results = {}
    ds = ImageFolderDataset(
        folder, global_batch_size=batch, train=True, num_workers=workers,
        worker_mode=worker_mode,
    )
    results["imagefolder"] = _rate(
        f"ImageFolder (PIL, {workers} {worker_mode}s)", ds.epoch(0), steps
    )
    try:
        tfds = TFRecordImageNetDataset(
            pattern, global_batch_size=batch, train=True
        )
        results["tfrecord-tfdata"] = _rate(
            "TFRecord (tf.data)", tfds.epoch(0), steps
        )
    except Exception as e:  # tensorflow optional
        print(f"TFRecord (tf.data) skipped: {e}")
    from distributeddeeplearning_tpu.data import make_dataset
    from distributeddeeplearning_tpu.config import TrainConfig

    cfg = TrainConfig(
        fake=False, data_dir=os.path.join(root, "tfrecords"),
        data_format="tfrecord-native", batch_size_per_device=batch,
        num_workers=workers, worker_mode=worker_mode,
    )
    try:
        nds = make_dataset(cfg, train=True)
        results["tfrecord-native"] = _rate(
            f"TFRecord (native reader, {workers} {worker_mode}s)",
            nds.epoch(0), steps,
        )
    except Exception as e:
        print(f"TFRecord (native) skipped: {e}")
    return results


def transfer(batch: int, image_size: int = 224, reps: int = 12):
    """Host→device transfer rate in isolation, per staging dtype — the
    middle leg of the e2e decomposition (host decode → transfer → step).
    Measures a sharded ``device_put`` of one global batch, fenced by a
    device readback (block_until_ready alone does not fence through the
    axon relay — see bench.py)."""
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh

    mesh = data_parallel_mesh(jax.device_count())
    rng = np.random.RandomState(0)
    base = rng.randint(0, 255, size=(batch, image_size, image_size, 3))
    touch = jax.jit(lambda x: jnp.sum(x[:, 0, 0, 0].astype(jnp.float32)))
    out = {}
    for name, arr in (
        ("float32", base.astype(np.float32)),
        ("bfloat16", base.astype(ml_dtypes.bfloat16)),
        ("uint8", base.astype(np.uint8)),
    ):
        labels = rng.randint(0, 1000, size=(batch,)).astype(np.int32)
        x, _ = shard_batch((arr, labels), mesh)
        float(touch(x))  # warm compile
        # (a) fenced: one put at a time — the latency-bound floor
        t0 = time.perf_counter()
        for _ in range(reps):
            x, _ = shard_batch((arr, labels), mesh)
            float(touch(x))
        fenced = (time.perf_counter() - t0) / reps
        # (b) streamed: enqueue every put, fence once — what the
        # prefetch pipeline actually achieves with transfers in flight
        t0 = time.perf_counter()
        xs = [shard_batch((arr, labels), mesh)[0] for _ in range(reps)]
        for x in xs:
            float(touch(x))
        streamed = (time.perf_counter() - t0) / reps
        mb = arr.nbytes / 1e6
        out[name] = batch / streamed
        print(
            f"transfer {name:8s}: {mb:6.1f} MB/batch  "
            f"fenced {fenced * 1e3:7.1f} ms ({batch / fenced:7.1f} img/s)  "
            f"streamed {streamed * 1e3:7.1f} ms "
            f"({mb / streamed / 1e3:5.2f} GB/s, {batch / streamed:7.1f} img/s)"
        )
    return out


def e2e(root: str, batch: int, steps: int):
    """Real pipeline → prefetch → compiled DP train step on the device.
    ``INPUT_STAGING=uint8`` stages raw bytes + on-device normalize."""
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data import make_dataset, staging_dtype
    from distributeddeeplearning_tpu.data.pipeline import prefetch_to_device
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    cfg = TrainConfig(
        fake=False,
        data_dir=os.path.join(root, "imagefolder"),
        batch_size_per_device=batch,
        num_workers=int(os.environ.get("NUM_WORKERS", "8")),
        input_staging=os.environ.get("INPUT_STAGING", "auto"),
    )
    data = make_dataset(cfg, train=True)
    model = ResNet(depth=50, num_classes=1000, dtype=jnp.bfloat16)
    mesh = data_parallel_mesh(jax.device_count())
    tx, _ = create_optimizer(cfg, steps_per_epoch=data.steps_per_epoch)
    state = replicate_state(create_train_state(model, cfg, tx), mesh)
    step = make_train_step(model, tx, mesh, cfg, donate_state=False)

    seen, t0 = 0, None
    warmup = 2
    metrics = None
    for i, batch_np in enumerate(
        prefetch_to_device(data.epoch(0), mesh, size=cfg.prefetch_batches)
    ):
        state, metrics = step(state, batch_np[:2])
        if i + 1 == warmup:
            float(metrics["loss"])  # fence: compile + pipeline spin-up done
            t0 = time.perf_counter()
            seen = 0
        elif i + 1 > warmup:
            seen += int(batch_np[0].shape[0])  # the GLOBAL batch delivered
        if i + 1 >= warmup + steps:
            break
    if t0 is None or metrics is None or seen == 0:
        raise SystemExit(
            "e2e: dataset too small for warmup + measurement — lower "
            "--batch or re-run `prepare` with more --images"
        )
    float(metrics["loss"])  # fence
    dt = time.perf_counter() - t0
    print(
        f"end-to-end real-data: {seen / dt:8.1f} img/s on "
        f"{jax.default_backend()} (batch {batch}, {seen} images)"
    )
    return seen / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=["prepare", "host", "transfer", "e2e"])
    ap.add_argument("--root", default=DEFAULT_ROOT)
    ap.add_argument("--images", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--worker-mode", default="thread",
                    choices=["thread", "process"])
    args = ap.parse_args()
    if args.mode == "prepare":
        prepare(args.root, args.images)
    elif args.mode == "host":
        host(args.root, args.steps, args.batch, args.workers, args.worker_mode)
    elif args.mode == "transfer":
        transfer(args.batch)
    else:
        e2e(args.root, args.batch, args.steps)


if __name__ == "__main__":
    sys.exit(main())
