"""Measure bytes/step + step time for ResNet50 perf variants on the chip.

PROFILE.md byte-reduction roadmap experiments: baseline vs bf16 BN stats
vs space-to-depth stem. Prints one line per variant with
``cost_analysis()["bytes accessed"]`` and 20-step wall time.

Usage: python scripts/profile_variants.py [variant ...]
Variants: base bf16stats s2d both fused
(default: all except ``fused`` — the recorded net-negative Pallas
fused-block experiment, ~2× slower; run it explicitly)
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models.resnet import ResNet
from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
from distributeddeeplearning_tpu.training import (
    create_optimizer,
    create_train_state,
    make_train_step,
)
from distributeddeeplearning_tpu.training.train_step import replicate_state

VARIANTS = {
    "base": {},
    "bf16stats": {"stats_dtype": jnp.bfloat16},
    "s2d": {"s2d_stem": True},
    "both": {"stats_dtype": jnp.bfloat16, "s2d_stem": True},
    # Pallas fused bottleneck segments (ops/pallas/fused_block.py)
    "fused": {"fused": True},
}


def run(name: str, batch_size: int = 256, steps: int = 20):
    kw = VARIANTS[name]
    cfg = TrainConfig(batch_size_per_device=batch_size)
    model = ResNet(depth=50, num_classes=1000, dtype=jnp.bfloat16, **kw)
    mesh = data_parallel_mesh(jax.device_count())
    tx, _ = create_optimizer(cfg, steps_per_epoch=cfg.steps_per_epoch())
    state = replicate_state(create_train_state(model, cfg, tx), mesh)
    step = make_train_step(model, tx, mesh, cfg)

    rng = np.random.RandomState(42)
    n = batch_size * jax.device_count()
    host = (
        rng.uniform(-1, 1, size=(n, 224, 224, 3)).astype(ml_dtypes.bfloat16),
        rng.randint(0, 1000, size=(n,)).astype(np.int32),
    )
    batch = shard_batch(host, mesh)

    # AOT-compile once and drive the compiled executable directly (the
    # jitted wrapper would compile the same program a second time).
    compiled = step.lower(state, batch).compile()
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    gb = cost.get("bytes accessed", float("nan")) / 1e9

    for _ in range(3):
        state, metrics = compiled(state, batch)
    float(metrics["loss"])  # fence
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = compiled(state, batch)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    ips = steps * n / dt
    print(
        f"{name:10s} bytes/step={gb:7.2f} GB  step={dt / steps * 1e3:6.1f} ms  "
        f"img/s={ips:7.1f}  loss={loss:.4f}",
        flush=True,
    )


if __name__ == "__main__":
    names = sys.argv[1:] or [v for v in VARIANTS if v != "fused"]
    for name in names:
        run(name)
