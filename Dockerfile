# Reproducible TPU-VM training image.
#
# The reference ships four images: a control-plane image (conda + az CLI
# + docker-in-docker, Docker/dockerfile:26-61) and three per-framework
# GPU images pinning CUDA/cuDNN/MPI/Horovod (e.g.
# HorovodTF/Docker/Dockerfile:5-58). On TPU the entire native tier those
# images exist to pin (NCCL, MPI, Horovod, cuDNN) is replaced by
# jax[tpu]+libtpu, so ONE image covers both roles: run it on a TPU VM
# for training, or anywhere for the CPU-mesh smoke path
# (XLA_FLAGS=--xla_force_host_platform_device_count=8).
#
#   make build   # docker build -t $DOCKER_REPOSITORY/ddl-tpu .
#   make smoke   # 2-process CPU-mesh training inside the image
#   make push    # push to the registry (reference 00_CreateImage cell 11)

FROM python:3.12-slim-bookworm

RUN apt-get update && apt-get install -y --no-install-recommends \
        git curl ca-certificates \
    && rm -rf /var/lib/apt/lists/*

# gcloud CLI — the control-plane role (reference Docker/dockerfile:49-54
# installs az CLI + azcopy; gcloud covers both provisioning and storage).
RUN curl -sSL https://sdk.cloud.google.com > /tmp/gcl \
    && bash /tmp/gcl --install-dir=/opt --disable-prompts \
    && rm /tmp/gcl
ENV PATH="/opt/google-cloud-sdk/bin:${PATH}"

WORKDIR /workspace

# Pinned python environment (reference pins TF 1.9/Horovod 0.13.2 etc.;
# here the equivalent contract is jax[tpu] + the input-pipeline deps).
RUN pip install --no-cache-dir \
        'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir \
        flax optax orbax-checkpoint chex einops \
        tensorflow-cpu pillow numpy pytest \
        jupyterlab nbconvert ipykernel scipy

COPY pyproject.toml ./
COPY distributeddeeplearning_tpu ./distributeddeeplearning_tpu
COPY examples ./examples
COPY notebooks ./notebooks
COPY scripts ./scripts
COPY tests ./tests
COPY launch.py bench.py __graft_entry__.py Makefile ./
RUN pip install --no-cache-dir -e .

# Interactive operator tier (reference Docker/dockerfile:26-61 +
# jupyter_notebook_config.py: its control-plane image serves the
# notebooks). Same notebooks, pinned runtime:
#   docker run -p 8888:8888 <image> \
#       jupyter lab --ip=0.0.0.0 --port=8888 --allow-root notebooks/
# and the headless proof is `docker run <image> make notebooks`.
EXPOSE 8888

# Smoke default: the reference's local container test runs
# `mpirun -np 2 … FAKE=True` (00_CreateImageAndTest cells 6-7); ours is
# the launcher's 2-process CPU-mesh equivalent.
CMD ["python", "launch.py", "--num-processes", "2", \
     "--devices-per-process", "4", "--platform", "cpu", \
     "--env", "FAKE=True", "--env", "FAKE_DATA_LENGTH=128", \
     "--env", "EPOCHS=1", "--env", "BATCHSIZE=4", \
     "--env", "IMAGE_SIZE=32", "--env", "NUM_CLASSES=8", \
     "--env", "MODEL=resnet18", \
     "examples/imagenet_keras_tpu.py"]
