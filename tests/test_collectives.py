import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.parallel import collectives
from distributeddeeplearning_tpu.parallel.mesh import (
    MeshConfig,
    batch_sharding,
    create_mesh,
    data_parallel_mesh,
    dp_size,
)


def test_topology(devices):
    assert collectives.size() == 8
    assert collectives.rank() == 0
    assert collectives.is_master()
    assert collectives.num_processes() == 1


def test_mesh_default_all_data(mesh8):
    assert mesh8.axis_names == ("data",)
    assert mesh8.shape["data"] == 8
    assert dp_size(mesh8) == 8


def test_mesh_wildcard_resolution():
    cfg = MeshConfig(axes=("data", "model"), shape=(-1, 2))
    assert cfg.resolve_shape(8) == (4, 2)
    mesh = create_mesh(cfg)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


def test_mesh_bad_shape_raises():
    import pytest

    with pytest.raises(ValueError):
        MeshConfig(axes=("data",), shape=(3,)).resolve_shape(8)
    with pytest.raises(ValueError):
        MeshConfig(axes=("a", "b"), shape=(-1, -1)).resolve_shape(8)


def test_allreduce_gradients_means_across_shards(mesh8):
    # Each device holds a distinct value; pmean must average all 8.
    x = jnp.arange(8.0)

    f = jax.jit(
        jax.shard_map(
            lambda v: collectives.allreduce_gradients(v, "data"),
            mesh=mesh8,
            in_specs=P("data"),
            out_specs=P("data"),
        )
    )
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


def test_allreduce_sum(mesh8):
    x = jnp.ones(8)
    f = jax.jit(
        jax.shard_map(
            lambda v: collectives.allreduce_sum(v, "data"),
            mesh=mesh8,
            in_specs=P("data"),
            out_specs=P("data"),
        )
    )
    np.testing.assert_allclose(np.asarray(f(x)), 8.0)


def test_broadcast_single_process_identity():
    tree = {"a": np.ones(3)}
    out = collectives.broadcast_from_master(tree)
    np.testing.assert_array_equal(out["a"], tree["a"])


def test_host_scalar_single_process():
    assert collectives.allreduce_host_scalar(2.5) == 2.5


def test_batch_sharding_spec(mesh8):
    sh = batch_sharding(mesh8)
    x = np.zeros((16, 4))
    arr = jax.device_put(x, sh)
    assert arr.sharding.spec == P("data")
    # each device gets 2 rows
    assert arr.addressable_shards[0].data.shape == (2, 4)


def test_create_mesh_axes_only_multiaxis(devices):
    # Regression: axes-only construction used to build (-1, -1) and raise.
    mesh = create_mesh(axes=("replica", "data"))
    assert mesh.shape["replica"] == 1 and mesh.shape["data"] == 8


def test_eval_step_requires_batch_axis(devices):
    import jax
    import pytest
    from jax.sharding import Mesh
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.training import make_eval_step

    mesh = Mesh(np.asarray(jax.devices()), ("model",))
    with pytest.raises(ValueError, match="batch axis"):
        make_eval_step(ResNet(depth=18), mesh)


# ---------------------------------------------------------------------------
# Hybrid DCN×ICI multi-slice mesh (round 5 — SURVEY.md §2a "ICI
# (intra-slice) and DCN (multi-slice)")
# ---------------------------------------------------------------------------

def test_hybrid_mesh_layout(devices):
    from distributeddeeplearning_tpu.parallel.mesh import create_hybrid_mesh

    mesh = create_hybrid_mesh(2)
    assert mesh.axis_names == ("replica", "data")
    assert mesh.shape["replica"] == 2 and mesh.shape["data"] == 4
    # Slices are contiguous in (process, id) order: slice 0 holds the
    # first 4 device ids — the virtual-device stand-in for hardware
    # slice grouping (Device.slice_index on real multi-slice jobs).
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert sorted(ids[0].tolist()) == sorted(d.id for d in devices[:4])
    assert sorted(ids[1].tolist()) == sorted(d.id for d in devices[4:])


def test_hybrid_mesh_inner_axes(devices):
    from distributeddeeplearning_tpu.parallel.mesh import create_hybrid_mesh

    mesh = create_hybrid_mesh(2, axes=("data", "model"), shape=(2, 2))
    assert mesh.axis_names == ("replica", "data", "model")
    assert dict(mesh.shape) == {"replica": 2, "data": 2, "model": 2}


def test_hybrid_mesh_rejects_bad_args(devices):
    import pytest

    from distributeddeeplearning_tpu.parallel.mesh import create_hybrid_mesh

    with pytest.raises(ValueError, match="slices"):
        create_hybrid_mesh(3)  # 8 devices don't split into 3 slices
    with pytest.raises(ValueError, match="implicit"):
        create_hybrid_mesh(2, axes=("replica", "data"))


def test_mesh_from_config_builds_hybrid(devices):
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.parallel.mesh import mesh_from_config

    cfg = TrainConfig(mesh_axes=("replica", "data"), mesh_shape=(2, 4))
    mesh = mesh_from_config(cfg)
    assert mesh.axis_names == ("replica", "data")
    assert mesh.shape["replica"] == 2 and mesh.shape["data"] == 4


def test_mesh_from_config_defaults_slices_from_hardware(devices, monkeypatch):
    """VERDICT r5 item 4: MESH_AXES=replica,data with NO MESH_SHAPE must
    follow the hardware slice count (Device.slice_index) — the old
    hardcoded 2 crashed every pod with a different slice count."""
    import types

    import jax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.parallel import mesh as mesh_mod

    captured = {}

    def fake_hybrid(num_slices, *, axes=("data",), shape=None, devices=None):
        captured["num_slices"] = num_slices
        return "mesh-sentinel"

    monkeypatch.setattr(mesh_mod, "create_hybrid_mesh", fake_hybrid)
    fakes = [
        types.SimpleNamespace(slice_index=i // 2, id=i, process_index=0)
        for i in range(8)  # 4 hardware slices x 2 chips
    ]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: fakes)
    cfg = TrainConfig(mesh_axes=("replica", "data"))  # no MESH_SHAPE
    assert mesh_mod.mesh_from_config(cfg) == "mesh-sentinel"
    assert captured["num_slices"] == 4

    # an explicit MESH_SHAPE always wins over hardware detection
    cfg2 = TrainConfig(mesh_axes=("replica", "data"), mesh_shape=(2, 4))
    mesh_mod.mesh_from_config(cfg2)
    assert captured["num_slices"] == 2


def test_mesh_from_config_errors_without_slice_topology(devices, monkeypatch):
    """VERDICT r5 item 4, the other half: devices with no slice_index
    (virtual CPU devices) carry nothing to derive the slice count from —
    the old silent `assume 2` is now an explicit error naming the fix."""
    import types

    import jax
    import pytest

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.parallel import mesh as mesh_mod

    cpu_fakes = [
        types.SimpleNamespace(id=i, process_index=0, platform="cpu")
        for i in range(8)
    ]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: cpu_fakes)
    cfg = TrainConfig(mesh_axes=("replica", "data"))  # no MESH_SHAPE
    with pytest.raises(ValueError, match="MESH_SHAPE"):
        mesh_mod.mesh_from_config(cfg)
    # ...and a PARTIAL slice_index (one device missing it) must error
    # too, not silently derive from the subset that has one.
    mixed = [
        types.SimpleNamespace(
            slice_index=i // 4, id=i, process_index=0, platform="tpu"
        )
        for i in range(7)
    ] + [types.SimpleNamespace(id=7, process_index=0, platform="tpu")]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: mixed)
    with pytest.raises(ValueError, match="MESH_SHAPE"):
        mesh_mod.mesh_from_config(cfg)
    # replica-only stays derivable with no hardware hint: every device
    # is its own replica (unambiguous, tested in the pure-replica test).


def test_hierarchical_pmean_matches_flat(devices):
    """Staged in-slice→cross-slice mean == single global mean (mean of
    means over equal groups), on a (replica=2, data=4) hybrid mesh."""
    from distributeddeeplearning_tpu.parallel.mesh import create_hybrid_mesh

    mesh = create_hybrid_mesh(2)
    x = jnp.arange(8.0)
    spec = P(("replica", "data"))

    hier = jax.jit(
        jax.shard_map(
            lambda v: collectives.hierarchical_allreduce_gradients(v),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
        )
    )
    flat = jax.jit(
        jax.shard_map(
            lambda v: jax.lax.pmean(v, ("replica", "data")),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
        )
    )
    np.testing.assert_allclose(np.asarray(hier(x)), np.full(8, 3.5))
    np.testing.assert_allclose(np.asarray(hier(x)), np.asarray(flat(x)))


def test_hybrid_train_step_runs_and_matches_dp(devices):
    """ONE train step on the hybrid (2-slice) mesh equals the same step on
    the flat dp mesh: hierarchy changes the reduction order, not the
    math. Also asserts the batch rides both axes."""
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
    from distributeddeeplearning_tpu.parallel.mesh import (
        create_hybrid_mesh,
        data_parallel_mesh,
    )
    from distributeddeeplearning_tpu.training import (
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    vocab, t = 64, 16
    cfg = TrainConfig(model="lm_tiny", num_classes=vocab, batch_size_per_device=2)
    model = TransformerLM(variant="tiny", vocab_size=vocab, max_seq_len=t)
    tx = optax.sgd(0.1)
    rng = np.random.RandomState(11)
    rows = rng.randint(0, vocab, size=(16, t + 1)).astype(np.int32)

    results = {}
    for name, mesh in (
        ("hybrid", create_hybrid_mesh(2)),
        ("flat", data_parallel_mesh()),
    ):
        state = replicate_state(
            create_train_state(
                model, cfg, tx, input_shape=(1, t), input_dtype=jnp.int32
            ),
            mesh,
        )
        batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh)
        if name == "hybrid":
            assert tuple(batch[0].sharding.spec) == (("replica", "data"),)
        step = make_train_step(model, tx, mesh, cfg, donate_state=False)
        new_state, metrics = step(state, batch)
        results[name] = (
            float(metrics["loss"]),
            np.asarray(
                jax.tree.leaves(new_state.params)[0], dtype=np.float32
            ),
        )
    assert np.isfinite(results["hybrid"][0])
    np.testing.assert_allclose(
        results["hybrid"][0], results["flat"][0], rtol=1e-5
    )
    np.testing.assert_allclose(
        results["hybrid"][1], results["flat"][1], rtol=1e-5, atol=1e-6
    )


def test_mesh_from_config_pure_replica(devices):
    # Regression (round-5 review): MESH_AXES=replica alone must build a
    # pure-replica mesh (every device its own slice), not crash on an
    # empty inner-shape expression.
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.parallel.mesh import (
        batch_sharding,
        mesh_from_config,
    )

    mesh = mesh_from_config(TrainConfig(mesh_axes=("replica",)))
    assert mesh.axis_names == ("replica",)
    assert mesh.shape["replica"] == 8
    assert batch_sharding(mesh).spec == P("replica")


def test_mesh_from_config_hybrid_shape_mismatch(devices):
    import pytest

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.parallel.mesh import mesh_from_config

    cfg = TrainConfig(mesh_axes=("replica", "data"), mesh_shape=(2,))
    with pytest.raises(ValueError, match="same length"):
        mesh_from_config(cfg)


def test_hybrid_mesh_pjit_engine_step(devices):
    """The GSPMD engine on a hybrid (replica,data) mesh: the rules table
    maps "batch" over BOTH axes, so one step runs with the DCN axis
    outermost — no engine changes needed."""
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
    from distributeddeeplearning_tpu.parallel.mesh import create_hybrid_mesh
    from distributeddeeplearning_tpu.training.pjit_step import (
        build_pjit_state,
        make_pjit_train_step,
    )

    mesh = create_hybrid_mesh(2)
    vocab, t = 64, 16
    cfg = TrainConfig(num_classes=vocab, batch_size_per_device=2, engine="pjit")
    model = TransformerLM(variant="tiny", vocab_size=vocab, max_seq_len=t)
    tx = optax.sgd(0.1)
    state = build_pjit_state(
        model, cfg, tx, mesh, input_shape=(1, t), input_dtype=jnp.int32
    )
    rng = np.random.RandomState(13)
    rows = rng.randint(0, vocab, size=(16, t + 1)).astype(np.int32)
    step = make_pjit_train_step(model, tx, mesh, cfg, donate_state=False)
    with mesh:
        batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh)
        assert tuple(batch[0].sharding.spec) == (("replica", "data"),)
        _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_hybrid_mesh_with_tensor_parallel_inner_axes(devices):
    """DCN×ICI×TP composition: 2 slices × (data=2, model=2) — the ViT
    TP step runs with replica outermost and the batch riding
    (replica, data); QKV stays sharded over model."""
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.vit import LOGICAL_RULES, ViT
    from distributeddeeplearning_tpu.parallel.mesh import create_hybrid_mesh
    from distributeddeeplearning_tpu.training.pjit_step import (
        create_sharded_train_state,
        make_pjit_train_step,
    )

    mesh = create_hybrid_mesh(2, axes=("data", "model"), shape=(2, 2))
    assert mesh.axis_names == ("replica", "data", "model")
    cfg = TrainConfig(num_classes=16, image_size=16, batch_size_per_device=2)
    model = ViT(variant="ti", patch_size=16, num_classes=16, dtype=jnp.bfloat16)
    tx = optax.sgd(0.1)
    state = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    assert tuple(qkv.sharding.spec) == (None, "model"), qkv.sharding
    rng = np.random.RandomState(17)
    step = make_pjit_train_step(model, tx, mesh, cfg, donate_state=False)
    with mesh:
        batch = shard_batch(
            (
                rng.randn(8, 16, 16, 3).astype(np.float32),
                rng.randint(0, 16, size=(8,)).astype(np.int32),
            ),
            mesh,
        )
        assert tuple(batch[0].sharding.spec) == (("replica", "data"),)
        _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
