import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.parallel import collectives
from distributeddeeplearning_tpu.parallel.mesh import (
    MeshConfig,
    batch_sharding,
    create_mesh,
    data_parallel_mesh,
    dp_size,
)


def test_topology(devices):
    assert collectives.size() == 8
    assert collectives.rank() == 0
    assert collectives.is_master()
    assert collectives.num_processes() == 1


def test_mesh_default_all_data(mesh8):
    assert mesh8.axis_names == ("data",)
    assert mesh8.shape["data"] == 8
    assert dp_size(mesh8) == 8


def test_mesh_wildcard_resolution():
    cfg = MeshConfig(axes=("data", "model"), shape=(-1, 2))
    assert cfg.resolve_shape(8) == (4, 2)
    mesh = create_mesh(cfg)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


def test_mesh_bad_shape_raises():
    import pytest

    with pytest.raises(ValueError):
        MeshConfig(axes=("data",), shape=(3,)).resolve_shape(8)
    with pytest.raises(ValueError):
        MeshConfig(axes=("a", "b"), shape=(-1, -1)).resolve_shape(8)


def test_allreduce_gradients_means_across_shards(mesh8):
    # Each device holds a distinct value; pmean must average all 8.
    x = jnp.arange(8.0)

    f = jax.jit(
        jax.shard_map(
            lambda v: collectives.allreduce_gradients(v, "data"),
            mesh=mesh8,
            in_specs=P("data"),
            out_specs=P("data"),
        )
    )
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


def test_allreduce_sum(mesh8):
    x = jnp.ones(8)
    f = jax.jit(
        jax.shard_map(
            lambda v: collectives.allreduce_sum(v, "data"),
            mesh=mesh8,
            in_specs=P("data"),
            out_specs=P("data"),
        )
    )
    np.testing.assert_allclose(np.asarray(f(x)), 8.0)


def test_broadcast_single_process_identity():
    tree = {"a": np.ones(3)}
    out = collectives.broadcast_from_master(tree)
    np.testing.assert_array_equal(out["a"], tree["a"])


def test_host_scalar_single_process():
    assert collectives.allreduce_host_scalar(2.5) == 2.5


def test_batch_sharding_spec(mesh8):
    sh = batch_sharding(mesh8)
    x = np.zeros((16, 4))
    arr = jax.device_put(x, sh)
    assert arr.sharding.spec == P("data")
    # each device gets 2 rows
    assert arr.addressable_shards[0].data.shape == (2, 4)


def test_create_mesh_axes_only_multiaxis(devices):
    # Regression: axes-only construction used to build (-1, -1) and raise.
    mesh = create_mesh(axes=("replica", "data"))
    assert mesh.shape["replica"] == 1 and mesh.shape["data"] == 8


def test_eval_step_requires_batch_axis(devices):
    import jax
    import pytest
    from jax.sharding import Mesh
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.training import make_eval_step

    mesh = Mesh(np.asarray(jax.devices()), ("model",))
    with pytest.raises(ValueError, match="batch axis"):
        make_eval_step(ResNet(depth=18), mesh)
