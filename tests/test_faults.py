"""Fault-tolerance fast battery: plan grammar, exit taxonomy, the
restart supervisor (jax-light e2e in the ``test_launch.py`` style), the
compile heartbeat, and the on-device non-finite guard.

The heavy resume-equivalence oracles (real training, 2-OS-process
worlds, bitwise param equality across a SIGKILL + supervisor resume)
live in ``tests/test_fault_tolerance.py``; this file is the
seconds-not-minutes tier that runs on every ``make fault-suite``.
"""

import json
import math
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from distributeddeeplearning_tpu import faults
from distributeddeeplearning_tpu.config import TrainConfig

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Unit: fault-plan grammar
# ---------------------------------------------------------------------------

def test_parse_fault_plan_grammar():
    plan = faults.parse_fault_plan(
        "kill:step=3,rank=1; term:step=5 ;hang:step=4,secs=9.5;"
        "nan:step=2;exit:step=6,code=121"
    )
    kinds = [f.kind for f in plan]
    assert kinds == ["kill", "term", "hang", "nan", "exit"]
    assert plan[0] == faults.Fault(kind="kill", step=3, rank=1)
    assert plan[1].rank is None  # no rank = every process
    assert plan[2].secs == 9.5
    assert plan[4].code == 121
    assert faults.parse_fault_plan("") == []


@pytest.mark.parametrize(
    "bad",
    [
        "explode:step=1",        # unknown kind
        "kill:rank=1",           # missing step
        "kill:step=0",           # steps are 1-based completed counts
        "kill:step=1,when=now",  # unknown key
        "kill:step",             # not key=value
    ],
)
def test_parse_fault_plan_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_fault_plan(bad)


def test_injector_rank_filtering_and_one_shot():
    plan = faults.parse_fault_plan("exit:step=2,rank=1;nan:step=3")
    inj0 = faults.FaultInjector(plan, rank=0)
    # rank-1 exit filtered out; the rankless nan stays
    assert not inj0.due_after(2)
    assert [f.kind for f in inj0.pending] == ["nan"]
    # nan faults never terminate — due_after ignores them
    assert not inj0.due_after(3)
    # poison fires once, then disarms
    batch = (np.ones((2, 2), np.float32), np.zeros((2,), np.int32))
    poisoned = inj0.poison(3, batch)
    assert np.isnan(np.asarray(poisoned[0])).all()
    assert np.asarray(poisoned[1]).dtype == np.int32  # ints untouched
    again = inj0.poison(3, batch)
    assert not np.isnan(np.asarray(again[0])).any()


def test_injector_from_env(monkeypatch):
    monkeypatch.delenv("FAULT_PLAN", raising=False)
    assert faults.FaultInjector.from_env() is None
    monkeypatch.setenv("FAULT_PLAN", "kill:step=3,rank=1")
    monkeypatch.setenv("DDL_PROCESS_ID", "0")
    assert faults.FaultInjector.from_env() is None  # targets rank 1 only
    monkeypatch.setenv("DDL_PROCESS_ID", "1")
    inj = faults.FaultInjector.from_env()
    assert inj is not None and inj.due_after(3)


# ---------------------------------------------------------------------------
# Unit: exit-code taxonomy
# ---------------------------------------------------------------------------

def test_exit_code_taxonomy():
    assert not faults.classify_exit(0).retryable
    assert not faults.classify_exit(faults.EXIT_NONFINITE).retryable
    assert not faults.classify_exit(faults.EXIT_TIMEOUT).retryable
    assert not faults.classify_exit(faults.EXIT_INTERRUPTED).retryable
    assert faults.classify_exit(faults.EXIT_HUNG).retryable
    assert faults.classify_exit(1).retryable
    kill = faults.classify_exit(-9)
    assert kill.retryable and kill.reason == "signal_SIGKILL"
    assert faults.normalize_rc(-9) == 137
    assert faults.normalize_rc(faults.EXIT_NONFINITE) == 121


def test_faultgen_cli_validate_and_exit_codes():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    res = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "validate",
         "kill:step=3,rank=1;hang:step=2,secs=5"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120, env=env,
    )
    assert res.returncode == 0, res.stderr
    assert "kill" in res.stdout and "process 1" in res.stdout
    assert "for 5s" in res.stdout
    bad = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "validate", "boom:step=1"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120, env=env,
    )
    assert bad.returncode == 2 and "invalid FAULT_PLAN" in bad.stderr
    codes = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "exit-codes"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120, env=env,
    )
    assert codes.returncode == 0
    assert "nonfinite_loss" in codes.stdout
    assert "signal_SIGKILL" in codes.stdout


def test_config_robustness_env_contract():
    cfg = TrainConfig.from_env({
        "CHECKPOINT_EVERY_STEPS": "25",
        "CHECKPOINT_ASYNC": "0",
        "RESUME": "false",
        "NONFINITE_ACTION": "warn",
    })
    assert cfg.checkpoint_every_steps == 25
    assert cfg.checkpoint_async is False
    assert cfg.resume is False
    assert cfg.nonfinite_action == "warn"
    # defaults: epoch-granular, async, resume on, guard aborting
    d = TrainConfig.from_env({})
    assert d.checkpoint_every_steps == 0
    assert d.checkpoint_async is True and d.resume is True
    assert d.nonfinite_action == "abort"
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    with pytest.raises(ValueError, match="NONFINITE_ACTION"):
        resolve_engine(d.replace(nonfinite_action="panic"))
    with pytest.raises(ValueError, match="CHECKPOINT_EVERY_STEPS"):
        resolve_engine(d.replace(checkpoint_every_steps=-1))


# ---------------------------------------------------------------------------
# E2e: restart supervisor over jax-light worlds (test_launch.py style)
# ---------------------------------------------------------------------------

def _run_launcher(args, timeout=600):
    return subprocess.run(
        [sys.executable, "launch.py", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=timeout,
    )


def test_supervisor_restarts_after_sigkill_and_resumes(tmp_path):
    """The crash → classify → backoff → relaunch → resume cycle: SIGKILL
    of process 1 after step 3 kills the world; the supervisor restarts
    it with resume enabled and the relaunched rank continues from its
    persisted progress instead of step 0."""
    obs_dir = tmp_path / "run"
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--max-restarts", "2",
            "--restart-backoff", "0.1",
            "--timeout", "120",
            "--obs-dir", str(obs_dir),
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "FAULT_PLAN=kill:step=3,rank=1",
            "--env", f"STATE_FILE={tmp_path}/state",
            "tests/_fault_child.py",
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "supervisor: attempt 0 failed (rc=-9, signal_SIGKILL" in out
    assert "restarting in 0.1s" in out
    # the relaunched rank resumed from its persisted step, not from 0
    assert "FAULT_CHILD_DONE 1 start=3" in out, out[-4000:]
    assert "FAULT_CHILD_DONE 0" in out
    # black box: SIGKILL cannot be handled, so the injector dumped the
    # ring itself before dying
    dump = obs_dir / "flight-p1.jsonl"
    assert dump.exists(), out[-2000:]
    head = json.loads(open(dump).readline())
    assert head["reason"] == "fault_kill"
    # per-attempt file identity: the restart did not truncate attempt 0
    assert (obs_dir / "events-p1.jsonl").exists()
    assert (obs_dir / "events-p1-r1.jsonl").exists()
    assert (obs_dir / "events-supervisor.jsonl").exists()
    # one merged timeline across both attempts + the supervisor
    recs = [json.loads(ln) for ln in open(obs_dir / "events.jsonl")]
    names = {r.get("name") for r in recs}
    assert {"attempt_start", "attempt_exit", "restart_scheduled",
            "fault_fired", "world_exit"} <= names
    assert len({r["run"] for r in recs if r.get("kind") == "meta"}) == 1
    # ...and the report renders the failure timeline
    rep = subprocess.run(
        [sys.executable, "scripts/obs_report.py", str(obs_dir)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "restart_scheduled" in rep.stdout
    assert "supervisor" in rep.stdout


def test_supervisor_treats_nonfinite_exit_as_terminal(tmp_path):
    """Exit 121 (the NaN guard's code) must NOT burn restarts: the run
    is deterministic, so a resume replays the same NaN."""
    res = _run_launcher(
        [
            "--num-processes", "1",
            "--max-restarts", "3",
            "--restart-backoff", "0.1",
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "FAULT_PLAN=exit:step=2,code=121",
            "tests/_fault_child.py",
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 121, out[-2000:]
    assert "non-retryable" in out
    assert "restarting in" not in out  # zero restart attempts


def test_supervisor_recovers_watchdog_killed_hang(tmp_path):
    """Hang → watchdog kill (125) → classified retryable → relaunch →
    resume past the hang step → clean exit."""
    res = _run_launcher(
        [
            "--num-processes", "1",
            "--max-restarts", "1",
            "--restart-backoff", "0.1",
            "--hang-timeout", "3",
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "FAULT_PLAN=hang:step=2,secs=300",
            "--env", f"STATE_FILE={tmp_path}/state",
            "tests/_fault_child.py",
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "declaring the world hung" in out
    assert "rc=125, world_hung" in out
    assert "FAULT_CHILD_DONE 0 start=2" in out  # resumed past the hang


def test_supervisor_suffixes_cache_dir(tmp_path):
    """The r5 KNOWN ISSUE guard: a restarted world sharing one
    ``COMPILATION_CACHE_DIR`` heap-corrupts this jax build, so every
    restart attempt must compile against ``<dir>-r<k>`` — the attempt-0
    dir is exported untouched, the relaunched world sees the suffix."""
    obs_dir = tmp_path / "run"
    cache = tmp_path / "xla-cache"
    res = _run_launcher(
        [
            "--num-processes", "1",
            "--max-restarts", "1",
            "--restart-backoff", "0.1",
            "--timeout", "120",
            "--obs-dir", str(obs_dir),
            "--env", "JAX_PLATFORMS=cpu",
            "--env", f"COMPILATION_CACHE_DIR={cache}",
            "--env", "FAULT_PLAN=kill:step=2,rank=0",
            "--env", f"STATE_FILE={tmp_path}/state",
            "tests/_fault_child.py",
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    # attempt 0: the configured dir, untouched
    assert f"FAULT_CHILD_CACHE_DIR 0 {cache}\n" in out
    # attempt 1: the suffixed dir, announced by the supervisor and
    # actually exported to the relaunched world
    assert "supervisor: restart attempt 1 uses compilation cache dir" in out
    assert f"FAULT_CHILD_CACHE_DIR 0 {cache}-r1" in out
    recs = [
        json.loads(ln) for ln in open(obs_dir / "events-supervisor.jsonl")
    ]
    suffixed = [r for r in recs if r.get("name") == "cache_dir_suffixed"]
    assert len(suffixed) == 1
    assert suffixed[0]["labels"]["dir"] == f"{cache}-r1"


def test_supervisor_restart_budget_exhausts(tmp_path):
    """A fault that recurs on every attempt (no state file -> no resume,
    the kill step is re-hit) drains max-restarts and surfaces the
    normalized (128+sig) final code."""
    res = _run_launcher(
        [
            "--num-processes", "1",
            "--max-restarts", "1",
            "--restart-backoff", "0.1",
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "FAULT_PLAN=kill:step=2",
            "tests/_fault_child.py",
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 137, out[-2000:]  # 128 + SIGKILL
    assert "restart budget exhausted (1)" in out


# ---------------------------------------------------------------------------
# E2e: compile heartbeat vs the hang watchdog
# ---------------------------------------------------------------------------

_HB_CHILD = textwrap.dedent(
    """
    import time
    from distributeddeeplearning_tpu.utils import heartbeat
    print("alive", flush=True)
    with heartbeat.during("aot_compile"):
        time.sleep(8)  # silent-but-compiling: used to be watchdog bait
    print("HB_CHILD_OK", flush=True)
    """
)


def test_heartbeat_keeps_compiling_world_alive(tmp_path):
    """An 8s-silent 'compile' under a 3s hang watchdog survives because
    the launcher exports DDL_HEARTBEAT_EVERY_S and counts the magic
    lines as liveness — while keeping them out of the streamed log."""
    script = tmp_path / "hb.py"
    script.write_text(_HB_CHILD)
    res = _run_launcher(
        [
            "--num-processes", "1",
            "--hang-timeout", "3",
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            str(script),
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "HB_CHILD_OK" in out
    from distributeddeeplearning_tpu.utils.heartbeat import MAGIC

    assert MAGIC not in out  # liveness lines never reach the log


def test_heartbeat_unit(monkeypatch):
    """during() is a no-op when disarmed and pumps MAGIC lines into its
    sink when armed."""
    import io
    import time

    from distributeddeeplearning_tpu.utils import heartbeat

    monkeypatch.delenv(heartbeat.ENV_VAR, raising=False)
    sink = io.StringIO()
    with heartbeat.during("x", sink=sink):
        time.sleep(0.1)
    assert sink.getvalue() == ""  # disarmed

    sink = io.StringIO()
    with heartbeat.during("compile", interval_s=0.02, sink=sink):
        time.sleep(0.15)
    lines = sink.getvalue().splitlines()
    assert len(lines) >= 3
    assert all(ln.startswith(heartbeat.MAGIC) for ln in lines)
    assert "compile" in lines[0]
    n = len(lines)
    time.sleep(0.1)  # thread must stop at context exit
    assert len(sink.getvalue().splitlines()) == n


# ---------------------------------------------------------------------------
# In-process: the on-device non-finite guard
# ---------------------------------------------------------------------------

def _guard_cfg(**kw):
    base = dict(
        model="resnet18",
        num_classes=8,
        image_size=8,
        batch_size_per_device=2,
        fake_data_length=32,
        epochs=1,
        compute_dtype="float32",
        log_every_steps=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _guard_fit(cfg, mesh8):
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticImageDataset,
    )
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    data = SyntheticImageDataset(
        length=cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        image_size=cfg.image_size,
        num_classes=cfg.num_classes,
    )
    model = get_model("resnet18", num_classes=8, dtype="float32")
    return loop.fit(model, cfg, data, mesh=mesh8, add_default_logger=False)


def test_nonfinite_guard_aborts_with_distinct_exit_code(
    mesh8, monkeypatch
):
    """FAULT_PLAN NaN injection -> the accumulator's on-device counter
    trips at the epoch boundary -> NonFiniteLossError carrying exit 121
    (SystemExit subclass: an uncaught escape exits the process with the
    supervisor's non-retryable code)."""
    monkeypatch.setenv("FAULT_PLAN", "nan:step=1")
    monkeypatch.delenv("DDL_PROCESS_ID", raising=False)
    with pytest.raises(faults.NonFiniteLossError) as ei:
        _guard_fit(_guard_cfg(), mesh8)
    assert ei.value.code == faults.EXIT_NONFINITE
    assert isinstance(ei.value, SystemExit)
    assert ei.value.nonfinite_steps >= 1


def test_nonfinite_guard_warn_mode_continues(mesh8, monkeypatch):
    monkeypatch.setenv("FAULT_PLAN", "nan:step=1")
    monkeypatch.delenv("DDL_PROCESS_ID", raising=False)
    res = _guard_fit(_guard_cfg(nonfinite_action="warn"), mesh8)
    assert math.isnan(res.history[0]["loss"])
    # the guard's count never leaks into user-facing history
    assert "nonfinite_steps" not in res.history[0]


def test_guard_costs_zero_extra_syncs(mesh8):
    """The acceptance invariant: with the guard armed (default abort
    mode), the loop still performs exactly one host materialisation per
    epoch — detection rides the existing epoch sync."""
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop
    from distributeddeeplearning_tpu.utils import hostsync

    cfg = TrainConfig(
        model="lm_tiny", num_classes=64, batch_size_per_device=2,
        fake_data_length=32, epochs=2, compute_dtype="float32",
        weight_decay=0.0, log_every_steps=0, nonfinite_action="abort",
    )
    data = SyntheticTokenDataset(
        length=cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        seq_len=16, vocab_size=64,
    )
    model = get_model(
        "lm_tiny", num_classes=64, dtype="float32", max_seq_len=16
    )
    hostsync.accountant().reset()
    with hostsync.track():
        res = loop.fit(
            model, cfg, data, mesh=mesh8, add_default_logger=False
        )
    acct = hostsync.accountant()
    assert acct.count == cfg.epochs, acct.by_label
    assert res.perf["host_sync_count"] == cfg.epochs
    assert math.isfinite(res.history[-1]["loss"])  # guard stayed quiet
