"""Unit oracle for batch-split per-replica BatchNorm (models/norm.py).

The engine-level equality test (``tests/test_pjit_step.py``) proves the
pjit engine matches the dp engine end-to-end; this file pins the module
itself: G-group statistics must equal running ``nn.BatchNorm``
separately on each batch split (what each dp replica computes), with
running stats averaged across splits (what the dp engine's ``pmean``
stores).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distributeddeeplearning_tpu.models.norm import (
    BatchNorm,
    active_groups,
    per_replica_bn,
)


def _init(mod, x):
    return mod.init(jax.random.PRNGKey(0), x)


def test_grouped_equals_per_split_batchnorm():
    x = jnp.asarray(np.random.RandomState(0).randn(8, 5, 6).astype(np.float32))
    ours = BatchNorm(use_running_average=False, momentum=0.9)
    ref = nn.BatchNorm(use_running_average=False, momentum=0.9)
    variables = _init(ref, x)  # identical trees — share them

    with per_replica_bn(2):
        y, mutated = ours.apply(variables, x, mutable=["batch_stats"])

    y_ref, ref_stats = [], []
    for half in jnp.split(x, 2, axis=0):
        yh, mh = ref.apply(variables, half, mutable=["batch_stats"])
        y_ref.append(yh)
        ref_stats.append(mh["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(jnp.concatenate(y_ref, 0)), atol=1e-5
    )
    # running stats = mean over splits of per-split updates (dp's pmean)
    for key in ("mean", "var"):
        want = (ref_stats[0][key] + ref_stats[1][key]) / 2
        np.testing.assert_allclose(
            np.asarray(mutated["batch_stats"][key]), np.asarray(want),
            atol=1e-6,
        )


def test_no_context_is_plain_batchnorm():
    x = jnp.asarray(np.random.RandomState(1).randn(6, 4).astype(np.float32))
    ours = BatchNorm(use_running_average=False)
    ref = nn.BatchNorm(use_running_average=False)
    variables = _init(ref, x)
    y, m = ours.apply(variables, x, mutable=["batch_stats"])
    y_ref, m_ref = ref.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))
    for key in ("mean", "var"):
        np.testing.assert_array_equal(
            np.asarray(m["batch_stats"][key]),
            np.asarray(m_ref["batch_stats"][key]),
        )


def test_eval_mode_ignores_grouping():
    x = jnp.asarray(np.random.RandomState(2).randn(8, 4).astype(np.float32))
    ours = BatchNorm(use_running_average=True)
    variables = nn.BatchNorm(use_running_average=True).init(
        jax.random.PRNGKey(0), x
    )
    with per_replica_bn(4):
        y = ours.apply(variables, x)
    y_ref = ours.apply(variables, x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))


def test_context_restores_on_exit():
    assert active_groups() == 1
    with per_replica_bn(8):
        assert active_groups() == 8
    assert active_groups() == 1


def test_indivisible_batch_falls_back():
    """B % G != 0 cannot be grouped — defer to plain BatchNorm rather
    than crash (the engine only requests G that divides the batch, but
    the module must stay safe standalone) AND surface the semantics
    downgrade with a warning (ADVICE r4: the silent sync-BN fallback
    must be visible)."""
    import pytest

    x = jnp.asarray(np.random.RandomState(3).randn(6, 4).astype(np.float32))
    ours = BatchNorm(use_running_average=False)
    ref = nn.BatchNorm(use_running_average=False)
    variables = _init(ref, x)
    with per_replica_bn(4):
        with pytest.warns(UserWarning, match="sync-BN"):
            y, _ = ours.apply(variables, x, mutable=["batch_stats"])
    y_ref, _ = ref.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))
