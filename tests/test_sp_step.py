"""Sequence-parallel LM training: DP×SP step matches single-device.

The core long-context claim: sharding the sequence over a mesh axis
(ring attention + globalised positions) produces the SAME training
update as unsharded training — asserted against a plain single-device
step on the full batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training import (
    create_train_state,
    make_sp_train_step,
)
from distributeddeeplearning_tpu.training.train_step import (
    cross_entropy_loss,
    replicate_state,
)
from jax.sharding import NamedSharding, PartitionSpec as P

VOCAB = 32
T = 32  # global sequence; 8 tokens per seq shard on the 2x4 mesh
B = 4
CFG = TrainConfig(
    num_classes=VOCAB, batch_size_per_device=2, weight_decay=0.0,
    compute_dtype="float32",
)


def _model(seq_axis=None, impl="xla"):
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T,
        dtype=jnp.float32, attn_impl=impl, seq_axis=seq_axis,
    )


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    rows = rng.randint(0, VOCAB, size=(B, T + 1)).astype(np.int32)
    return rows[:, :-1], rows[:, 1:]


@pytest.fixture(scope="module")
def sp_mesh(devices):
    return create_mesh(axes=("data", "seq"), shape=(2, 4))


def test_sp_step_matches_single_device(sp_mesh):
    """One DP×SP step == one full-batch single-device step (params+loss)."""
    tx = optax.sgd(0.1)
    sp_model = _model(seq_axis="seq", impl="ring")
    ref_model = _model()
    state0 = create_train_state(
        ref_model, CFG, tx, input_shape=(1, T), input_dtype=jnp.int32
    )
    tokens, labels = _batch()

    # reference: plain single-device step on the full [B, T] batch
    def ref_step(params, opt_state):
        def loss_fn(p):
            logits = ref_model.apply({"params": p}, tokens, train=False)
            return cross_entropy_loss(logits, labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return jax.tree.map(lambda p, u: p + u, params, updates), loss

    ref_params, ref_loss = ref_step(state0.params, state0.opt_state)

    # SP: tokens sharded over (data, seq)
    spec = NamedSharding(sp_mesh, P("data", "seq"))
    sp_state = replicate_state(state0, sp_mesh)
    step = make_sp_train_step(sp_model, tx, sp_mesh, CFG, donate_state=False)
    batch = (
        jax.device_put(tokens, spec),
        jax.device_put(labels, spec),
    )
    new_state, metrics = step(sp_state, batch)

    np.testing.assert_allclose(float(metrics["loss"]), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree.leaves(new_state.params), jax.tree.leaves(ref_params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_sp_step_loss_decreases(sp_mesh):
    tx = optax.sgd(0.5)
    model = _model(seq_axis="seq", impl="ring")
    state = replicate_state(
        create_train_state(
            model, CFG, tx, input_shape=(1, T), input_dtype=jnp.int32
        ),
        sp_mesh,
    )
    step = make_sp_train_step(model, tx, sp_mesh, CFG, donate_state=False)
    spec = NamedSharding(sp_mesh, P("data", "seq"))
    tokens, labels = _batch(seed=3)
    batch = (jax.device_put(tokens, spec), jax.device_put(labels, spec))
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_sp_step_rejects_mismatched_model(sp_mesh):
    tx = optax.sgd(0.1)
    with pytest.raises(ValueError, match="seq_axis"):
        make_sp_train_step(_model(), tx, sp_mesh, CFG)


def test_sp_step_rejects_non_ring_impl(sp_mesh):
    tx = optax.sgd(0.1)
    with pytest.raises(ValueError, match="ring"):
        make_sp_train_step(_model(seq_axis="seq", impl="xla"), tx, sp_mesh, CFG)


def test_sp_step_rejects_overlong_global_sequence(sp_mesh):
    """max_seq_len guards the GLOBAL sequence: local shards would pass the
    model's own check while dynamic_slice silently clamps positions."""
    tx = optax.sgd(0.1)
    model = TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T // 2,  # global T too long
        dtype=jnp.float32, attn_impl="ring", seq_axis="seq",
    )
    state = replicate_state(
        create_train_state(
            model, CFG, tx, input_shape=(1, T // 2), input_dtype=jnp.int32
        ),
        sp_mesh,
    )
    step = make_sp_train_step(model, tx, sp_mesh, CFG, donate_state=False)
    spec = NamedSharding(sp_mesh, P("data", "seq"))
    tokens, labels = _batch()
    with pytest.raises(ValueError, match="exceeds model.max_seq_len"):
        step(
            state,
            (jax.device_put(tokens, spec), jax.device_put(labels, spec)),
        )


def test_ring_rejects_unsharded_sequence(sp_mesh):
    """A bound-but-unsharded ring axis must raise, not compute garbage."""
    from distributeddeeplearning_tpu.parallel.ring_attention import ring_attention
    from distributeddeeplearning_tpu.utils import compat

    if compat.shimmed("pcast"):
        pytest.skip(
            "detection needs the vma type system (ring_attention's pcast "
            "probe); this jax has no vma — the check degrades to off"
        )

    def f(q):
        return ring_attention(q, q, q, axis_name="seq")

    q = jnp.zeros((2, 8, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="must actually be sharded"):
        jax.jit(
            jax.shard_map(
                f, mesh=sp_mesh, in_specs=P(), out_specs=P()
            )
        )(q)
