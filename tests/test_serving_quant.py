"""Quantized decode tier oracles (kv_dtype / weight_dtype = "int8" /
"fp8" — plus the fused-kernel env surface they share).

The quantized tier's contract, pinned here (CPU tier):

* **Cache shape contract** — ``kv_dtype="int8"`` turns the decode
  caches (dense rows AND the paged block pool) into int8 payload + f32
  per-head scale leaves; everything the engine templates from
  ``decode_cache_shapes`` follows.
* **Bitwise determinism** — two identical request loads produce
  bitwise-identical token streams AND bitwise-identical quantized pool
  bytes (quantize is round-half-to-even; no data-dependent branches).
* **Paged twin** — the quantized PAGED engine emits token-for-token
  what the quantized DENSE engine emits under greedy and seeded
  sampling: quantization and the block-pool layout compose without
  interacting.
* **Closed program set** — the int8 engine compiles exactly
  ``len(buckets) + 1`` programs and an admission/eviction churn
  triggers ZERO backend compiles (the existing churn oracle, extended
  to the quantized configuration).
* **Byte accounting** — ``byte_accounting()`` / the warmup gauges
  report int8 + scale bytes (never payload-only), and the quantized
  engine's per-token KV bytes land strictly below the native engine's.
* **force_token** — the teacher-forcing hook the serve_bench quality
  oracle uses: forcing the token the engine would have fed anyway is a
  no-op (self-replay == free run, bitwise), and forcing an empty slot
  is an error.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.inference import (
    decode_cache_shapes,
    decode_variant,
)
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.serving import (
    ReqSpec,
    Request,
    ServeConfig,
    Server,
    SlotEngine,
)

VOCAB, MAX_LEN = 64, 32
BUCKETS = (4, 8, 16)


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn
    import jax

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


@pytest.fixture(scope="module")
def _q_engine(model, params):
    eng = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS,
        kv_dtype="int8", weight_dtype="int8",
    )
    eng.warmup()
    return eng


@pytest.fixture
def q_engine(_q_engine):
    for s in _q_engine.active_slots:
        _q_engine.release(s)
    yield _q_engine
    for s in _q_engine.active_slots:
        _q_engine.release(s)


def _prompt(rng, n):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _flat_pool(engine):
    from flax import traverse_util
    from flax.core import unfreeze

    return {
        "/".join(p): np.asarray(leaf)
        for p, leaf in traverse_util.flatten_dict(
            unfreeze(engine._pool)
        ).items()
    }


def test_cache_shapes_carry_int8_and_scales(model):
    dense = decode_cache_shapes(
        decode_variant(model, kv_dtype="int8"), 2, MAX_LEN
    )
    from flax import traverse_util

    flat = {
        p[-1]: leaf
        for p, leaf in traverse_util.flatten_dict(dict(dense)).items()
    }
    assert flat["cached_k"].dtype == jnp.int8
    assert flat["cached_v"].dtype == jnp.int8
    assert flat["cached_k_scale"].dtype == jnp.float32
    # per head per position: K shape minus the head_dim axis, kept as 1
    assert flat["cached_k_scale"].shape == flat["cached_k"].shape[:-1] + (1,)
    paged = decode_cache_shapes(
        decode_variant(model, paged_blocks=9, paged_block_size=4,
                       kv_dtype="int8"),
        2, MAX_LEN,
    )
    pflat = {
        p[-1]: leaf
        for p, leaf in traverse_util.flatten_dict(dict(paged)).items()
    }
    assert pflat["paged_k"].dtype == jnp.int8
    assert pflat["paged_k_scale"].dtype == jnp.float32
    assert pflat["paged_k_scale"].shape == pflat["paged_k"].shape[:-1] + (1,)
    # invalid dtype rejected at the module boundary
    with pytest.raises(ValueError, match="kv_dtype"):
        decode_cache_shapes(
            decode_variant(model, kv_dtype="int4"), 1, MAX_LEN
        )


def _run_load(engine, seeds):
    rng = np.random.RandomState(7)
    server = Server(engine, prefills_per_step=2)
    handles = [
        server.submit(Request(
            prompt=_prompt(rng, n), max_new_tokens=m, temperature=t,
            top_k=k, rng=seed,
        ))
        for (n, m, t, k), seed in zip(
            [(3, 6, 0.0, None), (7, 9, 0.9, 8), (12, 4, 0.0, None),
             (16, 8, 0.7, 5), (5, 10, 1.1, 12), (9, 5, 0.0, None)],
            seeds,
        )
    ]
    server.drain()
    assert all(h.status == "done" for h in handles)
    return [list(h.new_tokens) for h in handles]


def test_quantized_write_gather_bitwise_deterministic(q_engine):
    """Same load twice through the quantized pool: token streams AND
    the int8/scale pool bytes bitwise-identical (run 2 starts from run
    1's residue — released rows are masked and fully overwritten, so
    state convergence is part of the claim)."""
    first = _run_load(q_engine, seeds=range(6))
    snap1 = _flat_pool(q_engine)
    second = _run_load(q_engine, seeds=range(6))
    snap2 = _flat_pool(q_engine)
    assert first == second
    for name in snap1:
        assert np.array_equal(snap1[name], snap2[name]), name


def test_paged_twin_matches_dense_quantized(model, params, q_engine):
    """Quantized paged engine == quantized dense engine token-for-token
    (greedy + seeded sampling mix) — layout and quantization compose."""
    dense_streams = _run_load(q_engine, seeds=range(10, 16))
    paged = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS,
        kv_layout="paged", block_size=4,
        kv_dtype="int8", weight_dtype="int8",
    )
    paged.warmup()
    paged_streams = _run_load(paged, seeds=range(10, 16))
    assert dense_streams == paged_streams


def test_int8_churn_zero_compiles_and_closed_programs(q_engine):
    """The existing churn oracle extended to the int8 config: programs
    == buckets + 1, admission/eviction/cancel churn compiles nothing."""
    from jax._src import monitoring

    assert q_engine.compile_count == len(q_engine.buckets) + 1
    q_engine.warmup()  # idempotent
    assert q_engine.compile_count == len(q_engine.buckets) + 1

    compiles = []
    monitoring.register_event_duration_secs_listener(
        lambda event, duration, **kw: compiles.append(event)
        if "backend_compile" in event else None
    )
    baseline = len(compiles)
    rng = np.random.RandomState(3)
    server = Server(q_engine, prefills_per_step=2)
    mk = lambda n, m, **kw: server.submit(Request(  # noqa: E731
        prompt=_prompt(rng, n), max_new_tokens=m, **kw
    ))
    wave = [
        mk(3, 8, temperature=0.9, top_k=8, rng=1),
        mk(8, 10, rng=2),
        mk(13, 10, temperature=0.7, top_k=5, rng=3),
        mk(16, 6, temperature=1.1, top_k=12, top_p=0.9, rng=4),
    ]
    for _ in range(4):
        server.step()
    wave[1].cancel()
    mk(5, 7, temperature=0.8, top_k=6, rng=5)  # reuses the freed slot
    server.drain()
    assert len(compiles) == baseline, compiles[baseline:]
    assert q_engine.compile_count == len(q_engine.buckets) + 1


def test_byte_accounting_int8_below_native(model, params, q_engine):
    native = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS
    )  # accounting needs no warmup
    a_nat = native.byte_accounting()
    a_q = q_engine.byte_accounting()
    assert a_q["kv_bytes_per_token"] < a_nat["kv_bytes_per_token"]
    assert a_q["param_bytes"] < a_nat["param_bytes"]
    # scales are IN the numbers: per-token bytes exceed the bare int8
    # payload (heads * head_dim * 2 tensors * layers)
    heads, head_dim, layers = 4, 32, 2
    payload_only = heads * head_dim * 2 * layers
    assert a_q["kv_bytes_per_token"] > payload_only
    # and the f32 engine's KV shrinks by ~the dtype ratio (scale
    # overhead keeps it above exactly 4x-less)
    assert a_q["kv_bytes_per_token"] < a_nat["kv_bytes_per_token"] / 3


def test_warmup_emits_byte_gauges(model, params, tmp_path):
    from distributeddeeplearning_tpu import obs

    bus = obs.configure(str(tmp_path), run_id="quant-test", proc=0,
                        install_handlers=False)
    try:
        eng = SlotEngine(
            model, params, num_slots=2, max_len=MAX_LEN, buckets=(8,),
            kv_dtype="int8", weight_dtype="int8",
        )
        eng.warmup()
        bus.flush()
    finally:
        obs.reset()
    from distributeddeeplearning_tpu.obs.report import (
        load, render, summarize,
    )

    summary = summarize(load([str(tmp_path)]))
    srv = summary["serving"]
    acct = eng.byte_accounting()
    assert srv["kv_bytes_per_token"] == pytest.approx(
        acct["kv_bytes_per_token"]
    )
    assert srv["param_bytes"] == pytest.approx(acct["param_bytes"])
    text = render(summary)
    assert "KV/token" in text


def test_force_token_self_replay_is_noop(q_engine):
    """Forcing the engine's own greedy stream back in reproduces it
    bitwise — the teacher-forcing hook changes context, not math."""
    rng = np.random.RandomState(9)
    prompt = _prompt(rng, 6)
    first, _ = q_engine.prefill(0, ReqSpec(prompt=prompt,
                                           max_new_tokens=8))
    free = [first]
    for _ in range(7):
        [(slot, tok, _e)] = q_engine.decode_step()
        free.append(tok)
    q_engine.release(0)
    first2, _ = q_engine.prefill(0, ReqSpec(prompt=prompt,
                                            max_new_tokens=8))
    forced = [first2]
    for i in range(7):
        q_engine.force_token(0, free[i])  # what it fed itself anyway
        [(slot, tok, _e)] = q_engine.decode_step()
        forced.append(tok)
    q_engine.release(0)
    assert forced == free
    with pytest.raises(ValueError, match="not occupied"):
        q_engine.force_token(1, 0)


def test_serve_config_quant_env_and_kwargs():
    cfg = ServeConfig.from_env({
        "SERVE_KV_DTYPE": "int8", "SERVE_WEIGHT_DTYPE": "int8",
    })
    assert cfg.kv_dtype == "int8" and cfg.weight_dtype == "int8"
    kw = cfg.engine_kwargs()
    assert kw["kv_dtype"] == "int8" and kw["weight_dtype"] == "int8"
    dflt = ServeConfig.from_env({})
    assert dflt.kv_dtype == "bf16" and dflt.weight_dtype == "bf16"
    with pytest.raises(ValueError, match="kv_dtype"):
        SlotEngine(
            TransformerLM(variant="tiny", vocab_size=8, max_seq_len=8),
            {}, kv_dtype="fp4",
        )
    with pytest.raises(ValueError, match="weight_dtype"):
        SlotEngine(
            TransformerLM(variant="tiny", vocab_size=8, max_seq_len=8),
            {}, weight_dtype="fp4",
        )


def test_serve_config_kernel_and_fp8_env_surface():
    """The round-10 knobs ride the same registry: fp8 parses as a real
    tier, SERVE_DECODE_KERNEL threads into engine_kwargs, and unknown
    values fail naming the supported list (not an int8 special case)."""
    cfg = ServeConfig.from_env({
        "SERVE_KV_DTYPE": "fp8", "SERVE_WEIGHT_DTYPE": "fp8",
        "SERVE_DECODE_KERNEL": "fused",
    })
    assert cfg.kv_dtype == "fp8" and cfg.weight_dtype == "fp8"
    assert cfg.decode_kernel == "fused"
    kw = cfg.engine_kwargs()
    assert kw["kv_dtype"] == "fp8" and kw["decode_kernel"] == "fused"
    assert ServeConfig.from_env({}).decode_kernel == "xla"
    with pytest.raises(ValueError, match=r"kv_dtype.*bf16.*int8.*fp8"):
        ServeConfig(kv_dtype="int4").engine_kwargs()
    with pytest.raises(ValueError, match="SERVE_DECODE_KERNEL"):
        ServeConfig(decode_kernel="pallas2").engine_kwargs()
    with pytest.raises(ValueError, match="decode_kernel"):
        SlotEngine(
            TransformerLM(variant="tiny", vocab_size=8, max_seq_len=8),
            {}, decode_kernel="turbo",
        )


def test_fp8_engine_falls_back_to_int8_when_unsupported(
    model, params, monkeypatch
):
    """The platform gate: where the fp8 probe fails (older TPU gens,
    exotic backends), the engine substitutes int8 — logged, and visible
    in the stored dtypes / byte accounting rather than silently kept."""
    from distributeddeeplearning_tpu.ops import quant as quantlib

    monkeypatch.setattr(quantlib, "fp8_supported", lambda: False)
    eng = SlotEngine(
        model, params, num_slots=2, max_len=MAX_LEN, buckets=(8,),
        kv_dtype="fp8", weight_dtype="fp8",
    )
    assert eng.kv_dtype == "int8" and eng.weight_dtype == "int8"
