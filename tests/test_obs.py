"""Event bus / flight recorder / trace capture / report oracles (ISSUE 2).

CPU-tier provable invariants:

* the bus writes schema-correct JSONL (meta first, monotonic t, run id,
  process identity) and the ring stays bounded;
* a SIGTERM'd / crashing process leaves a flight-recorder dump with its
  last N events — even events never flushed to the normal file;
* merge aligns multi-process files onto one wall clock; the report
  computes span percentiles, sync counts by label, and skew;
* the training loop emits through the bus with ZERO extra host syncs
  (asserted in test_sync_free_loop.py with the bus enabled);
* the trace controller starts/stops captures on the epoch boundary only
  (periodic + on-demand).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time
import types

import pytest

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.obs import report as obs_report
from distributeddeeplearning_tpu.obs import trace as obs_trace
from distributeddeeplearning_tpu.obs.bus import EventBus

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_bus():
    """Never leak a configured global bus (or crash handlers) across
    tests."""
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------------------
# Bus unit
# ---------------------------------------------------------------------------

def test_bus_writes_schema_jsonl(tmp_path):
    bus = EventBus(directory=str(tmp_path), proc=3, run_id="r-test")
    with bus.span("epoch", epoch=0):
        bus.span_event("step", 0.004, epoch=0)
        bus.counter("host_sync", 1, label="epoch_metrics")
        bus.gauge("epoch.loss", 1.25, epoch=0)
        bus.point("run_end")
    bus.flush()
    lines = [json.loads(ln) for ln in open(bus.path)]
    meta, events = lines[0], lines[1:]
    assert meta["kind"] == "meta" and meta["run"] == "r-test"
    assert meta["p"] == 3 and meta["pid"] == os.getpid()
    assert "mono0" in meta and "wall0" in meta
    assert [e["kind"] for e in events] == [
        "span", "counter", "gauge", "point", "span",
    ]  # the enclosing span lands at exit, after its contents
    by_name = {e["name"]: e for e in events}
    assert by_name["step"]["dur"] == pytest.approx(0.004)
    assert by_name["host_sync"]["labels"] == {"label": "epoch_metrics"}
    assert by_name["epoch"]["dur"] >= 0
    # monotonic timestamps, per-process sequence numbers
    assert all(e["p"] == 3 for e in events)
    assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)


def test_ring_is_bounded_and_keeps_latest():
    bus = EventBus(ring_size=16)  # ring-only: no directory
    for i in range(100):
        bus.point("tick", i=i)
    assert len(bus.ring) == 16
    assert [r["labels"]["i"] for r in bus.ring] == list(range(84, 100))
    assert bus.path is None  # nothing on disk


def test_flight_dump_contains_last_n_events(tmp_path):
    bus = EventBus(directory=str(tmp_path), proc=0, ring_size=8)
    for i in range(50):
        bus.point("tick", i=i)
    path = bus.dump_flight("unit-test")
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["kind"] == "flight_meta"
    assert lines[0]["reason"] == "unit-test"
    assert [r["labels"]["i"] for r in lines[1:]] == list(range(42, 50))


def test_configure_from_env_idempotent(tmp_path, monkeypatch):
    monkeypatch.setenv("OBS_DIR", str(tmp_path))
    monkeypatch.setenv("OBS_RUN_ID", "r-env")
    b1 = obs.configure_from_env()
    b2 = obs.configure_from_env()
    assert b1 is b2 and b1.run_id == "r-env"
    assert b1.directory == str(tmp_path)
    monkeypatch.delenv("OBS_DIR")
    assert obs.configure_from_env() is b1  # no OBS_DIR: keep current bus


def test_module_level_helpers_route_to_global_bus(tmp_path):
    bus = obs.configure(str(tmp_path), run_id="r-mod")
    obs.counter("c", 2, label="x")
    obs.gauge("g", 1.0)
    with obs.span("s"):
        pass
    obs.flush()
    kinds = [json.loads(ln)["kind"] for ln in open(bus.path)]
    assert kinds == ["meta", "counter", "gauge", "span"]


# ---------------------------------------------------------------------------
# Crash handlers (real processes)
# ---------------------------------------------------------------------------

_CHILD_SRC = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    from distributeddeeplearning_tpu import obs
    bus = obs.configure_from_env()
    for i in range(40):
        bus.point("tick", i=i)
    with bus.span("work"):
        pass
    bus.flush()
    bus.point("unflushed")  # in the ring only, never written normally
    print("READY", flush=True)
    {tail}
    """
)


def _spawn(tmp_path, tail, extra_env=None):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        OBS_DIR=str(tmp_path),
        OBS_RING_SIZE="16",
        DDL_PROCESS_ID="0",
        **(extra_env or {}),
    )
    return subprocess.Popen(
        [sys.executable, "-c",
         _CHILD_SRC.format(repo=REPO_ROOT, tail=tail)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def test_sigterm_leaves_flight_dump(tmp_path):
    """The preemption/watchdog black box: a killed process dumps its
    last N events even though they were never flushed."""
    proc = _spawn(tmp_path, "time.sleep(120)")
    # wait for READY so the bus exists and handlers are installed
    line = proc.stdout.readline()
    assert "READY" in line, line
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=30)
    assert rc != 0  # died by signal, semantics preserved
    dump = tmp_path / "flight-p0.jsonl"
    assert dump.exists()
    lines = [json.loads(ln) for ln in open(dump)]
    assert lines[0]["kind"] == "flight_meta"
    assert lines[0]["reason"] == "sigterm"
    names = [r["name"] for r in lines[1:]]
    assert "unflushed" in names  # ring caught what the file never saw
    assert len(lines) - 1 <= 16  # bounded by OBS_RING_SIZE


def test_unhandled_exception_leaves_flight_dump(tmp_path):
    proc = _spawn(tmp_path, "raise RuntimeError('boom')")
    out, _ = proc.communicate(timeout=60)
    assert proc.returncode == 1
    assert "boom" in out  # original traceback still printed
    lines = [json.loads(ln) for ln in open(tmp_path / "flight-p0.jsonl")]
    assert lines[0]["reason"] == "exception:RuntimeError"
    crash = [r for r in lines[1:] if r["name"] == "crash"]
    assert crash and "boom" in crash[0]["labels"]["error"]


# ---------------------------------------------------------------------------
# Merge + report
# ---------------------------------------------------------------------------

def _two_proc_run(tmp_path):
    for p in (0, 1):
        bus = EventBus(directory=str(tmp_path), proc=p, run_id="r-merge")
        t0 = time.monotonic()
        bus.span_event("step", 0.004, t=t0, epoch=0)
        bus.span_event("step", 0.004, t=t0 + 0.004, epoch=0)
        bus.span_event("step", 0.010, t=t0 + 0.008, epoch=0)
        bus.span_event("epoch", 0.050, t=t0, epoch=0, steps=3)
        bus.counter("host_sync", 1, label="epoch_metrics")
        bus.gauge("perf.compile_sec", 1.5 + p)
        bus.point("run_end")
        bus.close()
    return tmp_path


def test_merge_and_summarize(tmp_path):
    _two_proc_run(tmp_path)
    merged = obs_report.merge_run_dir(str(tmp_path))
    assert os.path.basename(merged) == "events.jsonl"
    # merged file: metas first, then events sorted by wall time
    lines = [json.loads(ln) for ln in open(merged)]
    metas = [r for r in lines if r["kind"] == "meta"]
    events = [r for r in lines if r["kind"] != "meta"]
    assert {m["p"] for m in metas} == {0, 1}
    walls = [e["wall"] for e in events]
    assert walls == sorted(walls)

    # a dir with a merged file loads identically to its parts
    summary = obs_report.summarize(obs_report.load([str(tmp_path)]))
    assert summary["run_ids"] == ["r-merge"]
    assert summary["spans"]["step"]["count"] == 6
    assert summary["spans"]["step"]["p50_ms"] == pytest.approx(4.0)
    assert summary["spans"]["step"]["p99_ms"] == pytest.approx(10.0)
    assert summary["host_sync_by_label"] == {"epoch_metrics": 2}
    assert summary["points"]["run_end"] == 2
    assert summary["epochs_seen"] == 1
    assert summary["max_epoch_skew_ms"] >= 0.0
    assert summary["step_s"] == pytest.approx(0.036)

    text = obs_report.render(summary)
    for needle in ("step", "epoch_metrics", "compile vs step", "timeline"):
        assert needle in text, text


def test_report_cli(tmp_path, capsys):
    """scripts/obs_report.py renders from a run dir (and --json mode)."""
    from scripts.obs_report import main as report_main

    _two_proc_run(tmp_path)
    assert report_main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "host syncs" in out and "step" in out
    assert report_main([str(tmp_path), "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["counters"]["host_sync"] == 2
    assert report_main([str(tmp_path / "missing")]) == 2


def test_report_tolerates_truncated_tail(tmp_path):
    """A process killed mid-write leaves a torn last line; loading must
    not explode (that is exactly the crash-forensics use case)."""
    bus = EventBus(directory=str(tmp_path), proc=0, run_id="r-torn")
    bus.point("ok")
    bus.close()
    with open(bus.path, "a") as fh:
        fh.write('{"t": 1.0, "kind": "point", "na')  # torn
    loaded = obs_report.load([str(tmp_path)])
    assert [e["name"] for e in loaded["events"]] == ["ok"]


# ---------------------------------------------------------------------------
# Trace controller
# ---------------------------------------------------------------------------

def _fake_profiler(monkeypatch):
    import jax

    calls = []
    fake = types.SimpleNamespace(
        start_trace=lambda d: calls.append(("start", d)),
        stop_trace=lambda: calls.append(("stop",)),
    )
    monkeypatch.setattr(jax, "profiler", fake)
    return calls


def test_trace_controller_periodic_and_on_demand(tmp_path, monkeypatch):
    calls = _fake_profiler(monkeypatch)
    ctrl = obs_trace.TraceController(str(tmp_path), every_n=2)
    assert ctrl.maybe_start(0) and ctrl.active
    assert not ctrl.maybe_start(0)  # never nested
    assert ctrl.maybe_stop(0) and not ctrl.active
    assert not ctrl.maybe_start(1)  # 1 % 2 != 0
    ctrl.request()  # on-demand (the SIGUSR1 path)
    assert ctrl.maybe_start(1)
    assert ctrl.maybe_stop(1)
    assert not ctrl.maybe_stop(1)  # stop is idempotent
    assert [c[0] for c in calls] == ["start", "stop", "start", "stop"]
    assert "trace-epoch0000" in calls[0][1]
    assert "trace-epoch0001" in calls[2][1]


def test_trace_from_env(tmp_path, monkeypatch):
    assert obs_trace.from_env(env={}) is None
    obs.configure(str(tmp_path))
    ctrl = obs_trace.from_env(env={"TRACE_EVERY_N_EPOCHS": "3"})
    assert ctrl is not None and ctrl.every_n == 3
    assert ctrl.directory == os.path.join(str(tmp_path), "traces")
    ctrl2 = obs_trace.from_env(
        env={"TRACE_ON_SIGNAL": "1", "TRACE_DIR": "/tmp/elsewhere"}
    )
    assert ctrl2 is not None and ctrl2.every_n == 0
    assert ctrl2.directory == "/tmp/elsewhere"


# ---------------------------------------------------------------------------
# Loop integration: fit() emits through the bus (incl. trace trigger)
# ---------------------------------------------------------------------------

def test_fit_emits_epoch_step_perf_and_trace_events(
    tmp_path, mesh8, monkeypatch
):
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.synthetic import SyntheticTokenDataset
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    calls = _fake_profiler(monkeypatch)
    monkeypatch.setenv("OBS_DIR", str(tmp_path))
    monkeypatch.setenv("TRACE_EVERY_N_EPOCHS", "1")
    cfg = TrainConfig(
        model="lm_tiny", num_classes=64, batch_size_per_device=2,
        fake_data_length=32, epochs=1, compute_dtype="float32",
        weight_decay=0.0, log_every_steps=0,
    )
    data = SyntheticTokenDataset(
        length=32, global_batch_size=cfg.global_batch_size,
        seq_len=16, vocab_size=64,
    )
    res = loop.fit(
        get_model("lm_tiny", num_classes=64, dtype="float32", max_seq_len=16),
        cfg, data, mesh=mesh8, add_default_logger=False,
    )
    bus = obs.get_bus()
    lines = [json.loads(ln) for ln in open(bus.path)]
    names = {(r["kind"], r["name"]) for r in lines[1:]}
    assert ("point", "run_begin") in names
    assert ("span", "step") in names
    assert ("span", "epoch") in names
    assert ("span", "epoch_materialize") in names
    assert ("gauge", "perf.host_sync_count") in names
    assert ("point", "run_end") in names
    # epoch gauges carry the materialised metrics (loss among them)
    gauges = {r["name"]: r["value"] for r in lines if r["kind"] == "gauge"}
    assert gauges["epoch.loss"] == res.history[0]["loss"]
    assert gauges["perf.host_sync_count"] == res.perf["host_sync_count"]
    # step spans: one per step, durations match the dispatch clock count
    steps = [r for r in lines if r["kind"] == "span" and r["name"] == "step"]
    assert len(steps) == data.steps_per_epoch
    # the per-epoch profiler capture really started and stopped
    assert ("point", "trace_start") in names
    assert ("point", "trace_stop") in names
    assert [c[0] for c in calls] == ["start", "stop"]


# ---------------------------------------------------------------------------
# Satellite units that ride along this file
# ---------------------------------------------------------------------------

def test_bench_records_route_through_bus(tmp_path, capsys):
    """bench.py --events contract: the canonical stdout JSON line is
    unchanged AND the same record lands on the bus as bench_result."""
    import bench

    bus = obs.configure(str(tmp_path))
    record = {"metric": "resnet50_synthetic_train_images_per_sec",
              "value": 123.4, "unit": "images/sec", "vs_baseline": 0.1}
    bench._emit_record(record)
    line = capsys.readouterr().out.strip()
    assert json.loads(line) == record  # driver protocol intact
    events = [json.loads(ln) for ln in open(bus.path)][1:]
    assert events[-1]["name"] == "bench_result"
    assert events[-1]["labels"]["metric"] == record["metric"]
    assert events[-1]["labels"]["value"] == 123.4


def test_heavy_refresh_duration_parsing():
    from scripts.heavy_refresh import parse_durations_log

    log = [
        "96.21s call     tests/test_vit.py::test_packed",
        "24.99s call     tests/test_fast.py::test_under",
        "30.00s setup    tests/test_x.py::test_setup_not_call",
        "110.5s call     tests/test_eff.py::test_loss",
        "garbage line",
    ]
    assert parse_durations_log(log, 25.0) == [
        "tests/test_vit.py::test_packed",
        "tests/test_eff.py::test_loss",
    ]


def test_decode_audit_cpu_honest_rows():
    from scripts.decode_audit import format_row, sweep_row

    on_chip = sweep_row(8, 11700.0, 2**26, 2**27, 20000.0, True)
    off_chip = sweep_row(8, 117.0, 2**26, 2**27, 20000.0, False)
    assert on_chip["pct_of_floor"] == pytest.approx(58.5)
    assert off_chip["pct_of_floor"] is None  # CPU: no roofline position
    assert off_chip["analytic_floor_tokens_per_sec"] == 20000.0
    assert "%" in format_row(on_chip)
    assert "n/a" in format_row(off_chip)


def test_decode_audit_paged_floor_accounts_table_bytes():
    """Paged-mode byte floor (ISSUE 6 satellite): the analytic
    bytes/step must stream the table-gathered K/V view (block-rounded)
    PLUS the int32 block tables — leaving the tables out would overstate
    pct_of_floor in paged mode. Shape-only, no compile."""
    import math

    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import traverse_util

    from distributeddeeplearning_tpu.inference import decode_variant
    from distributeddeeplearning_tpu.models.transformer_lm import (
        TransformerLM,
    )
    from scripts.decode_audit import paged_step_bytes, sweep_row

    model = TransformerLM(
        variant="tiny", vocab_size=64, max_seq_len=16, dtype=jnp.float32
    )
    shapes = jax.eval_shape(
        lambda r: decode_variant(model).init(
            r, jnp.zeros((2, 16), jnp.int32), train=False
        ),
        jax.random.PRNGKey(0),
    )["cache"]
    dense_kv = sum(
        math.prod(s.shape) * np.dtype(s.dtype).itemsize
        for p, s in traverse_util.flatten_dict(dict(shapes)).items()
        if p[-1] in ("cached_k", "cached_v")
    )
    view, table, scale = paged_step_bytes(model, 2, 16, block_size=4)
    # block-aligned max_len: the gathered view streams exactly the dense
    # KV bytes — the floor differs ONLY by the table overhead (and no
    # scale bytes exist on the native dtype)
    assert view == dense_kv
    assert table > 0
    assert scale == 0
    # non-dividing block size: rounding makes the view strictly larger
    view5, _, _ = paged_step_bytes(model, 2, 16, block_size=5)
    assert view5 > dense_kv
    # int8 mode: payload shrinks, f32 per-head scales appear itemized
    view8, _, scale8 = paged_step_bytes(model, 2, 16, block_size=4,
                                        kv_dtype="int8")
    assert view8 < view and scale8 > 0
    # the row itemizes the table bytes already inside bytes_per_step
    row = sweep_row(2, 100.0, view, view + table, 1000.0, False,
                    table_bytes=table)
    assert row["block_table_bytes"] == table
    assert "block_table_bytes" not in sweep_row(
        2, 100.0, dense_kv, dense_kv, 1000.0, False
    )
