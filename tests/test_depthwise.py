"""Parity oracle for the Pallas depthwise-conv experiment.

``ops/pallas/depthwise.py`` is a recorded NEGATIVE result (PROFILE.md
round-4): three kernel designs measured slower than or equal to XLA's
own (bad) depthwise lowering, so the model does NOT use it. The kernels
stay exact — these tests pin them to ``lax.conv_general_dilated``
(fwd + both grads) so the experiment remains trustworthy evidence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from distributeddeeplearning_tpu.ops.pallas.depthwise import (
    depthwise_conv2d,
    supports,
)


def _ref(x, kernel):
    return lax.conv_general_dilated(
        x.astype(jnp.float32),
        kernel.astype(jnp.float32),
        (1, 1),
        "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=x.shape[-1],
    )


@pytest.mark.parametrize(
    "b,h,w,c,k",
    [
        (2, 13, 11, 8, 3),  # ragged spatial dims, both edges masked
        (2, 9, 9, 8, 5),
        (1, 16, 16, 130, 3),  # C straddles a lane-tile boundary
    ],
)
def test_depthwise_matches_lax(b, h, w, c, k):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, h, w, c).astype(np.float32))
    kern = jnp.asarray(rng.randn(k, k, 1, c).astype(np.float32))
    out = depthwise_conv2d(x, kern, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(x, kern)), atol=1e-4
    )


def test_depthwise_grads_match_lax():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 9, 9, 8).astype(np.float32))
    kern = jnp.asarray(rng.randn(3, 3, 1, 8).astype(np.float32))

    def loss(fn):
        return lambda x, kk: jnp.sum(jnp.sin(fn(x, kk)))

    g = jax.grad(
        loss(lambda x, kk: depthwise_conv2d(x, kk, interpret=True)),
        argnums=(0, 1),
    )(x, kern)
    g_ref = jax.grad(loss(_ref), argnums=(0, 1))(x, kern)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(g_ref[1]), atol=1e-4)


def test_depthwise_supports_gating():
    assert supports(28, 28, 336, 5, 1)
    assert not supports(28, 28, 336, 5, 2)  # stride-2: XLA's
    assert not supports(28, 28, 336, 4, 1)  # even k
    with pytest.raises(ValueError):
        depthwise_conv2d(
            jnp.zeros((1, 8, 8, 4)), jnp.zeros((4, 4, 1, 4)), interpret=True
        )
