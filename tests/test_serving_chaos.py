"""Serving chaos-plane oracles (serving/chaos.py + the self-healing
fleet tier — router monitor, quarantine, breaker, brownout ladder).

The claims, each pinned here:

1. **Grammar/injector determinism** — the fleet-verb plan parses like
   FAULT_PLAN (shared lexical layer), rejects malformed directives, and
   the seeded injector arms/fires tick-deterministically.
2. **Straggler quarantine → splice parity** — a chaos-slowed replica's
   tick EWMA crosses the factor x fleet-median bar, it is quarantined
   (drained of placements, running work hedge re-routed), and every
   stream stays bitwise the sequential reference through the hedge.
3. **Corrupt detection → replay** — a flipped replay token is caught by
   the splice verifier, never delivered, the divergent replica is
   hard-faulted, and the stream heals bitwise from the deterministic
   prefix.
4. **Crash-loop breaker** — rejoins burn a per-replica restart budget
   with backoff; a flap beyond the budget opens the breaker
   (``fleet.breaker_open``), removes the replica, and the membership
   door stays shut; the controller holds scale-up after an opening.
5. **Brownout ladder** — sustained SLO burn steps down the declared
   stages (spec_off / max_new / shed with the distinct ``brownout``
   outcome), walks back up on recovery, every transition an obs point.
6. **Hung-pump containment** (heavy) — a hang makes the heartbeat
   stale, the monitor hard-faults, and ``stop()`` detaches the
   unjoinable thread (``fleet.thread_leaked``) instead of leaking it
   silently.

Engines are tiny (64-vocab lm) and replicas are pumped inline
(threaded=False) wherever determinism matters; the threaded drills are
registered heavy.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.inference import generate
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.serving import (
    BrownoutLadder,
    ChaosInjector,
    FleetConfig,
    Replica,
    Request,
    Router,
    ServeConfig,
    parse_brownout_stages,
    parse_chaos_plan,
    storm_plan,
)
from distributeddeeplearning_tpu.serving.chaos import (
    SLOW_UNIT_S,
    FleetFault,
)

VOCAB, MAX_LEN = 64, 32


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


def _scfg(**over):
    kw = dict(num_slots=2, buckets=(8,), prefills_per_step=2)
    kw.update(over)
    return ServeConfig(**kw)


def _fcfg(**over):
    kw = dict(
        replicas=2, quantum=64, max_restarts=1, restart_backoff_s=0.01,
        fault_join_s=0.5, straggler_factor=2.5, straggler_ticks=3,
        quarantine_ticks=8,
    )
    kw.update(over)
    return FleetConfig(**kw)


def _fresh_pair(model, params, n=2):
    return [
        Replica(k, model, params, _scfg(), max_len=MAX_LEN).start(
            threaded=False
        )
        for k in range(n)
    ]


def _prompt(rng, n=5):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _ref(model, params, prompt, max_new, **kw):
    return np.asarray(
        generate(model, params, np.asarray(prompt)[None],
                 max_new_tokens=max_new, **kw)
    )[0]


# -- grammar / config ----------------------------------------------------


def test_parse_chaos_plan_grammar():
    plan = parse_chaos_plan(
        "crash:tick=3,replica=0;slow:tick=5,replica=1,factor=6,secs=0.5;"
        "corrupt:tick=7,replica=1;flap:tick=4,replica=0,count=3;"
        "hang:tick=9,replica=1,secs=1.5"
    )
    kinds = [f.kind for f in plan]
    assert kinds == ["crash", "slow", "corrupt", "flap", "hang"]
    assert plan[1].factor == 6.0 and plan[1].secs == 0.5
    assert plan[3].count == 3
    assert parse_chaos_plan("") == []


@pytest.mark.parametrize("bad", [
    "melt:tick=3,replica=0",          # unknown verb
    "crash:replica=0",                # tick missing
    "crash:tick=3",                   # replica missing
    "crash:tick=0,replica=0",         # tick < 1
    "crash:tick=3,replica=0,count=2",  # count on non-flap
    "crash:tick=3,replica=0,factor=4",  # factor on non-slow
    "slow:tick=3,replica=0,factor=1",  # factor <= 1
    "flap:tick=3,replica=0,count=0",  # count < 1
    "crash:tick3,replica=0",          # not key=value
])
def test_parse_chaos_plan_rejects(bad):
    with pytest.raises(ValueError):
        parse_chaos_plan(bad)


def test_storm_plan_is_seeded_and_valid():
    a = storm_plan(2, seed=7)
    assert a == storm_plan(2, seed=7)       # deterministic in seed
    assert a != storm_plan(2, seed=8)
    faults = parse_chaos_plan(a)            # always re-parseable
    assert {f.kind for f in faults} == {
        "crash", "hang", "slow", "corrupt", "flap"
    }
    with pytest.raises(ValueError):
        storm_plan(2, verbs=("melt",))


def test_parse_brownout_stages():
    stages = parse_brownout_stages("spec_off, max_new:8, shed:1")
    assert [(s.kind, s.value) for s in stages] == [
        ("spec_off", 0), ("max_new", 8), ("shed", 1),
    ]
    for bad in ("nope", "max_new", "shed:0", "spec_off:3", ""):
        with pytest.raises(ValueError):
            parse_brownout_stages(bad)


def test_fleet_config_chaos_knobs_from_env():
    cfg = FleetConfig.from_env({
        "SERVE_STRAGGLER_FACTOR": "3.5",
        "SERVE_STRAGGLER_TICKS": "4",
        "SERVE_QUARANTINE_TICKS": "20",
        "SERVE_PUMP_HEARTBEAT_S": "2.5",
        "SERVE_REPLICA_MAX_RESTARTS": "2",
        "SERVE_REPLICA_RESTART_BACKOFF": "0.25",
        "SERVE_BROWNOUT_STAGES": "spec_off,shed:1",
        "SERVE_CHAOS_PLAN": "crash:tick=2,replica=0",
        "SERVE_CHAOS_SEED": "9",
    })
    assert cfg.straggler_factor == 3.5 and cfg.straggler_ticks == 4
    assert cfg.quarantine_ticks == 20
    assert cfg.heartbeat_timeout_s == 2.5
    assert cfg.max_restarts == 2 and cfg.restart_backoff_s == 0.25
    cfg.validate()
    with pytest.raises(ValueError):
        FleetConfig(straggler_factor=1.0).validate()
    with pytest.raises(ValueError):
        FleetConfig(brownout_stages="bogus").validate()
    with pytest.raises(ValueError):
        FleetConfig(chaos_plan="melt:tick=1,replica=0").validate()


# -- injector units ------------------------------------------------------


def test_injector_due_and_pump_actions():
    inj = ChaosInjector(parse_chaos_plan(
        "crash:tick=2,replica=0;slow:tick=3,replica=1,factor=4,secs=0.2"
    ))
    assert inj.due(1) == []
    due = inj.due(2)
    assert len(due) == 1 and due[0].kind == "crash"
    assert inj.due(2) == []                  # fires at most once
    now = time.monotonic()
    inj.arm_pump(due[0], now)
    assert inj.pump_action(1, now) is None   # wrong replica
    a = inj.pump_action(0, now)
    assert a["kind"] == "crash"
    assert inj.pump_action(0, now) is None   # crash is one-shot
    slow = inj.due(3)[0]
    inj.arm_pump(slow, now)
    a = inj.pump_action(1, now)
    assert a["kind"] == "slow"
    assert a["stall_s"] == pytest.approx(4 * SLOW_UNIT_S)
    assert inj.pump_action(1, now)["kind"] == "slow"  # persists...
    assert inj.pump_action(1, now + 1.0) is None      # ...then expires


def test_injector_flap_rearms_and_corrupt_flips_once():
    inj = ChaosInjector([FleetFault("flap", tick=1, replica=0, count=2)])
    f = inj.due(1)[0]
    now = time.monotonic()
    inj.arm_pump(f, now)
    assert inj.pump_action(0, now)["kind"] == "crash"
    assert inj.pump_action(0, now)["kind"] == "crash"  # re-armed cycle 2
    assert inj.pump_action(0, now) is None             # cycle budget spent
    c = FleetFault("corrupt", tick=1, replica=0)
    inj.arm_corrupt(c, fh_id=7)
    assert inj.maybe_corrupt(5, 10) == 10     # unarmed handle untouched
    assert inj.maybe_corrupt(7, 10) == 10 ^ 1  # armed: one flip
    assert inj.maybe_corrupt(7, 10) == 10      # one-shot
    assert any(e["kind"] == "corrupt" for e in inj.fired)


# -- straggler quarantine -> splice parity -------------------------------


def test_straggler_quarantine_hedges_with_bitwise_splice(model, params):
    """A chaos-slowed replica is quarantined off the straggler signal
    (EWMA vs fleet median) and its running requests hedge re-route:
    every stream stays bitwise the sequential reference, nothing is
    delivered twice, and the probation expires back to placeable."""
    reps = _fresh_pair(model, params)
    router = Router(
        config=_fcfg(straggler_ticks=2, quarantine_ticks=6),
        chaos=ChaosInjector(parse_chaos_plan(
            "slow:tick=2,replica=1,factor=8,secs=10"
        )),
    )
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(20)
    cases = []
    for i in range(8):
        p = _prompt(rng)
        cases.append((p, router.submit(Request(
            prompt=p, max_new_tokens=8, temperature=0.0,
        ))))
    quarantined_at = None
    for tick in range(4000):
        busy = router.step()
        if quarantined_at is None and reps[1].quarantined:
            quarantined_at = tick
        if not busy:
            break
    assert quarantined_at is not None, "straggler was never quarantined"
    # >= 1: a short probation may expire mid-drain and the still-slow
    # replica re-offend — every cycle is a legitimate quarantine.
    assert router.stats["quarantined"] >= 1
    delivered = {fh.id: list(fh.new_tokens) for _, fh in cases}
    for p, fh in cases:
        ref = _ref(model, params, p, 8)
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
        assert fh.restart_consistent
        assert fh.finish_reason == "length"
        assert fh.new_tokens == delivered[fh.id]
    # hedged work really moved (the slow replica lost running streams)
    assert router.stats["requeued"] > 0
    # probation expires: pump the (now idle) router past the window
    for _ in range(router.config.quarantine_ticks + 2):
        router.step()
    assert not reps[1].quarantined
    assert router.stats["unquarantined"] >= 1


# -- corrupt detection -> heal -------------------------------------------


def test_corrupt_token_detected_and_healed_never_delivered(model, params):
    """The corrupt verb flips one token of a hedged request's replay:
    the splice verifier catches it (fleet.splice_mismatch), the
    divergent replica is hard-faulted, and the final streams are
    bitwise the references — the flipped token never reaches a
    client."""
    reps = _fresh_pair(model, params)
    router = Router(
        config=_fcfg(max_restarts=2, restart_backoff_s=0.01,
                     quarantine_ticks=4),
        chaos=ChaosInjector(parse_chaos_plan(
            "corrupt:tick=3,replica=0"
        )),
    )
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(21)
    cases = []
    for i in range(6):
        p = _prompt(rng)
        cases.append((p, router.submit(Request(
            prompt=p, max_new_tokens=10, temperature=0.0,
        ))))
    router.drain(timeout=600)
    assert router.stats["splice_mismatch"] >= 1
    victims = [fh for _, fh in cases if fh.splice_mismatches]
    assert victims, "the flip never landed in a replay"
    for p, fh in cases:
        ref = _ref(model, params, p, 10)
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
        assert fh.restart_consistent  # healed
        assert fh.finish_reason == "length"
        # the corrupt token was never delivered: every delivered token
        # equals the deterministic reference (checked above), and the
        # mismatch count proves the flip DID happen.
    assert victims[0].attempts >= 3  # original + tainted replay + heal


# -- crash-loop breaker --------------------------------------------------


def test_flap_beyond_budget_opens_breaker_and_work_survives(model, params):
    """flap count=3 against a restart budget of 1: crash -> auto-rejoin
    (backoff) -> crash -> breaker opens (fleet.breaker_open), the
    replica is removed, its rid can never rejoin, and every request
    still completes bitwise on the survivor."""
    reps = _fresh_pair(model, params)
    router = Router(
        config=_fcfg(max_restarts=1, restart_backoff_s=0.01),
        chaos=ChaosInjector(parse_chaos_plan(
            "flap:tick=2,replica=1,count=3"
        )),
    )
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(22)
    cases = []
    for i in range(6):
        p = _prompt(rng)
        cases.append((p, router.submit(Request(
            prompt=p, max_new_tokens=6, temperature=0.0,
        ))))
    t0 = time.monotonic()
    while router.step() or any(
        r.state == "faulted" for r in router.replicas
    ):
        assert time.monotonic() - t0 < 600
    # one budgeted rejoin happened, then the breaker opened
    assert router.stats["rejoins"] == 1
    assert router.stats["breaker_open"] == 1
    assert [r.rid for r in router.replicas] == [0]
    for p, fh in cases:
        ref = _ref(model, params, p, 6)
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
        assert fh.restart_consistent
    # the membership door stays shut for the opened rid
    with pytest.raises(RuntimeError, match="breaker"):
        router.rejoin_replica(reps[1])
    with pytest.raises(RuntimeError, match="breaker"):
        router.add_replica(reps[1], start=False)


def test_controller_holds_scale_up_after_breaker_opens(model, params):
    from distributeddeeplearning_tpu.serving import (
        ControllerConfig,
        FleetController,
    )

    reps = _fresh_pair(model, params, n=1)
    router = Router(config=_fcfg(replicas=1, max_restarts=0))
    router.add_replica(reps[0], start=False)
    # Open a breaker synthetically: fault the replica with a zero
    # budget; the next monitor sweep opens and removes it.
    extra = Replica(1, model, params, _scfg(), max_len=MAX_LEN).start(
        threaded=False
    )
    router.add_replica(extra, start=False)
    router.fail_replica(1, RuntimeError("drill"))
    router.step()
    assert router.stats["breaker_open"] == 1
    built = []

    def factory(rid):
        r = Replica(rid, model, params, _scfg(), max_len=MAX_LEN)
        built.append(rid)
        return r

    ctl = FleetController(
        router, factory,
        ControllerConfig(min_replicas=1, max_replicas=3, up_ticks=1,
                         breaker_block_ticks=1000),
        reader=lambda: 5.0,  # permanently hot
        threaded_replicas=False,
    )
    assert ctl.tick() is None     # hot, but held by the open breaker
    assert built == []
    # The hold is one fleet.scaleup_denied + a tick-counted backoff —
    # the controller does not re-ask (or re-emit) every tick.
    assert [a for a in ctl.actions if a["action"] == "scaleup_denied"] == [
        {"action": "scaleup_denied", "reason": "breaker",
         "pressure": 5.0, "breaker_tick": router.last_breaker_tick},
    ]
    ctl.config.breaker_block_ticks = 0  # disable the hold
    assert ctl.tick() is None     # still backing off from the denial
    assert len(ctl.actions) == 1  # ...silently: no denial spam
    for _ in range(ctl.config.denied_backoff_ticks):
        router.step()             # walk the router clock past the backoff
    assert ctl.tick() == "scale_up"
    assert built == [2]
    router.close()


# -- brownout ladder -----------------------------------------------------


def test_brownout_ladder_steps_down_and_back_up(model, params):
    """Sustained burn steps through the declared stages (spec_off, then
    shed:1 with the distinct ``brownout`` outcome — never a silent
    drop); recovery walks back up in reverse order. Each transition is
    recorded."""
    reps = _fresh_pair(model, params)
    burn = {"on": False}

    def reader():
        return {
            "slo": [{
                "objective": "drill", "stat": "p99",
                "metric": "serve.ttft", "burning": burn["on"],
            }]
        }

    ladder = BrownoutLadder(
        parse_brownout_stages("spec_off,shed:1"),
        reader=reader, refresh_s=0.0, escalate_ticks=2, recover_ticks=2,
    )
    router = Router(config=_fcfg(), brownout=ladder)
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(23)
    # a weighted lane and the victim lane (lowest weight sheds first)
    router.set_tenant_weight("gold", 3.0)
    router.set_tenant_weight("cheap", 1.0)
    gold = [router.submit(Request(
        prompt=_prompt(rng), max_new_tokens=4, temperature=0.0,
    ), tenant="gold") for _ in range(4)]
    cheap_queued = [router.submit(Request(
        prompt=_prompt(rng), max_new_tokens=4, temperature=0.0,
    ), tenant="cheap") for _ in range(24)]
    burn["on"] = True
    for _ in range(4):
        router.step()
    assert ladder.level == 2
    assert all(r.engine.spec_suspended for r in reps)
    # the shed lane's queued requests finished with the distinct outcome
    shed = [fh for fh in cheap_queued if fh.finish_reason == "brownout"]
    assert shed and all(fh.done.is_set() for fh in shed)
    assert router.stats["brownout"] == len(shed)
    # an arriving request in the shed lane is rejected the same way
    fh = router.submit(Request(
        prompt=_prompt(rng), max_new_tokens=4,
    ), tenant="cheap")
    assert fh.finish_reason == "brownout" and fh.done.is_set()
    burn["on"] = False
    for _ in range(6):
        router.step()
    assert ladder.level == 0
    assert not any(r.engine.spec_suspended for r in reps)
    dirs = [t["direction"] for t in ladder.transitions]
    assert dirs == ["down", "down", "up", "up"]
    # the lane is open again after walk-up
    fh2 = router.submit(Request(
        prompt=_prompt(rng), max_new_tokens=4,
    ), tenant="cheap")
    router.drain(timeout=300)
    assert fh2.finish_reason == "length"
    assert all(fh.finish_reason == "length" for fh in gold)


def test_brownout_spec_off_keeps_greedy_parity(model, params):
    """The spec_off stage suspends speculation MID-STREAM and resumes
    it later: greedy output stays bitwise the sequential reference
    (the verify commits target tokens either way) and the program set
    never grows (the plain decode program was already compiled)."""
    from distributeddeeplearning_tpu.serving import Server, SlotEngine

    engine = SlotEngine(
        model, params, num_slots=2, max_len=MAX_LEN, buckets=(8,),
        spec_k=3, spec_draft="ngram",
    )
    engine.warmup()
    programs = engine.compile_count
    server = Server(engine, prefills_per_step=2)
    rng = np.random.RandomState(28)
    p = _prompt(rng)
    h = server.submit(Request(
        prompt=p, max_new_tokens=12, temperature=0.0,
    ))
    for _ in range(2):
        server.step()
    engine.spec_suspended = True   # brownout stage applies mid-stream
    for _ in range(3):
        server.step()
    engine.spec_suspended = False  # walk-up resumes speculation
    server.drain(timeout=300)
    ref = _ref(model, params, p, 12)
    np.testing.assert_array_equal(h.tokens, ref)
    assert h.finish_reason == "length"
    assert engine.compile_count == programs == engine.programs_expected


def test_brownout_max_new_caps_new_dispatches(model, params):
    reps = _fresh_pair(model, params)
    router = Router(config=_fcfg())
    for r in reps:
        router.add_replica(r, start=False)
    from distributeddeeplearning_tpu.serving import BrownoutStage

    router.apply_brownout_stage(BrownoutStage("max_new", 2), True, key=1)
    rng = np.random.RandomState(24)
    h = router.submit(Request(prompt=_prompt(rng), max_new_tokens=10))
    router.drain(timeout=300)
    assert len(h.new_tokens) == 2  # capped at dispatch
    router.apply_brownout_stage(BrownoutStage("max_new", 2), False, key=1)
    h2 = router.submit(Request(prompt=_prompt(rng), max_new_tokens=4))
    router.drain(timeout=300)
    assert len(h2.new_tokens) == 4  # cap reverted


# -- stream timeout contract (satellite) ---------------------------------


def test_fleet_stream_timeout_cancels_and_detaches(model, params):
    """FleetHandle.stream(timeout=) on expiry cancels the request —
    the next router tick reaps it as ``cancelled`` instead of leaving a
    zombie stream running (the chaos drills' no-leak contract)."""
    reps = _fresh_pair(model, params)
    router = Router(config=_fcfg())
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(25)
    fh = router.submit(Request(prompt=_prompt(rng), max_new_tokens=4))
    with pytest.raises(TimeoutError, match="cancelled"):
        # nothing is pumping: the wait must expire and cancel
        list(fh.stream(timeout=0.05))
    assert fh._cancel
    router.drain(timeout=300)
    assert fh.finish_reason == "cancelled"
    assert router.stats["cancelled"] == 1


# -- hung pump containment + full storm (heavy drills) -------------------


def test_hang_hard_faults_and_detaches_thread_leak(model, params):
    """THREADED drill: a chaos hang makes the pump heartbeat go stale
    mid-load; the monitor hard-faults the replica, stop() detaches the
    unjoinable thread (fleet.thread_leaked, leaked_threads bumps), the
    work re-routes bitwise, and the breaker's budgeted rejoin brings
    the replica back."""
    reps = [
        Replica(k, model, params, _scfg(), max_len=MAX_LEN).start(
            threaded=True
        )
        for k in range(2)
    ]
    t0 = time.monotonic()
    while not all(r.state == "ready" for r in reps):
        assert time.monotonic() - t0 < 600
        time.sleep(0.01)
    router = Router(
        config=_fcfg(
            heartbeat_timeout_s=0.3, fault_join_s=0.2,
            max_restarts=2, restart_backoff_s=0.05,
        ),
        chaos=ChaosInjector(parse_chaos_plan(
            "hang:tick=3,replica=1,secs=2.0"
        )),
    )
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(26)
    cases = []
    for i in range(8):
        p = _prompt(rng)
        cases.append((p, router.submit(Request(
            prompt=p, max_new_tokens=8, temperature=0.0,
        ))))
    t0 = time.monotonic()
    leaked_seen = False
    while router.step() or any(
        r.state == "faulted" for r in router.replicas
    ):
        leaked_seen = leaked_seen or reps[1].leaked_threads > 0
        assert time.monotonic() - t0 < 600
        time.sleep(0.005)
    assert leaked_seen and reps[1].leaked_threads == 1
    assert router.stats["rejoins"] >= 1  # budgeted auto-heal
    for p, fh in cases:
        ref = _ref(model, params, p, 8)
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
        assert fh.restart_consistent
    # double-fault guard: declaring the same replica failed twice more
    # neither double-requeues nor throws
    router.fail_replica(1, RuntimeError("drill"))
    moved_again = router.fail_replica(1, RuntimeError("drill"))
    assert moved_again == 0
    router.close()


def test_mixed_verb_storm_completes_with_parity(model, params):
    """The chaos_bench storm in miniature (inline, deterministic): one
    seeded mixed-verb plan over a 2-replica fleet — every request
    completes bitwise, the corrupt flip is caught and healed, and every
    surviving replica's program set is closed."""
    reps = _fresh_pair(model, params)
    plan = (
        "slow:tick=4,replica=1,factor=8,secs=0.6;"
        "crash:tick=8,replica=0;"
        "corrupt:tick=14,replica=1"
    )
    router = Router(
        config=_fcfg(max_restarts=3, restart_backoff_s=0.01,
                     straggler_ticks=2, quarantine_ticks=10),
        chaos=ChaosInjector(parse_chaos_plan(plan)),
    )
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(27)
    cases = []
    for i in range(10):
        p = _prompt(rng)
        cases.append((p, router.submit(Request(
            prompt=p, max_new_tokens=10, temperature=0.0,
        ))))
    t0 = time.monotonic()
    while router.step() or any(
        r.state == "faulted" for r in router.replicas
    ):
        assert time.monotonic() - t0 < 600
    for p, fh in cases:
        ref = _ref(model, params, p, 10)
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
        assert fh.restart_consistent
        assert fh.finish_reason == "length"
    assert router.stats["splice_mismatch"] >= 1  # corrupt was caught
    for r in router.replicas:
        assert r.engine.compile_count == r.engine.programs_expected
    snapshot = router.fleet_snapshot()
    assert all(row["state"] == "ready" for row in snapshot)
