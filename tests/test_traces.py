"""Trace-plane oracles: context propagation, critical-path
reconstruction, the obs-trace-ctx lint, and malformed-input hardening.

CPU-tier provable invariants (docs/OBSERVABILITY.md, trace plane):

* ``obs.trace_ctx`` stamps every emit (any bus) with the thread-local
  trace coordinates; nesting links ``parent`` within the same trace;
  causal child spans carry their ``cause``; other threads stay
  unstamped; ``obs.reset()`` drops the binding.
* The flight recorder's dump header names the traces the process held
  (``trace_open``/``trace_close``) so a crash post-mortem can join them.
* ``obs/traces.py`` rebuilds per-request critical paths from a
  synthetic timeline: phases sum to e2e within the documented
  tolerance, interventions keep their cause, sheds/orphans/tick-traces
  are classified, the top-slow digest fingers the dominant culprit,
  and the training reconstructor decomposes step windows.
* ddlint's ``obs-trace-ctx`` flags traced-family emits outside a bound
  context (function boundaries are barriers) and self-hosts clean.
* The report/tail readers degrade gracefully on what dying processes
  leave behind: truncated JSONL mid-record, empty event files, a trace
  whose parent span never closed (an orphan, not a crash).
"""

import ast
import json
import os
import textwrap
import threading

import pytest

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.analysis import (
    apply_suppressions,
    package_sources,
)
from distributeddeeplearning_tpu.analysis import contracts
from distributeddeeplearning_tpu.obs import report as obs_report
from distributeddeeplearning_tpu.obs import traces
from distributeddeeplearning_tpu.obs.bus import EventBus, TraceContext
from distributeddeeplearning_tpu.obs.tail import Tailer


@pytest.fixture(autouse=True)
def _fresh_bus():
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------------------
# Context propagation (obs/bus.py)
# ---------------------------------------------------------------------------

def test_trace_ctx_stamps_nests_and_restores():
    bus = EventBus(ring_size=16)
    bus.point("before")
    with obs.trace_ctx("aaaabbbbcccc") as ctx:
        bus.counter("serve.request", reason="eos")
        with obs.trace_ctx("aaaabbbbcccc", cause="hedge") as child:
            bus.span_event("fleet.reroute", 0.01)
        with obs.trace_ctx(None):  # passthrough keeps the binding
            assert obs.current_trace() is ctx
            bus.point("still.traced")
    bus.point("after")
    assert obs.current_trace() is None

    by_name = {r["name"]: r for r in bus.ring}
    assert "trace" not in by_name["before"]
    assert "trace" not in by_name["after"]
    req = by_name["serve.request"]
    assert req["trace"] == "aaaabbbbcccc" and req["span"] == ctx.span
    assert "parent" not in req and "cause" not in req
    rr = by_name["fleet.reroute"]
    # Nested under the same trace: the child links back to the
    # enclosing span and carries its cause.
    assert child.parent == ctx.span
    assert rr["parent"] == ctx.span and rr["cause"] == "hedge"
    assert rr["span"] != ctx.span
    assert by_name["still.traced"]["span"] == ctx.span


def test_trace_ctx_rebinds_ready_made_context():
    # How a component re-binds a context that crossed a thread boundary
    # on the Request object: bound as-is, span preserved.
    ctx = TraceContext("ddddeeeeffff", span="01234567")
    with obs.trace_ctx(ctx) as bound:
        assert bound is ctx
        assert obs.current_trace().span == "01234567"
    assert obs.current_trace() is None


def test_trace_ctx_is_thread_local():
    seen = {}
    with obs.trace_ctx(obs.new_trace_id()):
        t = threading.Thread(
            target=lambda: seen.update(ctx=obs.current_trace())
        )
        t.start()
        t.join()
    assert seen["ctx"] is None  # the binding never leaks across threads


def test_reset_drops_binding():
    with obs.trace_ctx("aaaabbbbcccc"):
        obs.reset()
        assert obs.current_trace() is None


def test_flight_dump_names_active_traces(tmp_path):
    bus = EventBus(directory=str(tmp_path), proc=0, run_id="r-t")
    bus.trace_open("aaaabbbbcccc", req=7, tenant="gold")
    bus.point("x")
    path = bus.dump_flight("test")
    header = json.loads(open(path).readline())
    assert header["kind"] == "flight_meta"
    active = header["active_traces"]
    assert active["aaaabbbbcccc"]["req"] == 7
    assert "opened_t" in active["aaaabbbbcccc"]
    bus.trace_close("aaaabbbbcccc")
    assert bus.active_traces() == {}
    # A dump with nothing in flight omits the header key entirely.
    header2 = json.loads(open(bus.dump_flight("test2")).readline())
    assert "active_traces" not in header2


# ---------------------------------------------------------------------------
# Critical-path reconstruction (obs/traces.py)
# ---------------------------------------------------------------------------

def _span(name, wall, dur, trace, **extra):
    return {"kind": "span", "name": name, "wall": wall, "dur": dur,
            "trace": trace, **extra}


def _synthetic_fleet():
    """A hand-built timeline: one clean request, one hedged decode-bound
    straggler, one brownout shed, one orphan, one engine-tick trace."""
    ev = [
        # t1: clean, phases sum exactly to e2e.
        {"kind": "point", "name": "fleet.submitted", "wall": 100.0,
         "trace": "t1", "labels": {"req": 1, "tenant": "gold"}},
        {"kind": "gauge", "name": "serve.queue_depth", "wall": 100.02,
         "trace": "t1", "value": 1},
        _span("serve.queue_wait", 100.02, 0.05, "t1"),
        _span("serve.prefill", 100.07, 0.03, "t1"),
        _span("serve.ttft", 100.0, 0.12, "t1"),
        _span("serve.decode_share", 100.10, 0.10, "t1"),
        _span("serve.delivery", 100.20, 0.01, "t1"),
        {"kind": "counter", "name": "serve.request", "wall": 100.21,
         "trace": "t1", "labels": {"reason": "eos", "tokens": 7}},
        # t2: hedged off replica 1 mid-decode; decode dominates.
        {"kind": "point", "name": "fleet.submitted", "wall": 100.0,
         "trace": "t2", "labels": {"req": 2, "tenant": "bronze"}},
        {"kind": "gauge", "name": "serve.queue_depth", "wall": 100.03,
         "trace": "t2", "value": 1},
        _span("serve.queue_wait", 100.03, 0.05, "t2"),
        _span("serve.prefill", 100.08, 0.03, "t2"),
        _span("serve.decode_share", 100.11, 0.40, "t2"),
        _span("fleet.reroute", 100.55, 0.20, "t2", cause="hedge",
              labels={"req": 2, "replica": 0, "src": 1, "attempt": 2}),
        _span("serve.queue_wait", 100.75, 0.05, "t2"),
        _span("serve.prefill", 100.80, 0.03, "t2"),
        _span("serve.decode_share", 100.83, 0.80, "t2"),
        _span("serve.delivery", 101.65, 0.01, "t2"),
        {"kind": "counter", "name": "serve.request", "wall": 101.66,
         "trace": "t2", "labels": {"reason": "length", "tokens": 16}},
        # t3: brownout shed at admission.
        {"kind": "point", "name": "fleet.submitted", "wall": 100.0,
         "trace": "t3", "labels": {"req": 3, "tenant": "bronze"}},
        {"kind": "counter", "name": "serve.brownout_shed", "wall": 100.01,
         "trace": "t3", "labels": {"tenant": "bronze"}},
        # t4: admission point, no terminal — an orphan.
        {"kind": "point", "name": "fleet.submitted", "wall": 100.0,
         "trace": "t4", "labels": {"req": 4, "tenant": "gold"}},
        {"kind": "gauge", "name": "serve.queue_depth", "wall": 100.05,
         "trace": "t4", "value": 2},
        # t5: the scheduler's shared engine-tick trace — not a request.
        _span("serve.decode_step", 100.0, 0.01, "t5"),
        _span("serve.decode_step", 100.02, 0.01, "t5"),
        # Unstamped background noise must not leak into any trace.
        {"kind": "gauge", "name": "proc.rss_mb", "wall": 100.0,
         "value": 10.0},
    ]
    return ev


def test_reconstruct_classifies_and_accounts():
    recon = traces.reconstruct(_synthetic_fleet())
    assert recon["count"] == 3
    assert recon["orphan_count"] == 1
    assert recon["sheds"] == 1
    assert recon["within_tolerance"] == 3
    assert recon["causes"] == {"hedge": 1, "brownout": 1}
    by_trace = {r["trace"]: r for r in recon["requests"]}
    assert set(by_trace) == {"t1", "t2", "t3"}  # t5 is no request at all

    t1 = by_trace["t1"]
    assert t1["outcome"] == "done" and t1["reason"] == "eos"
    assert t1["tokens"] == 7 and t1["attempts"] == 1
    assert t1["tenant"] == "gold" and t1["req"] == 1
    assert t1["e2e_s"] == pytest.approx(0.21)
    assert t1["phases"]["router_wait"] == pytest.approx(0.02)
    assert t1["phases"]["decode"] == pytest.approx(0.10)
    assert t1["gap_s"] == pytest.approx(0.0, abs=1e-6)
    assert t1["within_tolerance"]

    t2 = by_trace["t2"]
    assert t2["outcome"] == "done" and t2["attempts"] == 2
    assert t2["phases"]["decode"] == pytest.approx(1.20)
    assert t2["phases"]["reroute"] == pytest.approx(0.20)
    assert t2["phases"]["queue_wait"] == pytest.approx(0.10)
    assert t2["gap_s"] <= traces.gap_tolerance_s(t2["e2e_s"])
    [rr] = [i for i in t2["interventions"] if i["what"] == "fleet.reroute"]
    assert rr["cause"] == "hedge"
    assert rr["replica"] == 0 and rr["src"] == 1  # dest vs culprit

    t3 = by_trace["t3"]
    assert t3["outcome"] == "brownout"
    assert t3["causes"] == ["brownout"]

    [orphan] = recon["orphans"]
    assert orphan["trace"] == "t4" and orphan["outcome"] == "orphan"


def test_top_slow_fingers_dominant_culprit():
    recon = traces.reconstruct(_synthetic_fleet())
    p50s = traces.phase_p50s(recon["requests"])
    # Sheds never ran phases: they are excluded from the baseline.
    assert p50s["decode"] == pytest.approx(0.10)
    rows = traces.top_slow(recon["requests"], k=2, p50s=p50s)
    assert [r["trace"] for r in rows] == ["t2", "t1"]
    assert rows[0]["culprit"] == "decode"
    assert rows[0]["culprit_excess_s"] == pytest.approx(1.10)


def test_gap_over_tolerance_is_flagged_not_absorbed():
    ev = [
        {"kind": "point", "name": "fleet.submitted", "wall": 0.0,
         "trace": "tg", "labels": {"req": 9}},
        _span("serve.queue_wait", 0.0, 0.01, "tg"),
        # 3s of nothing, then the terminal: almost all wall unattributed.
        {"kind": "counter", "name": "serve.request", "wall": 3.0,
         "trace": "tg", "labels": {"reason": "eos", "tokens": 1}},
    ]
    [r] = traces.reconstruct(ev)["requests"]
    assert r["gap_s"] == pytest.approx(2.99)
    assert r["gap_tolerance_s"] == pytest.approx(max(
        traces.GAP_TOL_S, traces.GAP_TOL_FRAC * 3.0
    ))
    assert not r["within_tolerance"]


def test_training_attribution_decomposes_step_windows():
    ev = [
        {"kind": "span", "name": "step", "wall": 10.0, "dur": 0.5, "p": 0,
         "labels": {"epoch": 0}},
        {"kind": "span", "name": "data.wait", "wall": 10.5, "dur": 0.3,
         "p": 0},
        {"kind": "span", "name": "step", "wall": 10.9, "dur": 0.4, "p": 0,
         "labels": {"epoch": 0}},
    ]
    t = traces.training_attribution(ev)
    assert t["steps"] == 2 and t["procs"] == 1
    assert t["dispatch_s"] == pytest.approx(0.9)
    assert t["data_wait_s"] == pytest.approx(0.3)
    assert t["other_s"] == pytest.approx(0.1)
    assert t["wall_s"] == pytest.approx(1.3)
    assert t["slowest"][0]["wall_s"] == pytest.approx(0.8)
    # Serving-only runs have no step spans: no section, not zeros.
    assert traces.training_attribution(_synthetic_fleet()) is None


# ---------------------------------------------------------------------------
# ddlint: obs-trace-ctx
# ---------------------------------------------------------------------------

_LINT_FIXTURE = textwrap.dedent(
    """
    def naked(bus):
        bus.counter("serve.request", reason="eos")

    def wrapped(bus, h):
        with obs.trace_ctx(h.trace):
            bus.span_event("serve.prefill", 0.1)
            with bus.span("serve.decode_share"):
                pass

    def barrier(bus, h):
        with obs.trace_ctx(h.trace):
            def later():
                bus.span_event("serve.delivery", 0.1)
            return later

    def untraced_family(bus):
        bus.gauge("serve.queue_depth", 3)
    """
)


def test_obs_trace_ctx_flags_naked_and_respects_barriers():
    v = contracts._NakedTracedEmits()
    v.visit(ast.parse(_LINT_FIXTURE))
    flagged = [name for name, _, _ in v.naked]
    # The naked emit and the deferred closure (an outer `with` cannot
    # cover code that runs later) are caught; the wrapped emits and the
    # non-traced family are not.
    assert flagged == ["serve.request", "serve.delivery"]


def test_obs_trace_ctx_self_hosts_clean():
    out = apply_suppressions(
        contracts.run_obs_trace_ctx(), package_sources()
    )
    assert [f.format() for f in out if not f.suppressed] == []


def test_trace_hot_paths_exist():
    from distributeddeeplearning_tpu.analysis.contracts import (
        REPO_ROOT,
        TRACE_HOT_PATHS,
    )
    for rel in TRACE_HOT_PATHS:
        assert os.path.exists(os.path.join(REPO_ROOT, rel)), rel


# ---------------------------------------------------------------------------
# Malformed input: report + tail degrade, never raise
# ---------------------------------------------------------------------------

_META = {"kind": "meta", "run": "r-mal", "p": 0, "pid": 1,
         "mono0": 0.0, "wall0": 1000.0}


def _write_events(path, lines):
    with open(path, "w") as fh:
        fh.write("".join(lines))


def test_report_and_tail_survive_truncated_mid_record(tmp_path):
    p = str(tmp_path / "events-p0.jsonl")
    good = {"t": 1.0, "kind": "counter", "name": "serve.request", "p": 0,
            "value": 1, "trace": "aaaabbbbcccc",
            "labels": {"reason": "eos"}}
    _write_events(p, [
        json.dumps(_META) + "\n",
        json.dumps(good) + "\n",
        '{"t": 2.0, "kind": "coun',  # the process died mid-write
    ])
    loaded = obs_report.load([str(tmp_path)])
    assert len(loaded["events"]) == 1
    text = obs_report.render(obs_report.summarize(loaded))
    assert "serve.request" in text

    tailer = Tailer(str(tmp_path))
    first = tailer.poll()
    assert [e["name"] for e in first] == ["serve.request"]
    assert first[0]["wall"] == pytest.approx(1001.0)
    # The torn tail is held back, not mis-parsed: completing the line
    # later delivers the record on the next poll.
    with open(p, "a") as fh:
        fh.write('ter", "name": "late", "p": 0}\n')
    assert [e["name"] for e in tailer.poll()] == ["late"]
    assert tailer.errors == 0


def test_report_and_tail_survive_empty_event_file(tmp_path):
    p = str(tmp_path / "events-p0.jsonl")
    _write_events(p, [])
    loaded = obs_report.load([str(tmp_path)])
    assert loaded["events"] == []
    summary = obs_report.summarize(loaded)
    assert summary["traces"] is None  # nothing stamped, section omitted
    assert isinstance(obs_report.render(summary), str)
    assert Tailer(str(tmp_path)).poll() == []


def test_report_surfaces_never_closed_parent_span_as_orphan(tmp_path):
    # A request whose enclosing span never closed (the replica died
    # holding it): admission markers exist, no terminal, no span end.
    evs = [
        {"t": 1.0, "kind": "point", "name": "fleet.submitted", "p": 0,
         "trace": "deadbeefcafe", "span": "01234567",
         "labels": {"req": 5, "tenant": "gold"}},
        {"t": 1.1, "kind": "gauge", "name": "serve.queue_depth", "p": 0,
         "value": 1, "trace": "deadbeefcafe", "span": "01234567"},
        {"t": 1.2, "kind": "span", "name": "serve.prefill", "p": 0,
         "dur": 0.05, "trace": "deadbeefcafe", "parent": "01234567",
         "span": "89abcdef"},
    ]
    _write_events(
        str(tmp_path / "events-p0.jsonl"),
        [json.dumps(_META) + "\n"]
        + [json.dumps(e) + "\n" for e in evs],
    )
    loaded = obs_report.load([str(tmp_path)])
    summary = obs_report.summarize(loaded)
    tr = summary["traces"]
    assert tr is not None and tr["requests"] == 0
    assert tr["orphans"] == 1
    assert isinstance(obs_report.render(summary), str)
    recon = traces.reconstruct(loaded)
    [o] = recon["orphans"]
    assert o["trace"] == "deadbeefcafe" and o["events"] == 3
