"""Jax-light child for the supervisor / fault-injection e2e tests.

A stand-in "training loop" that exercises the whole restart machinery
without compiling anything: it counts steps, persists its progress to a
per-rank state file (the checkpoint analogue), consults the
``FAULT_PLAN`` injector after every step exactly like ``loop.fit`` does,
and emits through the obs bus. Run under ``launch.py --max-restarts``
this reproduces, in seconds, the crash → classify → backoff → relaunch →
resume cycle the real training oracles take minutes to drive.

Env contract: ``FAKE_STEPS`` (total steps, default 6), ``STATE_FILE``
(progress-file prefix; ``.{rank}`` appended), plus the launcher's
``DDL_PROCESS_ID``/``FAULT_PLAN``/``OBS_*``.
"""

import os
import time

from distributeddeeplearning_tpu import faults, obs


def main() -> None:
    bus = obs.configure_from_env()
    rank = int(os.environ.get("DDL_PROCESS_ID", "0"))
    steps = int(os.environ.get("FAKE_STEPS", "6"))
    injector = faults.FaultInjector.from_env()
    state_file = os.environ.get("STATE_FILE")
    path = f"{state_file}.{rank}" if state_file else None

    cache = os.environ.get("COMPILATION_CACHE_DIR")
    if cache:  # lets the supervisor e2e assert the per-attempt suffix
        print(f"FAULT_CHILD_CACHE_DIR {rank} {cache}", flush=True)

    if os.environ.get("ELASTIC"):  # elastic drills assert the rescale
        print(
            f"FAULT_CHILD_WORLD rank={rank} "
            f"world={os.environ.get('DDL_NUM_PROCESSES', '1')} "
            f"batch={os.environ.get('BATCHSIZE', '-')} "
            f"accum={os.environ.get('ACCUM_STEPS', '-')} "
            f"lr_world={os.environ.get('LR_WORLD_SIZE', '-')}",
            flush=True,
        )

    start = 0
    if path and os.path.exists(path):
        start = int(open(path).read().strip() or 0)

    for step in range(start + 1, steps + 1):
        print(f"step {step} rank {rank}", flush=True)
        with bus.span("fake_step", step=step, rank=rank):
            time.sleep(0.05)
        if path:  # "checkpoint": durable before any fault can fire
            with open(path, "w") as fh:
                fh.write(str(step))
        if injector is not None and injector.due_after(step):
            bus.flush()
            injector.fire_after(step)
    bus.flush()
    print(f"FAULT_CHILD_DONE {rank} start={start}", flush=True)


if __name__ == "__main__":
    main()
