"""Oracles for in-step microbatched gradient accumulation (ACCUM_STEPS).

What is certifiable on the CPU mesh, and how:

1. **Equivalence to the unaccumulated step, per engine.** For every
   engine (dp / pjit / sp / pp-gpipe / pp-1f1b), ``accum_steps∈{2,4}``
   on batch B produces the same params/metrics as ``accum_steps=1`` on
   B up to f32 reduction order. Exact bitwise equality between k and 1
   is mathematically unavailable — splitting the batch-dim reductions
   necessarily re-associates the f32 sums (measured ~1e-8 absolute on
   lm_tiny) — so the oracle asserts agreement at f32-ULP scale
   (atol 2e-7 / rtol 2e-4 on params after multiple optimizer steps),
   orders of magnitude tighter than any semantic bug (a mis-weighted
   microbatch is a >1e-1 event).
2. **The scan IS the chunked math, bitwise.** The dp engine's
   accumulated gradient path equals a host-driven loop that jits the
   same per-microbatch gradient and sums in the same order — exact
   equality, no tolerance (this pins the mean-weighting order: Σ then
   /k, f32).
3. **One dispatch per effective step.** ``state.step`` advances once
   per call; the sync-free-loop invariant (≤1 host sync per epoch)
   holds under ``accum_steps=4``; determinism is bitwise run-to-run.
4. **Ghost batch norm** (Hoffer et al. 2017): with frozen params
   (lr=0), one ``accum_steps=k`` dispatch folds BN running statistics
   exactly like k sequential unaccumulated dispatches over the same
   microbatches.
5. **Cache-key guard**: the lowered program differs between
   accum_steps values, so recertify rows differing only in ACCUM_STEPS
   cannot collide in a shared XLA persistent compilation cache (the
   cache key hashes the HLO module).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import prefetch_to_device
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokenDataset
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.training import loop
from distributeddeeplearning_tpu.training.engines import build_engine
from distributeddeeplearning_tpu.training.loop import _init_spec, resolve_engine
from distributeddeeplearning_tpu.training.optimizer import create_optimizer
from distributeddeeplearning_tpu.utils import hostsync

VOCAB, T = 64, 16

ENGINE_KW = {
    "dp": {},
    "pjit": {},
    "sp": dict(mesh_axes=("data", "seq"), mesh_shape=(2, 4)),
    "pp": dict(
        mesh_axes=("data", "pipe"), mesh_shape=(2, 4), pp_microbatches=2
    ),
    "pp-1f1b": dict(
        mesh_axes=("data", "pipe"), mesh_shape=(2, 4), pp_microbatches=2,
        pp_schedule="1f1b", engine="pp",
    ),
}


def _cfg(engine, accum_steps=1, **kw):
    base = dict(
        engine=engine,
        model="lm_tiny",
        num_classes=VOCAB,
        batch_size_per_device=8,
        fake_data_length=32,
        epochs=1,
        compute_dtype="float32",
        weight_decay=0.0,
        log_every_steps=0,
        accum_steps=accum_steps,
    )
    base.update(ENGINE_KW[engine])
    base.update(kw)
    return TrainConfig(**base)


def _data(cfg, seed=0):
    return SyntheticTokenDataset(
        length=cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        seq_len=T,
        vocab_size=VOCAB,
        seed=seed,
    )


def _build(cfg, data, mesh):
    from distributeddeeplearning_tpu.parallel.mesh import dp_size

    tx, _ = create_optimizer(cfg, data.steps_per_epoch, world_size=dp_size(mesh))
    model = get_model(
        "lm_tiny", num_classes=VOCAB, dtype=cfg.compute_dtype, max_seq_len=T
    )
    shape, dtype = _init_spec(data)
    return build_engine(
        model, cfg, tx, mesh, input_shape=shape, input_dtype=dtype
    )


def _run_epoch(cfg, mesh, data, eng):
    state = eng.state
    metrics = None
    for batch in prefetch_to_device(
        data.epoch(0), mesh, size=0, sharding=eng.batch_sharding
    ):
        state, metrics = eng.train_step(state, batch)
    return (
        jax.device_get(state.params),
        jax.device_get(metrics),
        int(jax.device_get(state.step)),
    )


@pytest.mark.parametrize("engine", ["dp", "pjit", "sp", "pp", "pp-1f1b"])
def test_accum_equivalent_to_unaccumulated(engine):
    """(1) + (3): k∈{2,4} matches k=1 at f32-ULP scale; one optimizer
    step per dispatch either way."""
    results = {}
    for k in (1, 2, 4):
        cfg = _cfg(engine, accum_steps=k)
        _, mesh = resolve_engine(cfg)
        data = _data(cfg)
        eng = _build(cfg, data, mesh)
        assert getattr(eng.train_step, "accum_steps", None) == k
        results[k] = _run_epoch(cfg, mesh, data, eng)
    params1, metrics1, steps1 = results[1]
    n_dispatches = _data(_cfg(engine)).steps_per_epoch
    assert steps1 == n_dispatches
    for k in (2, 4):
        params_k, metrics_k, steps_k = results[k]
        # effective-step accounting: one dispatch == one optimizer step
        assert steps_k == steps1
        for (path1, a), (path_k, b) in zip(
            jax.tree_util.tree_leaves_with_path(params1),
            jax.tree_util.tree_leaves_with_path(params_k),
        ):
            assert path1 == path_k
            np.testing.assert_allclose(
                a, b, rtol=2e-4, atol=2e-7,
                err_msg=f"{engine} k={k} param {path1}",
            )
        for m in ("loss", "accuracy", "grad_norm"):
            np.testing.assert_allclose(
                np.float32(metrics1[m]), np.float32(metrics_k[m]),
                rtol=1e-4, atol=1e-6, err_msg=f"{engine} k={k} metric {m}",
            )


def test_accum_scan_is_chunked_math_bitwise(mesh8):
    """(2): the dp engine's accumulated params are BITWISE equal to
    driving the identical per-microbatch sequence by hand — k jitted
    single-microbatch gradient steps whose f32 grads are summed in scan
    order, divided by k, and applied through the same optimizer. This
    pins the exact accumulation formula (f32 Σ in microbatch order, one
    /k at the end) with zero tolerance."""
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.training.train_step import (
        create_train_state,
        cross_entropy_loss,
        make_train_step,
        replicate_state,
    )

    k = 2
    cfg = TrainConfig(
        num_classes=VOCAB, compute_dtype="float32", weight_decay=0.0,
        batch_size_per_device=4, accum_steps=k,
    )
    model = get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                      max_seq_len=T)
    tx = optax.sgd(0.1, momentum=0.9)
    state0 = create_train_state(
        model, cfg, tx, input_shape=(1, T), input_dtype=jnp.int32
    )
    state0 = replicate_state(state0, mesh8)
    rng = np.random.RandomState(0)
    rows = rng.randint(0, VOCAB, size=(32, T + 1)).astype(np.int32)
    batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)

    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    accum_state, _ = step(state0, batch)
    accum_params = jax.device_get(accum_state.params)

    # Reference: same microbatch split (each device's local rows chunked
    # contiguously — globally that is rows[4i + 2j : 4i + 2j + 2] for
    # device i, microbatch j), same grad math (per-microbatch-mean loss,
    # pmean over devices AFTER accumulation), same order of f32 sums.
    def loss_fn(params, tokens, labels):
        logits, _ = model.apply(
            {"params": params}, tokens, train=True, mutable=["losses"]
        )
        return cross_entropy_loss(logits, labels, 0.0)

    grad_fn = jax.jit(jax.grad(loss_fn))
    tok = rows[:, :-1].reshape(8, 2, 2, T)  # [device, microbatch, rows, T]
    lab = rows[:, 1:].reshape(8, 2, 2, T)
    host_params = jax.device_get(state0.params)
    gacc = jax.tree.map(
        lambda p: np.zeros(p.shape, np.float32), host_params
    )
    for j in range(k):
        # per-device grads on microbatch j, then mean over devices ==
        # grad of the device-mean loss (linearity; the engine's pmean)
        dev_grads = [
            jax.device_get(grad_fn(host_params, tok[i, j], lab[i, j]))
            for i in range(8)
        ]
        mean_dev = jax.tree.map(
            lambda *gs: np.mean(np.stack(gs, 0), 0, dtype=np.float32),
            *dev_grads,
        )
        gacc = jax.tree.map(lambda a, g: a + g, gacc, mean_dev)
    grads = jax.tree.map(lambda a: (a / k).astype(np.float32), gacc)

    # One SGD+momentum update by hand (fresh optimizer state: buf = g).
    want = jax.tree.map(
        lambda p, g: np.float32(p + -0.1 * g), host_params, grads
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(want),
        jax.tree_util.tree_leaves_with_path(accum_params),
    ):
        # The device pmean and the host np.mean may differ in the last
        # ulp; everything else (scan order, Σ/k, update) is identical.
        np.testing.assert_allclose(
            a, b, rtol=0, atol=1e-7, err_msg=str(pa)
        )


def test_accum_deterministic_bitwise(mesh8):
    """(3): two identical accum_steps=4 runs are bit-identical."""
    def run():
        cfg = _cfg("dp", accum_steps=4)
        data = _data(cfg)
        eng = _build(cfg, data, mesh8)
        return _run_epoch(cfg, mesh8, data, eng)

    params_a, metrics_a, _ = run()
    params_b, metrics_b, _ = run()
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_array_equal(a, b)
    for m in metrics_a:
        np.testing.assert_array_equal(metrics_a[m], metrics_b[m])


def test_sync_free_loop_invariant_with_accum(mesh8):
    """(3): fit with accum_steps=4 still materialises exactly once per
    epoch, and the metric accumulator counts effective steps."""
    cfg = _cfg("dp", accum_steps=4, epochs=2)
    data = _data(cfg)
    model = get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                      max_seq_len=T)
    hostsync.accountant().reset()
    with hostsync.track():
        res = loop.fit(
            model, cfg, data, mesh=mesh8, add_default_logger=False
        )
    acct = hostsync.accountant()
    assert acct.count == cfg.epochs, acct.by_label
    assert acct.by_label.get("epoch_metrics") == cfg.epochs
    assert res.perf["host_sync_count"] == cfg.epochs
    assert res.perf["accum_steps"] == 4.0
    assert res.perf["effective_batch"] == float(cfg.global_batch_size)
    # throughput accounting: every delivered image counted exactly once
    expected = data.steps_per_epoch * cfg.global_batch_size
    assert res.history[0]["epoch_images"] == expected
    assert np.isfinite(res.history[-1]["loss"])


def test_ghost_batch_norm_folds_like_sequential_steps(mesh8):
    """(4): BN running statistics under accum_steps=k equal k sequential
    unaccumulated dispatches over the same microbatches when params are
    frozen (lr=0 — the only regime where the comparison is well-posed:
    sequential steps would otherwise move params between microbatches
    while one accumulated dispatch cannot)."""
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.training.train_step import (
        create_train_state,
        make_train_step,
        replicate_state,
    )

    k = 2
    cfg = TrainConfig(
        num_classes=8, image_size=16, compute_dtype="float32",
        weight_decay=0.0, batch_size_per_device=4,
    )
    model = ResNet(depth=18, num_classes=8, dtype=jnp.float32)
    tx = optax.sgd(0.0)  # frozen params: updates are exact zeros
    rng = np.random.RandomState(0)
    images = rng.randn(32, 16, 16, 3).astype(np.float32)
    labels = rng.randint(0, 8, 32).astype(np.int32)

    def fresh_state():
        st = create_train_state(
            model, cfg, tx, input_shape=(1, 16, 16, 3)
        )
        return replicate_state(st, mesh8)

    # accumulated: ONE dispatch over the full batch, k in-step microbatches
    accum_step = make_train_step(
        model, tx, mesh8, cfg.replace(accum_steps=k), donate_state=False
    )
    state_a, _ = accum_step(fresh_state(), shard_batch((images, labels), mesh8))

    # sequential reference: k plain dispatches over the same microbatches.
    # Device i's j-th in-step microbatch holds global rows
    # [4i+2j, 4i+2j+2) — regroup so sequential dispatch j feeds every
    # device exactly those rows.
    plain_step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    state_b = fresh_state()
    im = images.reshape(8, k, 2, 16, 16, 3)
    lb = labels.reshape(8, k, 2)
    for j in range(k):
        mb = (
            im[:, j].reshape(16, 16, 16, 3),
            lb[:, j].reshape(16),
        )
        state_b, _ = plain_step(state_b, shard_batch(mb, mesh8))

    bs_a = jax.device_get(state_a.batch_stats)
    bs_b = jax.device_get(state_b.batch_stats)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(bs_a),
        jax.tree_util.tree_leaves_with_path(bs_b),
    ):
        assert pa == pb
        # identical folds, but the accumulated path pmeans the running
        # stats once (after the scan) where the sequential path pmeans
        # per dispatch — a 1-2 ulp re-association on var≈1 values
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7, err_msg=str(pa))
    # and the frozen-params premise really held
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state_a.params)),
        jax.tree.leaves(jax.device_get(state_b.params)),
    ):
        np.testing.assert_array_equal(a, b)


def test_accum_changes_compiled_program(mesh8):
    """(5): the lowered HLO differs between accum_steps values — the XLA
    persistent-cache key (an HLO-module hash) cannot collide between
    recertify rows that differ only in ACCUM_STEPS."""
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.training.train_step import (
        create_train_state,
        make_train_step,
        replicate_state,
    )

    cfg = TrainConfig(
        num_classes=VOCAB, compute_dtype="float32", weight_decay=0.0,
        batch_size_per_device=4,
    )
    model = get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                      max_seq_len=T)
    tx = optax.sgd(0.1)
    state = replicate_state(
        create_train_state(
            model, cfg, tx, input_shape=(1, T), input_dtype=jnp.int32
        ),
        mesh8,
    )
    rng = np.random.RandomState(0)
    rows = rng.randint(0, VOCAB, size=(32, T + 1)).astype(np.int32)
    batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)
    texts = {}
    for k in (1, 2):
        step = make_train_step(
            model, tx, mesh8, cfg.replace(accum_steps=k), donate_state=False
        )
        texts[k] = step.lower(state, batch).as_text()
    assert texts[1] != texts[2]
    # the accumulated program really carries the scan loop
    assert "while" in texts[2]


def test_trace_time_divisibility_error(mesh8):
    """Actual-batch divisibility failures name every number (the staged
    batch can disagree with the config; the trace-time guard is the
    authoritative one)."""
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.training.train_step import (
        create_train_state,
        make_train_step,
        replicate_state,
    )

    cfg = TrainConfig(
        num_classes=VOCAB, compute_dtype="float32", weight_decay=0.0,
        batch_size_per_device=4, accum_steps=4,
    )
    model = get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                      max_seq_len=T)
    tx = optax.sgd(0.1)
    state = replicate_state(
        create_train_state(
            model, cfg, tx, input_shape=(1, T), input_dtype=jnp.int32
        ),
        mesh8,
    )
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    rng = np.random.RandomState(0)
    rows = rng.randint(0, VOCAB, size=(16, T + 1)).astype(np.int32)  # 2/shard
    bad = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)
    with pytest.raises(ValueError, match="ACCUM_STEPS=4.*per-shard batch 2"):
        step(state, bad)
