"""FSDP / ZeRO-3 tests: weights sharded over the data axis itself.

The GSPMD engine + the FSDP rules table must (a) physically shard every
annotated kernel and its optimizer moments over ``data``, (b) still
compute the exact single-device update (XLA's all-gather / reduce-
scatter insertion is numerically transparent), and (c) be reachable from
config (``ENGINE=pjit PARAM_SHARDING=fsdp``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models.sharding import (
    FSDP_RULES,
    rules_table,
)
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.models.vit import ViT
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training.pjit_step import (
    build_pjit_state,
    create_sharded_train_state,
    make_pjit_train_step,
)

VOCAB, T = 32, 8
CFG = TrainConfig(num_classes=VOCAB, weight_decay=0.0,
                  compute_dtype="float32", param_sharding="fsdp")


def _lm():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T, dtype=jnp.float32
    )


def test_rules_table_lookup():
    assert rules_table("fsdp") is FSDP_RULES
    assert dict(FSDP_RULES)["embed"] == "data"
    assert dict(FSDP_RULES)["heads"] is None  # no model axis needed
    with pytest.raises(ValueError, match="unknown sharding rules"):
        rules_table("zero2")


def test_fsdp_shards_params_and_moments_over_data(mesh8):
    model = _lm()
    tx = optax.adamw(1e-3)
    state = create_sharded_train_state(
        model, CFG, tx, mesh8, FSDP_RULES,
        input_shape=(1, T), input_dtype=jnp.int32,
    )
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    assert tuple(qkv.sharding.spec)[:1] == ("data",)  # embed dim sharded
    # each device holds 1/8 of the matrix
    assert qkv.addressable_shards[0].data.shape[0] == qkv.shape[0] // 8
    embed = state.params["tok_embed"]
    assert tuple(embed.sharding.spec) == (None, "data")  # vocab dim whole
    # adam moments mirror the param sharding (ZeRO-1/2)
    moments = [
        l for l in jax.tree.leaves(state.opt_state)
        if getattr(l, "shape", None) == qkv.shape
    ]
    assert moments
    for m in moments:
        assert tuple(m.sharding.spec)[:1] == ("data",)
    # LayerNorm stays replicated (standard FSDP small-param choice)
    ln = state.params["block0"]["ln1"]["scale"]
    assert all(p is None for p in tuple(ln.sharding.spec))


def test_fsdp_update_matches_single_device(mesh8):
    model = _lm()
    tx = optax.sgd(0.1, momentum=0.9)
    rng = np.random.RandomState(0)
    rows = rng.randint(0, VOCAB, size=(16, T + 1)).astype(np.int32)

    results = []
    for mesh, rules in (
        (mesh8, FSDP_RULES),
        (create_mesh(devices=jax.devices()[:1]), FSDP_RULES),
    ):
        state = create_sharded_train_state(
            model, CFG, tx, mesh, rules,
            input_shape=(1, T), input_dtype=jnp.int32,
        )
        step = make_pjit_train_step(model, tx, mesh, CFG, donate_state=False)
        with mesh:
            s, metrics = step(
                state, shard_batch((rows[:, :-1], rows[:, 1:]), mesh)
            )
        results.append((float(metrics["loss"]), jax.device_get(s.params)))
    assert np.isclose(results[0][0], results[1][0], rtol=1e-5)
    for a, b in zip(
        jax.tree.leaves(results[0][1]), jax.tree.leaves(results[1][1])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fsdp_vit_from_config(mesh8):
    """ENGINE=pjit PARAM_SHARDING=fsdp reaches FSDP through the shared
    build point, for the vision family too."""
    cfg = TrainConfig.from_env(
        {"ENGINE": "pjit", "PARAM_SHARDING": "fsdp"},
        num_classes=10, image_size=16, compute_dtype="float32",
        weight_decay=0.0,
    )
    assert cfg.param_sharding == "fsdp"
    model = ViT(variant="ti", patch_size=16, num_classes=10, dtype=jnp.float32)
    tx = optax.sgd(0.05)
    state = build_pjit_state(
        model, cfg, tx, mesh8, input_shape=(1, 16, 16, 3)
    )
    fc1 = state.params["block0"]["mlp"]["fc1"]["kernel"]
    assert tuple(fc1.sharding.spec)[:1] == ("data",)
    step = make_pjit_train_step(model, tx, mesh8, cfg, donate_state=False)
    rng = np.random.RandomState(1)
    batch = (
        rng.randn(16, 16, 16, 3).astype(np.float32),
        rng.randint(0, 10, size=(16,)).astype(np.int32),
    )
    with mesh8:
        losses = []
        b = shard_batch(batch, mesh8)
        for _ in range(4):
            state, metrics = step(state, b)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_fsdp_checkpoint_roundtrip(tmp_path, mesh8):
    from distributeddeeplearning_tpu.training.checkpoint import CheckpointManager

    model = _lm()
    tx = optax.sgd(0.1)
    state = create_sharded_train_state(
        model, CFG, tx, mesh8, FSDP_RULES,
        input_shape=(1, T), input_dtype=jnp.int32,
    )
    mgr = CheckpointManager(str(tmp_path / "fsdp_ckpt"))
    mgr.save(0, state, force=True)
    mgr.wait()
    mgr.close()
    mgr2 = CheckpointManager(str(tmp_path / "fsdp_ckpt"))
    fresh = create_sharded_train_state(
        model, CFG, tx, mesh8, FSDP_RULES,
        input_shape=(1, T), input_dtype=jnp.int32,
        rng=jax.random.PRNGKey(9),
    )
    restored, epoch = mgr2.maybe_restore(fresh)
    mgr2.close()
    assert epoch == 1
    a = state.params["block0"]["attn"]["qkv"]["kernel"]
    b = restored.params["block0"]["attn"]["qkv"]["kernel"]
    assert tuple(b.sharding.spec) == tuple(a.sharding.spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
    )


def test_fsdp_moe_composition(mesh8):
    """FSDP × MoE must not collide on the data axis: weight-embed shards
    over data while the MoE activation constraints use the distinct
    'act_embed' logical name (replicated), so the spec never names one
    mesh axis twice."""
    model = TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T,
        dtype=jnp.float32, moe_experts=4,
    )
    tx = optax.sgd(0.1)
    state = create_sharded_train_state(
        model, CFG, tx, mesh8, FSDP_RULES,
        input_shape=(1, T), input_dtype=jnp.int32,
    )
    w1 = state.params["block1"]["moe"]["w1"]
    # expert weights: ("expert","embed","mlp") -> embed dim over data
    assert tuple(w1.sharding.spec)[:2] == (None, "data"), w1.sharding
    step = make_pjit_train_step(model, tx, mesh8, CFG, donate_state=False)
    rng = np.random.RandomState(7)
    rows = rng.randint(0, VOCAB, size=(16, T + 1)).astype(np.int32)
    with mesh8:
        batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)
        state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_param_sharding_validation():
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    with pytest.raises(ValueError, match="unknown sharding rules"):
        resolve_engine(TrainConfig(engine="pjit", param_sharding="zero2"))
    with pytest.raises(ValueError, match="requires ENGINE=pjit"):
        resolve_engine(TrainConfig(engine="dp", param_sharding="fsdp"))
