import pytest

from distributeddeeplearning_tpu.config import TrainConfig, _str_to_bool


def test_defaults_match_reference_constants():
    c = TrainConfig()
    assert c.batch_size_per_device == 64  # _BATCHSIZE
    assert c.base_lr == 0.001  # _LR
    assert c.momentum == 0.9
    assert c.fake_data_length == 1_281_167
    assert c.lr_decay_epochs == (30, 60, 80)
    assert c.warmup_epochs == 5
    assert c.seed == 42


def test_epochs_env_is_int():
    # Reference defect §2c.2: EPOCHS env var stayed a str and broke
    # `_EPOCHS * length`. Must parse to int here.
    c = TrainConfig.from_env({"EPOCHS": "3"})
    assert c.epochs == 3
    assert isinstance(c.epochs * 10, int)


def test_bool_parsing_is_strict():
    # Reference's `"t" in v.lower()` made "faulty" truthy.
    assert _str_to_bool("True") and _str_to_bool("t") and _str_to_bool("1")
    assert not _str_to_bool("False") and not _str_to_bool("faulty")
    assert not _str_to_bool("0")


def test_env_contract():
    env = {
        "DISTRIBUTED": "True",
        "FAKE": "False",
        "FAKE_DATA_LENGTH": "1000",
        "VALIDATION": "True",
        "BATCHSIZE": "32",
        "LR": "0.01",
        "MODEL": "resnet18",
        "AZ_BATCHAI_INPUT_TRAIN": "/data/train",
        "AZ_BATCHAI_INPUT_TEST": "/data/val",
        "AZ_BATCHAI_OUTPUT_MODEL": "/out",
    }
    c = TrainConfig.from_env(env)
    assert c.distributed and not c.fake and c.validation
    assert c.fake_data_length == 1000
    assert c.batch_size_per_device == 32
    assert c.base_lr == 0.01
    assert c.model == "resnet18"
    assert c.data_dir == "/data/train"
    assert c.val_data_dir == "/data/val"
    assert c.model_dir == "/out"
    # pipeline knobs (round 4)
    c2 = TrainConfig.from_env(
        {"INPUT_STAGING": "uint8", "PREFETCH_BATCHES": "4"}
    )
    assert c2.input_staging == "uint8" and c2.prefetch_batches == 4


def test_overrides_beat_env():
    c = TrainConfig.from_env({"EPOCHS": "3"}, epochs=7)
    assert c.epochs == 7


def test_accum_steps_env_contract():
    c = TrainConfig.from_env({"ACCUM_STEPS": "4"})
    assert c.accum_steps == 4
    assert TrainConfig().accum_steps == 1  # default: no accumulation
    # ACCUM_STEPS (in-step scan) and GRAD_ACCUM_STEPS (multi-dispatch
    # MultiSteps) are independent knobs
    c2 = TrainConfig.from_env({"ACCUM_STEPS": "2", "GRAD_ACCUM_STEPS": "3"})
    assert c2.accum_steps == 2 and c2.grad_accum_steps == 3


def test_accum_steps_validation_names_the_numbers():
    from distributeddeeplearning_tpu.training.accum import (
        resolve_accum_steps,
        validate_accum_config,
    )

    with pytest.raises(ValueError, match=">= 1"):
        resolve_accum_steps(TrainConfig(accum_steps=0))
    # per-shard batch not divisible: message names global batch, shard
    # count, per-shard batch, and the offending accum_steps
    cfg = TrainConfig(batch_size_per_device=6, accum_steps=4)
    with pytest.raises(ValueError) as ei:
        validate_accum_config(cfg)
    msg = str(ei.value)
    assert "6" in msg and "ACCUM_STEPS=4" in msg and "shard" in msg
    # valid split passes and returns k
    assert validate_accum_config(
        TrainConfig(batch_size_per_device=8, accum_steps=4)
    ) == 4
    # ENGINE=pp: each accumulation microbatch must still split into
    # pp_microbatches pipeline microbatches
    pp = TrainConfig(
        engine="pp", batch_size_per_device=8, accum_steps=4,
        pp_microbatches=4, pp_stages=4,
    )
    with pytest.raises(ValueError, match="PP_MICROBATCHES"):
        validate_accum_config(pp)
    ok = TrainConfig(
        engine="pp", batch_size_per_device=16, accum_steps=2,
        pp_microbatches=4, pp_stages=4,
    )
    assert validate_accum_config(ok) == 2
