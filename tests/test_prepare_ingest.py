"""Raw-ILSVRC-tar → training onboarding (VERDICT r3 #5).

Builds a synthetic mini-ILSVRC2012 distribution with the REAL layout —
an outer train tar nesting one tar per class, a flat validation tar,
and a devkit tar.gz carrying ``meta.mat`` (written with scipy, the same
MATLAB container the real devkit uses) plus the ground-truth id list —
then drives ``prepare.py ingest`` end-to-end and trains a step from the
result. The reference needed two notebook cells of shell, a generated
50k-line ``valprep.sh``, and manual staging for the same path
(``/root/reference/00_DataProcessing.ipynb`` cells 3-13).
"""

import io
import os
import tarfile

import numpy as np
import pytest

from distributeddeeplearning_tpu.data.prepare import (
    devkit_val_mapping,
    ingest,
)

WNIDS = ("n01440764", "n01443537", "n01484850")
VAL_IDS = [3, 1, 2, 1, 3, 2]  # ILSVRC2012_IDs of the 6 validation images


def _jpeg_bytes(rng) -> bytes:
    from PIL import Image

    arr = rng.randint(0, 255, size=(24, 24, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    return buf.getvalue()


def _add_bytes(tar, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tar.addfile(info, io.BytesIO(data))


@pytest.fixture(scope="module")
def mini_ilsvrc(tmp_path_factory):
    """(train_tar, val_tar, devkit_tgz) with the distribution's layout."""
    from scipy.io import savemat

    root = tmp_path_factory.mktemp("ilsvrc")
    rng = np.random.RandomState(0)

    # train: outer tar of per-class tars, 4 images each
    train_tar = root / "ILSVRC2012_img_train.tar"
    with tarfile.open(train_tar, "w") as outer:
        for wnid in WNIDS:
            inner = io.BytesIO()
            with tarfile.open(fileobj=inner, mode="w") as class_tar:
                for i in range(4):
                    _add_bytes(
                        class_tar, f"{wnid}_{i}.JPEG", _jpeg_bytes(rng)
                    )
            _add_bytes(outer, f"{wnid}.tar", inner.getvalue())

    # validation: flat tar, labels only in the devkit
    val_tar = root / "ILSVRC2012_img_val.tar"
    with tarfile.open(val_tar, "w") as tar:
        for i in range(len(VAL_IDS)):
            _add_bytes(
                tar, f"ILSVRC2012_val_{i + 1:08d}.JPEG", _jpeg_bytes(rng)
            )

    # devkit: meta.mat synset table (one non-leaf parent + 3 leaves,
    # deliberately NOT in wnid order) + ground-truth ids
    synsets = np.zeros(
        (4, 1),
        dtype=[
            ("ILSVRC2012_ID", "O"),
            ("WNID", "O"),
            ("words", "O"),
            ("num_children", "O"),
        ],
    )
    rows = [
        (1, WNIDS[1], "fish a", 0),
        (2, WNIDS[0], "fish b", 0),
        (3, WNIDS[2], "shark", 0),
        (4, "n99999999", "animal (parent)", 2),
    ]
    for i, (ilsvrc_id, wnid, words, children) in enumerate(rows):
        synsets[i, 0] = (
            np.array([[ilsvrc_id]]),
            np.array([wnid]),
            np.array([words]),
            np.array([[children]]),
        )
    meta = io.BytesIO()
    savemat(meta, {"synsets": synsets})
    truth = "".join(f"{i}\n" for i in VAL_IDS).encode()

    devkit = root / "ILSVRC2012_devkit_t12.tar.gz"
    with tarfile.open(devkit, "w:gz") as tar:
        _add_bytes(tar, "ILSVRC2012_devkit_t12/data/meta.mat", meta.getvalue())
        _add_bytes(
            tar,
            "ILSVRC2012_devkit_t12/data/ILSVRC2012_validation_ground_truth.txt",
            truth,
        )
    return str(train_tar), str(val_tar), str(devkit)


def test_devkit_mapping(mini_ilsvrc):
    _, _, devkit = mini_ilsvrc
    mapping = devkit_val_mapping(devkit)
    assert len(mapping) == len(VAL_IDS)
    assert mapping[0] == ("ILSVRC2012_val_00000001.JPEG", WNIDS[2])  # id 3
    assert mapping[1] == ("ILSVRC2012_val_00000002.JPEG", WNIDS[1])  # id 1
    # only leaf synsets are classes: the parent wnid never appears
    assert all(wnid in WNIDS for _, wnid in mapping)


def test_ingest_raw_tars_to_training(mini_ilsvrc, tmp_path):
    train_tar, val_tar, devkit = mini_ilsvrc
    out = tmp_path / "imagenet"
    stats = ingest(
        train_tar, val_tar, devkit, str(out), num_shards=2, val_shards=1
    )
    assert stats["train_images"] == 12
    assert stats["val_images"] == len(VAL_IDS)
    assert stats["val_sorted"] == len(VAL_IDS)
    assert stats["train_tfrecords"] == 12
    # ImageFolder layouts for both splits, leftovers cleaned up
    assert sorted(os.listdir(out / "train")) == sorted(WNIDS)
    assert set(os.listdir(out / "validation")) <= set(WNIDS)
    assert not (out / "_val_flat").exists()
    # the derived mapping is kept for reuse
    assert (out / "val_wnids.txt").exists()

    # the produced shards feed the real reader → one train step
    from distributeddeeplearning_tpu.data.imagenet import (
        TFRecordImageNetDataset,
    )

    ds = TFRecordImageNetDataset(
        str(out / "tfrecords" / "train" / "imagenet-*"),
        global_batch_size=4, image_size=16, train=True,
    )
    assert ds.length == 12
    images, labels = next(ds.epoch(0))
    assert images.shape == (4, 16, 16, 3)
    assert labels.min() >= 0 and labels.max() < 3


def test_ingest_cli(mini_ilsvrc, tmp_path, capsys):
    from distributeddeeplearning_tpu.data.prepare import main

    train_tar, val_tar, devkit = mini_ilsvrc
    assert (
        main(
            [
                "ingest",
                "--train-tar", train_tar,
                "--val-tar", val_tar,
                "--devkit", devkit,
                "--out", str(tmp_path / "o"),
                "--no-tfrecords",
            ]
        )
        == 0
    )
    assert "train_images=12" in capsys.readouterr().out
