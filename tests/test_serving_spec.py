"""Speculative decode tier oracles (``SlotEngine(spec_k > 0)``).

The speculative tier's contract, pinned here (CPU tier):

* **Greedy losslessness** — a speculative greedy stream is bitwise the
  sequential ``inference.generate`` stream (and therefore the non-spec
  engine's stream) whatever the co-scheduling: staggered joins, mixed
  buckets, mid-stream cancels with immediate slot reuse. Dense AND
  paged twins, int8 self-draft AND n-gram prompt-lookup sources —
  correctness never depends on draft quality.
* **Distribution preservation** — the rejection-sampling acceptance
  (``sampling.spec_verify_slots``) leaves sampled output distributed
  EXACTLY as ``inference._sample`` (point-mass proposals: accept with
  the target's own probability, resample from the draft-masked
  residual). Chi-squared-bounded against ``_sample`` at fixed seeds.
* **Closed program set, enlarged** — verify (+ draft programs for the
  int8 source) join the set at warmup; ``compile_count ==
  programs_expected`` and an admission/eviction churn compiles nothing.
* **Lookahead reservation** — the verify writes ``spec_k`` candidate
  positions past the committed cursor; paged admission reserves the
  blocks, dense admission reserves ``max_len`` headroom.
* **SERVE_SPEC_* config contract** — env parsing, engine kwargs, the
  rejection rules (``spec_k < 0``, int8 draft on an int8-weight
  target, unknown sources).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.inference import _sample, generate
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.serving import (
    NgramDrafter,
    ReqSpec,
    Request,
    ServeConfig,
    Server,
    SlotEngine,
)
from distributeddeeplearning_tpu.serving.sampling import spec_verify_slots

VOCAB, MAX_LEN = 64, 48
BUCKETS = (4, 8, 16)
K = 3


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


@pytest.fixture(scope="module")
def _spec_engine(model, params):
    """Warmed int8-self-draft engine, shared module-wide."""
    eng = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS,
        spec_k=K, spec_draft="int8",
    )
    eng.warmup()
    return eng


@pytest.fixture
def spec_engine(_spec_engine):
    for s in _spec_engine.active_slots:
        _spec_engine.release(s)
    yield _spec_engine
    for s in _spec_engine.active_slots:
        _spec_engine.release(s)


@pytest.fixture(scope="module")
def _paged_spec_engine(model, params):
    """Warmed paged twin on the zero-device-cost n-gram source."""
    eng = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS,
        kv_layout="paged", block_size=4,
        spec_k=K, spec_draft="ngram",
    )
    eng.warmup()
    return eng


@pytest.fixture
def paged_spec_engine(_paged_spec_engine):
    for s in _paged_spec_engine.active_slots:
        _paged_spec_engine.release(s)
    yield _paged_spec_engine
    for s in _paged_spec_engine.active_slots:
        _paged_spec_engine.release(s)


def _prompt(rng, n):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _assert_greedy_parity(h, model, params):
    ref = np.asarray(generate(
        model, params, np.asarray(h.request.prompt, np.int32)[None],
        max_new_tokens=h.request.max_new_tokens,
        eos_token=h.request.eos_token,
    ))[0]
    got = h.tokens
    assert got.shape[0] <= ref.shape[0]
    np.testing.assert_array_equal(got, ref[: got.shape[0]])


# -- host-side drafter (serving/spec.py) ---------------------------------


def test_ngram_drafter_lookup_and_fallback():
    d = NgramDrafter(3)
    # suffix [2, 3] recurs at index 1 — continuation is [4, 1, 2]
    np.testing.assert_array_equal(
        d.propose([1, 2, 3, 4, 1, 2, 3], 3), [4, 1, 2]
    )
    # no 2-gram match, but the 1-gram suffix [9] recurs -> continues it
    np.testing.assert_array_equal(d.propose([9, 5, 9, 7, 9], 2), [7, 9])
    # nothing recurs: the deliberately-rejectable zero proposal
    np.testing.assert_array_equal(d.propose([1, 2, 3, 4], 2), [0, 0])
    # match near the end: short continuation cycles, never zero-pads
    np.testing.assert_array_equal(d.propose([7, 8, 7, 8], 4)[:2], [7, 8])
    assert d.stats["proposals"] == 4
    assert d.stats["lookups_hit"] == 3
    with pytest.raises(ValueError, match="ngram n"):
        NgramDrafter(1)


# -- config contract ------------------------------------------------------


def test_spec_config_env_kwargs_and_validation(model):
    cfg = ServeConfig.from_env({
        "SERVE_SPEC_K": "4", "SERVE_SPEC_DRAFT": "ngram",
        "SERVE_SPEC_NGRAM_N": "5",
    })
    assert cfg.spec_k == 4 and cfg.spec_draft == "ngram"
    assert cfg.spec_ngram_n == 5
    kw = cfg.engine_kwargs()
    assert kw["spec_k"] == 4 and kw["spec_draft"] == "ngram"
    assert kw["spec_ngram_n"] == 5
    dflt = ServeConfig.from_env({})
    assert dflt.spec_k == 0
    assert "spec_k" not in dflt.engine_kwargs()  # off = old kwargs shape
    tiny = TransformerLM(variant="tiny", vocab_size=8, max_seq_len=8)
    with pytest.raises(ValueError, match="spec_k"):
        SlotEngine(tiny, {}, spec_k=-1)
    with pytest.raises(ValueError, match="spec_draft"):
        SlotEngine(tiny, {}, spec_k=2, spec_draft="off")
    with pytest.raises(ValueError, match="spec_draft"):
        SlotEngine(tiny, {}, spec_k=2, spec_draft="medium")
    # int8 draft on an int8-weight target: no cheaper tier to draft from
    with pytest.raises(ValueError, match="weight tier"):
        SlotEngine(tiny, {}, spec_k=2, spec_draft="int8",
                   weight_dtype="int8")
    with pytest.raises(ValueError, match="spec_ngram_n"):
        SlotEngine(tiny, {}, spec_k=2, spec_draft="ngram", spec_ngram_n=1)
    # spec_k=0 leaves the other knobs inert (no validation tripwires)
    SlotEngine(tiny, {}, spec_k=0, spec_draft="off")


def test_spec_headroom_reserved_at_admission(spec_engine):
    """Dense lookahead reservation: prompt + max_new + spec_k must fit
    max_len — dynamic_update_slice clamps out-of-range verify writes
    backwards, which would corrupt committed rows."""
    ok = ReqSpec(np.zeros(8, np.int32), MAX_LEN - 8 - K)
    spec_engine.validate_spec(ok)
    too_long = ReqSpec(np.zeros(8, np.int32), MAX_LEN - 8 - K + 1)
    with pytest.raises(ValueError, match="lookahead"):
        spec_engine.validate_spec(too_long)


# -- greedy losslessness (the flagship oracle) ---------------------------


def test_spec_greedy_bitwise_staggered_mixed_lengths(
    spec_engine, model, params
):
    """8 greedy requests over 4 slots, mixed buckets, staggered joins,
    different max_new — every speculative stream bitwise-equal to
    sequential generate, and speculation actually engaged (accepted
    drafts > 0, multi-token commits happened)."""
    rng = np.random.RandomState(0)
    acc0 = spec_engine.spec_stats["tokens_accepted"]
    server = Server(spec_engine, prefills_per_step=1)
    handles = [
        server.submit(Request(prompt=_prompt(rng, n), max_new_tokens=m))
        for n, m in [(3, 6), (7, 9), (12, 4), (16, 10),
                     (4, 12), (9, 3), (14, 7), (5, 5)]
    ]
    server.drain()
    assert all(h.status == "done" for h in handles)
    assert all(
        len(h.new_tokens) == h.request.max_new_tokens for h in handles
    )
    for h in handles:
        _assert_greedy_parity(h, model, params)
    assert spec_engine.spec_stats["tokens_accepted"] > acc0


def test_spec_greedy_paged_twin_bitwise(paged_spec_engine, model, params):
    """The paged + n-gram twin of the flagship: parity holds through
    block-table routing and whatever the (model-free) drafter proposes."""
    rng = np.random.RandomState(1)
    server = Server(paged_spec_engine, prefills_per_step=2)
    handles = [
        server.submit(Request(prompt=_prompt(rng, n), max_new_tokens=m))
        for n, m in [(3, 8), (8, 10), (13, 6), (16, 9), (5, 12)]
    ]
    server.drain()
    assert all(h.status == "done" for h in handles)
    for h in handles:
        _assert_greedy_parity(h, model, params)


def test_spec_sampled_churn_cancel_zero_compiles(spec_engine):
    """Sampled + greedy mix under churn (staggered joins, a mid-stream
    cancel freeing a slot that is immediately re-admitted into): the
    whole run triggers ZERO backend compiles, and the same seeded load
    replayed is bitwise-deterministic (speculative sampled streams are
    deterministic given the request rng, tick for tick)."""
    from jax._src import monitoring

    compiles = []
    monitoring.register_event_duration_secs_listener(
        lambda event, duration, **kw: compiles.append(event)
        if "backend_compile" in event else None
    )
    baseline = len(compiles)

    def run_load():
        rng = np.random.RandomState(2)
        server = Server(spec_engine, prefills_per_step=2)
        mk = lambda n, m, seed, **kw: server.submit(Request(  # noqa: E731
            prompt=_prompt(rng, n), max_new_tokens=m, rng=seed, **kw
        ))
        wave1 = [
            mk(3, 10, 11, temperature=0.9, top_k=8),
            mk(8, 12, 12, temperature=0.7, top_k=5),
            mk(13, 12, 13),  # greedy neighbour in the same pool
            mk(16, 8, 14, temperature=1.1, top_k=40, top_p=0.9),
        ]
        for _ in range(2):
            server.step()
        victim = wave1[1]
        victim.cancel()
        wave2 = [mk(5, 9, 21, temperature=0.8, top_k=6)]
        server.drain()
        assert victim.status == "cancelled"
        return [list(h.new_tokens) for h in wave1 + wave2]

    first = run_load()
    second = run_load()
    assert len(compiles) == baseline, compiles[baseline:]
    assert first == second


def test_spec_eos_truncates_mid_commit(spec_engine, model, params):
    """An eos landing inside a multi-token commit cuts the stream at
    the eos token — same semantics as the non-spec engine and
    generate's pad-after-eos."""
    rng = np.random.RandomState(3)
    prompt = _prompt(rng, 5)
    ref = np.asarray(generate(model, params, prompt[None],
                              max_new_tokens=12))[0]
    eos = int(ref[5 + 2])  # third greedy token becomes the eos
    server = Server(spec_engine)
    h = server.submit(Request(
        prompt=prompt, max_new_tokens=12, eos_token=eos,
    ))
    server.drain()
    assert h.finish_reason == "eos"
    gen = ref[5:]
    first = int(np.argmax(gen == eos))
    assert len(h.new_tokens) == first + 1
    assert h.new_tokens[-1] == eos
    _assert_greedy_parity(h, model, params)
    assert spec_engine.occupancy == 0.0


def test_generate_engine_route_spec_greedy_bitwise(
    spec_engine, model, params
):
    """inference.generate(engine=spec server): greedy B=1 and B>1
    bitwise through the speculative pool."""
    rng = np.random.RandomState(4)
    server = Server(spec_engine)
    p1 = rng.randint(0, VOCAB, size=(1, 6)).astype(np.int32)
    ref = np.asarray(generate(model, params, p1, max_new_tokens=8))
    got = np.asarray(generate(model, params, p1, max_new_tokens=8,
                              engine=server))
    np.testing.assert_array_equal(got, ref)
    pb = rng.randint(0, VOCAB, size=(3, 5)).astype(np.int32)
    ref = np.asarray(generate(model, params, pb, max_new_tokens=6))
    got = np.asarray(generate(model, params, pb, max_new_tokens=6,
                              engine=server))
    np.testing.assert_array_equal(got, ref)


# -- distribution preservation (rejection sampler vs _sample) ------------


@pytest.mark.parametrize(
    "temperature,top_k,top_p,draft_tok",
    [
        (1.0, None, None, 3),   # plain temperature
        (0.8, 4, None, 2),      # top-k filter (draft outside the kept set
                                # on these logits: pure residual path)
        (1.0, None, 0.9, 5),    # nucleus filter
    ],
)
def test_spec_rejection_sampler_matches_sample_distribution(
    temperature, top_k, top_p, draft_tok
):
    """Two-sample chi-squared: N committed first tokens from the
    speculative acceptance vs N draws from inference._sample on the
    same logits/config. Fixed seeds — deterministic, not flaky. Bound:
    the 0.999 quantile of chi2(dof) is ~'dof + 4*sqrt(dof) + 10'; we
    use a slightly looser static bound per config."""
    v, n = 16, 3000
    rng = np.random.RandomState(0)
    logits0 = (rng.randn(v)).astype(np.float32)
    logits1 = (rng.randn(v)).astype(np.float32)
    keys = np.asarray(
        jax.random.split(jax.random.PRNGKey(7), n * 2), np.uint32
    ).reshape(n, 2, 2)
    logits = np.broadcast_to(
        np.stack([logits0, logits1])[None], (n, 2, v)
    ).astype(np.float32)
    drafts = np.full((n, 1), draft_tok, np.int32)
    committed, _ = jax.jit(spec_verify_slots)(
        jnp.asarray(logits), jnp.asarray(drafts), jnp.asarray(keys),
        jnp.full((n,), temperature, jnp.float32),
        jnp.full((n,), top_k or 0, jnp.int32),
        jnp.full((n,), top_p or 0.0, jnp.float32),
    )
    first = np.asarray(committed)[:, 0]
    ref_keys = jax.random.split(jax.random.PRNGKey(99), n)
    ref = np.asarray(jax.jit(jax.vmap(
        lambda kk: _sample(
            jnp.asarray(logits0)[None], kk, temperature, top_k, top_p
        )[0]
    ))(ref_keys))
    o1 = np.bincount(first, minlength=v).astype(np.float64)
    o2 = np.bincount(ref, minlength=v).astype(np.float64)
    tot = o1 + o2
    chi2 = float(np.sum(np.where(
        tot > 0, (o1 - o2) ** 2 / np.maximum(tot, 1), 0.0
    )))
    dof = int((tot > 0).sum()) - 1
    bound = dof + 4 * np.sqrt(dof) + 10
    assert chi2 < bound, (chi2, dof, bound)


# -- program budget -------------------------------------------------------


def test_spec_program_count_enlarged_but_closed(
    spec_engine, paged_spec_engine
):
    """int8 source: decode + buckets prefills + verify + draft phase +
    buckets draft prefills. ngram source: decode + buckets + verify.
    Warmup stays idempotent at the new counts."""
    want_int8 = 2 * len(BUCKETS) + 3
    assert spec_engine.programs_expected == want_int8
    assert spec_engine.compile_count == want_int8
    spec_engine.warmup()
    assert spec_engine.compile_count == want_int8
    want_ngram = len(BUCKETS) + 2
    assert paged_spec_engine.programs_expected == want_ngram
    assert paged_spec_engine.compile_count == want_ngram
    paged_spec_engine.warmup()
    assert paged_spec_engine.compile_count == want_ngram


# -- paged lookahead reservation -----------------------------------------


def test_spec_paged_block_reservation_lookahead(model, params):
    """Paged admission reserves spec_k positions ahead: blocks_needed
    grows vs the non-spec engine, a request that would exactly fill the
    pool without lookahead no longer fits, and worst-case validation
    names the pool."""
    bs = 4
    base = SlotEngine(
        model, params, num_slots=2, max_len=MAX_LEN, buckets=BUCKETS,
        kv_layout="paged", block_size=bs, num_blocks=9,
        prefix_cache=False,
    )
    spec = SlotEngine(
        model, params, num_slots=2, max_len=MAX_LEN, buckets=BUCKETS,
        kv_layout="paged", block_size=bs, num_blocks=9,
        prefix_cache=False, spec_k=K, spec_draft="ngram",
    )
    # 8 prompt + 9 new -> 16 written positions = 4 blocks without
    # lookahead; +3 lookahead crosses into a 5th block.
    assert base.blocks_needed(8, 9) == 4
    assert spec.blocks_needed(8, 9) == 5
    req = ReqSpec(np.zeros(8, np.int32), 9)
    # The 8-block free pool (9 minus the trash block) fits two plain
    # requests but NOT two speculative ones.
    assert base.can_admit(req) and spec.can_admit(req)
    base.allocator.alloc(4)
    spec.allocator.alloc(4)
    assert base.can_admit(req)
    assert not spec.can_admit(req)
    with pytest.raises(ValueError, match="KV blocks"):
        spec.validate_spec(ReqSpec(np.zeros(16, np.int32), 22))


# -- teacher forcing (the PR-8 hook, speculative edition) ----------------


def test_spec_force_token_teacher_forcing(spec_engine, model, params):
    """force_token drives the verify's NEXT window: given the same
    forced context, the spec tick's first committed token equals the
    non-spec greedy token at that context (generate reference)."""
    rng = np.random.RandomState(5)
    prompt = _prompt(rng, 6)
    spec_engine.prefill(0, ReqSpec(prompt=prompt, max_new_tokens=10))
    forced = int(prompt[0])  # an off-policy context token
    spec_engine.force_token(0, forced)
    [(slot, toks, _eos)] = spec_engine.spec_step()
    assert slot == 0
    ctx = np.concatenate([prompt, [forced]]).astype(np.int32)
    ref = np.asarray(generate(
        model, params, ctx[None], max_new_tokens=1,
    ))[0]
    assert toks[0] == int(ref[-1])
    spec_engine.release(0)


# -- observability --------------------------------------------------------


def test_spec_obs_gauges_counters_and_report(spec_engine, tmp_path):
    """serve.spec_* gauges/counters land on the bus; the obs_report
    serving view carries them and renders the acceptance line."""
    from distributeddeeplearning_tpu import obs
    from distributeddeeplearning_tpu.obs.report import (
        load, render, summarize,
    )

    bus = obs.configure(str(tmp_path), run_id="spec-test", proc=0,
                        install_handlers=False)
    try:
        server = Server(spec_engine)
        rng = np.random.RandomState(6)
        hs = [server.submit(Request(prompt=_prompt(rng, n),
                                    max_new_tokens=8))
              for n in (4, 9)]
        server.drain()
        assert all(h.status == "done" for h in hs)
        bus.flush()
    finally:
        obs.reset()
    summary = summarize(load([str(tmp_path)]))
    srv = summary["serving"]
    assert srv is not None
    acc, rej = srv["spec_tokens_accepted"], srv["spec_tokens_rejected"]
    assert acc + rej > 0
    assert srv["spec_accept_rate"] is not None
    assert srv["spec_draft_ms"] is not None
    assert srv["spec_verify_ms"] is not None
    text = render(summary)
    assert "speculative:" in text
    assert "draft tokens" in text


def test_spec_accept_rate_slo_watchable():
    """The accept-rate gauge feeds the live plane like any other metric:
    an SLO_SPEC objective on serve.spec_accept_rate:last evaluates from
    the rollup aggregator and burns when acceptance collapses."""
    from distributeddeeplearning_tpu.obs.rollup import WindowedAggregator
    from distributeddeeplearning_tpu.obs.slo import (
        SloEngine, parse_slo_spec,
    )

    eng = SloEngine(
        parse_slo_spec("serve.spec_accept_rate:last >= 0.5"),
        emit=lambda name, **kw: None,
    )
    agg = WindowedAggregator(10.0, slice_s=1.0, retain_s=eng.retain_s())
    agg.add({"kind": "gauge", "name": "serve.spec_accept_rate",
             "value": 0.9, "wall": 1000.0})
    st = eng.evaluate(agg, now=1000.0)[0]
    assert not st["burning"]
    agg.add({"kind": "gauge", "name": "serve.spec_accept_rate",
             "value": 0.1, "wall": 1001.0})
    st = eng.evaluate(agg, now=1001.0)[0]
    assert st["burn"] > 1.0
